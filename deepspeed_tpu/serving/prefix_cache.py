"""Radix-tree prefix cache over paged KV (SGLang's RadixAttention idea).

The trie is HOST-ONLY bookkeeping: it maps token-id prefixes to page ids
of the :class:`~deepspeed_tpu.serving.paged_pool.PagedKVPool`. Each edge
is one FULL page of ``page_size`` token ids (a tuple key), each node
holds the page id whose K/V columns were computed for exactly that
prefix, and the trie itself owns ONE refcount on every cached page —
independent of any slot's mapping, so a request can retire while its
prompt pages stay warm for the next request with the same prefix.

Only FULL pages are ever cached: a partially-filled page is still being
written by its owning slot (decode appends land there), so sharing it
would let one request's garbage corrupt another's attention window.
Page granularity also makes matching trivially correct: the K/V content
of a page is a pure function of (token ids, positions) for this model
family, so equal full-page prefixes ⇒ bitwise-equal cache columns.

Eviction is leaf-LRU: when the pool runs out of free pages it asks the
trie to drop its least-recently-matched LEAF nodes (an interior node's
page is useless without its children only in the sense of deeper
matches — but a leaf is always droppable, and dropping leaves first
converges to dropping whole cold branches). Unref-ing a node's page
frees it only when no live slot still maps it.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


class _Node:
    __slots__ = ("children", "page", "stamp", "parent", "key")

    def __init__(self, parent: Optional["_Node"], key, page: int, stamp: int):
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.parent = parent
        self.key = key          # the full-page token tuple edge from parent
        self.page = page        # pool page id holding this prefix's K/V
        self.stamp = stamp      # LRU clock of the last match touching it


class PrefixCache:
    """Token-id radix tree over refcounted KV pages (one page per edge)."""

    def __init__(self, page_size: int):
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.page_size = int(page_size)
        self.root = _Node(None, None, -1, 0)
        self._clock = 0
        # lookup accounting (match() only; peek() is cost-estimation and
        # must not disturb LRU order or the hit counters)
        self.hits = 0
        self.misses = 0
        self.hit_tokens = 0
        self.inserted_pages = 0
        self.evicted_nodes = 0

    # ------------------------------------------------------------------
    def _keys(self, tokens) -> List[Tuple[int, ...]]:
        """Full-page token tuples of ``tokens`` (the trailing partial
        page, if any, is dropped — never cached, never matched)."""
        toks = np.asarray(tokens).reshape(-1)
        ps = self.page_size
        n = len(toks) // ps
        return [tuple(int(t) for t in toks[i * ps:(i + 1) * ps])
                for i in range(n)]

    @property
    def num_nodes(self) -> int:
        count = 0
        stack = [self.root]
        while stack:
            node = stack.pop()
            count += len(node.children)
            stack.extend(node.children.values())
        return count

    def page_counts(self) -> Dict[int, int]:
        """page id -> number of trie references (for the pool's refcount
        audit; a page may legally back several nodes only if insert ever
        deduped — it doesn't today, so counts are 0/1)."""
        counts: Dict[int, int] = {}
        stack = list(self.root.children.values())
        while stack:
            node = stack.pop()
            counts[node.page] = counts.get(node.page, 0) + 1
            stack.extend(node.children.values())
        return counts

    # ------------------------------------------------------------------
    def match(self, tokens) -> List[int]:
        """Longest cached full-page prefix of ``tokens``: the page ids to
        map into the admitting slot, in order. Touches LRU stamps and
        the hit/miss counters (one lookup = one hit or one miss)."""
        self._clock += 1
        pages: List[int] = []
        node = self.root
        for key in self._keys(tokens):
            child = node.children.get(key)
            if child is None:
                break
            child.stamp = self._clock
            pages.append(child.page)
            node = child
        if pages:
            self.hits += 1
            self.hit_tokens += len(pages) * self.page_size
        else:
            self.misses += 1
        return pages

    def peek(self, tokens) -> int:
        """Number of full pages a :meth:`match` would return, WITHOUT
        touching LRU stamps or counters — admission cost estimation."""
        n = 0
        node = self.root
        for key in self._keys(tokens):
            child = node.children.get(key)
            if child is None:
                break
            n += 1
            node = child
        return n

    def insert(self, tokens, page_ids: Sequence[int], pool) -> int:
        """Cache ``tokens``'s full pages, backed by ``page_ids`` (the
        admitting slot's pages, in order — one per full page). Existing
        nodes are kept (equal prefixes have bitwise-equal pages, so the
        older copy is as good and already shared); each NEW node takes
        one ``pool.ref_page`` on its page so the cache outlives the
        slot. Returns the number of new nodes created."""
        self._clock += 1
        keys = self._keys(tokens)
        if len(page_ids) < len(keys):
            keys = keys[:len(page_ids)]
        node = self.root
        created = 0
        for key, pid in zip(keys, page_ids):
            child = node.children.get(key)
            if child is None:
                pool.ref_page(int(pid))
                child = _Node(node, key, int(pid), self._clock)
                node.children[key] = child
                created += 1
                self.inserted_pages += 1
            else:
                child.stamp = self._clock
            node = child
        return created

    # ------------------------------------------------------------------
    def _leaves(self) -> List[_Node]:
        out = []
        stack = list(self.root.children.values())
        while stack:
            node = stack.pop()
            if node.children:
                stack.extend(node.children.values())
            else:
                out.append(node)
        return out

    def evict(self, pool, need: int = 1) -> int:
        """Drop least-recently-matched LEAF nodes until ``need`` pages
        have actually been FREED (a node whose page a live slot still
        maps frees nothing now — the node is dropped anyway, releasing
        the trie's claim). Returns the number of pages freed."""
        freed = 0
        while freed < need:
            leaves = self._leaves()
            if not leaves:
                break
            victim = min(leaves, key=lambda n: n.stamp)
            victim.parent.children.pop(victim.key, None)
            self.evicted_nodes += 1
            if pool.unref_page(victim.page):
                freed += 1
        return freed

    def evictable_pages(self, pool) -> int:
        """Pages that would return to the free pool if the WHOLE trie
        were dropped right now: cached pages no live slot maps (their
        only reference is the trie's)."""
        return sum(1 for pid in self.page_counts()
                   if int(pool.page_refs[pid]) == 1)

    def clear(self, pool) -> None:
        """Drop every node, releasing the trie's page references."""
        stack = list(self.root.children.values())
        self.root.children.clear()
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            pool.unref_page(node.page)
