"""Thread bridge between the asyncio front end and the synchronous
serving engine.

``ServingEngine.step()`` is a blocking host loop that must never run on
the event loop (a single decode dispatch would stall every connection),
and the engine is not thread-safe (one mutable slot table, one pool).
:class:`AsyncEngineBridge` therefore gives the engine a DEDICATED step
thread and funnels EVERY engine interaction — submit, cancel, stats
reads — through a thread-safe op queue serviced between steps. The
asyncio side never touches the engine directly:

* :meth:`submit` enqueues a submit op and returns ``(request,
  TokenStream)``; the stream is an async iterator fed one event per new
  token, fan-out happening after every ``step()`` from the per-request
  ``output_tokens`` delta.
* **Backpressure** — each stream's buffer is a bounded ``asyncio.Queue``.
  The step thread must never block on a slow reader, so an overflowing
  stream is closed with a ``slow_consumer`` error and its engine-side
  request cancelled (freeing the slot/pages for clients that ARE
  reading) rather than stalling the batch.
* :meth:`cancel` is the ``DELETE /v1/requests/{id}`` path: the op runs
  :meth:`ServingEngine.cancel` between steps, so a mid-PREFILLING or
  mid-decode cancellation lands on a step boundary where the rollback
  (slot release, page refcount decrement) is exception-safe by
  construction.
* :meth:`stop` **drains on shutdown**: in-flight requests finish (or
  hit the drain timeout) before the thread exits; still-open streams
  then get a terminal ``shutdown`` event, so no reader hangs.

The step thread parks on the op queue when the engine is idle (no
polling spin) with a short timeout so deadline expiry still fires for
queued work.
"""

from __future__ import annotations

import asyncio
import queue as _queue
import threading
from typing import Any, Dict, List, Optional, Tuple

from ..request import Request, RequestState

#: lifecycle states with nothing left to stream
_TERMINAL = (RequestState.FINISHED, RequestState.REJECTED,
             RequestState.FAILED)


class TokenStream:
    """Async iterator over one request's streamed events.

    Events are plain dicts: ``{"event": "token", "token": int,
    "index": int}`` per generated token, then exactly one terminal
    event — ``{"event": "done", "reason": ...}`` (includes
    ``"cancelled"``) or ``{"event": "error", "reason": ...}``. The
    terminal event is yielded too (the SSE layer forwards it), after
    which iteration stops."""

    def __init__(self, maxsize: int, loop: asyncio.AbstractEventLoop):
        self.q: asyncio.Queue = asyncio.Queue(maxsize=maxsize)
        self.loop = loop
        self.request_id: Optional[int] = None
        self.req: Optional[Request] = None
        self.sent = 0             # tokens already fanned out (step thread)
        self.closed = False       # producer-side: terminal event emitted
        self._finished = False    # consumer-side: terminal event yielded

    def __aiter__(self) -> "TokenStream":
        return self

    async def __anext__(self) -> Dict[str, Any]:
        if self._finished:
            raise StopAsyncIteration
        ev = await self.q.get()
        if ev.get("event") in ("done", "error"):
            self._finished = True
        return ev


class AsyncEngineBridge:
    """Owns the engine's step thread; see module docstring."""

    def __init__(self, srv: Any, stream_buffer: int = 256,
                 idle_poll_s: float = 0.02,
                 drain_timeout_s: float = 30.0):
        if stream_buffer < 2:
            raise ValueError(f"stream_buffer must be >= 2 (token + "
                             f"terminal event), got {stream_buffer}")
        self.srv = srv
        self.stream_buffer = int(stream_buffer)
        self.idle_poll_s = float(idle_poll_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self._ops: "_queue.Queue[Tuple]" = _queue.Queue()
        self._streams: Dict[int, TokenStream] = {}
        self._thread: Optional[threading.Thread] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._stopping = False
        self._draining = False
        self.steps = 0            # step-thread iterations that ran step()
        self._thread_error: Optional[BaseException] = None

    # -- lifecycle (event-loop side) -----------------------------------
    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    async def start(self) -> None:
        if self.running:
            raise RuntimeError("bridge already started")
        self._loop = asyncio.get_running_loop()
        # graftlint: allow[unguarded-shared-write] -- written before Thread.start(), whose happens-before edge publishes them; only _apply_op writes them afterwards
        self._stopping = self._draining = False
        self._thread = threading.Thread(
            target=self._run, name="serving-step", daemon=True)
        self._thread.start()

    async def stop(self, drain: bool = True) -> None:
        """Stop the step thread. With ``drain=True`` (default), seated
        and queued requests run to completion first (bounded by
        ``drain_timeout_s``); streams still open after the thread exits
        get a terminal ``shutdown`` event either way."""
        if self._thread is None:
            return
        self._ops.put(("stop", drain, None, None))
        await asyncio.get_running_loop().run_in_executor(
            None, self._thread.join)
        self._thread = None
        # ops that raced the shutdown decision (enqueued after the step
        # thread's final queue drain) must fail fast, not hang their
        # awaiting coroutines; nothing services the queue anymore and
        # _require_running rejects new ops from here on
        self._reject_pending_ops("stopped")
        # safety net: terminal events for anything the thread left open
        for st in list(self._streams.values()):
            self._emit(st, [{"event": "done", "reason": "shutdown",
                             "request_id": st.request_id}])
        # graftlint: allow[unguarded-shared-write] -- step thread joined above; this is the post-mortem cleanup, single-threaded by construction
        self._streams.clear()
        if self._thread_error is not None:
            raise self._thread_error

    # -- async API (event-loop side) -----------------------------------
    async def submit(self, prompt, **submit_kw
                     ) -> Tuple[Request, TokenStream]:
        """Submit a generation request from the event loop. Returns the
        engine's :class:`Request` (check ``state`` — a REJECTED request
        carries ``reject_reason``/``retry_after_s`` and its stream just
        yields one terminal ``rejected`` event) and its token stream."""
        self._require_running()
        stream = TokenStream(self.stream_buffer, self._loop)
        fut: asyncio.Future = self._loop.create_future()
        self._ops.put(("submit", (prompt, submit_kw), stream, fut))
        req = await fut
        return req, stream

    async def cancel(self, request_id: int) -> bool:
        """Cancel by id (client disconnect / DELETE). Returns whether
        the engine still knew the request."""
        self._require_running()
        fut: asyncio.Future = self._loop.create_future()
        self._ops.put(("cancel", int(request_id), None, fut))
        return await fut

    async def call(self, fn):
        """Run ``fn(srv)`` on the step thread between steps and return
        its result — the only sanctioned way for the front end to READ
        engine state (stats, load state, Prometheus exposition); the
        engine's dicts are mutated mid-step, so even reads must be
        serialized onto the step thread."""
        self._require_running()
        fut: asyncio.Future = self._loop.create_future()
        self._ops.put(("call", fn, None, fut))
        return await fut

    def _require_running(self) -> None:
        if not self.running or self._loop is None:
            raise RuntimeError("bridge is not running (call start())")

    # -- step thread ---------------------------------------------------
    def _run(self) -> None:
        try:
            self._loop_body()
        except BaseException as e:  # surfaced by stop()
            self._thread_error = e
            self._fail_open_streams(repr(e))
            self._reject_pending_ops("step thread crashed")

    def _has_work(self) -> bool:
        srv = self.srv
        # duck-typed: a ReplicaRouter exposes has_work() (aggregated over
        # alive replicas); a bare engine is probed through its internals
        probe = getattr(srv, "has_work", None)
        if callable(probe):
            return bool(probe())
        return bool(srv.live_count or srv.scheduler.pending
                    or getattr(srv, "_prefill_queue", None))

    def _loop_body(self) -> None:
        srv = self.srv
        drain_deadline = None
        while True:
            # 1) drain ops; park here when idle (no busy spin, but wake
            #    within idle_poll_s so queued-work deadlines still expire)
            budget = 64
            try:
                block = not self._has_work() and not self._stopping
                op = self._ops.get(block=block,
                                   timeout=self.idle_poll_s if block
                                   else None)
            except _queue.Empty:
                op = None
            while op is not None:
                self._apply_op(op)
                budget -= 1
                if budget <= 0:
                    break  # bounded: submit floods must not starve step()
                try:
                    op = self._ops.get_nowait()
                except _queue.Empty:
                    op = None
            # 2) stop/drain bookkeeping
            if self._stopping:
                if drain_deadline is None:
                    drain_deadline = (srv._now() + self.drain_timeout_s
                                      if self._draining else srv._now())
                if not self._draining or not self._has_work() \
                        or srv._now() >= drain_deadline:
                    self._fail_open_streams("shutdown", kind="done")
                    self._reject_pending_ops("stopping")
                    return
            # 3) one engine step when there is work
            if self._has_work():
                srv.step()
                self.steps += 1
                self._fan_out()

    def _apply_op(self, op: Tuple) -> None:
        kind, payload, stream, fut = op
        srv = self.srv
        if kind == "stop":
            self._stopping = True
            self._draining = bool(payload)
            return
        try:
            if kind == "submit":
                prompt, kw = payload
                req = srv.submit(prompt, **kw)
                stream.req = req
                stream.request_id = req.request_id
                if req.state is RequestState.REJECTED:
                    self._emit(stream, [{
                        "event": "done", "reason": "rejected",
                        "request_id": req.request_id,
                        "reject_reason":
                            getattr(req.reject_reason, "value",
                                    req.reject_reason),
                        "retry_after_s": req.retry_after_s}])
                else:
                    self._streams[req.request_id] = stream
                self._resolve(fut, req)
            elif kind == "cancel":
                req = srv.cancel(payload)
                st = self._streams.pop(payload, None)
                if st is not None and req is not None:
                    self._emit(st, [self._terminal_event(req)])
                self._resolve(fut, req is not None)
            elif kind == "call":
                self._resolve(fut, payload(srv))
        except BaseException as e:
            self._reject(fut, e)

    def _fan_out(self) -> None:
        """After one step: push each tracked request's new tokens, and a
        terminal event when it retired. Preempted requests stay tracked
        — their ``output_tokens`` (and our ``sent`` cursor) survive the
        bounce by design."""
        for rid, st in list(self._streams.items()):
            req = st.req
            new = req.output_tokens[st.sent:]
            if new:
                base = st.sent
                self._emit(st, [
                    {"event": "token", "token": int(t),
                     "index": base + i, "request_id": rid}
                    for i, t in enumerate(new)])
                st.sent += len(new)
            if req.state in _TERMINAL:
                self._emit(st, [self._terminal_event(req)])
                del self._streams[rid]

    @staticmethod
    def _terminal_event(req: Request) -> Dict[str, Any]:
        reason = getattr(req.finish_reason, "value", req.finish_reason)
        if req.state is RequestState.FAILED:
            return {"event": "error", "reason": reason or "error",
                    "request_id": req.request_id,
                    "tokens": len(req.output_tokens)}
        return {"event": "done", "reason": reason or "unknown",
                "request_id": req.request_id,
                "tokens": len(req.output_tokens)}

    def _fail_open_streams(self, reason: str, kind: str = "error") -> None:
        for rid, st in list(self._streams.items()):
            self._emit(st, [{"event": kind, "reason": reason,
                             "request_id": rid}])
        self._streams.clear()

    def _reject_pending_ops(self, why: str) -> None:
        """Reject the futures of ops still queued once the step thread
        can no longer service them (post-drain stop, thread crash). A
        lost op must fail fast — before this existed, a ``call()`` or
        ``submit()`` racing ``stop()`` could enqueue after the thread's
        final queue drain and await its future forever. Runs on either
        side of the boundary (the queue is thread-safe and ``_reject``
        marshals through the loop)."""
        while True:
            try:
                kind, _payload, _stream, fut = self._ops.get_nowait()
            except _queue.Empty:
                return
            self._reject(fut, RuntimeError(
                f"bridge {why}: {kind} op was not serviced"))

    # -- cross-thread plumbing -----------------------------------------
    def _resolve(self, fut: Optional[asyncio.Future], value) -> None:
        if fut is not None:
            self._loop.call_soon_threadsafe(self._set_result, fut, value)

    def _reject(self, fut: Optional[asyncio.Future],
                err: BaseException) -> None:
        if fut is not None:
            self._loop.call_soon_threadsafe(self._set_exception, fut, err)

    @staticmethod
    def _set_result(fut: asyncio.Future, value) -> None:
        if not fut.done():
            fut.set_result(value)

    @staticmethod
    def _set_exception(fut: asyncio.Future, err: BaseException) -> None:
        if not fut.done():
            fut.set_exception(err)

    def _emit(self, st: TokenStream, events: List[Dict[str, Any]]) -> None:
        """Push events onto a stream's queue from ANY thread (the loop
        thread delivers). Never blocks the caller."""
        if st.closed:
            return
        for ev in events:
            if ev.get("event") in ("done", "error"):
                st.closed = True
        self._loop.call_soon_threadsafe(self._deliver, st, events)

    def _deliver(self, st: TokenStream, events: List[Dict[str, Any]]
                 ) -> None:
        """Runs on the event loop: enqueue without blocking; a full
        buffer means the consumer stopped reading — close the stream
        with ``slow_consumer`` and cancel the engine-side request
        (backpressure policy: protect the batch, drop the deaf reader).
        """
        for ev in events:
            try:
                st.q.put_nowait(ev)
            except asyncio.QueueFull:
                st.closed = True
                while not st.q.empty():
                    st.q.get_nowait()
                st.q.put_nowait({"event": "error",
                                 "reason": "slow_consumer",
                                 "request_id": st.request_id})
                if st.request_id is not None:
                    # free the engine-side slot/pages; drop the tracking
                    # entry via the normal cancel op
                    self._ops.put(("cancel", st.request_id, None, None))
                return
