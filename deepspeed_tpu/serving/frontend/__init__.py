"""Async serving front end: HTTP/SSE server, engine step-thread bridge,
and SLO-aware multi-tenant priority scheduling.

Pure host-side code — no jax imports, zero compiled programs (pinned by
the analysis-tier inventory test): the engine's jitted surface is
untouched by design, and graftcheck proves the signature set unchanged.

Modules:

* :mod:`.priority` — :class:`PriorityScheduler` (priority classes,
  fair-share token budgets, per-tenant rate limits/quotas) plus its
  :class:`PriorityConfig`/:class:`TenantPolicy` knobs.
* :mod:`.bridge` — :class:`AsyncEngineBridge`, the dedicated step
  thread + thread-safe op queue + per-request async token streams.
* :mod:`.server` — :class:`ServingFrontend`, the stdlib-only
  asyncio HTTP/1.1 + Server-Sent-Events server.
"""

from .bridge import AsyncEngineBridge, TokenStream
from .priority import PriorityConfig, PriorityScheduler, TenantPolicy
from .server import ServingFrontend

__all__ = [
    "AsyncEngineBridge",
    "TokenStream",
    "PriorityConfig",
    "PriorityScheduler",
    "TenantPolicy",
    "ServingFrontend",
]
