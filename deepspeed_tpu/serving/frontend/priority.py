"""Priority-class scheduling with fair-share token budgets and
per-tenant admission control.

:class:`PriorityScheduler` extends the FIFO scheduler with three
production concerns the reference serving shells (DeepSpeed-MII / the
inference server entry points) handle in front of the engine:

* **Priority classes** — every :class:`~..request.Request` carries a
  ``priority_class``; :class:`PriorityConfig` orders the classes from
  highest to lowest rank. ``grant`` seats work in rank order (strict
  priority for slots) but splits the per-step prefill TOKEN budget into
  fair shares, so a flood of high-class prompts cannot monopolise every
  step's prefill budget and starve lower classes of admission entirely
  — each class with waiting work gets its share slice first, and
  whatever a class leaves unspent cascades to the others
  (work-conserving).
* **Per-tenant rate limits** — a token bucket per tenant (cost =
  ``prompt_len + max_new_tokens``, i.e. the worst-case tokens the
  request can consume) refilled on the injected monotonic ``clock``;
  an empty bucket rejects with :data:`RejectReason.RATE_LIMITED` and a
  refill-time ``retry_after_s`` hint.
* **Per-tenant queue quotas** — a bounded number of queued requests per
  tenant (:data:`RejectReason.TENANT_QUOTA`), so one tenant cannot fill
  the shared admission queue.

The scheduler stays host-only and device-free, and it deliberately
keeps the base class's SINGLE arrival-ordered deque: ``requeue_front``
/ ``requeue_back`` / ``expire`` / ``check_invariants`` all keep working
unchanged, and ``grant``/``head`` impose priority order by scanning (the
queue is bounded by ``max_queue_depth``, so the scan is O(depth) with a
small constant — not a hot path).

Liveness: the FIFO head-liveness guarantee (see
:meth:`FIFOScheduler.grant`) is preserved for the HIGHEST-RANKED waiter:
when nothing has been granted or spent this step, it is granted even if
its cost exceeds its fair share (bounded overshoot). Because rank order
is total, the lowest class becomes the highest-ranked waiter whenever
the classes above it are idle — so it still makes progress; no
starvation livelock (regression-pinned).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..request import RejectReason, Request
from ..scheduler import FIFOScheduler

DEFAULT_CLASSES = ("interactive", "standard", "batch")


@dataclasses.dataclass
class TenantPolicy:
    """Admission policy for one tenant (or the ``"*"`` wildcard).

    ``tokens_per_s`` is the token-bucket refill rate; cost per request
    is its worst-case token footprint (``prompt_len + max_new_tokens``).
    ``burst_tokens`` is the bucket capacity (defaults to 4x the rate —
    one second of burst headroom times four). ``max_queued`` bounds the
    tenant's simultaneously queued requests.
    """

    tokens_per_s: Optional[float] = None
    burst_tokens: Optional[float] = None
    max_queued: Optional[int] = None

    def __post_init__(self):
        if self.tokens_per_s is not None and self.tokens_per_s <= 0:
            raise ValueError("tokens_per_s must be positive")
        if self.burst_tokens is None and self.tokens_per_s is not None:
            self.burst_tokens = 4.0 * self.tokens_per_s

    @classmethod
    def resolve(cls, value) -> "TenantPolicy":
        if isinstance(value, cls):
            return value
        if isinstance(value, dict):
            return cls(**value)
        raise TypeError(f"TenantPolicy expects dict or TenantPolicy, "
                        f"got {type(value).__name__}")


@dataclasses.dataclass
class PriorityConfig:
    """Class ranking, fair shares and tenant policies.

    ``classes`` orders priority classes from HIGHEST to LOWEST rank
    (rank 0 preempts/sheds last). ``shares`` weights the fair-share
    split of the per-step prefill token budget among classes that have
    waiting work (missing classes weigh 1.0). ``default_class`` is
    stamped on requests submitted without one (defaults to the LOWEST
    class — unclassified traffic must not outrank paying tiers).
    ``tenants`` maps tenant id -> :class:`TenantPolicy`; the ``"*"``
    entry, when present, applies to tenants without their own policy.
    """

    classes: Tuple[str, ...] = DEFAULT_CLASSES
    shares: Dict[str, float] = dataclasses.field(default_factory=dict)
    default_class: Optional[str] = None
    tenants: Dict[str, TenantPolicy] = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        self.classes = tuple(self.classes)
        if not self.classes:
            raise ValueError("PriorityConfig needs at least one class")
        if len(set(self.classes)) != len(self.classes):
            raise ValueError(f"duplicate priority classes: {self.classes}")
        for cls_name, w in self.shares.items():
            if cls_name not in self.classes:
                raise ValueError(f"share for unknown class {cls_name!r}")
            if w <= 0:
                raise ValueError(f"share for {cls_name!r} must be positive")
        if self.default_class is None:
            self.default_class = self.classes[-1]
        elif self.default_class not in self.classes:
            raise ValueError(f"default_class {self.default_class!r} not in "
                             f"classes {self.classes}")
        self.tenants = {t: TenantPolicy.resolve(p)
                        for t, p in self.tenants.items()}

    def share(self, cls_name: str) -> float:
        return float(self.shares.get(cls_name, 1.0))

    @classmethod
    def resolve(cls, value) -> "PriorityConfig":
        """Coerce the ``priority=`` knob: ``True`` -> defaults, a dict
        -> field overrides, an instance -> itself."""
        if isinstance(value, cls):
            return value
        if value is True:
            return cls()
        if isinstance(value, dict):
            return cls(**value)
        raise TypeError(f"priority expects True, dict or PriorityConfig, "
                        f"got {type(value).__name__}")


class _TokenBucket:
    """Classic token bucket on an injected monotonic clock."""

    def __init__(self, rate: float, burst: float, now: float):
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        self.last = now

    def take(self, n: float, now: float) -> Optional[float]:
        """Charge ``n`` tokens. Returns None on success, else the
        seconds until the bucket will hold ``n`` (the retry hint)."""
        self.tokens = min(self.burst, self.tokens
                          + max(0.0, now - self.last) * self.rate)
        self.last = now
        if n <= self.tokens:
            self.tokens -= n
            return None
        return (n - self.tokens) / self.rate

    def refund(self, n: float) -> None:
        self.tokens = min(self.burst, self.tokens + n)


class PriorityScheduler(FIFOScheduler):
    """FIFO scheduler + priority classes, fair shares, tenant limits.

    Storage is the inherited single arrival-ordered deque; priority is
    imposed at ``grant``/``head`` time, so every base-class path that
    walks ``self.queue`` (requeue, expiry, invariant audits) works
    unmodified.
    """

    def __init__(self, num_slots: int, max_queue_depth: int = 64,
                 policy: str = "continuous", capacity: Optional[int] = None,
                 page_size: Optional[int] = None,
                 num_pages: Optional[int] = None, page_headroom: int = 0,
                 priority=True,
                 clock: Optional[Callable[[], float]] = None):
        super().__init__(num_slots, max_queue_depth=max_queue_depth,
                         policy=policy, capacity=capacity,
                         page_size=page_size, num_pages=num_pages,
                         page_headroom=page_headroom)
        self.config = PriorityConfig.resolve(priority)
        self._rank = {name: i for i, name in enumerate(self.config.classes)}
        # the ONE clock for every time-dependent decision in admission
        # (rate-bucket refill); the engine injects its own so deadlines,
        # expiry and rate limits can never drift apart (and tests can
        # drive a fake clock through all of them at once)
        self.clock = clock if clock is not None else time.monotonic
        self._buckets: Dict[str, _TokenBucket] = {}

    # -- class/tenant lookups ------------------------------------------
    def rank_of(self, cls_name: str) -> int:
        """0 = highest priority. Unknown classes fail loudly."""
        try:
            return self._rank[cls_name]
        except KeyError:
            raise ValueError(
                f"unknown priority class {cls_name!r}; configured classes: "
                f"{self.config.classes}") from None

    def class_of_rank(self, rank: int) -> str:
        return self.config.classes[rank]

    def _policy_for(self, tenant: str) -> Optional[TenantPolicy]:
        pol = self.config.tenants.get(tenant)
        if pol is None:
            pol = self.config.tenants.get("*")
        return pol

    def class_depths(self) -> Dict[str, int]:
        """Queued-request count per class (telemetry/healthz)."""
        depths = {c: 0 for c in self.config.classes}
        for r in self.queue:
            depths[r.priority_class] = depths.get(r.priority_class, 0) + 1
        return depths

    # -- admission ------------------------------------------------------
    def submit(self, req: Request) -> Tuple[bool, Optional[RejectReason]]:
        """Tenant quota -> tenant rate limit -> base admission control.

        Quota is checked before the rate bucket so a quota rejection
        never burns bucket tokens; a base-admission rejection (queue
        full / prompt too long) REFUNDS the bucket — only requests that
        actually join the queue consume rate."""
        if req.priority_class == "default" \
                and "default" not in self._rank:
            # a bare Request carries the dataclass default; stamp the
            # configured default class so rank lookups are total
            req.priority_class = self.config.default_class
        self.rank_of(req.priority_class)  # fail loudly on unknown class
        pol = self._policy_for(req.tenant)
        charged = 0.0
        if pol is not None:
            if pol.max_queued is not None:
                queued = sum(1 for r in self.queue if r.tenant == req.tenant)
                if queued >= pol.max_queued:
                    return False, RejectReason.TENANT_QUOTA
            if pol.tokens_per_s is not None:
                now = self.clock()
                bucket = self._buckets.get(req.tenant)
                if bucket is None:
                    bucket = _TokenBucket(pol.tokens_per_s,
                                          pol.burst_tokens, now)
                    self._buckets[req.tenant] = bucket
                need = float(req.prompt_len + req.max_new_tokens)
                hint = bucket.take(need, now)
                if hint is not None:
                    req.retry_after_s = hint
                    return False, RejectReason.RATE_LIMITED
                charged = need
        ok, reason = super().submit(req)
        if not ok and charged:
            self._buckets[req.tenant].refund(charged)
        return ok, reason

    # -- priority-ordered grant ----------------------------------------
    def head(self) -> Optional[Request]:
        """The request ``grant`` would pop first: oldest waiter of the
        highest-priority class with queued work."""
        best = None
        best_rank = len(self.config.classes)
        for r in self.queue:
            k = self.rank_of(r.priority_class)
            if k < best_rank:
                best, best_rank = r, k
                if k == 0:
                    break
        return best

    def head_within(self, max_rank: int) -> Optional[Request]:
        """Oldest waiter whose class rank is <= ``max_rank`` (i.e. at
        least that priority), or None — the burn-rate preemption path
        asks this to decide whether a protected-class request is stuck
        behind shed-class residents."""
        best = None
        best_rank = max_rank + 1
        for r in self.queue:
            k = self.rank_of(r.priority_class)
            if k < best_rank:
                best, best_rank = r, k
                if k == 0:
                    break
        return best

    def grant(self, free_slots: int, live_slots: int,
              token_budget: Optional[int] = None,
              cost=None, spent: int = 0,
              page_budget: Optional[int] = None,
              page_cost=None) -> List[Request]:
        """Priority grant: strict rank order for SLOTS, fair-share split
        of the prefill TOKEN budget.

        Pass 1 walks classes from highest rank down, granting each class
        FIFO-within-class against its fair-share slice of the remaining
        token budget (``shares`` weights, classes with no waiters
        excluded). Pass 2 is work-conserving: leftover budget (slices a
        class could not spend) is re-offered in rank order. The page
        budget stays STRICT and GLOBAL exactly as in the base class —
        the first head that does not fit the page budget stops the whole
        grant, because letting lower classes consume pages the blocked
        head needs would invert priority under memory pressure (pressure
        preemption, not over-grant, is what frees pages).

        Liveness: the highest-ranked waiter inherits the base class's
        head-liveness overshoot — when nothing was granted or spent yet,
        it is granted even over budget. With higher classes idle the
        lowest class IS the highest-ranked waiter, so every class
        eventually progresses (no starvation livelock; pinned).
        """
        if self.policy == "gang" and live_slots > 0:
            return []
        if not self.queue or free_slots <= 0:
            return []
        by_rank: Dict[int, List[Request]] = {}
        for r in self.queue:
            by_rank.setdefault(self.rank_of(r.priority_class), []).append(r)
        ranks = sorted(by_rank)

        budgeted = token_budget is not None
        remaining = (token_budget - spent) if budgeted else 0
        slices: Dict[int, float] = {}
        if budgeted:
            total_share = sum(self.config.share(self.class_of_rank(k))
                              for k in ranks)
            for k in ranks:
                slices[k] = (max(0, remaining)
                             * self.config.share(self.class_of_rank(k))
                             / total_share)

        granted: List[Request] = []
        granted_ids = set()
        pages_left = page_budget
        page_blocked = False

        def fits_pages(req: Request) -> Tuple[bool, int]:
            if pages_left is None:
                return True, 0
            pc = page_cost(req) if page_cost is not None else 0
            return pc <= pages_left, pc

        # pass 1: per-class fair-share slices, rank order
        for k in ranks:
            if len(granted) >= free_slots or page_blocked:
                break
            for req in by_rank[k]:
                if len(granted) >= free_slots:
                    break
                ok_pages, pc = fits_pages(req)
                if not ok_pages:
                    page_blocked = True  # strict + global: stop everything
                    break
                c = (cost(req) if cost is not None else 0) if budgeted else 0
                if budgeted and c > min(slices[k], remaining):
                    # (min with the global remainder: a higher class's
                    # liveness overshoot must not be spent twice)
                    # head-liveness overshoot: the very first grantable
                    # waiter (== self.head()) goes through regardless
                    if granted or spent > 0:
                        break  # this class's slice is spent; next class
                if budgeted:
                    slices[k] -= c
                    remaining -= c
                if pages_left is not None:
                    pages_left -= pc
                granted.append(req)
                granted_ids.add(id(req))

        # pass 2: work-conserving leftover, rank order, global remainder
        if budgeted and not page_blocked and remaining > 0:
            for k in ranks:
                if len(granted) >= free_slots:
                    break
                for req in by_rank[k]:
                    if id(req) in granted_ids:
                        continue
                    if len(granted) >= free_slots:
                        break
                    ok_pages, pc = fits_pages(req)
                    if not ok_pages:
                        page_blocked = True
                        break
                    c = cost(req) if cost is not None else 0
                    if c > remaining:
                        break  # FIFO-within-class: don't skip past a head
                    remaining -= c
                    if pages_left is not None:
                        pages_left -= pc
                    granted.append(req)
                    granted_ids.add(id(req))
                if page_blocked:
                    break

        if granted:
            self.queue = type(self.queue)(
                r for r in self.queue if id(r) not in granted_ids)
        return granted
