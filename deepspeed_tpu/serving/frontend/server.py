"""Stdlib-only asyncio HTTP/1.1 + SSE serving front end.

No web framework, no new dependencies: ``asyncio.start_server`` plus a
hand-rolled HTTP/1.1 request parser and ``text/event-stream`` writer.
The surface mirrors the reference project's inference-server entry
points (DeepSpeed-MII's REST/gRPC shell around the inference engine):

* ``POST /v1/generate`` — submit a generation request; the response is
  a Server-Sent-Events stream: one ``start`` event carrying the
  ``request_id`` (the cancellation handle), one ``token`` event per
  generated token, then exactly one terminal ``done``/``error`` event.
  Rejections map to HTTP errors BEFORE the stream starts: 429 with a
  ``Retry-After`` header (queue full / shed / rate-limited / tenant
  quota) or 400 (prompt too long / bad request).
* ``DELETE /v1/requests/{id}`` — cancel a queued or running request;
  the engine frees its slot/pages through the preemption rollback.
* ``GET /healthz`` — load state from the :class:`LoadStateMachine`
  (``healthy``/``pressured``/``overloaded``), queue/slot occupancy and
  per-class queue depths; 503 + ``Retry-After`` when overloaded so
  upstream balancers back off before the engine has to shed. When the
  bridge fronts a :class:`ReplicaRouter` the payload gains a ``fleet``
  object (per-role replica counts, transfers in flight, last scale
  event) and the load state aggregates over prefill-capable replicas.
* ``GET /metrics`` — the Prometheus exposition. A bare engine serves
  its own ``MetricsRegistry.to_prometheus``; a router serves the
  MERGED fleet exposition (``FleetTelemetry.to_prometheus``): router
  series unlabeled, every replica's series labeled
  ``replica="i",role="..."``, plus derived ``fleet_*`` gauges
  (merged-digest p50/p99, fleet goodput/burn, journey completeness,
  transfer-latency quantiles).

Every engine interaction goes through the :class:`AsyncEngineBridge`
(one dedicated step thread; see ``bridge.py``) — handlers never touch
the engine directly. A client disconnect mid-stream surfaces as a write
failure (or cancelled handler task) and triggers ``bridge.cancel``, so
an abandoned stream releases its slot within a step.
"""

from __future__ import annotations

import asyncio
import json
from typing import Any, Dict, Optional, Tuple

from .bridge import AsyncEngineBridge

_MAX_HEADER_BYTES = 16 * 1024
_MAX_BODY_BYTES = 8 * 1024 * 1024

#: RejectReason.value -> (HTTP status, include Retry-After)
_REJECT_STATUS = {
    "queue_full": (429, True),
    "retry_after": (429, True),
    "rate_limited": (429, True),
    "tenant_quota": (429, True),
    "prompt_too_long": (400, False),
}

_STATUS_TEXT = {200: "OK", 400: "Bad Request", 404: "Not Found",
                405: "Method Not Allowed", 413: "Payload Too Large",
                429: "Too Many Requests", 500: "Internal Server Error",
                503: "Service Unavailable"}


class _BadRequest(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status


async def _read_request(reader: asyncio.StreamReader
                        ) -> Optional[Tuple[str, str, Dict[str, str],
                                            bytes]]:
    """Parse one HTTP/1.1 request; returns (method, path, headers,
    body) or None on a clean EOF before any bytes."""
    try:
        head = await reader.readuntil(b"\r\n\r\n")
    except asyncio.IncompleteReadError as e:
        if not e.partial:
            return None
        raise _BadRequest(400, "truncated request head")
    except asyncio.LimitOverrunError:
        raise _BadRequest(413, "request head too large")
    if len(head) > _MAX_HEADER_BYTES:
        raise _BadRequest(413, "request head too large")
    lines = head.decode("latin-1").split("\r\n")
    parts = lines[0].split(" ")
    if len(parts) != 3 or not parts[2].startswith("HTTP/1."):
        raise _BadRequest(400, f"malformed request line: {lines[0]!r}")
    method, path = parts[0].upper(), parts[1]
    headers: Dict[str, str] = {}
    for line in lines[1:]:
        if not line:
            continue
        name, sep, value = line.partition(":")
        if not sep:
            raise _BadRequest(400, f"malformed header: {line!r}")
        headers[name.strip().lower()] = value.strip()
    body = b""
    if "content-length" in headers:
        try:
            n = int(headers["content-length"])
        except ValueError:
            raise _BadRequest(400, "bad Content-Length")
        if n < 0 or n > _MAX_BODY_BYTES:
            raise _BadRequest(413, "body too large")
        body = await reader.readexactly(n)
    return method, path, headers, body


def _response(status: int, body: bytes, content_type: str,
              extra_headers: Optional[Dict[str, str]] = None) -> bytes:
    lines = [f"HTTP/1.1 {status} {_STATUS_TEXT.get(status, 'Unknown')}",
             f"Content-Type: {content_type}",
             f"Content-Length: {len(body)}",
             "Connection: close"]
    for k, v in (extra_headers or {}).items():
        lines.append(f"{k}: {v}")
    return ("\r\n".join(lines) + "\r\n\r\n").encode("latin-1") + body


def _json_response(status: int, obj: Any,
                   extra_headers: Optional[Dict[str, str]] = None) -> bytes:
    return _response(status, json.dumps(obj).encode("utf-8"),
                     "application/json", extra_headers)


def _sse_frame(event: str, data: Dict[str, Any]) -> bytes:
    return (f"event: {event}\ndata: {json.dumps(data)}\n\n"
            ).encode("utf-8")


class ServingFrontend:
    """The HTTP server plus its engine bridge. Typical use::

        frontend = ServingFrontend(serving_engine, port=0)
        await frontend.start()          # binds; frontend.port is real
        ...
        await frontend.stop(drain=True)
    """

    def __init__(self, srv: Any, host: str = "127.0.0.1", port: int = 0,
                 bridge: Optional[AsyncEngineBridge] = None,
                 **bridge_kw: Any):
        self.srv = srv
        self.host = host
        self.port = port
        self.bridge = bridge if bridge is not None \
            else AsyncEngineBridge(srv, **bridge_kw)
        self._server: Optional[asyncio.AbstractServer] = None

    # -- lifecycle ------------------------------------------------------
    async def start(self) -> None:
        if not self.bridge.running:
            await self.bridge.start()
        self._server = await asyncio.start_server(
            self._handle, host=self.host, port=self.port)
        self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self, drain: bool = True) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        if self.bridge.running:
            await self.bridge.stop(drain=drain)

    # -- connection handler --------------------------------------------
    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            try:
                parsed = await _read_request(reader)
                if parsed is None:
                    return
                method, path, headers, body = parsed
                await self._route(method, path, body, reader, writer)
            except _BadRequest as e:
                writer.write(_json_response(e.status,
                                            {"error": str(e)}))
                await writer.drain()
            except (ConnectionResetError, BrokenPipeError,
                    asyncio.IncompleteReadError):
                pass  # client went away; generate() already cancelled
            except Exception as e:  # handler bug: 500, keep serving
                try:
                    writer.write(_json_response(
                        500, {"error": f"{type(e).__name__}: {e}"}))
                    await writer.drain()
                except (ConnectionResetError, BrokenPipeError):
                    pass
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except (ConnectionResetError, BrokenPipeError):
                pass

    async def _route(self, method: str, path: str, body: bytes,
                     reader: asyncio.StreamReader,
                     writer: asyncio.StreamWriter) -> None:
        if path == "/v1/generate":
            if method != "POST":
                writer.write(_json_response(405, {"error": "POST only"}))
            else:
                await self._generate(body, reader, writer)
                return
        elif path.startswith("/v1/requests/"):
            if method != "DELETE":
                writer.write(_json_response(405, {"error": "DELETE only"}))
            else:
                await self._cancel(path, writer)
        elif path == "/healthz":
            await self._healthz(writer)
        elif path == "/metrics":
            # a router fronts a FLEET: serve the merged exposition
            # (router series unlabeled, replica series replica=/role=
            # labeled, fleet_* gauges derived from merged digests)
            text = await self.bridge.call(
                lambda srv: srv.fleet.to_prometheus()
                if hasattr(srv, "fleet")
                else srv.registry.to_prometheus())
            writer.write(_response(200, text.encode("utf-8"),
                                   "text/plain; version=0.0.4"))
        else:
            writer.write(_json_response(404, {"error": f"no route "
                                              f"{method} {path}"}))
        await writer.drain()

    # -- endpoints ------------------------------------------------------
    async def _generate(self, body: bytes, reader: asyncio.StreamReader,
                        writer: asyncio.StreamWriter) -> None:
        try:
            payload = json.loads(body.decode("utf-8")) if body else {}
        except (ValueError, UnicodeDecodeError):
            raise _BadRequest(400, "body must be JSON")
        if not isinstance(payload, dict):
            raise _BadRequest(400, "body must be a JSON object")
        prompt = payload.get("prompt")
        if not isinstance(prompt, list) or not prompt or \
                not all(isinstance(t, int) for t in prompt):
            raise _BadRequest(400, "prompt must be a non-empty list of "
                                   "token ids")
        kw: Dict[str, Any] = {}
        for key in ("max_new_tokens", "eos_token_id", "deadline_ms",
                    "priority", "tenant"):
            if payload.get(key) is not None:
                kw[key] = payload[key]
        unknown = set(payload) - {"prompt", "max_new_tokens",
                                  "eos_token_id", "deadline_ms",
                                  "priority", "tenant"}
        if unknown:
            raise _BadRequest(400, f"unknown fields: {sorted(unknown)}")
        try:
            req, stream = await self.bridge.submit(prompt, **kw)
        except (ValueError, TypeError) as e:
            raise _BadRequest(400, str(e))

        if req.reject_reason is not None:
            status, retry = _REJECT_STATUS.get(
                getattr(req.reject_reason, "value", str(req.reject_reason)),
                (429, True))
            extra = {}
            if retry and req.retry_after_s is not None:
                extra["Retry-After"] = f"{max(req.retry_after_s, 0.0):.3f}"
            writer.write(_json_response(status, {
                "error": "rejected",
                "reject_reason": getattr(req.reject_reason, "value",
                                         str(req.reject_reason)),
                "retry_after_s": req.retry_after_s,
                "request_id": req.request_id}, extra))
            await writer.drain()
            return

        # accepted: stream SSE. From here on, failures mean the CLIENT
        # went away — cancel engine-side and swallow the write error.
        writer.write(("HTTP/1.1 200 OK\r\n"
                      "Content-Type: text/event-stream\r\n"
                      "Cache-Control: no-store\r\n"
                      "Connection: close\r\n\r\n").encode("latin-1"))
        writer.write(_sse_frame("start", {
            "request_id": req.request_id,
            "priority_class": req.priority_class,
            "tenant": req.tenant}))
        try:
            await writer.drain()
            async for ev in stream:
                writer.write(_sse_frame(ev.get("event", "message"), ev))
                await writer.drain()
        except (ConnectionResetError, BrokenPipeError, OSError,
                asyncio.CancelledError):
            # disconnect mid-stream (or server task cancellation):
            # release the slot/pages via the engine's cancel rollback
            if self.bridge.running:
                await asyncio.shield(self.bridge.cancel(req.request_id))
            raise

    async def _cancel(self, path: str,
                      writer: asyncio.StreamWriter) -> None:
        tail = path[len("/v1/requests/"):]
        try:
            rid = int(tail)
        except ValueError:
            raise _BadRequest(400, f"bad request id {tail!r}")
        known = await self.bridge.cancel(rid)
        if known:
            writer.write(_json_response(200, {"cancelled": rid}))
        else:
            writer.write(_json_response(404, {
                "error": f"request {rid} unknown or already finished"}))

    async def _healthz(self, writer: asyncio.StreamWriter) -> None:
        def probe(srv: Any) -> Dict[str, Any]:
            # duck-typed over both a single ServingEngine and a
            # ReplicaRouter fleet (which has no scheduler/pool of its
            # own but aggregates the same numbers)
            load = getattr(srv, "_load", None)
            if hasattr(load, "state"):
                state = load.state.name.lower()
            else:
                state = getattr(srv, "health_state", "healthy")
            sched = getattr(srv, "scheduler", None)
            pool = getattr(srv, "pool", None)
            out = {
                "state": state,
                "queue_depth": sched.pending if sched is not None
                else srv.pending,
                "live_slots": srv.live_count,
                "num_slots": pool.num_slots if pool is not None
                else srv.num_slots,
                "step_id": srv.step_id,
            }
            deg = getattr(srv, "_degradation", None)
            if deg is not None:
                out["retry_after_s"] = deg.retry_after_s
            if sched is not None and hasattr(sched, "class_depths"):
                out["class_queue_depths"] = sched.class_depths()
            slo = getattr(srv, "slo", None)
            if slo is not None:
                out["class_alerts"] = dict(slo.class_alerts)
                out["goodput"] = slo.goodput()
            if hasattr(srv, "fleet_topology"):
                out["fleet"] = srv.fleet_topology()
            if hasattr(srv, "fleet"):
                # fleet health: per-replica alert states, per-role
                # queue depth/backlog, journey completeness
                out["fleet_health"] = srv.fleet.health_summary()
            return out

        info = await self.bridge.call(probe)
        if info["state"] == "overloaded":
            extra = {}
            if info.get("retry_after_s") is not None:
                extra["Retry-After"] = f"{info['retry_after_s']:.3f}"
            writer.write(_json_response(503, info, extra))
        else:
            writer.write(_json_response(200, info))
