"""Fixed-shape slot pool of per-slot KV cache.

The pool owns ONE statically-shaped cache pytree in the exact layout the
model's flax ``cache`` collection uses (``{"cache_store": {...}}`` with
k/v ``(L, num_slots, KV, cache_d, max_seq_len)``), allocated through the
module-declared :class:`~deepspeed_tpu.models.transformer_lm.KVCacheSpec`
— batch dimension = slots. Continuous batching then never changes a
shape: admitting, retiring and reusing slots are all data movement
inside the same buffers, so the jitted decode step compiles once and is
replayed for the server's lifetime (alive-masking: a retired slot is
padding, its garbage writes and attention contributions are masked out
by the per-slot ``index`` lengths, not by a recompile).

Admission writes a single-sequence prefill cache into the slot's batch
row with a dynamic-index update (slot id is a traced operand — one
compile covers every slot). The prefill cache is allocated at full
``max_seq_len`` by ``_CacheStore``, so the row write overwrites ALL of
the retired occupant's stale state, scales and garbage included.
"""

from __future__ import annotations

import heapq
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ..parallel import mesh as mesh_mod


class SlotPool:
    """``num_slots`` independently-occupied rows of one shared KV cache."""

    def __init__(self, spec: Any, num_slots: int, sharding: Any = None):
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        self.spec = spec
        self.num_slots = num_slots
        self.capacity = int(spec.max_seq_len)
        # sharding the owning engine's jitted steps emit: a single
        # Sharding applied to every leaf, or a PER-LEAF resolver
        # ``fn(key, leaf) -> Sharding`` (the parallel/axis_rules seam —
        # k/v shard over (data, model) while ``index`` shards only over
        # data); falls back to replicated-on-the-global-mesh for
        # standalone pools
        if sharding is None and mesh_mod.has_mesh():
            sharding = NamedSharding(mesh_mod.get_mesh(), PartitionSpec())
        self._sharding = sharding
        # the flax "cache" collection pytree the engine's decode consumes
        self.cache: Dict[str, Any] = self._fresh_cache()
        # host mirror of the per-slot cache index (device truth lives in
        # cache["cache_store"]["index"]); decode needs the (B,) positions
        # each step and reading them back from device would sync
        self.starts = np.zeros((num_slots,), np.int32)
        self._free = list(range(num_slots))
        heapq.heapify(self._free)  # smallest slot first: deterministic layout
        # free-SET mirror of the heap: membership checks (the double-free
        # guard) are O(1) instead of an O(n) heap scan on every release
        self._free_set = set(self._free)
        # donate the pool (updated in place in HBM); the (L, 1, ...)
        # prefill cache is NOT donated — its shapes can never alias the
        # (L, num_slots, ...) outputs, so donating it only warns
        self._admit_jit = jax.jit(self._admit_row, donate_argnums=(0,))
        self._admit_rows_jit = jax.jit(self._admit_rows, donate_argnums=(0,))

    # ------------------------------------------------------------------
    def _place_leaf(self, key: str, leaf):
        """Commit one cache leaf to its sharding (see ``__init__``)."""
        if self._sharding is None:
            return leaf
        sh = self._sharding(key, leaf) if callable(self._sharding) \
            else self._sharding
        return leaf if sh is None else jax.device_put(leaf, sh)

    def _fresh_cache(self) -> Dict[str, Any]:
        """Zeroed pool pytree, committed to the replicated sharding the
        engine's jitted steps emit. A bare ``jnp.zeros`` pool is
        UNCOMMITTED, so the first admission would compile against
        ``UnspecifiedValue`` input shardings — one executable for the
        cold pool and a second once decode outputs (NamedSharding-
        committed) flow back in as the donated pool argument. Committing
        up front keeps each admit jit at exactly one executable for the
        pool's lifetime (the recompile watchdog pins this)."""
        store = self.spec.stacked_cache(self.num_slots)
        if self._sharding is not None:
            store = {k: self._place_leaf(k, v) for k, v in store.items()}
        return {"cache_store": store}

    def _index_from_mirror(self):
        """Device ``index`` rebuilt from the host mirror, committed like
        every other pool leaf (see :meth:`_fresh_cache` — a bare
        ``jnp.asarray`` would flip the leaf back to uncommitted and
        fork the admit/decode executables on sharding mismatch)."""
        # explicit copy: the CPU backend may zero-copy a numpy buffer,
        # and the mirror is mutated in place by later advance() calls
        idx = jnp.array(self.starts, copy=True)
        if self._sharding is not None:
            idx = self._place_leaf("index", idx)
        return idx

    # ------------------------------------------------------------------
    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def live_count(self) -> int:
        return self.num_slots - len(self._free)

    def alloc(self) -> int:
        if not self._free:
            raise RuntimeError("slot pool exhausted (scheduler bug: admit "
                               "called without a free slot)")
        slot = heapq.heappop(self._free)
        self._free_set.discard(slot)
        return slot

    def release(self, slot: int) -> None:
        """Return a slot to the free pool. Double-releasing corrupts the
        free heap (the slot would be granted to TWO requests whose cache
        rows then clobber each other), so it raises instead of silently
        corrupting ``free_count`` — the guard is an O(1) set-membership
        check against the heap's set mirror."""
        if not 0 <= slot < self.num_slots:
            raise ValueError(f"slot {slot} out of range "
                             f"[0, {self.num_slots})")
        if slot in self._free_set:
            raise RuntimeError(f"double release of slot {slot} (already "
                               f"free; scheduler/engine bug)")
        heapq.heappush(self._free, slot)
        self._free_set.add(slot)

    def reset(self) -> None:
        """Recovery path: free every slot and reallocate a zeroed device
        cache. Used after a mid-step exception — a failed dispatch may
        have consumed the donated cache buffers, so the old pytree can't
        be trusted (or even alive) afterwards."""
        self.cache = self._fresh_cache()
        self.starts[:] = 0
        self._free = list(range(self.num_slots))
        heapq.heapify(self._free)
        self._free_set = set(self._free)

    def reset_row(self, slot: int) -> None:
        """Zero a freshly-alloc'd slot's index (host mirror AND device)
        before an incremental (chunked) prefill starts writing it: the
        retired occupant's index would otherwise offset the first chunk's
        write. Pure index movement — the stale K/V itself is dead by
        masking and gets overwritten chunk by chunk."""
        self.starts[slot] = 0
        cs = dict(self.cache["cache_store"])
        cs["index"] = self._index_from_mirror()
        self.cache = {"cache_store": cs}

    # ------------------------------------------------------------------
    @staticmethod
    def _admit_row(pool: dict, pre: dict, slot, length):
        """Write the (L, 1, ...) prefill cache into batch row ``slot`` and
        set that slot's index to the TRUE prompt length (the prefill ran
        at a padded bucket width; attention masking and the next write
        offset both key off ``index``, so right-padding stays invisible)."""

        def write(dst, src):
            idx = (jnp.zeros((), jnp.int32), jnp.asarray(slot, jnp.int32)) + \
                (jnp.zeros((), jnp.int32),) * (dst.ndim - 2)
            return jax.lax.dynamic_update_slice(dst, src.astype(dst.dtype), idx)

        out = {k: write(pool[k], pre[k]) for k in pool if k != "index"}
        out["index"] = pool["index"].at[jnp.asarray(slot, jnp.int32)].set(
            jnp.asarray(length, jnp.int32), mode="drop")
        return out

    @staticmethod
    def _admit_rows(pool: dict, pre: dict, slots, lengths):
        """Scatter a BATCHED (L, nB, ...) prefill cache into ``nB`` slot
        rows in one program. ``slots``/``lengths`` are (nB,) int32 and
        traced, so one compile covers every slot combination at a given
        batch bucket; padding rows carry slot == num_slots, which JAX's
        scatter drop-mode discards instead of writing anywhere."""
        out = {k: pool[k].at[:, slots].set(pre[k].astype(pool[k].dtype),
                                           mode="drop")
               for k in pool if k != "index"}
        out["index"] = pool["index"].at[slots].set(
            jnp.asarray(lengths, jnp.int32), mode="drop")
        return out

    def admit_rows(self, prefill_cache: dict, slots, lengths) -> None:
        """Install ``nB`` prefilled sequences into ``nB`` slots (alloc'd
        by the caller) in ONE jitted multi-row scatter — the batched
        admission path. ``slots`` may contain the sentinel ``num_slots``
        for batch-bucket padding rows (dropped, never written); real
        entries must be alloc'd and in range."""
        slots = np.asarray(slots, np.int32)
        lengths = np.asarray(lengths, np.int32)
        if slots.shape != lengths.shape or slots.ndim != 1:
            raise ValueError(f"admit_rows needs matching 1-D slots/lengths; "
                             f"got {slots.shape} vs {lengths.shape}")
        real = slots < self.num_slots
        if np.any(lengths[real] > self.capacity):
            raise ValueError(f"sequence length {int(lengths[real].max())} "
                             f"exceeds slot capacity {self.capacity}")
        self.cache = {"cache_store": self._admit_rows_jit(
            self.cache["cache_store"], prefill_cache["cache_store"],
            jnp.asarray(slots), jnp.asarray(lengths))}
        self.starts[slots[real]] = lengths[real]

    def admit(self, prefill_cache: dict, slot: int, length: int) -> None:
        """Install a prefilled sequence into ``slot`` (alloc'd by caller)."""
        if length > self.capacity:
            raise ValueError(f"sequence length {length} exceeds slot "
                             f"capacity {self.capacity}")
        self.cache = {"cache_store": self._admit_jit(
            self.cache["cache_store"], prefill_cache["cache_store"],
            jnp.asarray(slot, jnp.int32), jnp.asarray(length, jnp.int32))}
        self.starts[slot] = length

    def advance(self, lengths) -> None:
        """Advance the cache state machine after one decode/verify step.

        * ``advance(1)`` (scalar) — the uniform plain-decode case: every
          slot moved one position and the device ``index`` was ALREADY
          advanced inside the jitted step (dead-slot writes land in
          masked padding), so only the host mirror moves here.
        * ``advance(lengths)`` ((num_slots,) array) — the speculative
          case: slots accepted DIFFERENT numbers of tokens, while the
          verify program advanced the device ``index`` uniformly by
          K+1. The mirror advances per slot and the device ``index`` is
          overwritten from it — this IS the KV rollback: rejected draft
          positions beyond a slot's accepted length become masked
          padding (invisible to attention, overwritten by the next
          write) without reshaping or recompiling anything.
        """
        if np.ndim(lengths) == 0:
            self.starts += int(lengths)
            return
        lengths = np.asarray(lengths, np.int32)
        if lengths.shape != self.starts.shape:
            raise ValueError(f"advance lengths shape {lengths.shape} != "
                             f"({self.num_slots},)")
        self.starts += lengths
        cs = dict(self.cache["cache_store"])
        cs["index"] = self._index_from_mirror()
        self.cache = {"cache_store": cs}

    def consistency_errors(self) -> list:
        """Internal-bookkeeping audit for ``check_invariants()``: the
        free heap and its set mirror must agree exactly and every free
        slot must be a valid id. Returns human-readable violation
        strings (empty = healthy) instead of raising, so the engine can
        aggregate pool problems with its own request/slot cross-checks."""
        errors = []
        if len(self._free) != len(self._free_set):
            errors.append(f"free heap ({len(self._free)}) and free set "
                          f"({len(self._free_set)}) sizes differ")
        if set(self._free) != self._free_set:
            errors.append(f"free heap {sorted(self._free)} != free set "
                          f"{sorted(self._free_set)}")
        bad = [s for s in self._free_set
               if not 0 <= s < self.num_slots]
        if bad:
            errors.append(f"free slots out of range: {sorted(bad)}")
        if len(set(self._free)) != len(self._free):
            errors.append(f"duplicate slots in free heap: "
                          f"{sorted(self._free)}")
        return errors

    def positions(self) -> np.ndarray:
        """(num_slots,) decode positions, clamped into the allocation so
        long-dead slots can't push position-embedding lookups or cache
        writes past the last (masked) column."""
        return np.minimum(self.starts, self.capacity - 1).astype(np.int32)
