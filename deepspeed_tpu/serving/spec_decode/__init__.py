"""Speculative decoding over the fixed-shape slot pool.

Draft-verify decode (Leviathan et al. 2023; Chen et al. 2023): a cheap
drafter proposes up to K tokens per live slot, ONE fixed-shape
verification forward scores all ``(num_slots, K+1)`` positions against
the target model, and the longest draft prefix the target reproduces is
accepted — up to K+1 tokens emitted per decode step, with greedy output
bitwise identical to plain decoding (the corrected token at the first
mismatch IS the token plain decode would have produced).

The subsystem keeps the serving engine's zero-recompile shape
discipline: verification always runs at batch = ``num_slots`` and width
``K+1`` (dead / non-speculating slots ride along with ``draft_len`` 0,
degrading gracefully to a plain decode step for that slot), and rejected
draft positions are rolled back by per-slot ``index`` masking inside the
same allocated KV buffers — never a reshape, never a new compile.

Pieces:

* :class:`~.config.SpecDecodeConfig` — the ``spec_decode`` block
  accepted by ``ServingEngine`` / ``ds.init_serving``.
* :class:`~.drafter.Drafter` — the pluggable proposal interface;
  :class:`~.drafter.NGramDrafter` (prompt-lookup: suffix-match the
  slot's own history, zero model cost) and
  :class:`~.drafter.SmallModelDrafter` (any second ``InferenceEngine``
  sharing the tokenizer).
* :mod:`~.verify` — the pure verification/acceptance function jitted by
  ``InferenceEngine.verify_k`` (greedy accept-prefix + rejection-
  sampling accept for ``do_sample``).
"""

from .config import SpecDecodeConfig, make_drafter  # noqa: F401
from .drafter import (Drafter, NGramDrafter,  # noqa: F401
                      SmallModelDrafter)
from .verify import make_verify_fn  # noqa: F401

__all__ = ["SpecDecodeConfig", "make_drafter", "Drafter", "NGramDrafter",
           "SmallModelDrafter", "make_verify_fn"]
