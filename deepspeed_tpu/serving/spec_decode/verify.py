"""The verification/acceptance program — pure function, jitted once.

One fixed-shape chunked-decode forward scores the current token plus K
draft positions for ALL ``num_slots`` rows (``(B, K+1)`` inputs,
per-slot ``(B,)`` cache offsets), then acceptance runs in the same
compiled program:

* **greedy** — accept the longest draft prefix whose tokens equal the
  target model's own argmax continuations; the token at the first
  mismatch is the argmax the target would have produced anyway, so
  emitted output is bitwise identical to plain decoding.
* **do_sample** — rejection sampling (Leviathan et al. 2023 §2.3).
  Both shipped drafters are deterministic given context, so the draft
  distribution q is a point mass and ``min(1, p/q)`` reduces to
  ``p(d_j)`` under the serving sampler's filtered distribution; on the
  first rejection the replacement is drawn from the residual (p with
  the rejected token removed, renormalized), which keeps the output
  distribution exactly the target model's.

The cache comes back with every verified position written (the chunk
writes K+1 positions for every row, dead slots included — their writes
land in masked padding). ROLLBACK of rejected positions is the caller's
per-slot ``index`` update (:meth:`SlotPool.advance`): stale K/V beyond
the accepted length is dead by masking, never reshaped or recompiled.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def make_verify_fn(decode_fn, filter_fn):
    """Build the verify body over the engine's traced ``decode_fn``
    ((params, cache, tokens, pos) -> (logits, cache)) and its sampling
    ``filter_fn`` ((..., V) logits, temperature, top_k, top_p) — the SAME
    filter the serving sampler uses, so acceptance probabilities match
    the distribution plain decode would have sampled from."""

    def verify(params, cache, tokens, pos, draft, draft_len, rng,
               temperature, greedy, top_k, top_p):
        """tokens: (B, K+1) int32 — [current, draft_0..draft_{K-1}];
        pos: (B,) int32 decode positions; draft: (B, K) int32;
        draft_len: (B,) int32 in [0, K] (0 = not speculating / dead).
        Returns (cache, out (B, K+1) int32, n_emit (B,) int32): row i
        emits out[i, :n_emit[i]] — accepted prefix + bonus/correction."""
        B, T = tokens.shape
        K = T - 1
        logits, cache = decode_fn(params, cache, tokens, pos)
        last = logits.astype(jnp.float32)            # (B, K+1, V)
        V = last.shape[-1]
        targets = jnp.argmax(last, axis=-1)          # (B, K+1) greedy next
        in_draft = jnp.arange(K)[None, :] < draft_len[:, None]

        # greedy: accept while the target reproduces the draft
        g_accept = (draft == targets[:, :K]) & in_draft
        # sampling: accept d_j w.p. p(d_j) under the filtered distribution
        # (point-mass q — both drafters are deterministic given context)
        filt = filter_fn(last, temperature, top_k, top_p)
        probs = jax.nn.softmax(filt, axis=-1)
        p_draft = jnp.take_along_axis(probs[:, :K], draft[..., None],
                                      axis=-1)[..., 0]
        rng_acc, rng_bonus = jax.random.split(rng)
        u = jax.random.uniform(rng_acc, (B, K))
        s_accept = (u < p_draft) & in_draft

        accept = jnp.where(greedy, g_accept, s_accept)
        acc = jnp.cumprod(accept.astype(jnp.int32), axis=1)
        n_acc = acc.sum(axis=1)                      # (B,) in [0, K]

        # bonus/correction token from position n_acc: greedy takes the
        # argmax (== what plain decode emits there); sampling draws from
        # the residual — p with the rejected token removed when the stop
        # was a true rejection (not draft exhaustion)
        bonus_filt = jnp.take_along_axis(filt, n_acc[:, None, None],
                                         axis=1)[:, 0]          # (B, V)
        rejected = jnp.take_along_axis(draft,
                                       jnp.clip(n_acc, 0, K - 1)[:, None],
                                       axis=1)[:, 0]
        was_rejection = n_acc < draft_len
        residual = jnp.where((jnp.arange(V)[None, :] == rejected[:, None])
                             & was_rejection[:, None], -1e30, bonus_filt)
        sampled = jax.random.categorical(rng_bonus, residual, axis=-1)
        g_bonus = jnp.take_along_axis(targets, n_acc[:, None], axis=1)[:, 0]
        bonus = jnp.where(greedy, g_bonus, sampled).astype(jnp.int32)

        j = jnp.arange(K + 1)[None, :]
        draft_pad = jnp.pad(draft, ((0, 0), (0, 1)))
        out = jnp.where(j < n_acc[:, None], draft_pad,
                        jnp.where(j == n_acc[:, None], bonus[:, None], 0))
        return cache, out.astype(jnp.int32), n_acc + 1

    return verify
