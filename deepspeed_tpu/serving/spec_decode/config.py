"""The ``spec_decode`` config block for the serving engine.

Accepted anywhere the serving engine is built::

    ds.init_serving(model, ..., spec_decode={"drafter": "ngram", "k": 4})

``drafter`` selects the proposal source: ``"ngram"`` (prompt-lookup —
no second model, proposes by suffix-matching the slot's own generated
history; the right default for repetitive/extractive traffic),
``"model"`` (a second, smaller ``InferenceEngine`` passed as
``draft_engine``), or a ready :class:`~.drafter.Drafter` instance.
"""

from __future__ import annotations

import dataclasses
from typing import Any


@dataclasses.dataclass
class SpecDecodeConfig:
    """Server-global speculative-decoding knobs.

    ``k`` is the draft length: every decode step verifies exactly
    ``k`` draft positions (+1 for the current token) in one fixed-shape
    forward, so larger ``k`` trades verify-forward width for more
    tokens per accepted step. The slot pool reserves ``k`` positions of
    KV headroom per sequence (the verify chunk writes ``k+1`` positions
    past the live offset before rollback), so admission control tightens
    to ``prompt + max_new_tokens <= capacity - k``.
    """

    enabled: bool = True
    drafter: Any = "ngram"      # "ngram" | "model" | Drafter instance
    k: int = 4                  # draft tokens proposed/verified per step
    max_ngram: int = 3          # n-gram drafter: longest suffix to match
    min_ngram: int = 1          # n-gram drafter: shortest suffix to match
    draft_engine: Any = None    # InferenceEngine for drafter="model"

    @classmethod
    def from_value(cls, value):
        """Coerce the ``spec_decode=`` argument: ``None``/``False`` ->
        ``None`` (speculation off), ``True`` -> defaults, dict -> kwargs,
        instance -> itself."""
        if value is None or value is False:
            return None
        if isinstance(value, cls):
            return value
        if value is True:
            return cls()
        if isinstance(value, dict):
            return cls(**value)
        raise TypeError(f"spec_decode must be a dict, SpecDecodeConfig, "
                        f"bool or None; got {type(value).__name__}")

    def validate(self, capacity: int) -> None:
        if self.k < 1:
            raise ValueError(f"spec_decode.k must be >= 1, got {self.k}")
        if self.k + 1 >= capacity:
            raise ValueError(
                f"spec_decode.k({self.k}) + 1 must be < the KV capacity "
                f"({capacity}); the verify chunk writes k+1 positions")
        if not (1 <= self.min_ngram <= self.max_ngram):
            raise ValueError(
                f"need 1 <= min_ngram({self.min_ngram}) <= "
                f"max_ngram({self.max_ngram})")


def make_drafter(cfg: SpecDecodeConfig):
    """Resolve the config's ``drafter`` selector into a Drafter."""
    from .drafter import Drafter, NGramDrafter, SmallModelDrafter

    if isinstance(cfg.drafter, Drafter):
        return cfg.drafter
    if cfg.drafter == "ngram":
        return NGramDrafter(max_ngram=cfg.max_ngram, min_ngram=cfg.min_ngram)
    if cfg.drafter == "model":
        if cfg.draft_engine is None:
            raise ValueError("spec_decode drafter='model' requires "
                             "draft_engine= (a second InferenceEngine "
                             "sharing the tokenizer)")
        return SmallModelDrafter(cfg.draft_engine)
    raise ValueError(f"unknown drafter {cfg.drafter!r}; expected 'ngram', "
                     f"'model' or a Drafter instance")
