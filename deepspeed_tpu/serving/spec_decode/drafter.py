"""Drafters: propose K tokens per live slot for one verify forward.

A drafter is HOST-side policy with a fixed-shape contract: given one
history per slot (``None`` for dead slots AND for slots still
``PREFILLING`` under stall-free chunked admission — the serving engine
withholds their histories, so no draft is ever proposed against a
half-written cache row), return ``(tokens, counts)`` where ``tokens``
is ``(num_slots, K)`` int32 and ``counts`` is ``(num_slots,)`` int32
with ``counts[i]`` real proposals in row ``i`` (the rest is padding the
verifier masks). A slot with ``counts == 0`` degrades to a plain decode
step inside the same verify program — no shape change, no recompile,
just zero accepted drafts.

Correctness never depends on the drafter: verification accepts exactly
the prefix the target model reproduces (greedy) or rejection-samples
losslessly (``do_sample``), so a bad proposal costs only wasted verify
width, never wrong output.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

_MIN_DRAFT_BUCKET = 16


def bucket_width(n: int, cap: int) -> int:
    """Next power-of-two >= n (min 16), capped at ``cap`` — the same
    bucketing the serving engine uses for prefill, bounding draft-side
    recompiles at log2(capacity) across arbitrary history lengths."""
    b = _MIN_DRAFT_BUCKET
    while b < n:
        b *= 2
    return min(b, cap)


class Drafter:
    """Pluggable proposal interface (see module docstring contract)."""

    name = "drafter"

    def propose(self, histories: List[Optional[np.ndarray]], k: int
                ) -> Tuple[np.ndarray, np.ndarray]:
        """``histories[slot]`` is prompt+generated tokens (int32, includes
        the not-yet-decoded current token) or ``None`` for a dead slot.
        Returns ``(tokens (num_slots, k) int32, counts (num_slots,) int32)``.

        Failure contract: ``propose`` runs inside the serving engine's
        exception-safe step — a drafter that raises aborts the step
        cleanly (``ServingEngine._abort_step``: no slot leaks, running
        requests FAIL with ``finish_reason="error"``, the error
        propagates to the caller). A drafter that cannot produce drafts
        should return ``counts`` of zeros instead of raising — zero-draft
        rows reduce verify to plain decode at zero extra cost."""
        raise NotImplementedError


class NGramDrafter(Drafter):
    """Prompt-lookup decoding: propose the continuation of the most
    recent earlier occurrence of the history's own suffix (Saxena 2023
    prompt-lookup; the assisted-generation candidate strategy). Zero
    model cost — pure host suffix matching — so its draft overhead is
    microseconds and any acceptance at all is profit. Wins on
    repetitive/extractive traffic (summarization, code edits, retrieval
    answers that quote the prompt); on non-repetitive text acceptance
    tends to zero and throughput degrades gracefully to plain decode."""

    name = "ngram"

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1):
        if not (1 <= min_ngram <= max_ngram):
            raise ValueError(f"need 1 <= min_ngram({min_ngram}) <= "
                             f"max_ngram({max_ngram})")
        self.max_ngram = max_ngram
        self.min_ngram = min_ngram

    def _continuation(self, h: np.ndarray, k: int) -> Optional[np.ndarray]:
        T = len(h)
        # longest suffix first: a longer matched context extrapolates
        # better; fall through to shorter n on no match
        for n in range(min(self.max_ngram, T - 1), self.min_ngram - 1, -1):
            pat = h[T - n:]
            # candidate windows h[s:s+n] must end before the final
            # position so at least one continuation token exists
            win = np.lib.stride_tricks.sliding_window_view(h[:T - 1], n)
            hits = np.nonzero((win == pat).all(axis=1))[0]
            if len(hits):
                s = int(hits[-1])  # most recent occurrence
                return h[s + n:s + n + k]
        return None

    def propose(self, histories, k):
        B = len(histories)
        tokens = np.zeros((B, k), np.int32)
        counts = np.zeros((B,), np.int32)
        for i, h in enumerate(histories):
            if h is None:
                continue
            h = np.asarray(h, np.int32)
            if len(h) < self.min_ngram + 1:
                continue
            cont = self._continuation(h, k)
            if cont is not None and len(cont):
                tokens[i, :len(cont)] = cont
                counts[i] = len(cont)
        return tokens, counts


class SmallModelDrafter(Drafter):
    """Draft with a second (smaller) ``InferenceEngine`` sharing the
    target's tokenizer — the classic two-model speculative setup.

    Stateless per step: one bucketed batched ``prefill_last`` over every
    live slot's history (per-slot ``last_pos``, right-padded to a
    power-of-two width) seeds a fresh draft KV cache, then ``k-1``
    single-token greedy decode steps extend it. Recompiles stay bounded
    (log2 prefill buckets + one decode program). The per-step draft
    prefill is O(history) — worth it only when the draft model is much
    smaller than the target; for repetitive traffic prefer
    :class:`NGramDrafter`, whose overhead is microseconds.

    Proposals are greedy, i.e. deterministic given the context, so the
    verifier's point-mass rejection-sampling treatment stays lossless
    for ``do_sample`` too.
    """

    name = "model"

    def __init__(self, engine):
        self.engine = engine
        self._argmax = None

    def propose(self, histories, k):
        eng = self.engine
        eng._ensure_params(jnp.zeros((1, 2), jnp.int32))
        if getattr(eng, "_jit_prefill_at", None) is None:
            raise ValueError("SmallModelDrafter requires the draft module "
                             "to expose prefill_last(input_ids, last_pos)")
        spec = eng.kv_cache_spec()
        if spec is None:
            raise ValueError("SmallModelDrafter requires the draft module "
                             "to declare kv_cache_spec()")
        cap = int(spec.max_seq_len)
        B = len(histories)
        # keep the most recent window that still leaves room for k draft
        # positions; truncation only shifts absolute positions the draft
        # model sees (draft quality, never correctness — verify guards)
        keep = max(cap - k - 1, 1)
        rows = [None if h is None else np.asarray(h, np.int32)[-keep:]
                for h in histories]
        lens = np.array([0 if r is None else len(r) for r in rows], np.int32)
        W = bucket_width(max(int(lens.max()), 1), cap)
        ids = np.zeros((B, W), np.int32)
        for i, r in enumerate(rows):
            if r is not None:
                ids[i, :len(r)] = r
        last_pos = np.maximum(lens - 1, 0).astype(np.int32)
        logits, cache = eng._jit_prefill_at(eng.params, jnp.asarray(ids),
                                            jnp.asarray(last_pos))
        # the batched prefill ran at padded width W; per-slot TRUE lengths
        # mask the right-padding's garbage KV, exactly as the slot pool's
        # admit does (vector index is the slot-pooled decode contract)
        cs = dict(cache["cache_store"])
        cs["index"] = jnp.asarray(lens)
        cache = {"cache_store": cs}
        if self._argmax is None:
            self._argmax = jax.jit(lambda lg: jnp.argmax(
                lg[:, -1, :].astype(jnp.float32), axis=-1).astype(jnp.int32))
        cur = self._argmax(logits)
        toks = [cur]
        pos = lens.copy()
        for _ in range(k - 1):
            logits, cache = eng._jit_decode(eng.params, cache, cur[:, None],
                                            jnp.asarray(pos))
            cur = self._argmax(logits)
            toks.append(cur)
            pos += 1
        tokens = np.stack([np.asarray(t) for t in toks], axis=1)
        counts = np.where(lens > 0, k, 0).astype(np.int32)
        return tokens.astype(np.int32), counts
