"""Data-parallel replica router: one front door over N serving engines.

Tensor parallelism (the ``model`` mesh axis) shrinks per-token latency;
data parallelism over REPLICAS grows aggregate throughput. The router is
the host half of that trade: it fronts N independent
:class:`~deepspeed_tpu.serving.engine.ServingEngine` replicas — each
with its own slot pool, scheduler and compiled programs — behind a
single ``submit``/``step``/``cancel`` surface shaped exactly like one
engine, so the async front end (:mod:`.frontend.bridge`) drives a
router or a bare engine interchangeably.

Dispatch policy, in priority order:

1. **Session stickiness** — ``submit(..., session=key)`` pins every
   request of a conversation to the replica that served it last, so its
   paged prefix cache keeps compounding across turns.
2. **Prefix affinity** — with paged KV, each replica's
   :class:`~deepspeed_tpu.serving.prefix_cache.PrefixCache` trie is
   ``peek``-scored against the prompt (a pure read: no LRU mutation)
   and the longest full-page hit wins. A cached prefix is worth more
   than an idle replica: skipped prefill chunks beat queue position.
3. **Least loaded** — fewest ``live + pending`` requests.
4. **Lowest replica index** — the deterministic tie-break; two routers
   fed the same request sequence dispatch identically (pinned by test).

Admission spill: when the chosen replica REJECTS (queue full, page
footprint), the router retries the remaining replicas in the same
ranked order before surfacing the rejection — N bounded queues behave
like one shared admission queue until every one of them is full.

Failure containment: a replica whose ``step()`` raises is marked dead
and never stepped again. Every request it still owed — queued, seated
mid-prefill, decoding, or FAILED by the engine's own mid-step abort —
is scrubbed back to QUEUED (``Request.seed_tokens`` carries prompt +
generated-so-far, so greedy resume is bitwise identical to never having
failed) and re-submitted to a surviving sibling. Slots and pages of the
dead replica die with it; siblings' invariants stay clean.

Request ids stay globally unique across replicas: replica ``i``'s
engine counter is offset to ``i * ID_STRIDE`` at construction, so a
router-issued id names one request no matter which replica seated it.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from .engine import ServingEngine
from .request import FinishReason, Request, RequestState

# id-space stride per replica: replica i issues ids in
# [i*ID_STRIDE, (i+1)*ID_STRIDE) — collision would need a billion
# requests through one replica in one process lifetime
ID_STRIDE = 1_000_000_000


class NoLiveReplicaError(RuntimeError):
    """Every replica has failed; the router can no longer make progress."""


class ReplicaRouter:
    """Route requests across data-parallel :class:`ServingEngine` replicas.

    ``replicas`` must be non-empty; each should be built on its own
    :class:`~deepspeed_tpu.inference.engine.InferenceEngine` (they may
    share a mesh — DP over replicas is a host-side construct; the mesh
    ``data`` axis shards slots WITHIN a replica). ``affinity=False``
    disables prefix-trie scoring (dispatch is then sticky-session →
    least-loaded only).
    """

    def __init__(self, replicas: Sequence[ServingEngine],
                 affinity: bool = True):
        if not replicas:
            raise ValueError("ReplicaRouter needs at least one replica")
        self.replicas: List[ServingEngine] = list(replicas)
        self.affinity = bool(affinity)
        self._alive: List[bool] = [True] * len(self.replicas)
        for i, rep in enumerate(self.replicas):
            # offset, don't overwrite: a replica with prior traffic keeps
            # its issued ids unique within its own stripe
            rep._next_id += i * ID_STRIDE
        self._owner: Dict[int, int] = {}       # request_id -> replica idx
        self._session: Dict[str, int] = {}     # session key -> replica idx
        self._tracked: Dict[int, Request] = {}  # live (non-terminal) reqs
        self.dispatched = [0] * len(self.replicas)
        self.affinity_hits = 0
        self.spills = 0          # admissions that fell through to a sibling
        self.failovers = 0       # requests re-homed off a dead replica

    # -- introspection -------------------------------------------------
    @property
    def num_replicas(self) -> int:
        return len(self.replicas)

    @property
    def alive_replicas(self) -> List[int]:
        return [i for i, a in enumerate(self._alive) if a]

    @property
    def live_count(self) -> int:
        return sum(r.live_count for i, r in enumerate(self.replicas)
                   if self._alive[i])

    @property
    def pending(self) -> int:
        return sum(r.scheduler.pending for i, r in enumerate(self.replicas)
                   if self._alive[i])

    def has_work(self) -> bool:
        """Any alive replica holding queued, prefilling or running work —
        the bridge's step-gate probe (duck-typed: it prefers a callable
        ``has_work`` over reading engine internals)."""
        return any(
            r.live_count or r.scheduler.pending
            or getattr(r, "_prefill_queue", None)
            for i, r in enumerate(self.replicas) if self._alive[i])

    def _now(self) -> float:
        return self.replicas[0]._now()

    # -- dispatch ------------------------------------------------------
    def _load(self, i: int) -> int:
        r = self.replicas[i]
        return r.live_count + r.scheduler.pending

    def _rank(self, prompt, session: Optional[str]) -> List[int]:
        """Replica indices in dispatch-preference order (alive only)."""
        alive = self.alive_replicas
        if not alive:
            raise NoLiveReplicaError("all replicas have failed")
        if session is not None:
            home = self._session.get(session)
            if home is not None and self._alive[home]:
                self.affinity_hits += 1
                return [home] + [i for i in alive if i != home]
        scores = {i: 0 for i in alive}
        if self.affinity:
            for i in alive:
                trie = getattr(self.replicas[i].pool, "prefix", None)
                if trie is not None:
                    scores[i] = int(trie.peek(prompt))
        # sort: longest prefix hit, then least loaded, then lowest index
        ranked = sorted(alive, key=lambda i: (-scores[i], self._load(i), i))
        if scores[ranked[0]] > 0:
            self.affinity_hits += 1
        return ranked

    def submit(self, prompt, session: Optional[str] = None,
               **kwargs: Any) -> Request:
        """Route one request. Same contract as ``ServingEngine.submit``
        (never raises on load; REJECTED carries a reason), plus
        ``session=`` stickiness. A rejection by the preferred replica
        spills to the next-ranked sibling; the LAST rejection is
        returned only when every alive replica refused."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        ranked = self._rank(prompt, session)
        req: Optional[Request] = None
        for n, i in enumerate(ranked):
            req = self.replicas[i].submit(prompt, **kwargs)
            if req.state is not RequestState.REJECTED:
                if n > 0:
                    self.spills += 1
                self.dispatched[i] += 1
                self._owner[req.request_id] = i
                self._tracked[req.request_id] = req
                if session is not None:
                    self._session[session] = i
                return req
        return req  # every replica rejected: surface the last verdict

    # -- stepping ------------------------------------------------------
    def step(self) -> List[Request]:
        """One iteration of every alive replica. A replica that raises is
        retired and its requests fail over to the ranked siblings; the
        error is contained, not propagated (mirrors a multi-host serving
        tier losing one worker). Raises :class:`NoLiveReplicaError` only
        when no replica survives to inherit the work."""
        finished: List[Request] = []
        for i, rep in enumerate(self.replicas):
            if not self._alive[i]:
                continue
            try:
                finished.extend(rep.step())
            except Exception:
                self._alive[i] = False
                self._fail_over(i)
        for req in finished:
            self._tracked.pop(req.request_id, None)
            self._owner.pop(req.request_id, None)
        if not any(self._alive):
            raise NoLiveReplicaError("all replicas have failed")
        return finished

    def _fail_over(self, dead: int) -> None:
        """Re-home every request the dead replica still owed.

        The engine's own ``_abort_step`` has already rolled its state to
        one of three shapes — QUEUED in its scheduler, seated in
        ``_slot_req`` (when the failure bypassed the abort path), or
        FAILED with reason ``error`` — and ``check_invariants`` on the
        corpse is meaningless. The router scrubs each survivor back to a
        fresh QUEUED request (keeping ``output_tokens``: they are the
        resume seed) and re-submits through a sibling's admission
        control, so capacity limits still hold under failover."""
        rep = self.replicas[dead]
        owed: List[Request] = []
        seen: set = set()

        def _take(req: Request) -> None:
            if id(req) in seen:
                return
            seen.add(id(req))
            owed.append(req)

        for r in list(rep.scheduler.queue):
            _take(r)
        rep.scheduler.queue.clear()
        for r in list(rep._slot_req.values()):
            _take(r)
        rep._slot_req.clear()
        rep._prefill_queue[:] = []
        # FAILED-by-abort requests the router still tracks: the engine
        # already charged the failure, but the CLIENT contract is that a
        # replica loss is invisible — resurrect and re-home them too
        for rid, r in list(self._tracked.items()):
            if self._owner.get(rid) == dead \
                    and r.state is RequestState.FAILED \
                    and r.finish_reason is FinishReason.ERROR:
                _take(r)
        owed.sort(key=lambda r: r.request_id)  # oldest first, deterministic
        for r in owed:
            if r.state in (RequestState.FINISHED, RequestState.REJECTED):
                continue
            r.state = RequestState.QUEUED
            r.slot = None
            r.prefill_pos = 0
            r.admit_time = None
            r.finish_reason = None
            r.finish_time = None
            r.preemptions += 1
            placed = False
            for i in self._rank(r.seed_tokens, None):
                accepted, _ = self.replicas[i].scheduler.submit(r)
                if accepted:
                    self._owner[r.request_id] = i
                    self._tracked[r.request_id] = r
                    self.failovers += 1
                    placed = True
                    break
            if not placed:
                r.state = RequestState.FAILED
                r.finish_reason = FinishReason.ERROR
                r.finish_time = self._now()
                self._tracked.pop(r.request_id, None)
                self._owner.pop(r.request_id, None)
        # sticky sessions homed on the corpse re-route on next submit
        for key, idx in list(self._session.items()):
            if idx == dead:
                del self._session[key]

    def run_until_drained(self, max_steps: Optional[int] = None,
                          stall_patience: Optional[int] = None
                          ) -> List[Request]:
        """Step until no alive replica has work (mirror of the engine
        method; ``stall_patience`` is accepted for signature parity but
        stall detection lives in each replica)."""
        del stall_patience
        out: List[Request] = []
        steps = 0
        while self.has_work():
            out.extend(self.step())
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        return out

    # -- per-request / lifecycle ---------------------------------------
    def cancel(self, request_id: int) -> Optional[Request]:
        idx = self._owner.get(request_id)
        if idx is None or not self._alive[idx]:
            return None
        req = self.replicas[idx].cancel(request_id)
        if req is not None:
            self._tracked.pop(request_id, None)
            self._owner.pop(request_id, None)
        return req

    def end_warmup(self) -> None:
        for i in self.alive_replicas:
            self.replicas[i].end_warmup()

    def check_invariants(self) -> None:
        """Cross-replica audit: every ALIVE replica's slot/queue/pool
        bookkeeping must hold (dead replicas are tombstones — their
        state was deliberately stripped by failover)."""
        # ownership entries may not outlive tracking: _owner and
        # _tracked are populated and retired together, so a stale
        # _owner key is an unbounded host-side leak
        stale = set(self._owner) - set(self._tracked)
        if stale:
            raise AssertionError(
                f"router _owner map holds {len(stale)} request id(s) "
                f"no longer tracked: {sorted(stale)[:5]}")
        for i in self.alive_replicas:
            self.replicas[i].check_invariants()

    @property
    def recompiles(self) -> int:
        """Post-warmup recompiles summed over alive replicas' watchdogs."""
        total = 0
        for i in self.alive_replicas:
            wd = self.replicas[i].watchdog
            if wd is not None:
                total += wd.recompiles
        return total

    def stats(self) -> dict:
        """Router-level counters plus each alive replica's SLO snapshot."""
        return {
            "replicas": self.num_replicas,
            "alive": self.alive_replicas,
            "dispatched": list(self.dispatched),
            "affinity_hits": self.affinity_hits,
            "spills": self.spills,
            "failovers": self.failovers,
            "per_replica": {i: self.replicas[i].stats()
                            for i in self.alive_replicas},
        }
