"""Data-parallel replica router: one front door over N serving engines.

Tensor parallelism (the ``model`` mesh axis) shrinks per-token latency;
data parallelism over REPLICAS grows aggregate throughput. The router is
the host half of that trade: it fronts N independent
:class:`~deepspeed_tpu.serving.engine.ServingEngine` replicas — each
with its own slot pool, scheduler and compiled programs — behind a
single ``submit``/``step``/``cancel`` surface shaped exactly like one
engine, so the async front end (:mod:`.frontend.bridge`) drives a
router or a bare engine interchangeably.

Dispatch policy, in priority order:

1. **Session stickiness** — ``submit(..., session=key)`` pins every
   request of a conversation to the replica that served it last, so its
   paged prefix cache keeps compounding across turns.
2. **Prefix affinity** — with paged KV, each replica's
   :class:`~deepspeed_tpu.serving.prefix_cache.PrefixCache` trie is
   ``peek``-scored against the prompt (a pure read: no LRU mutation)
   and the longest full-page hit wins. A cached prefix is worth more
   than an idle replica: skipped prefill chunks beat queue position.
3. **Least loaded** — fewest ``live + pending`` requests.
4. **Lowest replica index** — the deterministic tie-break; two routers
   fed the same request sequence dispatch identically (pinned by test).

Admission spill: when the chosen replica REJECTS (queue full, page
footprint), the router retries the remaining replicas in the same
ranked order before surfacing the rejection — N bounded queues behave
like one shared admission queue until every one of them is full.

Failure containment: a replica whose ``step()`` raises is marked dead
and never stepped again. Every request it still owed — queued, seated
mid-prefill, decoding, or FAILED by the engine's own mid-step abort —
is scrubbed back to QUEUED (``Request.seed_tokens`` carries prompt +
generated-so-far, so greedy resume is bitwise identical to never having
failed) and re-submitted to a surviving sibling. Slots and pages of the
dead replica die with it; siblings' invariants stay clean.

Request ids stay globally unique across replicas: replica ``i``'s
engine counter is offset to ``i * ID_STRIDE`` at construction, so a
router-issued id names one request no matter which replica seated it.

Disaggregated prefill/decode (the DistServe/Splitwise split): replicas
may carry a ``role`` — ``"both"`` (the classic colocated engine),
``"prefill"`` (chunked admission only; finished requests park in
``pending_handoffs()``), or ``"decode"``. The router becomes the
topology controller: submissions route to prefill-capable replicas
(least-loaded), and after every fleet step the router drains each
prefill replica's parked handoffs — copying the request's live KV pages
across pools with ``PagedKVPool.import_pages`` (one fixed-shape jitted
program) and seating them on a decode replica chosen sticky-session
first, then by a SHARED FIRST-PAGE INDEX over the whole decode pool's
prefix tries (global prefix affinity: the handoff lands where the
prompt's first page is already cached, and the transfer skips every
trie-hit page), then least-loaded. Transfers are synchronous within the
drain — ``transfers_in_flight`` must read zero at every step boundary
(audited by :meth:`check_invariants`).

Fleet observability (ISSUE 20): every routed request carries a
*journey* — a fleet-unique trace context minted at submit and stamped
onto each home replica's TimelineStore events — and the router logs a
hop at every boundary it controls (dispatch, page transfer, failover,
terminal). :meth:`journey` stitches the cross-replica record into one
ordered timeline; :meth:`export_trace` renders the whole fleet as ONE
Perfetto document (one process lane per replica plus the router's own,
flow arrows across handoff/transfer/failover boundaries, scale events
as instant markers); ``router.fleet`` (a
:class:`~deepspeed_tpu.telemetry.fleet.FleetTelemetry`) merges every
replica's registry/digests into one labeled Prometheus exposition and
writes ONE fleet-scoped post-mortem when any replica dies on a fatal
condition.

The fleet is ELASTIC: :meth:`add_replica` / :meth:`retire_replica`
reshape it at runtime (retirement drains through the same failover
scrub — greedy output is bitwise identical to never having moved), and
:meth:`maybe_autoscale` drives both from the PR 8 burn-rate signals: a
role whose replicas sustain a ``page`` alert spawns a sibling, a role
idling with spare replicas retires one.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..telemetry.fleet import FleetTelemetry
from ..telemetry.registry import MetricsRegistry
from ..telemetry.slo import QuantileDigest
from ..telemetry.tracer import Tracer, export_merged
from ..telemetry.watchdog import RecompileAfterWarmupError
from .engine import ServingEngine
from .request import FinishReason, Request, RequestState
from .resilience import InvariantViolation, ServingStalledError

# id-space stride per replica: replica i issues ids in
# [i*ID_STRIDE, (i+1)*ID_STRIDE) — collision would need a billion
# requests through one replica in one process lifetime
ID_STRIDE = 1_000_000_000


class NoLiveReplicaError(RuntimeError):
    """Every replica has failed; the router can no longer make progress."""


class ReplicaRouter:
    """Route requests across data-parallel :class:`ServingEngine` replicas.

    ``replicas`` must be non-empty; each should be built on its own
    :class:`~deepspeed_tpu.inference.engine.InferenceEngine` (they may
    share a mesh — DP over replicas is a host-side construct; the mesh
    ``data`` axis shards slots WITHIN a replica). ``affinity=False``
    disables prefix-trie scoring (dispatch is then sticky-session →
    least-loaded only).
    """

    def __init__(self, replicas: Sequence[ServingEngine],
                 affinity: bool = True,
                 spawner: Optional[Any] = None,
                 scale_patience: int = 3,
                 tracer: Optional[Tracer] = None,
                 dump_dir: Optional[str] = None,
                 journey_capacity: int = 4096):
        if not replicas:
            raise ValueError("ReplicaRouter needs at least one replica")
        self.replicas: List[ServingEngine] = list(replicas)
        self.affinity = bool(affinity)
        self._alive: List[bool] = [True] * len(self.replicas)
        self.roles: List[str] = [getattr(r, "role", "both")
                                 for r in self.replicas]
        self._check_role_coverage(self.roles)
        for i, rep in enumerate(self.replicas):
            # offset, don't overwrite: a replica with prior traffic keeps
            # its issued ids unique within its own stripe
            rep._next_id += i * ID_STRIDE
            self._join_observability(i, rep)
        self._owner: Dict[int, int] = {}       # request_id -> replica idx
        self._session: Dict[str, int] = {}     # session key -> replica idx
        self._tracked: Dict[int, Request] = {}  # live (non-terminal) reqs
        self.dispatched = [0] * len(self.replicas)
        self.affinity_hits = 0
        self.spills = 0          # admissions that fell through to a sibling
        self.failovers = 0       # requests re-homed off a dead replica
        # -- disaggregation / elasticity (ISSUE 19) --------------------
        self.transfers = 0       # completed prefill->decode handoffs
        self.transfer_bytes = 0
        self.prefix_routed = 0   # handoffs placed via the shared
        #                          first-page index (global prefix hit)
        self.transfer_pages_saved = 0  # pages a destination trie hit
        #                          kept off the wire (adopt hit_pages)
        self._transfers_in_flight = 0  # nonzero ONLY inside one drain
        self._req_session: Dict[int, str] = {}   # rid -> session key
        self._decode_session: Dict[str, int] = {}  # session -> decode idx
        self.spawner = spawner   # role -> ServingEngine factory (autoscale)
        self.scale_patience = int(scale_patience)
        self._hot_streak: Dict[str, int] = {}
        self._idle_streak: Dict[str, int] = {}
        self.scale_events: List[dict] = []
        self.last_scale_event: Optional[dict] = None
        self._warmed = False
        self.registry = MetricsRegistry()
        self.registry.add_collector(self._collect_metrics)
        # -- fleet observability (ISSUE 20) ----------------------------
        # the router's OWN tracer: dispatch/transfer spans, failover
        # and scale-event instants — one extra process lane in the
        # merged Perfetto export. Disabled by default like the engine's.
        self.tracer = tracer if tracer is not None else Tracer(enabled=False)
        self.tracer.process_name = "router"
        self.dump_dir = dump_dir
        # request journeys: jid -> {request_id, hops, homes, terminal},
        # a bounded log — the fleet post-mortem's dispatch record and
        # the stitcher's spine
        self._journey_seq = 0
        self._journey_capacity = int(journey_capacity)
        self._journeys: "OrderedDict[int, dict]" = OrderedDict()
        self._rid_journey: Dict[int, int] = {}
        self._journey_ns = 0       # self-timed bookkeeping (overhead_pct)
        # per-transfer wire latency, mergeable into the fleet exposition
        self.transfer_latency = QuantileDigest()
        self.fleet = FleetTelemetry(self, dump_dir=dump_dir)

    @staticmethod
    def _check_role_coverage(roles: Sequence[str]) -> None:
        for role in roles:
            if role not in ("both", "prefill", "decode"):
                raise ValueError(f"unknown replica role {role!r}")
        if any(r != "both" for r in roles):
            if not any(r in ("both", "prefill") for r in roles):
                raise ValueError("split-role fleet has no prefill-capable "
                                 "replica")
            if not any(r in ("both", "decode") for r in roles):
                raise ValueError("split-role fleet has no decode-capable "
                                 "replica")

    def _join_observability(self, i: int, rep: ServingEngine) -> None:
        """Stamp fleet identity onto a joining replica: ``replica_id``
        on the engine and its TimelineStore (every timeline event then
        carries ``replica=i`` for the journey stitcher) and a process
        name on its tracer (the Perfetto process-lane label in the
        merged export)."""
        rep.replica_id = i
        rep.timelines.replica_id = i
        rep.tracer.process_name = \
            f"replica{i}:{getattr(rep, 'role', 'both')}"

    def _collect_metrics(self) -> None:
        """Registry collector (runs at every snapshot/scrape): copy the
        router-owned counters in — ``router_fleet_size`` and
        ``router_transfers_total`` in ``/metrics``."""
        reg = self.registry
        reg.gauge("router/fleet_size").set(float(len(self.alive_replicas)))
        reg.counter("router/transfers_total").value = float(self.transfers)
        reg.counter("router/transfer_bytes_total").value = \
            float(self.transfer_bytes)
        # stats["bytes"] counts only pages that crossed pools (trie-hit
        # pages never move), so the bytes counter IS wire bytes
        reg.counter("router/transfer_wire_bytes_total").value = \
            float(self.transfer_bytes)
        reg.counter("router/failovers_total").value = float(self.failovers)
        reg.counter("router/journeys_total").value = \
            float(self._journey_seq)
        reg.counter("router/prefix_routed_total").value = \
            float(self.prefix_routed)
        reg.gauge("router/transfers_in_flight").set(
            float(self._transfers_in_flight))
        for role in ("prefill", "decode", "both"):
            idxs = self._role_indices(role)
            reg.gauge(f"router/replicas_{role}").set(float(len(idxs)))
            reg.gauge(f"router/load_{role}").set(
                float(sum(self._load(i) for i in idxs)))

    # -- introspection -------------------------------------------------
    @property
    def num_replicas(self) -> int:
        return len(self.replicas)

    @property
    def alive_replicas(self) -> List[int]:
        return [i for i, a in enumerate(self._alive) if a]

    @property
    def live_count(self) -> int:
        return sum(r.live_count for i, r in enumerate(self.replicas)
                   if self._alive[i])

    @property
    def pending(self) -> int:
        return sum(r.scheduler.pending for i, r in enumerate(self.replicas)
                   if self._alive[i])

    @property
    def num_slots(self) -> int:
        """Total decode capacity across the alive fleet (the frontend's
        ``/healthz`` probe reads this where a single engine would report
        ``pool.num_slots``)."""
        return sum(self.replicas[i].pool.num_slots
                   for i in self.alive_replicas)

    @property
    def step_id(self) -> int:
        """Fleet progress marker: the furthest replica's step counter."""
        return max((self.replicas[i].step_id
                    for i in self.alive_replicas), default=0)

    @property
    def health_state(self) -> str:
        """Aggregate fleet load state for the frontend. Admission needs
        a prefill-capable replica and the router dispatches to the
        least-loaded one, so the fleet is only overloaded when EVERY
        prefill-capable replica is."""
        order = {"healthy": 0, "pressured": 1, "overloaded": 2}
        states = []
        for i in self.prefill_capable:
            lm = getattr(self.replicas[i], "_load", None)
            states.append(lm.state.name.lower() if lm is not None
                          else "healthy")
        if not states:
            return "overloaded"
        return min(states, key=lambda s: order.get(s, 0))

    def has_work(self) -> bool:
        """Any alive replica holding queued, prefilling or running work —
        the bridge's step-gate probe (duck-typed: it prefers a callable
        ``has_work`` over reading engine internals)."""
        return any(
            r.live_count or r.scheduler.pending
            or getattr(r, "_prefill_queue", None)
            for i, r in enumerate(self.replicas) if self._alive[i])

    def _now(self) -> float:
        return self.replicas[0]._now()

    # -- roles ---------------------------------------------------------
    def _role_indices(self, role: str) -> List[int]:
        return [i for i in self.alive_replicas if self.roles[i] == role]

    @property
    def prefill_capable(self) -> List[int]:
        """Alive replicas that can run admission ('prefill' or 'both')."""
        return [i for i in self.alive_replicas
                if self.roles[i] in ("prefill", "both")]

    @property
    def decode_capable(self) -> List[int]:
        """Alive replicas that can run the decode loop."""
        return [i for i in self.alive_replicas
                if self.roles[i] in ("decode", "both")]

    # -- request journeys (ISSUE 20) -----------------------------------
    _TERMINAL_HOPS = ("finish", "reject", "cancel", "failed")

    def _mint_journey(self, req: Request) -> int:
        """Trace context for one request: a fleet-unique journey id
        (its own counter — request ids are striped per replica, so
        replica 0's ids would collide with a unified journey space)."""
        jid = self._journey_seq
        self._journey_seq += 1
        self._journeys[jid] = {"id": jid, "request_id": req.request_id,
                               "hops": [], "homes": [], "terminal": None}
        while len(self._journeys) > self._journey_capacity:
            _, old = self._journeys.popitem(last=False)
            self._rid_journey.pop(old["request_id"], None)
        self._rid_journey[req.request_id] = jid
        req.journey_id = jid
        return jid

    def _hop(self, req: Request, kind: str,
             replica: Optional[int] = None, **attrs) -> None:
        """Append one replica-boundary crossing to the request's
        journey (dispatch, transfer, failover, terminal). Self-timed:
        this is the router's only hot-path observability cost, and the
        fleet ``overhead_pct`` must charge it honestly."""
        t0 = time.perf_counter_ns()
        jid = req.journey_id
        rec = self._journeys.get(jid) if jid is not None else None
        if rec is not None:
            req.hop += 1
            hop = {"kind": kind, "hop": req.hop, "t": self._now(),
                   "replica": replica}
            hop.update(attrs)
            rec["hops"].append(hop)
            if replica is not None and replica not in rec["homes"]:
                rec["homes"].append(replica)
            if kind in self._TERMINAL_HOPS:
                rec["terminal"] = kind
        self._journey_ns += time.perf_counter_ns() - t0

    @property
    def journey_overhead_s(self) -> float:
        return self._journey_ns / 1e9

    def journey_of(self, request_id: int) -> Optional[int]:
        """Journey id for a request id (None once evicted/unknown)."""
        return self._rid_journey.get(request_id)

    def journey(self, journey_id: int) -> Optional[dict]:
        """The STITCHER: merge one journey's cross-replica record.

        Returns the router's hop log plus every home replica's timeline
        events for the request — each event stamped with its replica —
        in one list ordered on the shared ``perf_counter_ns`` clock
        (router hops carry the injected-clock ``t``, converted to ns on
        the same epoch when the default clock is in use). ``complete``
        is the fleet-truth probe: a terminal hop was recorded AND no
        home's timeline is still open or parked mid-handoff — a request
        stranded between homes is complete on NEITHER."""
        rec = self._journeys.get(journey_id)
        if rec is None:
            return None
        rid = rec["request_id"]
        events: List[dict] = []
        open_homes: List[int] = []
        parked_homes: List[int] = []
        for i, rep in enumerate(self.replicas):
            tl = rep.timelines.get(rid)
            if not tl:
                continue
            for e in tl:
                events.append({"t_ns": e["t_ns"], "replica": i,
                               "source": "timeline",
                               "event": e["event"], "attrs": e["attrs"]})
            if rep.timelines.is_open(rid):
                open_homes.append(i)
            if rid in rep.timelines.parked_ids():
                parked_homes.append(i)
        for h in rec["hops"]:
            events.append({"t_ns": int(h["t"] * 1e9),
                           "replica": h.get("replica"),
                           "source": "router", "event": h["kind"],
                           "attrs": {k: v for k, v in h.items()
                                     if k not in ("kind", "t")}})
        events.sort(key=lambda e: e["t_ns"])
        complete = (rec["terminal"] is not None
                    and not open_homes and not parked_homes)
        return {"id": journey_id, "request_id": rid,
                "hops": list(rec["hops"]), "homes": list(rec["homes"]),
                "terminal": rec["terminal"], "events": events,
                "complete": complete, "open_homes": open_homes,
                "parked_homes": parked_homes}

    def journey_summary(self) -> dict:
        """Fleet completeness rollup: of the journeys that reached a
        terminal hop, how many stitch COMPLETE (every home's timeline
        closed, none parked). The ``--require-complete-journeys`` gate
        holds ``complete == finished``."""
        finished = complete = 0
        incomplete: List[int] = []
        for jid, rec in list(self._journeys.items()):
            if rec["terminal"] is None:
                continue
            finished += 1
            j = self.journey(jid)
            if j is not None and j["complete"]:
                complete += 1
            else:
                incomplete.append(jid)
        return {"total": len(self._journeys), "finished": finished,
                "complete": complete, "incomplete": incomplete[:16]}

    def recent_journeys(self, n: int = 32) -> List[dict]:
        """Tail of the journey log (hops only, no timeline merge) — the
        router's dispatch record inside the fleet post-mortem."""
        out = []
        for jid in list(self._journeys)[-n:]:
            rec = self._journeys[jid]
            out.append({"id": jid, "request_id": rec["request_id"],
                        "homes": list(rec["homes"]),
                        "terminal": rec["terminal"],
                        "hops": list(rec["hops"])})
        return out

    def export_trace(self, path: str) -> int:
        """Write ONE merged Perfetto document for the whole fleet: the
        router's lane first (dispatch spans, scale/failover instants),
        then one process lane per replica; flow arrows drawn at every
        handoff/transfer/failover pair render across lanes. Returns
        the event count."""
        tracers: List[Tuple[str, Tracer]] = [("router", self.tracer)]
        for i, rep in enumerate(self.replicas):
            tracers.append((f"replica{i}:{self.roles[i]}", rep.tracer))
        return export_merged(path, tracers)

    def _classify_failure(self, error: BaseException) -> str:
        if isinstance(error, InvariantViolation):
            return "invariant_violation"
        if isinstance(error, ServingStalledError):
            return "stalled"
        if isinstance(error, RecompileAfterWarmupError):
            return "recompile_after_warmup"
        return "replica_error"

    # -- dispatch ------------------------------------------------------
    def _load(self, i: int) -> int:
        r = self.replicas[i]
        return r.live_count + r.scheduler.pending

    def _rank(self, prompt, session: Optional[str]) -> List[int]:
        """Replica indices in dispatch-preference order. Admission (and
        failover re-admission, which re-prefills) only ever lands on
        prefill-capable replicas; decode-only replicas receive work
        exclusively through the handoff path."""
        alive = self.prefill_capable
        if not alive:
            if not self.alive_replicas:
                raise NoLiveReplicaError("all replicas have failed")
            raise NoLiveReplicaError("no prefill-capable replica alive")
        if session is not None:
            home = self._session.get(session)
            if home is not None and self._alive[home]:
                self.affinity_hits += 1
                return [home] + [i for i in alive if i != home]
        scores = {i: 0 for i in alive}
        if self.affinity:
            for i in alive:
                trie = getattr(self.replicas[i].pool, "prefix", None)
                if trie is not None:
                    scores[i] = int(trie.peek(prompt))
        # sort: longest prefix hit, then least loaded, then lowest index
        ranked = sorted(alive, key=lambda i: (-scores[i], self._load(i), i))
        if scores[ranked[0]] > 0:
            self.affinity_hits += 1
        return ranked

    def submit(self, prompt, session: Optional[str] = None,
               **kwargs: Any) -> Request:
        """Route one request. Same contract as ``ServingEngine.submit``
        (never raises on load; REJECTED carries a reason), plus
        ``session=`` stickiness. A rejection by the preferred replica
        spills to the next-ranked sibling; the LAST rejection is
        returned only when every alive replica refused."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        ranked = self._rank(prompt, session)
        req: Optional[Request] = None
        for n, i in enumerate(ranked):
            req = self.replicas[i].submit(prompt, **kwargs)
            if req.state is not RequestState.REJECTED:
                if n > 0:
                    self.spills += 1
                self.dispatched[i] += 1
                self._owner[req.request_id] = i
                self._tracked[req.request_id] = req
                if session is not None:
                    self._session[session] = i
                    self._req_session[req.request_id] = session
                self._mint_journey(req)
                self._hop(req, "dispatch", replica=i, spills=n)
                return req
        if req is not None:
            # every replica rejected: the journey still exists (and is
            # terminal) so a refused request audits like any other
            self._mint_journey(req)
            self._hop(req, "reject",
                      reason=str(req.reject_reason)
                      if req.reject_reason else None)
        return req  # every replica rejected: surface the last verdict

    # -- stepping ------------------------------------------------------
    def step(self) -> List[Request]:
        """One iteration of every alive replica. A replica that raises is
        retired and its requests fail over to the ranked siblings; the
        error is contained, not propagated (mirrors a multi-host serving
        tier losing one worker). Raises :class:`NoLiveReplicaError` only
        when no replica survives to inherit the work."""
        finished: List[Request] = []
        for i, rep in enumerate(self.replicas):
            if not self._alive[i]:
                continue
            try:
                finished.extend(rep.step())
            except Exception as e:
                self._alive[i] = False
                # ONE fleet-scoped post-mortem before the scrub mutates
                # anything: every replica's ring + the router's journey
                # and scale log, trigger replica marked
                self.fleet.dump(self._classify_failure(e), error=e,
                                trigger_replica=i)
                self.tracer.instant("router/replica_failed", replica=i,
                                    reason=self._classify_failure(e))
                self._fail_over(i)
        self._drain_handoffs()
        for req in finished:
            self._hop(req, "finish",
                      replica=self._owner.get(req.request_id),
                      reason=str(req.finish_reason)
                      if req.finish_reason else None)
            self._tracked.pop(req.request_id, None)
            self._owner.pop(req.request_id, None)
            self._req_session.pop(req.request_id, None)
        if self.spawner is not None:
            self.maybe_autoscale(self.spawner)
        if not any(self._alive):
            raise NoLiveReplicaError("all replicas have failed")
        return finished

    def _fail_over(self, dead: int) -> None:
        """Re-home every request the dead replica still owed.

        The engine's own ``_abort_step`` has already rolled its state to
        one of three shapes — QUEUED in its scheduler, seated in
        ``_slot_req`` (when the failure bypassed the abort path), or
        FAILED with reason ``error`` — and ``check_invariants`` on the
        corpse is meaningless. The router scrubs each survivor back to a
        fresh QUEUED request (keeping ``output_tokens``: they are the
        resume seed) and re-submits through a sibling's admission
        control, so capacity limits still hold under failover."""
        rep = self.replicas[dead]
        owed: List[Request] = []
        seen: set = set()

        def _take(req: Request) -> None:
            if id(req) in seen:
                return
            seen.add(id(req))
            owed.append(req)

        for r in list(rep.scheduler.queue):
            _take(r)
        rep.scheduler.queue.clear()
        for r in list(rep._slot_req.values()):
            _take(r)
        rep._slot_req.clear()
        rep._prefill_queue[:] = []
        if getattr(rep, "_handoff_ready", None):
            rep._handoff_ready.clear()
        # FAILED-by-abort requests the router still tracks: the engine
        # already charged the failure, but the CLIENT contract is that a
        # replica loss is invisible — resurrect and re-home them too
        for rid, r in list(self._tracked.items()):
            if self._owner.get(rid) == dead \
                    and r.state is RequestState.FAILED \
                    and r.finish_reason is FinishReason.ERROR:
                _take(r)
        owed.sort(key=lambda r: r.request_id)  # oldest first, deterministic
        for r in owed:
            if r.state in (RequestState.FINISHED, RequestState.REJECTED):
                continue
            r.state = RequestState.QUEUED
            r.slot = None
            r.prefill_pos = 0
            r.admit_time = None
            r.finish_reason = None
            r.finish_time = None
            r.preemptions += 1
            placed = False
            for i in self._rank(r.seed_tokens, None):
                accepted, _ = self.replicas[i].scheduler.submit(r)
                if accepted:
                    self._owner[r.request_id] = i
                    self._tracked[r.request_id] = r
                    self.failovers += 1
                    placed = True
                    # close the corpse's timeline (terminal: nothing
                    # more will ever be recorded there) and open the
                    # re-home on the inheritor, flow arrow across lanes
                    rep.timelines.record(
                        r.request_id, "failed_over", terminal=True,
                        src_replica=dead, dst_replica=i,
                        journey=r.journey_id)
                    self.replicas[i].timelines.record(
                        r.request_id, "resumed", src_replica=dead,
                        dst_replica=i, journey=r.journey_id,
                        preemptions=r.preemptions)
                    if r.journey_id is not None:
                        rep.tracer.flow("s", "journey", r.journey_id,
                                        cat="journey")
                        self.replicas[i].tracer.flow(
                            "f", "journey", r.journey_id, cat="journey")
                    self._hop(r, "failover", replica=i, src=dead)
                    break
            if not placed:
                r.state = RequestState.FAILED
                r.finish_reason = FinishReason.ERROR
                r.finish_time = self._now()
                rep.timelines.record(r.request_id, "failed",
                                     terminal=True, src_replica=dead,
                                     journey=r.journey_id)
                self._hop(r, "failed", src=dead)
                self._tracked.pop(r.request_id, None)
                self._owner.pop(r.request_id, None)
        # sticky sessions homed on the corpse re-route on next submit
        for key, idx in list(self._session.items()):
            if idx == dead:
                del self._session[key]

    # -- disaggregated handoff orchestration ---------------------------
    def _first_page_index(self) -> Dict[tuple, int]:
        """The SHARED first-page index: first-page token tuple -> decode
        replica whose prefix trie caches it. Rebuilt from the alive
        decode pool's trie roots once per drain (root children ARE the
        first-page edges), so prefix-affine handoff placement scores
        hits across the WHOLE decode pool instead of one sticky
        replica. Ties go to the lowest index — deterministic routing."""
        index: Dict[tuple, int] = {}
        for i in self.decode_capable:
            trie = getattr(self.replicas[i].pool, "prefix", None)
            if trie is None:
                continue
            for key in trie.root.children:
                index.setdefault(key, i)
        return index

    def _pick_decode(self, req: Request,
                     index: Dict[tuple, int]) -> Optional[int]:
        """Decode replica for one handoff: sticky session first (the
        conversation's earlier turns already decoded there), then the
        shared first-page index (global prefix affinity — the transfer
        itself shrinks by every trie-hit page), then least loaded.
        Only replicas with a free slot qualify; ``None`` means park the
        request and retry next step."""
        ready = [i for i in self.decode_capable
                 if self.replicas[i].pool._free_set]
        if not ready:
            return None
        session = self._req_session.get(req.request_id)
        if session is not None:
            home = self._decode_session.get(session)
            if home in ready:
                self.affinity_hits += 1
                return home
        if self.affinity:
            seed = np.asarray(req.seed_tokens).reshape(-1)
            ps = getattr(self.replicas[ready[0]].pool, "page_size", 0)
            if ps and len(seed) >= ps:
                key = tuple(int(t) for t in seed[:ps])
                home = index.get(key)
                if home in ready:
                    self.prefix_routed += 1
                    return home
        return min(ready, key=lambda i: (self._load(i), i))

    def _transfer(self, req: Request, src_idx: int,
                  index: Dict[tuple, int]) -> bool:
        """Move one parked request from prefill replica ``src_idx`` to a
        decode replica: ``adopt`` copies+seats the pages over there,
        ``finish_handoff`` releases the source seat. The in-flight
        counter brackets exactly this window — it must be zero again at
        every step boundary. A failed adopt leaves the request parked
        on the source (nothing seated on the destination — adopt
        unwinds) for retry; a destination WEDGED enough to raise is
        retired through the same path as a step failure."""
        src = self.replicas[src_idx]
        dst_idx = self._pick_decode(req, index)
        if dst_idx is None:
            return False
        dst = self.replicas[dst_idx]
        src_slot = req.slot
        jid = req.journey_id
        self._transfers_in_flight += 1
        if jid is not None:
            # flow start on the SOURCE lane; the finish lands on the
            # destination lane after adoption — the arrow crosses the
            # process boundary in the merged export
            src.tracer.flow("s", "journey", jid, cat="journey")
        try:
            with self.tracer.span("router/transfer", journey=jid,
                                  src=src_idx, dst=dst_idx,
                                  request=req.request_id):
                stats = dst.adopt(req, src)
        except Exception as e:
            # mid-transfer death: adopt already unwound every page it
            # touched on the destination; the request is STILL seated on
            # the source, still parked, and retries on a sibling
            self._alive[dst_idx] = False
            self.fleet.dump(self._classify_failure(e), error=e,
                            trigger_replica=dst_idx)
            self._fail_over(dst_idx)
            return False
        finally:
            self._transfers_in_flight -= 1
        src.finish_handoff(req, src_slot, dst_replica=dst_idx)
        if jid is not None:
            dst.tracer.flow("f", "journey", jid, cat="journey")
        self._owner[req.request_id] = dst_idx
        self.transfers += 1
        wire_bytes = int(stats["bytes"])
        self.transfer_bytes += wire_bytes
        self.transfer_pages_saved += int(stats.get("hit_pages", 0))
        self.transfer_latency.add(stats["seconds"] * 1e3)
        self.registry.histogram("router/transfer_ms").observe(
            stats["seconds"] * 1e3)
        self.registry.histogram("router/transfer_pages",
                                buckets=(1, 2, 4, 8, 16, 32, 64)).observe(
            float(stats["pages"]))
        self.registry.histogram(
            "router/transfer_wire_bytes",
            buckets=(1024, 4096, 16384, 65536, 262144, 1048576,
                     4194304, 16777216)).observe(float(wire_bytes))
        self._hop(req, "transfer", replica=dst_idx, src=src_idx,
                  pages=int(stats["pages"]),
                  hit_pages=int(stats.get("hit_pages", 0)),
                  bytes=wire_bytes, ms=stats["seconds"] * 1e3)
        session = self._req_session.get(req.request_id)
        if session is not None:
            self._decode_session[session] = dst_idx
        return True

    def _drain_handoffs(self) -> None:
        """After every fleet step: hand each prefill replica's finished
        prefills to the decode pool. Transfers complete synchronously
        here (the engines' step loops never observe a half-moved
        request)."""
        srcs = [i for i in self.alive_replicas
                if self.roles[i] == "prefill"
                and self.replicas[i].pending_handoffs()]
        if not srcs:
            return
        index = self._first_page_index() if self.affinity else {}
        for i in srcs:
            if not self._alive[i]:
                continue  # retired by a failover during this drain
            for req in self.replicas[i].pending_handoffs():
                if self._transfer(req, i, index):
                    # the adopted prompt is now cached on the destination
                    # trie; keep the index current within this drain
                    if self.affinity:
                        index = self._first_page_index()

    # -- elasticity ----------------------------------------------------
    def add_replica(self, replica: ServingEngine,
                    role: Optional[str] = None) -> int:
        """Scale-out: join a replica to the rotation at runtime. The
        newcomer must arrive TRAFFIC-WARMED (its provisioner drove a
        warm sweep through every program family it will serve, the same
        way the benches warm an arm before ``end_warmup``): when the
        fleet is already past warmup the newcomer's watchdog arms
        immediately, so a scale event compiles NOTHING post-warmup
        (pinned by test). Returns the new replica index."""
        role = role if role is not None else getattr(replica, "role", "both")
        if role not in ("both", "prefill", "decode"):
            raise ValueError(f"unknown replica role {role!r}")
        i = len(self.replicas)
        replica._next_id += i * ID_STRIDE
        self.replicas.append(replica)
        self._alive.append(True)
        self.roles.append(role)
        self.dispatched.append(0)
        self._join_observability(i, replica)
        if self._warmed:
            replica.end_warmup()
        self._record_scale("add", i, role)
        return i

    def retire_replica(self, i: int) -> None:
        """Scale-in: drain replica ``i`` through the failover scrub
        (every request it owes — queued, mid-prefill, decoding, parked
        for handoff — re-homes on a sibling with its generated tokens
        as the resume seed; greedy output is bitwise identical) and
        remove it from rotation. Refuses to retire the last replica of
        a needed capability."""
        if not (0 <= i < len(self.replicas)) or not self._alive[i]:
            raise ValueError(f"replica {i} is not alive")
        survivors = [j for j in self.alive_replicas if j != i]
        if not survivors:
            raise ValueError("cannot retire the last alive replica")
        roles_left = [self.roles[j] for j in survivors]
        if not any(r in ("both", "prefill") for r in roles_left):
            raise ValueError("cannot retire the last prefill-capable "
                             "replica")
        if any(r != "both" for r in roles_left + [self.roles[i]]) \
                and not any(r in ("both", "decode") for r in roles_left):
            raise ValueError("cannot retire the last decode-capable "
                             "replica")
        self._alive[i] = False
        self._fail_over(i)
        self._record_scale("retire", i, self.roles[i])

    def _record_scale(self, action: str, idx: int, role: str) -> None:
        event = {"action": action, "replica": idx, "role": role,
                 "time": self._now(),
                 "fleet_size": len(self.alive_replicas)}
        self.scale_events.append(event)
        self.last_scale_event = event
        # instant marker on the router lane: scale events punctuate the
        # merged fleet trace alongside the journeys they reshape
        self.tracer.instant("router/scale", action=action, replica=idx,
                            role=role,
                            fleet_size=len(self.alive_replicas))

    def _role_hot(self, role: str, idxs: List[int]) -> bool:
        """Sustained-overload signal for one role: any replica paging on
        its burn-rate tracker, or (when no SLO tracker is configured)
        saturated slots with a backlog. A decode role's backlog is the
        fleet's PARKED HANDOFFS — pages filled upstream that cannot
        seat downstream — since the router never queues fresh
        submissions on a decode-only replica."""
        parked = sum(len(self.replicas[j].pending_handoffs())
                     for j in self.prefill_capable)
        for i in idxs:
            rep = self.replicas[i]
            slo = getattr(rep, "slo", None)
            if slo is not None and slo.alert_state == "page":
                return True
            backlog = rep.scheduler.pending
            if role in ("decode", "both"):
                backlog += parked
            if rep.live_count >= rep.pool.num_slots and backlog > 0:
                return True
        return False

    def _role_idle(self, idxs: List[int]) -> bool:
        return all(self._load(i) == 0
                   and not self.replicas[i].pending_handoffs()
                   for i in idxs)

    def maybe_autoscale(self, spawn) -> List[dict]:
        """One elasticity decision pass (called each step when a
        ``spawner`` is configured, or directly by an external control
        loop). Per role: ``scale_patience`` consecutive hot checks →
        ``spawn(role)`` joins a new replica of that role;
        ``scale_patience`` consecutive idle checks with spare capacity
        → the highest-indexed idle replica retires. Returns the scale
        events this pass produced."""
        before = len(self.scale_events)
        for role in ("prefill", "decode", "both"):
            idxs = self._role_indices(role)
            if not idxs:
                continue
            if self._role_hot(role, idxs):
                self._hot_streak[role] = self._hot_streak.get(role, 0) + 1
                self._idle_streak[role] = 0
                if self._hot_streak[role] >= self.scale_patience:
                    self.add_replica(spawn(role), role)
                    self._hot_streak[role] = 0
            elif self._role_idle(idxs):
                self._idle_streak[role] = self._idle_streak.get(role, 0) + 1
                self._hot_streak[role] = 0
                if self._idle_streak[role] >= self.scale_patience \
                        and len(idxs) > 1:
                    self.retire_replica(idxs[-1])
                    self._idle_streak[role] = 0
            else:
                self._hot_streak[role] = 0
                self._idle_streak[role] = 0
        return self.scale_events[before:]

    def fleet_topology(self) -> dict:
        """The ``/healthz`` fleet block: per-role alive counts, transfer
        progress, and the most recent scale event."""
        return {
            "roles": {role: self._role_indices(role)
                      for role in ("prefill", "decode", "both")
                      if self._role_indices(role)},
            "counts": {role: len(self._role_indices(role))
                       for role in ("prefill", "decode", "both")},
            "fleet_size": len(self.alive_replicas),
            "transfers_in_flight": self._transfers_in_flight,
            "transfers_total": self.transfers,
            "prefix_routed_total": self.prefix_routed,
            "last_scale_event": self.last_scale_event,
        }

    def run_until_drained(self, max_steps: Optional[int] = None,
                          stall_patience: Optional[int] = None
                          ) -> List[Request]:
        """Step until no alive replica has work (mirror of the engine
        method; ``stall_patience`` is accepted for signature parity but
        stall detection lives in each replica)."""
        del stall_patience
        out: List[Request] = []
        steps = 0
        while self.has_work():
            out.extend(self.step())
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        return out

    # -- per-request / lifecycle ---------------------------------------
    def cancel(self, request_id: int) -> Optional[Request]:
        idx = self._owner.get(request_id)
        if idx is None or not self._alive[idx]:
            return None
        req = self.replicas[idx].cancel(request_id)
        if req is not None:
            self._hop(req, "cancel", replica=idx)
            self._tracked.pop(request_id, None)
            self._owner.pop(request_id, None)
        return req

    def end_warmup(self) -> None:
        self._warmed = True
        for i in self.alive_replicas:
            self.replicas[i].end_warmup()

    def check_invariants(self) -> None:
        """Cross-replica audit: every ALIVE replica's slot/queue/pool
        bookkeeping must hold (dead replicas are tombstones — their
        state was deliberately stripped by failover)."""
        # ownership entries may not outlive tracking: _owner and
        # _tracked are populated and retired together, so a stale
        # _owner key is an unbounded host-side leak
        try:
            stale = set(self._owner) - set(self._tracked)
            if stale:
                raise AssertionError(
                    f"router _owner map holds {len(stale)} request id(s) "
                    f"no longer tracked: {sorted(stale)[:5]}")
            # transfers are synchronous inside one drain: any in-flight
            # count surviving to a step boundary is an accounting leak
            if self._transfers_in_flight:
                raise AssertionError(
                    f"{self._transfers_in_flight} page transfer(s) still "
                    f"in flight at a step boundary")
        except AssertionError as e:
            self.fleet.dump("invariant_violation", error=e)
            raise
        for i in self.alive_replicas:
            rep = self.replicas[i]
            try:
                # every parked handoff must belong to a prefill-role
                # replica the router still tracks — an untracked parked
                # request can never be adopted and would pin its slot
                # forever
                for r in rep.pending_handoffs():
                    if self.roles[i] != "prefill":
                        raise AssertionError(
                            f"replica {i} (role {self.roles[i]}) holds "
                            f"parked handoff {r.request_id}")
                    if self._tracked.get(r.request_id) is not r:
                        raise AssertionError(
                            f"parked handoff {r.request_id} on replica "
                            f"{i} is not router-tracked")
                rep.check_invariants()
            except AssertionError as e:
                # a violated invariant ANYWHERE is a fleet event: dump
                # every ring, mark the replica that tripped
                self.fleet.dump("invariant_violation", error=e,
                                trigger_replica=i)
                raise

    @property
    def recompiles(self) -> int:
        """Post-warmup recompiles summed over alive replicas' watchdogs."""
        total = 0
        for i in self.alive_replicas:
            wd = self.replicas[i].watchdog
            if wd is not None:
                total += wd.recompiles
        return total

    def stats(self) -> dict:
        """Router-level counters plus each alive replica's SLO snapshot."""
        return {
            "replicas": self.num_replicas,
            "alive": self.alive_replicas,
            "roles": list(self.roles),
            "dispatched": list(self.dispatched),
            "affinity_hits": self.affinity_hits,
            "spills": self.spills,
            "failovers": self.failovers,
            "transfers": self.transfers,
            "transfer_bytes": self.transfer_bytes,
            "transfer_pages_saved": self.transfer_pages_saved,
            "prefix_routed": self.prefix_routed,
            "scale_events": len(self.scale_events),
            "fleet": self.fleet_topology(),
            "journeys": self.journey_summary(),
            "router_metrics": self.registry.snapshot(),
            "per_replica": {i: self.replicas[i].stats()
                            for i in self.alive_replicas},
        }
