"""Continuous-batching serving subsystem.

Turns :class:`~deepspeed_tpu.inference.engine.InferenceEngine` from a
whole-batch decoder into a request-level server: a FIFO admission queue
(:mod:`.scheduler`), a fixed-shape slot pool of per-slot KV cache sized
from the module's declared :func:`kv_cache_spec` (:mod:`.slot_pool`),
iteration-level scheduling with per-request SLO metrics
(:mod:`.engine`, :mod:`.metrics`), optional draft–verify speculative
decoding over the same fixed shapes (:mod:`.spec_decode`), the
fault-tolerance layer — deadlines, preemption, graceful degradation,
deterministic fault injection (:mod:`.resilience`) — paged KV with
refcounted copy-on-write prefix caching (:mod:`.paged_pool`,
:mod:`.prefix_cache`; ``paged_kv=True``), and the async network front
end — HTTP/SSE server, step-thread bridge, priority/tenant scheduling
(:mod:`.frontend`; ``priority=True``).
Entry point: ``deepspeed_tpu.init_serving(...)`` or
:class:`ServingEngine` directly.
"""

from .engine import ServingEngine  # noqa: F401
from .metrics import ServingMetrics  # noqa: F401
from .paged_pool import PagedKVPool, PagePoolExhausted  # noqa: F401
from .prefix_cache import PrefixCache  # noqa: F401
from .request import (FinishReason, RejectReason, Request,  # noqa: F401
                      RequestState)
from .resilience import (DegradationConfig, FaultInjector,  # noqa: F401
                         InjectedFault, InvariantViolation, LoadState,
                         ServingStalledError)
from .router import (ID_STRIDE, NoLiveReplicaError,  # noqa: F401
                     ReplicaRouter)
from .scheduler import FIFOScheduler  # noqa: F401
from .slot_pool import SlotPool  # noqa: F401
from .spec_decode import (  # noqa: F401
    Drafter, NGramDrafter, SmallModelDrafter, SpecDecodeConfig)
from .frontend import (AsyncEngineBridge, PriorityConfig,  # noqa: F401
                       PriorityScheduler, ServingFrontend, TenantPolicy,
                       TokenStream)

__all__ = ["ServingEngine", "ServingMetrics", "Request", "RequestState",
           "FinishReason", "RejectReason", "FIFOScheduler", "SlotPool",
           "PagedKVPool", "PagePoolExhausted", "PrefixCache",
           "SpecDecodeConfig", "Drafter", "NGramDrafter",
           "SmallModelDrafter", "DegradationConfig", "FaultInjector",
           "InjectedFault", "InvariantViolation", "LoadState",
           "ServingStalledError", "ReplicaRouter", "NoLiveReplicaError",
           "ID_STRIDE", "AsyncEngineBridge", "TokenStream",
           "PriorityScheduler", "PriorityConfig", "TenantPolicy",
           "ServingFrontend"]
