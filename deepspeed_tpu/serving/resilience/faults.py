"""Deterministic fault injection for the serving engine.

Chaos testing only proves anything if a failing run can be REPLAYED:
the injector is therefore fully deterministic — every injection point
draws from its own seeded generator (stream identity keyed by a stable
CRC of the point name, never by Python's salted ``hash``), and an
explicit ``schedule`` can pin faults to exact call ordinals ("fail the
3rd admission") independent of wall clock. The engine threads one
injector through its hot path at five named points:

``admit_oom``
    raised inside ``_admit``/``_admit_batch`` after the slot is taken,
    before any request state is committed — exercises the PR-2
    admission rollback (slot returned, request re-queued at the head).
``drafter_error``
    raised from the drafter's ``propose`` (via
    :class:`FaultInjectingDrafter`) — exercises the exception-safe
    step abort with speculative decoding enabled.
``nan_logits``
    overwrites ONE live slot's decode logits row with NaN — exercises
    the per-slot numerics guard (only the poisoned request fails).
``step_host_error``
    raised on the host between admission and decode — exercises the
    mid-step abort path while requests are RUNNING.
``slow_dispatch``
    sleeps ``slow_ms`` inside the step — exercises the step wall-time
    watchdog and the load-state machine's latency signal.
``state_corruption``
    fires at the step boundary; the engine responds by deliberately
    corrupting its own slot bookkeeping (a seated slot marked free, or
    a free slot leaked) — exercises the ``check_invariants()`` audit
    and the flight-recorder post-mortem path with REAL corruption, the
    one failure class the other points are designed never to cause.

A point that raises uses :class:`InjectedFault` (a ``RuntimeError``
subclass) so harnesses can catch *injected* failures precisely while
real bugs still propagate.
"""

from __future__ import annotations

import time
import zlib
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

#: every injection point the engine threads the injector through
POINTS = ("admit_oom", "drafter_error", "nan_logits", "step_host_error",
          "slow_dispatch", "state_corruption")


class InjectedFault(RuntimeError):
    """An exception raised on purpose by a :class:`FaultInjector`."""

    def __init__(self, point: str, nth: int):
        super().__init__(f"injected fault at '{point}' (call #{nth})")
        self.point = point
        self.nth = nth


class FaultInjector:
    """Seeded, replayable fault source with named injection points.

    Two firing modes compose per point:

    * ``schedule={point: [call ordinals]}`` — fire on exactly those
      1-based calls of the point (the chaos bench's fixed schedule);
    * ``rates={point: p}`` — fire each call with probability ``p`` from
      the point's own seeded stream (soak testing).

    ``counts``/``fired`` expose per-point call and fire totals so a
    harness can assert every scheduled fault actually landed.
    """

    def __init__(self, seed: int = 0,
                 rates: Optional[Dict[str, float]] = None,
                 schedule: Optional[Dict[str, Iterable[int]]] = None,
                 slow_ms: float = 2.0):
        self.seed = int(seed)
        if slow_ms < 0:
            raise ValueError(f"slow_ms must be >= 0, got {slow_ms}")
        self.slow_ms = float(slow_ms)
        self.rates: Dict[str, float] = {}
        for point, rate in (rates or {}).items():
            self._check_point(point)
            if not 0.0 <= float(rate) <= 1.0:
                raise ValueError(f"rate for '{point}' must be in [0, 1], "
                                 f"got {rate}")
            self.rates[point] = float(rate)
        self.schedule: Dict[str, set] = {}
        self.counts: Dict[str, int] = {p: 0 for p in POINTS}
        self.fired: Dict[str, int] = {p: 0 for p in POINTS}
        # one independent deterministic stream per point: firing order at
        # one point can never perturb another point's draws
        self._rngs = {p: np.random.default_rng(
            (self.seed, zlib.crc32(p.encode()))) for p in POINTS}
        if schedule:
            self.load_schedule(schedule, reset_counts=False)

    @staticmethod
    def _check_point(point: str) -> None:
        if point not in POINTS:
            raise ValueError(f"unknown injection point '{point}'; expected "
                             f"one of {POINTS}")

    # ------------------------------------------------------------------
    def load_schedule(self, schedule: Dict[str, Iterable[int]],
                      reset_counts: bool = True) -> None:
        """(Re)arm the ordinal schedule — e.g. keep the injector quiet
        through warmup, then load the measured run's fault plan."""
        armed: Dict[str, set] = {}
        for point, ordinals in schedule.items():
            self._check_point(point)
            armed[point] = {int(n) for n in ordinals}
            if any(n < 1 for n in armed[point]):
                raise ValueError(f"schedule ordinals are 1-based; got "
                                 f"{sorted(armed[point])} for '{point}'")
        self.schedule = armed
        if reset_counts:
            self.counts = {p: 0 for p in POINTS}

    def _roll(self, point: str) -> bool:
        self._check_point(point)
        self.counts[point] += 1
        hit = self.counts[point] in self.schedule.get(point, ())
        rate = self.rates.get(point, 0.0)
        if rate:
            # always consume the draw so the stream stays aligned
            # whether or not the schedule already fired this call
            hit = bool(self._rngs[point].random() < rate) or hit
        if hit:
            self.fired[point] += 1
        return hit

    # -- the point APIs the engine calls -------------------------------
    def check(self, point: str) -> None:
        """Raise :class:`InjectedFault` if ``point`` fires this call."""
        if self._roll(point):
            raise InjectedFault(point, self.counts[point])

    def fires(self, point: str) -> bool:
        """Non-raising roll: returns whether ``point`` fires this call.
        For points whose effect the CALLER applies (state_corruption)."""
        return self._roll(point)

    def maybe_sleep(self, point: str = "slow_dispatch") -> bool:
        """Sleep ``slow_ms`` if ``point`` fires; returns whether it did."""
        if self._roll(point):
            time.sleep(self.slow_ms / 1e3)
            return True
        return False

    def corrupt_logits(self, logits: Any, rows: Sequence[int]
                       ) -> Tuple[Any, Optional[int]]:
        """Poison one row of a (num_slots, ...) logits batch with NaN.

        ``rows`` are the LIVE slot ids (dead slots are padding nobody
        reads — poisoning them would test nothing). Returns the
        (possibly corrupted) logits and the poisoned slot id, or
        ``(logits, None)`` when the point does not fire."""
        if not rows or not self._roll("nan_logits"):
            return logits, None
        import jax
        import jax.numpy as jnp
        pick = int(self._rngs["nan_logits"].integers(len(rows)))
        slot = int(rows[pick])
        host = np.array(logits, copy=True)
        host[slot] = np.nan
        poisoned = jnp.asarray(host, dtype=logits.dtype)
        # re-commit to the original array's placement: a bare host
        # upload has different sharding/layout than the jitted decode
        # output, and THAT (not shape) would recompile every downstream
        # program on the injection step — the chaos row's zero-recompile
        # gate must measure the engine, not the injector
        if getattr(logits, "sharding", None) is not None:
            poisoned = jax.device_put(poisoned, logits.sharding)
        return poisoned, slot

    def summary(self) -> Dict[str, Dict[str, int]]:
        return {"counts": dict(self.counts), "fired": dict(self.fired)}


class FaultInjectingDrafter:
    """Drafter wrapper that threads the ``drafter_error`` point through
    ``propose`` — the serving engine installs it around the configured
    drafter when a :class:`FaultInjector` is attached, so drafter
    failures surface exactly where a real drafter would throw (inside
    the speculative step, after admission, before verify)."""

    def __init__(self, inner: Any, injector: FaultInjector):
        self.inner = inner
        self.injector = injector

    @property
    def name(self) -> str:
        return getattr(self.inner, "name", "drafter")

    def propose(self, histories: List[Optional[np.ndarray]], k: int):
        self.injector.check("drafter_error")
        return self.inner.propose(histories, k)
