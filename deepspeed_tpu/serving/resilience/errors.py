"""Exception types of the serving resilience surface.

These are deliberately tiny and dependency-free so every layer
(engine, scheduler, pool, bench harness, tests) can raise and catch
them without import cycles.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional


class ServingStalledError(RuntimeError):
    """``run_until_drained`` detected that no request can make progress.

    Raised instead of spinning forever when consecutive steps change
    nothing (no token emitted, no prefill advanced, no admission, no
    retirement) while work is still queued or seated. Carries a dump of
    the stuck request states so the operator sees *what* is wedged, not
    just that something is.
    """

    def __init__(self, message: str, dump: Optional[List[Dict[str, Any]]] = None):
        super().__init__(message)
        self.dump = dump or []


class InvariantViolation(AssertionError):
    """``ServingEngine.check_invariants`` found inconsistent state.

    One exception carries EVERY violation found in the sweep (not just
    the first) — under injected faults the second violation is usually
    the informative one.
    """

    def __init__(self, violations: List[str]):
        self.violations = list(violations)
        super().__init__(
            "serving invariants violated:\n  - " + "\n  - ".join(self.violations))
