"""Victim selection for slot preemption.

When the engine must free a slot (queue pressure past the configured
threshold, or an operator calling ``preempt()``), the victim policy
decides WHO loses their seat. The policy is youngest / lowest-progress
first: preempting the request with the fewest generated tokens wastes
the least completed work (its whole history is re-prefilled on resume,
so sunk cost is proportional to progress), and among equals the most
recently admitted goes first (it has waited the least and its
re-queue-at-the-front costs the least extra latency).

Requests admitted fewer than ``min_run_steps`` steps ago are
ineligible — a freshly seated request must make SOME progress before
it can be bounced again, or pressure-preemption degenerates into
admission thrash that generates zero tokens.
"""

from __future__ import annotations

from typing import Iterable, List

from ..request import Request, RequestState

#: preemptable lifecycle states (QUEUED has no slot; terminal states
#: have nothing left to free)
PREEMPTABLE_STATES = (RequestState.RUNNING, RequestState.PREFILLING)


def select_victims(candidates: Iterable[Request], n: int = 1,
                   current_step: int = 0,
                   min_run_steps: int = 2) -> List[Request]:
    """Rank preemption candidates youngest/lowest-progress first and
    return up to ``n`` eligible victims.

    ``candidates`` are seated requests (RUNNING or PREFILLING);
    anything else is skipped. Eligibility additionally requires the
    request to have held its slot for at least ``min_run_steps``
    scheduler steps (``current_step - last_admit_step``)."""
    eligible = [
        r for r in candidates
        if r.state in PREEMPTABLE_STATES
        and (current_step - r.last_admit_step) >= min_run_steps]
    # fewest generated tokens first (least sunk work), then most recent
    # admission, then newest request id — a total, deterministic order
    eligible.sort(key=lambda r: (len(r.output_tokens), -r.last_admit_step,
                                 -r.request_id))
    return eligible[:max(n, 0)]
