"""Victim selection for slot preemption.

When the engine must free a slot (queue pressure past the configured
threshold, or an operator calling ``preempt()``), the victim policy
decides WHO loses their seat. The policy is youngest / lowest-progress
first: preempting the request with the fewest generated tokens wastes
the least completed work (its whole history is re-prefilled on resume,
so sunk cost is proportional to progress), and among equals the most
recently admitted goes first (it has waited the least and its
re-queue-at-the-front costs the least extra latency).

Requests admitted fewer than ``min_run_steps`` steps ago are
ineligible — a freshly seated request must make SOME progress before
it can be bounced again, or pressure-preemption degenerates into
admission thrash that generates zero tokens.
"""

from __future__ import annotations

from typing import Callable, Iterable, List, Optional

from ..request import Request, RequestState

#: preemptable lifecycle states (QUEUED has no slot; terminal states
#: have nothing left to free)
PREEMPTABLE_STATES = (RequestState.RUNNING, RequestState.PREFILLING)


def select_victims(candidates: Iterable[Request], n: int = 1,
                   current_step: int = 0,
                   min_run_steps: int = 2,
                   class_rank: Optional[Callable[[Request], int]] = None,
                   ) -> List[Request]:
    """Rank preemption candidates youngest/lowest-progress first and
    return up to ``n`` eligible victims.

    ``candidates`` are seated requests (RUNNING or PREFILLING);
    anything else is skipped. Eligibility additionally requires the
    request to have held its slot for at least ``min_run_steps``
    scheduler steps (``current_step - last_admit_step``).

    With ``class_rank`` (priority scheduling; maps a request to its
    class rank, 0 = highest priority), the LOWEST class is victimized
    first — rank dominates the sunk-work tiebreak, so an interactive
    request is never bounced while a batch request holds a slot."""
    eligible = [
        r for r in candidates
        if r.state in PREEMPTABLE_STATES
        and (current_step - r.last_admit_step) >= min_run_steps]
    # lowest priority class first (when ranked), then fewest generated
    # tokens (least sunk work), then most recent admission, then newest
    # request id — a total, deterministic order
    rank = class_rank if class_rank is not None else (lambda r: 0)
    eligible.sort(key=lambda r: (-rank(r), len(r.output_tokens),
                                 -r.last_admit_step, -r.request_id))
    return eligible[:max(n, 0)]
