"""Fault-tolerant serving: the robustness surface over the serving
engine.

The reference framework ships elasticity, retry/abort launcher paths
and checkpoint recovery; this package is the serving-side equivalent,
built as four coupled pieces the engine hooks into:

* request lifecycle hardening — per-request deadlines, a per-step
  wall-time watchdog, and a NaN/inf logits guard that fails only the
  poisoned slot (``engine.py`` hooks; reasons in ``request.py``);
* preemption — ``ServingEngine.preempt`` plus the automatic
  youngest/lowest-progress victim policy (:mod:`.preemption`);
* graceful degradation — the HEALTHY/PRESSURED/OVERLOADED load-state
  machine (:mod:`.degradation`);
* deterministic fault injection — seeded, schedulable failures at
  named engine points, for the chaos suite and the
  ``bench.py serving-chaos`` row (:mod:`.faults`).
"""

from .degradation import (DegradationConfig, LoadState,  # noqa: F401
                          LoadStateMachine)
from .errors import InvariantViolation, ServingStalledError  # noqa: F401
from .faults import (POINTS, FaultInjectingDrafter,  # noqa: F401
                     FaultInjector, InjectedFault)
from .preemption import select_victims  # noqa: F401

__all__ = ["DegradationConfig", "LoadState", "LoadStateMachine",
           "InvariantViolation", "ServingStalledError", "POINTS",
           "FaultInjector", "FaultInjectingDrafter", "InjectedFault",
           "select_victims"]
