"""Graceful-degradation load-state machine.

Overload handling is a LADDER, not a cliff: the state machine watches
the signals :class:`~deepspeed_tpu.serving.metrics.ServingMetrics`
already collects (queue depth, rolling inter-token step-gap p99) and
walks ``HEALTHY -> PRESSURED -> OVERLOADED`` as they worsen. Each rung
trades a little quality-of-service for stability, cheapest lever
first:

* ``PRESSURED`` — shrink the per-step prefill token budget toward one
  chunk: admissions slow down, live decode slots keep their latency.
* ``OVERLOADED`` — additionally suspend speculative drafting (the
  verify program still runs, with zero proposals — same shapes, no
  recompile) and shed NEW submissions with the ``retry_after`` reject
  reason so the queue stops growing.

Escalation is immediate (overload compounds per step); de-escalation
requires ``cooldown_steps`` consecutive calmer observations so the
server doesn't flap around a threshold. Every transition is reported
to the caller, which mirrors it into monitor events, the tracer (a
counter track + instants, so Perfetto shows the ladder), and metrics.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, Optional, Tuple


class LoadState(enum.IntEnum):
    """Ordered load levels; the int value is the monitor/trace encoding."""

    HEALTHY = 0
    PRESSURED = 1
    OVERLOADED = 2


@dataclasses.dataclass
class DegradationConfig:
    """Thresholds and dynamics of the load-state machine.

    ``queue_*`` compare against the admission queue depth;
    ``gap_p99_*_ms`` (optional) against the rolling p99 of whole-step
    inter-token gaps over the last ``window`` steps. A signal may be
    disabled by leaving its thresholds ``None``; the machine takes the
    WORST level any enabled signal reports.
    """

    queue_pressured: Optional[int] = 8
    queue_overloaded: Optional[int] = 16
    gap_p99_pressured_ms: Optional[float] = None
    gap_p99_overloaded_ms: Optional[float] = None
    window: int = 32             # step-gap samples in the rolling p99
    cooldown_steps: int = 8      # calm observations before de-escalating
    retry_after_s: float = 1.0   # hint stamped on shed requests

    @classmethod
    def from_value(cls, value: Any) -> Optional["DegradationConfig"]:
        """``None``/``False`` -> disabled, ``True`` -> defaults, dict ->
        overrides, instance -> itself."""
        if value is None or value is False:
            return None
        if value is True:
            cfg = cls()
        elif isinstance(value, cls):
            cfg = value
        elif isinstance(value, dict):
            unknown = set(value) - {f.name for f in dataclasses.fields(cls)}
            if unknown:
                raise ValueError(f"unknown degradation keys {sorted(unknown)}")
            cfg = cls(**value)
        else:
            raise TypeError(f"degradation must be None/bool/dict/"
                            f"DegradationConfig, got {type(value).__name__}")
        cfg.validate()
        return cfg

    def validate(self) -> None:
        for lo, hi, what in ((self.queue_pressured, self.queue_overloaded,
                              "queue"),
                             (self.gap_p99_pressured_ms,
                              self.gap_p99_overloaded_ms, "gap_p99")):
            if (lo is None) != (hi is None):
                raise ValueError(f"{what} thresholds must be set together "
                                 f"(got pressured={lo}, overloaded={hi})")
            if lo is not None and not 0 < lo <= hi:
                raise ValueError(f"need 0 < {what}_pressured ({lo}) <= "
                                 f"{what}_overloaded ({hi})")
        if self.queue_pressured is None and self.gap_p99_pressured_ms is None:
            raise ValueError("degradation enabled but every signal is "
                             "disabled (all thresholds None)")
        if self.window < 1 or self.cooldown_steps < 1:
            raise ValueError(f"window ({self.window}) and cooldown_steps "
                             f"({self.cooldown_steps}) must be >= 1")
        if self.retry_after_s < 0:
            raise ValueError(f"retry_after_s must be >= 0, "
                             f"got {self.retry_after_s}")


class LoadStateMachine:
    """Hysteretic HEALTHY/PRESSURED/OVERLOADED tracker (see module doc)."""

    def __init__(self, cfg: DegradationConfig):
        self.cfg = cfg
        self.state = LoadState.HEALTHY
        self._calm = 0
        # (step, old, new) history — the chaos bench reports it and the
        # tests assert the ladder was actually walked
        self.transitions: list = []

    # ------------------------------------------------------------------
    @staticmethod
    def _level(value: Optional[float], pressured: Optional[float],
               overloaded: Optional[float]) -> LoadState:
        if value is None or pressured is None:
            return LoadState.HEALTHY
        if value >= overloaded:
            return LoadState.OVERLOADED
        if value >= pressured:
            return LoadState.PRESSURED
        return LoadState.HEALTHY

    def classify(self, queue_depth: int,
                 gap_p99_ms: Optional[float]) -> LoadState:
        """Instantaneous level: the worst any enabled signal reports."""
        cfg = self.cfg
        return max(
            self._level(queue_depth, cfg.queue_pressured,
                        cfg.queue_overloaded),
            self._level(gap_p99_ms, cfg.gap_p99_pressured_ms,
                        cfg.gap_p99_overloaded_ms))

    def update(self, queue_depth: int, gap_p99_ms: Optional[float],
               step: int = 0) -> Optional[Tuple[LoadState, LoadState]]:
        """Feed one step's signals; returns ``(old, new)`` on a
        transition, ``None`` otherwise. Escalates immediately,
        de-escalates only after ``cooldown_steps`` consecutive calmer
        observations (straight to the observed level — a recovered
        server should not crawl back one rung per cooldown)."""
        desired = self.classify(queue_depth, gap_p99_ms)
        if desired > self.state:
            old, self.state = self.state, desired
            self._calm = 0
            self.transitions.append((step, old, desired))
            return (old, desired)
        if desired < self.state:
            self._calm += 1
            if self._calm >= self.cfg.cooldown_steps:
                old, self.state = self.state, desired
                self._calm = 0
                self.transitions.append((step, old, desired))
                return (old, desired)
        else:
            self._calm = 0
        return None
