"""Per-request SLO metrics, aggregated and emitted through the monitor.

Every finished request contributes its derived latencies (TTFT, queue
wait, per-token gap) to the aggregate; :meth:`ServingMetrics.snapshot`
reduces them to the serving-SLO quantiles (p50/p99 TTFT, req/s,
tokens/s) the benchmark row and dashboards report. When a
:class:`~deepspeed_tpu.monitor.monitor.Monitor` is attached, each
retirement writes ``serving/*`` events — the same ``(tag, value,
step)`` event path training metrics use, so the existing
TensorBoard/W&B/CSV/JSONL sinks pick serving traffic up with zero new
plumbing.

Every monitor event carries ONE step axis: the serving engine's
monotonic step counter (``step_fn``), so rejection, finish, and
speculative-efficiency series line up across sinks. (They used to mix
request ids and decode-step counts — useless for correlating a
rejection burst with the decode stall that caused it.) Standalone
instances without a ``step_fn`` fall back to ``decode_steps``.

When a :class:`~deepspeed_tpu.telemetry.MetricsRegistry` is attached,
the same observations also land in Prometheus-exportable
counters/histograms (``serving/*``).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from .request import FinishReason, RejectReason, Request


def _pct(values: List[float], q: float) -> Optional[float]:
    return float(np.percentile(np.asarray(values), q)) if values else None


class ServingMetrics:
    """Accumulates finished/rejected requests; reduces to SLO aggregates."""

    def __init__(self, monitor: Optional[Any] = None,
                 registry: Optional[Any] = None,
                 step_fn: Optional[Any] = None):
        self.monitor = monitor
        self.registry = registry
        self._step_fn = step_fn
        self.finished: List[Request] = []
        self.rejected: Dict[str, int] = {}
        self.failed: int = 0
        self.failed_reasons: Dict[str, int] = {}
        self.preempted: int = 0
        self.step_overruns: int = 0
        self.load_transitions: int = 0
        # decode-step aggregates (speculative decoding efficiency):
        # slot_steps counts (live slot, step) pairs so tokens/decode-step
        # is per-slot — plain decode pins it at exactly 1.0 and any
        # accepted draft pushes it above, regardless of batch occupancy
        self.decode_steps: int = 0
        self.decode_tokens: int = 0
        self.slot_steps: int = 0
        self.drafted: int = 0
        self.accepted_drafts: int = 0
        self.draft_time: float = 0.0
        self.step_time: float = 0.0
        # prefill/decode split (stall-free admission): stall_time is the
        # subset of prefill wall-time that ran while decode slots were
        # live — the head-of-line blocking the chunked/budgeted admission
        # policy exists to bound
        self.prefill_tokens: int = 0
        self.prefill_dispatches: int = 0
        self.prefill_time: float = 0.0
        self.stall_time: float = 0.0
        # paged-KV prefix cache (admission-time trie lookups): hit
        # tokens are seed tokens whose prefill was SKIPPED by mapping
        # cached pages — the TTFT lever the paging bench row measures
        self.prefix_lookups: int = 0
        self.prefix_hits: int = 0
        self.prefix_hit_tokens: int = 0
        self.prefix_lookup_tokens: int = 0
        # whole-step wall times for steps where a RUNNING request was
        # waiting at step start: each is one user-visible inter-token
        # gap, admissions included. The per-request mean (per_token_*)
        # amortizes a monolithic prefill stall away; the p99 of THESE is
        # the jitter/SLO tail stall-free admission exists to bound
        self.step_gaps: List[float] = []

    # ------------------------------------------------------------------
    def _step(self) -> int:
        """The shared step axis for every monitor event (see module doc)."""
        return int(self._step_fn()) if self._step_fn is not None \
            else self.decode_steps

    def _inc(self, name: str, amount: float = 1.0) -> None:
        if self.registry is not None:
            self.registry.counter(name).inc(amount)

    def _observe_ms(self, name: str, seconds: float) -> None:
        if self.registry is not None:
            self.registry.histogram(name).observe(seconds * 1e3)

    def record_rejection(self, req: Request) -> None:
        # validate against the closed enum BEFORE emitting: a typo'd
        # reason must fail here, not silently fork a new metrics series
        reason = RejectReason.of(req.reject_reason).value
        self.rejected[reason] = self.rejected.get(reason, 0) + 1
        self._inc(f"serving/rejected/{reason}")
        if self.monitor is not None and getattr(self.monitor, "enabled", True):
            self.monitor.write_events([
                (f"serving/rejected/{reason}", 1.0, self._step())])

    def record_failure(self, req: Request) -> None:
        """A running request killed mid-flight: a step-wide engine
        exception (``error``) or a per-slot NaN/inf logits detection
        (``numerical_error``)."""
        reason = FinishReason.of(req.finish_reason or FinishReason.ERROR).value
        self.failed += 1
        self.failed_reasons[reason] = self.failed_reasons.get(reason, 0) + 1
        self._inc("serving/failed")
        self._inc(f"serving/failed/{reason}")
        if self.monitor is not None and getattr(self.monitor, "enabled", True):
            step = self._step()
            self.monitor.write_events([
                ("serving/failed", 1.0, step),
                (f"serving/failed/{reason}", 1.0, step)])

    def record_preemption(self, req: Request) -> None:
        """A seated request evicted back to the queue (slot reclaimed;
        its generated tokens ride along and are re-prefilled on
        resume)."""
        self.preempted += 1
        self._inc("serving/preempted")
        if self.monitor is not None and getattr(self.monitor, "enabled", True):
            self.monitor.write_events([
                ("serving/preempted", 1.0, self._step())])

    def record_step_overrun(self, seconds: float, budget_ms: float) -> None:
        """One scheduler step blew through the per-step wall-time budget
        (the step watchdog fired)."""
        self.step_overruns += 1
        self._inc("serving/step_overruns")
        self._observe_ms("serving/step_overrun_ms", seconds)
        if self.monitor is not None and getattr(self.monitor, "enabled", True):
            self.monitor.write_events([
                ("serving/step_overrun_ms", seconds * 1e3, self._step())])

    def record_load_state(self, old: Any, new: Any) -> None:
        """A graceful-degradation transition; the event value is the NEW
        level's int encoding so dashboards plot the ladder directly."""
        self.load_transitions += 1
        self._inc("serving/load_transitions")
        if self.monitor is not None and getattr(self.monitor, "enabled", True):
            self.monitor.write_events([
                ("serving/load_state", float(int(new)), self._step())])

    def record_decode_step(self, emitted: int, live_slots: int,
                           drafted: int = 0, accepted: int = 0,
                           draft_s: float = 0.0, step_s: float = 0.0) -> None:
        """One decode (or draft+verify) step: ``emitted`` tokens across
        ``live_slots`` live slots; ``drafted``/``accepted`` count draft
        proposals offered/accepted (0/0 when speculation is off)."""
        self.decode_steps += 1
        self.decode_tokens += emitted
        self.slot_steps += live_slots
        self.drafted += drafted
        self.accepted_drafts += accepted
        self.draft_time += draft_s
        self.step_time += step_s
        self._inc("serving/decode_tokens", emitted)
        if drafted and self.monitor is not None and \
                getattr(self.monitor, "enabled", True):
            step = self._step()
            self.monitor.write_events([
                ("serving/spec_acceptance", accepted / drafted, step),
                ("serving/spec_tokens_per_slot_step",
                 emitted / max(live_slots, 1), step),
            ])

    def record_step_gap(self, seconds: float) -> None:
        """One full scheduler step during which at least one RUNNING
        request was waiting on its next token (see ``step_gaps``)."""
        self.step_gaps.append(seconds)
        self._observe_ms("serving/step_gap_ms", seconds)

    def record_prefill(self, tokens: int, seconds: float,
                       blocking: bool) -> None:
        """One prefill dispatch (bucketed admission batch or one chunk):
        ``tokens`` of prompt processed in ``seconds``; ``blocking`` means
        live decode slots were waiting on it (stall time)."""
        self.prefill_tokens += tokens
        self.prefill_dispatches += 1
        self.prefill_time += seconds
        self._inc("serving/prefill_tokens", tokens)
        if blocking:
            self.stall_time += seconds

    def record_prefix(self, hit_tokens: int, seed_len: int) -> None:
        """One admission-time prefix-cache lookup: ``hit_tokens`` of the
        ``seed_len``-token seed were served from cached pages (0 = miss).
        Mirrored into the registry as ``paging/*`` counters so the
        Prometheus export carries the hit ratio."""
        self.prefix_lookups += 1
        self.prefix_lookup_tokens += seed_len
        if hit_tokens > 0:
            self.prefix_hits += 1
            self.prefix_hit_tokens += hit_tokens
            self._inc("paging/prefix_hits")
            self._inc("paging/prefix_hit_tokens", hit_tokens)
        else:
            self._inc("paging/prefix_misses")
        if self.monitor is not None and getattr(self.monitor, "enabled", True):
            self.monitor.write_events([
                ("serving/prefix_hit_tokens", float(hit_tokens),
                 self._step())])

    def record_finish(self, req: Request) -> None:
        reason = FinishReason.of(req.finish_reason).value  # closed enum
        self.finished.append(req)
        self._inc("serving/finished")
        if req.ttft is not None:
            self._observe_ms("serving/ttft_ms", req.ttft)
        if req.queue_wait is not None:
            self._observe_ms("serving/queue_wait_ms", req.queue_wait)
        if req.per_token_latency is not None:
            self._observe_ms("serving/per_token_ms", req.per_token_latency)
        if self.monitor is not None and getattr(self.monitor, "enabled", True):
            step = self._step()
            if reason not in (FinishReason.EOS, FinishReason.LENGTH):
                # the abnormal retirements (length_cap: capacity sizing;
                # deadline: SLO misses) are ops-worthy — each gets its
                # own per-reason event series
                self.monitor.write_events([
                    (f"serving/finished/{reason}", 1.0, step)])
            self.monitor.write_events([
                ("serving/ttft_ms", (req.ttft or 0.0) * 1e3, step),
                ("serving/queue_wait_ms", (req.queue_wait or 0.0) * 1e3,
                 step),
                ("serving/per_token_ms", (req.per_token_latency or 0.0) * 1e3,
                 step),
                ("serving/new_tokens", float(len(req.output_tokens)),
                 step),
            ])

    # ------------------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """Aggregate SLO view over everything finished so far.

        ``requests_per_s`` spans first submit -> last finish: it charges
        the server for queueing delay, which is the number a capacity
        planner actually needs (completions per wall-second under the
        offered load), not a best-case decode rate.
        """
        done = self.finished
        ttfts = [r.ttft for r in done if r.ttft is not None]
        waits = [r.queue_wait for r in done if r.queue_wait is not None]
        gaps = [r.per_token_latency for r in done
                if r.per_token_latency is not None]
        new_tokens = sum(len(r.output_tokens) for r in done)
        span = None
        if done:
            t0 = min(r.submit_time for r in done if r.submit_time is not None)
            t1 = max(r.finish_time for r in done if r.finish_time is not None)
            span = max(t1 - t0, 1e-9)
        return {
            "completed": len(done),
            "rejected": dict(self.rejected),
            "failed": self.failed,
            "failed_reasons": dict(self.failed_reasons),
            "preempted": self.preempted,
            "deadline_expired": sum(
                1 for r in done if r.finish_reason == FinishReason.DEADLINE),
            "cancelled": sum(
                1 for r in done if r.finish_reason == FinishReason.CANCELLED),
            "step_overruns": self.step_overruns,
            "load_transitions": self.load_transitions,
            "new_tokens": new_tokens,
            "decode_steps": self.decode_steps,
            "tokens_per_decode_step": (
                self.decode_tokens / self.slot_steps
                if self.slot_steps else None),
            "spec_drafted": self.drafted,
            "spec_accepted": self.accepted_drafts,
            "spec_acceptance_rate": (
                self.accepted_drafts / self.drafted
                if self.drafted else None),
            "draft_overhead_pct": (
                100.0 * self.draft_time / self.step_time
                if self.step_time > 0 else None),
            "prefix_lookups": self.prefix_lookups,
            "prefix_hits": self.prefix_hits,
            "prefix_hit_rate": (
                self.prefix_hits / self.prefix_lookups
                if self.prefix_lookups else None),
            "prefix_hit_tokens": self.prefix_hit_tokens,
            "prefix_token_hit_rate": (
                self.prefix_hit_tokens / self.prefix_lookup_tokens
                if self.prefix_lookup_tokens else None),
            "prefill_tokens": self.prefill_tokens,
            "prefill_dispatches": self.prefill_dispatches,
            "prefill_time_s": self.prefill_time,
            "stall_time_s": self.stall_time,
            "decode_time_s": self.step_time,
            "requests_per_s": (len(done) / span) if span else None,
            "tokens_per_s": (new_tokens / span) if span else None,
            "ttft_p50_ms": _pct([t * 1e3 for t in ttfts], 50),
            "ttft_p99_ms": _pct([t * 1e3 for t in ttfts], 99),
            "queue_wait_p50_ms": _pct([w * 1e3 for w in waits], 50),
            "per_token_p50_ms": _pct([g * 1e3 for g in gaps], 50),
            "per_token_p99_ms": _pct([g * 1e3 for g in gaps], 99),
            "step_gap_p50_ms": _pct([g * 1e3 for g in self.step_gaps], 50),
            "step_gap_p99_ms": _pct([g * 1e3 for g in self.step_gaps], 99),
        }
