"""Admission queue + slot-grant policy for continuous batching.

The scheduler is deliberately host-only and device-free: it owns the
FIFO queue, enforces admission control (bounded queue depth,
prompt-fits-in-capacity) and decides WHICH queued requests get a slot
this step. Two policies:

* ``"continuous"`` — iteration-level scheduling (Orca; PAPERS.md):
  every step, any free slot is immediately refilled from the queue.
  Retirements and admissions interleave with decode, so slots never
  idle while work is queued.
* ``"gang"`` — the static-batch discipline ``generate()`` imposes,
  expressed in the same machinery: admit only when the pool is fully
  drained, then seat a whole batch at once. This is the baseline arm of
  the serving benchmark — same engine, same kernels, only the admission
  policy differs — so the bench row isolates the scheduling win.

Under the serving engine's stall-free mode, ``grant`` additionally
enforces a per-step prefill TOKEN BUDGET (Sarathi-style): admission
stops charging new prompts once the step's prefill work — bucketed
whole-prompt admissions plus at most one in-flight chunk — would exceed
the budget, so a burst of arrivals can no longer stall live decode
slots behind an unbounded prefill wave.
"""

from __future__ import annotations

import collections
from typing import Deque, List, Optional, Tuple

from .request import RejectReason, Request, RequestState

POLICIES = ("continuous", "gang")


class FIFOScheduler:
    """Bounded FIFO admission queue with a pluggable slot-grant policy."""

    def __init__(self, num_slots: int, max_queue_depth: int = 64,
                 policy: str = "continuous", capacity: Optional[int] = None,
                 page_size: Optional[int] = None,
                 num_pages: Optional[int] = None, page_headroom: int = 0):
        if policy not in POLICIES:
            raise ValueError(f"unknown policy {policy!r}; expected one of "
                             f"{POLICIES}")
        self.num_slots = num_slots
        self.max_queue_depth = max_queue_depth
        self.policy = policy
        self.capacity = capacity
        # paged-KV admission accounting: with a PagedKVPool the real
        # admission currency is PAGES, not rows — ``capacity`` alone
        # would accept a request the page pool can never hold under
        # oversubscription. ``page_headroom`` is extra columns charged
        # per request (speculative verify's k-past-the-index writes).
        self.page_size = int(page_size) if page_size is not None else None
        self.num_pages = int(num_pages) if num_pages is not None else None
        self.page_headroom = int(page_headroom)
        self.queue: Deque[Request] = collections.deque()

    def page_footprint(self, req: Request) -> Optional[int]:
        """Worst-case page count ``req`` could ever need (seed + its
        remaining generation budget + headroom), or None when the pool
        is not paged."""
        if self.page_size is None:
            return None
        cols = (req.seed_len + req.max_new_tokens - len(req.output_tokens)
                + self.page_headroom)
        return -(-cols // self.page_size)

    @property
    def pending(self) -> int:
        return len(self.queue)

    def head(self) -> Optional[Request]:
        """The request the next ``grant`` would pop first, or None.

        The engine peeks at this (never at ``queue[0]`` directly) for
        starvation/pressure decisions, so subclasses with a different
        grant order (priority scheduling) redefine "head" in one place.
        """
        return self.queue[0] if self.queue else None

    # ------------------------------------------------------------------
    def submit(self, req: Request) -> Tuple[bool, Optional[RejectReason]]:
        """Admission control. Returns ``(accepted, reject_reason)``;
        accepted requests join the FIFO queue. Capacity is checked
        against the request's FULL footprint (seed + remaining budget) so
        a preempted request that could never finish is refused rather
        than admitted to die at the length cap."""
        if self.capacity is not None and \
                req.seed_len + req.max_new_tokens - len(req.output_tokens) \
                > self.capacity:
            return False, RejectReason.PROMPT_TOO_LONG
        if self.num_pages is not None:
            # page-denominated footprint check: even with the WHOLE pool
            # free (every other request preempted and the prefix cache
            # fully evicted) this request could never seat its worst
            # case — reject now, not at an unseatable queue head
            fp = self.page_footprint(req)
            if fp is not None and fp > self.num_pages:
                return False, RejectReason.PROMPT_TOO_LONG
        if len(self.queue) >= self.max_queue_depth:
            return False, RejectReason.QUEUE_FULL
        req.state = RequestState.QUEUED
        self.queue.append(req)
        return True, None

    def requeue_front(self, reqs: List[Request]) -> None:
        """Put granted-but-never-admitted (or manually preempted)
        requests back at the HEAD of the queue, preserving their
        RELATIVE order: after ``requeue_front([a, b])`` the queue pops
        ``a`` then ``b`` then whatever was already waiting. The reversed
        ``appendleft`` walk is what makes that hold — appendleft-ing in
        forward order would reverse the batch, a FIFO inversion that
        reorders same-step aborted grants on re-admission (pinned by a
        regression test). Bypasses admission control — these requests
        already passed it."""
        for r in reversed(reqs):
            r.state = RequestState.QUEUED
            self.queue.appendleft(r)

    def requeue_back(self, reqs: List[Request]) -> None:
        """Requeue at the TAIL — the automatic pressure-preemption path.
        A pressure victim must NOT go to the head: the very next grant
        would hand it back its own freed slot (a swap loop that preempts
        forever and generates nothing). Sending it behind the arrivals
        that caused the pressure yields round-robin time-slicing
        instead. Bypasses admission control, like ``requeue_front``."""
        for r in reqs:
            r.state = RequestState.QUEUED
            self.queue.append(r)

    def expire(self, now: float) -> List[Request]:
        """Remove and return queued requests whose deadline has passed —
        a request that expired while WAITING should not cost a slot and
        a prefill before being retired. The engine stamps these
        ``finish_reason="deadline"`` through the normal retire path."""
        expired = [r for r in self.queue if r.expired(now)]
        if expired:
            self.queue = collections.deque(
                r for r in self.queue if not r.expired(now))
        return expired

    def grant(self, free_slots: int, live_slots: int,
              token_budget: Optional[int] = None,
              cost=None, spent: int = 0,
              page_budget: Optional[int] = None,
              page_cost=None) -> List[Request]:
        """Pop the requests that may take a slot this step.

        With ``token_budget``/``cost`` (the stall-free admission policy),
        each pop is charged ``cost(req)`` prefill tokens against the
        budget and the FIFO head blocks further grants when it no longer
        fits — per-step prefill work is bounded by tokens, not by how
        many slots happen to be free. ``spent`` is prefill work the
        caller already committed this step (an in-flight chunk).

        HEAD-LIVENESS GUARANTEE (pinned by regression tests, relied on
        by the priority scheduler): when NOTHING has been spent or
        granted yet this step, the head is granted even if its cost
        alone exceeds the budget (bounded overshoot beats a permanently
        stuck queue). Consequence: on any step where a slot is free and
        no prefill work was already committed, the next-to-pop request
        makes progress — no token budget, however small, can livelock
        the queue. ``PriorityScheduler`` preserves exactly this property
        for its highest-ranked waiter, which is how the lowest class
        still makes progress when higher classes are idle: it IS the
        highest-ranked waiter then.

        With ``page_budget``/``page_cost`` (paged KV), each pop is also
        charged ``page_cost(req)`` fresh pages (its uncached prefix).
        The page budget is STRICT — no liveness overshoot: over-granting
        pages doesn't slow the step down, it makes seating raise
        PagePoolExhausted and abort the whole step. Starvation is the
        engine's job, not an overshoot's: pressure preemption frees
        victims' pages, and the submit-time footprint check guarantees
        the head fits an otherwise-empty pool."""
        if self.policy == "gang" and live_slots > 0:
            return []  # batch-synchronous: wait for the whole gang to drain
        granted: List[Request] = []
        remaining = None if token_budget is None else token_budget - spent
        pages_left = page_budget
        while self.queue and len(granted) < free_slots:
            pc = 0
            if pages_left is not None:
                pc = page_cost(self.queue[0]) if page_cost is not None else 0
                if pc > pages_left:
                    break
            if remaining is not None:
                c = cost(self.queue[0]) if cost is not None else 0
                if c > remaining and (granted or spent > 0):
                    break
                remaining -= c
            if pages_left is not None:
                pages_left -= pc
            granted.append(self.queue.popleft())
        return granted
