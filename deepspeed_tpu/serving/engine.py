"""ServingEngine: request-level continuous batching over InferenceEngine.

``InferenceEngine.generate()`` is whole-batch synchronous — every
request must arrive together and the batch holds its slots until the
slowest member finishes. This front-end turns the same compiled
machinery (the jitted ``prefill_last`` and donated single-step decode)
into a server: requests arrive one at a time via :meth:`submit`, each
:meth:`step` admits queued prompts into free slots of the fixed-shape
:class:`~deepspeed_tpu.serving.slot_pool.SlotPool` and runs ONE decode
step for all live slots, and finished sequences retire immediately so
their slot goes back to work (Orca-style iteration-level scheduling;
PAPERS.md).

Shape discipline is what keeps this fast on TPU: the decode step always
runs at batch = ``num_slots`` with per-slot (B,) cache offsets, so slot
churn never changes a compiled program — dead slots ride along as
masked padding. Prompt prefills are right-padded to power-of-two
buckets and the true last position is projected via
``prefill_last(input_ids, last_pos)``, bounding prefill recompiles at
log2(max_seq_len) for arbitrary prompt lengths.

With a ``spec_decode`` config the decode step becomes draft–verify
speculative decoding over the same fixed shapes: a host-side
:class:`~deepspeed_tpu.serving.spec_decode.Drafter` proposes up to K
tokens per live slot, one jitted ``verify_k`` forward scores all
``(num_slots, K+1)`` positions at once, and each slot keeps its
accepted prefix plus the target model's bonus/correction token — up to
K+1 tokens per slot per step, bitwise identical to plain greedy decode.
Rejected draft positions are rolled back by the per-slot cache ``index``
(:meth:`SlotPool.advance`), never by reshaping, so speculation adds
exactly one more compiled program regardless of churn.
"""

from __future__ import annotations

import time
from typing import Any, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.logging import log_dist
from .metrics import ServingMetrics
from .request import Request, RequestState
from .scheduler import FIFOScheduler
from .slot_pool import SlotPool

_MIN_PREFILL_BUCKET = 16


class ServingEngine:
    """Continuous-batching server over a built
    :class:`~deepspeed_tpu.inference.engine.InferenceEngine`.

    Construct via :func:`deepspeed_tpu.init_serving`. Sampling knobs
    default to the inference config's (greedy unless ``do_sample``);
    they are server-global — per-request ``max_new_tokens`` and
    ``eos_token_id`` ride on the :class:`Request`.
    """

    def __init__(self, engine: Any, num_slots: int = 4,
                 max_queue_depth: int = 64, policy: str = "continuous",
                 do_sample: bool = False,
                 temperature: Optional[float] = None,
                 top_k: Optional[int] = None, top_p: Optional[float] = None,
                 seed: int = 0, monitor: Optional[Any] = None,
                 spec_decode: Optional[Any] = None):
        self.engine = engine
        # materialize params + jits before sizing anything off the module
        engine._ensure_params(jnp.zeros((1, 2), jnp.int32))
        spec = engine.kv_cache_spec()
        if spec is None:
            raise ValueError(
                "serving requires the module to declare kv_cache_spec() "
                "(the slot pool allocates through it); the unified "
                "TransformerLM family does")
        if getattr(engine, "_jit_prefill_at", None) is None:
            raise ValueError(
                "serving requires the module to expose prefill_last("
                "input_ids, last_pos) for bucketed slot prefill")
        cfg = engine._config
        self.pool = SlotPool(spec, num_slots)
        self._spec = None
        self._drafter = None
        sched_capacity = self.pool.capacity
        if spec_decode is not None:
            from .spec_decode import SpecDecodeConfig, make_drafter
            sc = SpecDecodeConfig.from_value(spec_decode)
            if sc is not None and sc.enabled:  # False / enabled=False: off
                sc.validate(self.pool.capacity)
                self._spec = sc
                self._drafter = make_drafter(sc)
                # verify writes k+1 positions past a slot's live offset
                # (rejected tail = masked padding). Reserving k columns of
                # headroom at admission keeps even a fully-rejected chunk
                # inside the allocation, so the dynamic-slice writes can
                # never clamp into another request's live columns.
                sched_capacity = self.pool.capacity - sc.k
        self.scheduler = FIFOScheduler(num_slots, max_queue_depth,
                                       policy=policy,
                                       capacity=sched_capacity)
        self.metrics = ServingMetrics(monitor)
        self.temperature = cfg.temperature if temperature is None else temperature
        self.top_k = cfg.top_k if top_k is None else top_k
        self.top_p = cfg.top_p if top_p is None else top_p
        self._greedy = jnp.asarray(not do_sample)
        self._rng = jax.random.PRNGKey(seed)
        self._slot_req: dict = {}                      # slot -> Request
        self._current = np.zeros((num_slots,), np.int32)  # last token per slot
        self._next_id = 0
        self._now = time.perf_counter
        log_dist(f"ServingEngine: slots={num_slots} policy={policy} "
                 f"capacity={self.pool.capacity} "
                 f"max_queue_depth={max_queue_depth}", ranks=[0])

    # ------------------------------------------------------------------
    @property
    def live_count(self) -> int:
        return len(self._slot_req)

    @property
    def pending(self) -> int:
        return self.scheduler.pending

    def submit(self, prompt, max_new_tokens: int = 32,
               eos_token_id: Optional[int] = None) -> Request:
        """Enqueue one generation request. Never raises on load: admission
        control marks the returned request ``REJECTED`` with a
        ``reject_reason`` (``"queue_full"``, ``"prompt_too_long"``) so
        callers can shed or retry."""
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got "
                             f"{max_new_tokens}")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        req = Request(self._next_id, prompt, max_new_tokens, eos_token_id)
        self._next_id += 1
        req.submit_time = self._now()
        accepted, reason = self.scheduler.submit(req)
        if not accepted:
            req.state = RequestState.REJECTED
            req.reject_reason = reason
            self.metrics.record_rejection(req)
        return req

    # ------------------------------------------------------------------
    def _sample(self, logits) -> np.ndarray:
        self._rng, sub = jax.random.split(self._rng)
        return np.asarray(self.engine._jit_sample(
            logits, sub, jnp.asarray(self.temperature, jnp.float32),
            int(self.top_k), float(self.top_p), self._greedy))

    @staticmethod
    def _bucket(n: int, cap: int) -> int:
        b = _MIN_PREFILL_BUCKET
        while b < n:
            b *= 2
        return min(b, cap)

    def _admit(self, req: Request, finished: List[Request]) -> None:
        eng = self.engine
        slot = self.pool.alloc()
        try:
            T = req.prompt_len
            width = self._bucket(T, self.pool.capacity)
            ids = np.zeros((1, width), np.int32)
            ids[0, :T] = req.prompt
            req.admit_time = self._now()
            logits, pre_cache = eng._jit_prefill_at(
                eng.params, jnp.asarray(ids), jnp.asarray(T - 1, jnp.int32))
            self.pool.admit(pre_cache, slot, T)
            token = int(self._sample(logits)[0])  # device sync: token exists
            req.first_token_time = self._now()
            req.slot = slot
            self._slot_req[slot] = req
            req.state = RequestState.RUNNING
            req.output_tokens.append(token)
            self._current[slot] = token
        except Exception:
            # undo the partial admission so the request can be re-queued
            # with no trace: the slot goes back, timing/output state is
            # reset, and _abort_step sees a clean QUEUED request
            self._slot_req.pop(slot, None)
            self.pool.release(slot)
            req.state = RequestState.QUEUED
            req.slot = None
            req.admit_time = None
            req.first_token_time = None
            del req.output_tokens[:]
            raise
        self._maybe_retire(req, token, finished)

    def _maybe_retire(self, req: Request, token: int,
                      finished: List[Request]) -> None:
        if req.eos_token_id is not None and token == req.eos_token_id:
            req.finish_reason = "eos"
        elif len(req.output_tokens) >= req.max_new_tokens:
            req.finish_reason = "length"
        else:
            return
        req.state = RequestState.FINISHED
        req.finish_time = self._now()
        self.pool.release(req.slot)
        del self._slot_req[req.slot]
        self.metrics.record_finish(req)
        finished.append(req)

    # ------------------------------------------------------------------
    def step(self) -> List[Request]:
        """One scheduler iteration: admit into free slots, then one decode
        (or draft+verify) step for every live slot. Returns the requests
        that finished.

        Exception-safe: if the engine throws mid-step, no slot leaks —
        granted-but-unadmitted requests go back to the head of the queue,
        requests whose KV state is unrecoverable are FAILED (reason
        ``"error"``), the pool is reset, and the error propagates."""
        finished: List[Request] = []
        granted = self.scheduler.grant(self.pool.free_count, self.live_count)
        try:
            for req in granted:
                self._admit(req, finished)
            if self._slot_req:
                t0 = self._now()
                if self._spec is not None:
                    self._spec_decode_step(finished, t0)
                else:
                    self._decode_step(finished, t0)
        except Exception:
            self._abort_step(granted)
            raise
        return finished

    def _decode_step(self, finished: List[Request], t0: float) -> None:
        eng = self.engine
        live = len(self._slot_req)
        tokens = jnp.asarray(self._current[:, None])
        pos = jnp.asarray(self.pool.positions())
        logits, cache = eng._jit_decode(eng.params, self.pool.cache,
                                        tokens, pos)
        self.pool.cache = cache
        self.pool.advance(1)
        nxt = self._sample(logits)
        emitted = 0
        for slot, req in list(self._slot_req.items()):
            token = int(nxt[slot])
            req.output_tokens.append(token)
            self._current[slot] = token
            emitted += 1
            self._maybe_retire(req, token, finished)
        self.metrics.record_decode_step(emitted, live,
                                        step_s=self._now() - t0)

    def _spec_decode_step(self, finished: List[Request], t0: float) -> None:
        """Draft K tokens per live slot, verify them all in ONE fixed-shape
        (num_slots, K+1) forward, keep each slot's accepted prefix + bonus
        token, and roll back rejected KV via the per-slot index."""
        eng = self.engine
        K = self._spec.k
        B = self.pool.num_slots

        histories: List[Optional[np.ndarray]] = [None] * B
        for slot, req in self._slot_req.items():
            histories[slot] = req.tokens()
        draft, draft_len = self._drafter.propose(histories, K)
        draft = np.asarray(draft, np.int32)
        draft_len = np.clip(np.asarray(draft_len, np.int32), 0, K)
        t_draft = self._now() - t0

        tokens = np.concatenate([self._current[:, None], draft], axis=1)
        self._rng, sub = jax.random.split(self._rng)
        cache, out, n_emit = eng.verify_k(
            self.pool.cache, jnp.asarray(tokens),
            jnp.asarray(self.pool.positions()), jnp.asarray(draft),
            jnp.asarray(draft_len), sub,
            jnp.asarray(self.temperature, jnp.float32), self._greedy,
            int(self.top_k), float(self.top_p))
        self.pool.cache = cache
        out = np.asarray(out)          # (B, K+1) emitted tokens per row
        n_emit = np.asarray(n_emit)    # (B,) accepted drafts + 1

        deltas = np.zeros((B,), np.int32)
        emitted = drafted = accepted = 0
        live = list(self._slot_req.items())
        for slot, req in live:
            e = int(n_emit[slot])
            # the cache row holds e new positions regardless of how many
            # tokens the request actually consumes below: if eos/budget
            # truncates the emission, the request retires this step, so
            # the surplus becomes dead padding in a freed slot
            deltas[slot] = e
            drafted += int(draft_len[slot])
            accepted += e - 1
            for token in out[slot, :e].tolist():
                req.output_tokens.append(token)
                self._current[slot] = token
                emitted += 1
                self._maybe_retire(req, token, finished)
                if req.state is not RequestState.RUNNING:
                    break
        self.pool.advance(deltas)      # per-slot KV rollback
        self.metrics.record_decode_step(emitted, len(live), drafted=drafted,
                                        accepted=accepted, draft_s=t_draft,
                                        step_s=self._now() - t0)

    def _abort_step(self, granted: List[Request]) -> None:
        """Mid-step exception recovery: never leak a slot. Requests the
        failed _admit already rolled back to QUEUED re-join the queue
        head; running requests lose their (possibly donated-away) KV
        state and are FAILED; the pool restarts from a fresh cache."""
        self.scheduler.requeue_front(
            [r for r in granted if r.state is RequestState.QUEUED])
        for req in self._slot_req.values():
            req.state = RequestState.FAILED
            req.finish_reason = "error"
            req.finish_time = self._now()
            self.metrics.record_failure(req)
        self._slot_req.clear()
        self._current[:] = 0
        self.pool.reset()

    def run_until_drained(self, max_steps: Optional[int] = None
                          ) -> List[Request]:
        """Step until the queue and every slot are empty (or ``max_steps``).
        Every step with live work produces at least one token and every
        request's budget is finite, so this terminates."""
        out: List[Request] = []
        steps = 0
        while self.scheduler.pending or self._slot_req:
            out.extend(self.step())
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        return out

    def stats(self) -> dict:
        """Aggregate SLO snapshot (see ServingMetrics.snapshot)."""
        return self.metrics.snapshot()
