"""ServingEngine: request-level continuous batching over InferenceEngine.

``InferenceEngine.generate()`` is whole-batch synchronous — every
request must arrive together and the batch holds its slots until the
slowest member finishes. This front-end turns the same compiled
machinery (the jitted ``prefill_last`` and donated single-step decode)
into a server: requests arrive one at a time via :meth:`submit`, each
:meth:`step` admits queued prompts into free slots of the fixed-shape
:class:`~deepspeed_tpu.serving.slot_pool.SlotPool` and runs ONE decode
step for all live slots, and finished sequences retire immediately so
their slot goes back to work (Orca-style iteration-level scheduling;
PAPERS.md).

Shape discipline is what keeps this fast on TPU: the decode step always
runs at batch = ``num_slots`` with per-slot (B,) cache offsets, so slot
churn never changes a compiled program — dead slots ride along as
masked padding. Prompt prefills are right-padded to power-of-two
buckets and the true last position is projected via
``prefill_last(input_ids, last_pos)``, bounding prefill recompiles at
log2(max_seq_len) for arbitrary prompt lengths.

Admission is STALL-FREE by default (``prefill_chunk > 0``,
Sarathi-style; PAPERS.md): each step spends at most a prefill token
budget before the decode dispatch, so a burst of arrivals can no
longer stall every live slot behind an unbounded prefill wave.
Prompts longer than the chunk width are seated ``PREFILLING`` and
stream into their slot's cache row one bounded
``prefill_chunk(input_ids, start_pos, last_idx)`` dispatch per step
(window-masked attention against the already-written positions — the
jitted program slices the target row out and writes only it back, so
live neighbours are untouched); shorter prompts waiting at the same
bucket width are prefilled in ONE batched dispatch (batch dim bucketed
to powers of two) and scattered into their slots by a single jitted
multi-row admit. Compile count stays bounded by
log2(num_slots) x log2(max_seq_len) admission programs plus one chunk
program; greedy outputs remain bitwise identical to serial admission.
``prefill_chunk=0`` restores the serial one-prompt-per-dispatch
admission (the benchmark's baseline arm).

With a ``spec_decode`` config the decode step becomes draft–verify
speculative decoding over the same fixed shapes: a host-side
:class:`~deepspeed_tpu.serving.spec_decode.Drafter` proposes up to K
tokens per live slot, one jitted ``verify_k`` forward scores all
``(num_slots, K+1)`` positions at once, and each slot keeps its
accepted prefix plus the target model's bonus/correction token — up to
K+1 tokens per slot per step, bitwise identical to plain greedy decode.
Rejected draft positions are rolled back by the per-slot cache ``index``
(:meth:`SlotPool.advance`), never by reshaping, so speculation adds
exactly one more compiled program regardless of churn.

FAULT TOLERANCE (the :mod:`.resilience` package) hardens the loop
without ever changing a compiled shape:

* per-request deadlines (``submit(..., deadline_ms=...)``) expire
  queued requests before they cost a prefill and retire seated ones
  through the same slot-release/index-masking rollback speculation
  uses (``finish_reason="deadline"``);
* ``preempt()`` evicts a seated request and re-queues it carrying its
  generated-so-far tokens; re-admission prefills prompt + outputs
  through the existing bucketed/chunked paths (fixed shapes, zero new
  programs) and greedy output is bitwise identical to an un-preempted
  run. Automatic victim selection (youngest first) kicks in when the
  queue exceeds ``preempt_queue_threshold`` — those victims re-queue at
  the BACK (round-robin time-slicing), or the very next grant would
  hand each victim its own freed slot forever;
* a HEALTHY/PRESSURED/OVERLOADED load-state machine progressively
  shrinks the prefill token budget, suspends speculative drafting
  (zero-length drafts through the SAME verify program — no recompile),
  and finally sheds new submissions with ``retry_after``;
* an optional NaN/inf logits guard (``guard_numerics``) fails ONLY the
  poisoned slot (``finish_reason="numerical_error"``); the other slots'
  tokens from the same dispatch are kept;
* a seeded :class:`~deepspeed_tpu.serving.resilience.FaultInjector`
  threads deterministic failures through five named points for the
  chaos suite and ``bench.py serving-chaos``.
"""

from __future__ import annotations

import time
from typing import Any, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ..telemetry import (FlightRecorder, MetricsRegistry, ProgramCostModel,
                         RecompileAfterWarmupError, RecompileWatchdog,
                         SLOTracker, TimelineStore, Tracer)
from ..utils.logging import log_dist
from ..utils.timer import SynchronizedWallClockTimer
from .metrics import ServingMetrics
from .paged_pool import PagedKVPool, PagePoolExhausted
from .request import FinishReason, RejectReason, Request, RequestState
from .resilience import (DegradationConfig, FaultInjectingDrafter,
                         InvariantViolation, LoadState, LoadStateMachine,
                         ServingStalledError, select_victims)
from .scheduler import FIFOScheduler
from .slot_pool import SlotPool

# jitted entry points the recompile watchdog wraps; verify_k is created
# lazily on first use, so _ensure_watch re-checks the list every step.
# The paged entries only exist on a PagedKVPool (attach skips absentees).
_WATCHED_ENGINE_JITS = ("_jit_prefill_at", "_jit_decode",
                        "_jit_prefill_chunk", "_jit_sample",
                        "_jit_verify_k", "_jit_decode_scan")
_WATCHED_POOL_JITS = ("_admit_jit", "_admit_rows_jit",
                      "_paged_decode_jit", "_paged_verify_jit",
                      "_paged_decode_kernel_jit",
                      "_paged_verify_kernel_jit",
                      "_paged_chunk_jit", "_jit_copy_page",
                      "_jit_gather_pages", "_jit_scatter_pages")
_WATCHED_SERVING_JITS = ("_jit_finite", "_jit_cur_scatter", "_jit_spec_cur")
# the model drafter jits its own last-token argmax (lazily, on the
# first propose); unwatched it was the one serving-side jit that could
# recompile post-warmup without attribution — found by the graftlint
# jit inventory, pinned by tests/unit/analysis/test_inventory.py
_WATCHED_DRAFTER_JITS = ("_argmax",)

_MIN_PREFILL_BUCKET = 16


class ServingEngine:
    """Continuous-batching server over a built
    :class:`~deepspeed_tpu.inference.engine.InferenceEngine`.

    Construct via :func:`deepspeed_tpu.init_serving`. Sampling knobs
    default to the inference config's (greedy unless ``do_sample``);
    they are server-global — per-request ``max_new_tokens`` and
    ``eos_token_id`` ride on the :class:`Request`.
    """

    def __init__(self, engine: Any, num_slots: int = 4,
                 max_queue_depth: int = 64, policy: str = "continuous",
                 do_sample: bool = False,
                 temperature: Optional[float] = None,
                 top_k: Optional[int] = None, top_p: Optional[float] = None,
                 seed: int = 0, monitor: Optional[Any] = None,
                 spec_decode: Optional[Any] = None,
                 prefill_chunk: int = 64,
                 prefill_token_budget: Optional[int] = None,
                 tracer: Optional[Any] = None,
                 registry: Optional[Any] = None,
                 strict_recompile: bool = False,
                 timeline_capacity: int = 4096,
                 deadline_default_ms: Optional[float] = None,
                 step_wall_budget_ms: Optional[float] = None,
                 guard_numerics: bool = False,
                 degradation: Optional[Any] = None,
                 preempt_queue_threshold: Optional[int] = None,
                 preempt_min_run_steps: int = 2,
                 fault_injector: Optional[Any] = None,
                 paged_kv: Any = False,
                 cost_model: Any = False,
                 slo: Any = None,
                 flight_recorder: Any = True,
                 dump_dir: Optional[str] = None,
                 priority: Any = None,
                 clock: Optional[Any] = None,
                 overlap: bool = False,
                 role: str = "both"):
        self.engine = engine
        # ONE monotonic clock for every time-dependent decision —
        # deadline stamps, queue expiry, SLO latencies, degradation
        # cooldowns AND the front end's rate buckets all read this
        # callable. Injectable so tests drive a fake clock through all
        # of them at once, and so the front end can share it; wall-clock
        # time.time() must never leak into deadline paths (NTP steps
        # would fire or defer deadlines arbitrarily).
        self._now = clock if clock is not None else time.perf_counter
        # materialize params + jits before sizing anything off the module
        engine._ensure_params(jnp.zeros((1, 2), jnp.int32))
        spec = engine.kv_cache_spec()
        if spec is None:
            raise ValueError(
                "serving requires the module to declare kv_cache_spec() "
                "(the slot pool allocates through it); the unified "
                "TransformerLM family does")
        if getattr(engine, "_jit_prefill_at", None) is None:
            raise ValueError(
                "serving requires the module to expose prefill_last("
                "input_ids, last_pos) for bucketed slot prefill")
        cfg = engine._config
        # pin the pool to the axis-rules placement for the engine's mesh
        # so the cold cache matches the committed arrays its jitted
        # steps hand back (otherwise the first admission compiles a
        # second executable). The per-leaf resolver shards k/v over
        # (data, model) where the mesh and shapes allow it and resolves
        # to the historical replicated placement everywhere else — on a
        # TP=1/DP=1 mesh every leaf is replicated, which is how the
        # single-chip path stays the bitwise oracle.
        rep = None
        if getattr(engine, "mesh", None) is not None:
            from ..parallel.axis_rules import cache_leaf_sharding
            rep = cache_leaf_sharding(
                "paged" if paged_kv else "stacked", mesh=engine.mesh)
        # kept for the current-token twin: every host-built slots-shaped
        # array is committed through the same resolver (key "index") so
        # its placement always matches the pool's per-slot index leaf
        self._pool_sharding = rep
        # -- paged KV (ISSUE 7): page-pooled storage + prefix cache ----
        # paged_kv: False (contiguous rows), True (paged, defaults), or a
        # dict {"num_pages": int, "page_size": int, "prefix_cache": bool}
        capacity = int(spec.max_seq_len)
        if paged_kv:
            knobs = dict(paged_kv) if isinstance(paged_kv, dict) else {}
            page_size = knobs.pop("page_size", None)
            if page_size is None:
                # default: the prefill chunk width (ISSUE 7) — one chunk
                # fills one page — auto-halved the same way the chunk is
                # until it divides the capacity
                page_size = int(prefill_chunk) if prefill_chunk > 0 else 64
                page_size = max(1, min(page_size, capacity))
                while page_size > 1 and capacity % page_size != 0:
                    page_size //= 2
            num_pages = knobs.pop("num_pages", None)
            use_prefix = bool(knobs.pop("prefix_cache", True))
            # paged_kernel: "off" (dense gather/scatter composition — the
            # bitwise oracle), "on" (fused in-place paged-attention
            # kernel, interpret mode off-TPU), "auto" (kernel on TPU)
            paged_kernel = str(knobs.pop("kernel", "auto"))
            if knobs:
                raise ValueError(f"unknown paged_kv keys: {sorted(knobs)}; "
                                 f"expected num_pages/page_size/"
                                 f"prefix_cache/kernel")
            self.pool = PagedKVPool(spec, num_slots, num_pages=num_pages,
                                    page_size=int(page_size), sharding=rep,
                                    prefix_cache=use_prefix,
                                    kernel=paged_kernel)
        else:
            self.pool = SlotPool(spec, num_slots, sharding=rep)
        self._paged = isinstance(self.pool, PagedKVPool)
        self._spec = None
        self._drafter = None
        sched_capacity = self.pool.capacity
        if spec_decode is not None:
            from .spec_decode import SpecDecodeConfig, make_drafter
            sc = SpecDecodeConfig.from_value(spec_decode)
            if sc is not None and sc.enabled:  # False / enabled=False: off
                sc.validate(self.pool.capacity)
                self._spec = sc
                self._drafter = make_drafter(sc)
                # verify writes k+1 positions past a slot's live offset
                # (rejected tail = masked padding). Reserving k columns of
                # headroom at admission keeps even a fully-rejected chunk
                # inside the allocation, so the dynamic-slice writes can
                # never clamp into another request's live columns.
                sched_capacity = self.pool.capacity - sc.k
        sched_kw = dict(
            max_queue_depth=max_queue_depth, policy=policy,
            capacity=sched_capacity,
            # page-denominated admission (oversubscription makes row
            # capacity a fiction): reject what the whole pool could
            # never hold; spec decode's k-past-the-index verify writes
            # are headroom columns, mirroring the row-capacity reserve
            page_size=self.pool.page_size if self._paged else None,
            num_pages=self.pool.num_pages if self._paged else None,
            page_headroom=(self._spec.k if self._spec is not None else 0))
        # priority: None/False (plain FIFO), True (default classes), a
        # PriorityConfig kwargs dict, or an instance. Imported lazily:
        # frontend/ imports serving modules, so a top-level import here
        # would be circular.
        if priority:
            from .frontend.priority import PriorityScheduler
            self.scheduler = PriorityScheduler(
                num_slots, priority=priority, clock=self._now, **sched_kw)
        else:
            self.scheduler = FIFOScheduler(num_slots, **sched_kw)
        self._priority = getattr(self.scheduler, "config", None) \
            if priority else None
        # -- telemetry -------------------------------------------------
        # the tracer defaults to DISABLED: span() then costs one branch
        # + a shared null span, keeping the instrumented hot path within
        # the 2% overhead budget when nobody is tracing
        if tracer is True:
            tracer = Tracer()
        self.tracer = tracer if tracer is not None else Tracer(enabled=False)
        self.registry = registry if registry is not None else MetricsRegistry()
        self.step_id = 0                 # monotonic scheduler-step counter
        self.timelines = TimelineStore(capacity=timeline_capacity,
                                       tracer=self.tracer)
        self.watchdog = RecompileWatchdog(
            registry=self.registry, tracer=self.tracer, monitor=monitor,
            strict=strict_recompile, step_fn=lambda: self.step_id)
        self.metrics = ServingMetrics(monitor, registry=self.registry,
                                      step_fn=lambda: self.step_id)
        # -- efficiency & goodput telemetry (ISSUE 8) ------------------
        # cost_model: False (off), True (defaults), a ProgramCostModel
        # kwargs dict, or an instance. Off by default: the lazy AOT
        # harvest compiles each program once more, a warmup cost test
        # suites constructing many servers shouldn't pay.
        if cost_model is True:
            cost_model = ProgramCostModel(registry=self.registry)
        elif isinstance(cost_model, dict):
            cost_model = ProgramCostModel(registry=self.registry,
                                          **cost_model)
        elif not cost_model:
            cost_model = None
        self.costs = cost_model
        # _ensure_watch subscribes the cost model to every watched jit
        self.watchdog.cost_model = self.costs
        # slo: None/False (off), True (default SLOConfig), dict/SLOConfig
        self.slo = (SLOTracker(slo, registry=self.registry,
                               tracer=self.tracer, monitor=monitor)
                    if slo else None)
        # flight_recorder: True (defaults), int capacity, kwargs dict,
        # an instance, or False. Default ON — one deque append per step.
        if flight_recorder is True:
            flight_recorder = FlightRecorder(dump_dir=dump_dir)
        elif isinstance(flight_recorder, bool):
            flight_recorder = None
        elif isinstance(flight_recorder, int):
            flight_recorder = FlightRecorder(capacity=flight_recorder,
                                             dump_dir=dump_dir)
        elif isinstance(flight_recorder, dict):
            flight_recorder = FlightRecorder(
                **{"dump_dir": dump_dir, **flight_recorder})
        elif flight_recorder is not None and dump_dir is not None \
                and flight_recorder.dump_dir is None:
            flight_recorder.dump_dir = dump_dir
        self.recorder = flight_recorder
        self.dump_dir = dump_dir
        self._tokens_emitted = 0        # lifetime tokens (all paths)
        self._tokens_prev = 0           # snapshot for per-step deltas
        self._telemetry_ns = 0          # step-boundary instrumentation
        # fleet identity: assigned by ReplicaRouter at join time, stamped
        # onto every timeline event so cross-replica journeys stitch
        self.replica_id: Optional[int] = None
        # accumulated step wall — the overhead_pct denominator when no
        # cost model is attached (the fleet aggregator's fallback)
        self.step_wall_s = 0.0
        self.registry.add_collector(self._collect_telemetry_health)
        if self._paged:
            # pool-internal events (CoW copies, trie evictions) land in
            # the same registry as the engine-side paging/* series
            self.pool.registry = self.registry
        # -- resilience ------------------------------------------------
        if deadline_default_ms is not None and deadline_default_ms <= 0:
            raise ValueError(f"deadline_default_ms must be > 0, got "
                             f"{deadline_default_ms}")
        if step_wall_budget_ms is not None and step_wall_budget_ms <= 0:
            raise ValueError(f"step_wall_budget_ms must be > 0, got "
                             f"{step_wall_budget_ms}")
        if preempt_queue_threshold is not None and preempt_queue_threshold < 1:
            raise ValueError(f"preempt_queue_threshold must be >= 1, got "
                             f"{preempt_queue_threshold}")
        self.deadline_default_ms = deadline_default_ms
        self.step_wall_budget_ms = step_wall_budget_ms
        self.preempt_queue_threshold = preempt_queue_threshold
        self.preempt_min_run_steps = int(preempt_min_run_steps)
        self._degradation = DegradationConfig.from_value(degradation)
        self._load = (LoadStateMachine(self._degradation)
                      if self._degradation is not None else None)
        self.faults = fault_injector
        if self.faults is not None and self._drafter is not None:
            # surface drafter faults exactly where a real drafter throws
            self._drafter = FaultInjectingDrafter(self._drafter, self.faults)
        # one tiny always-fixed-shape program: (num_slots,) bool of "is
        # every logit in this row finite". Guarding decode logits (not
        # every intermediate) catches poisoned rows before their token
        # is committed, at one watched jit and zero recompiles.
        if guard_numerics:
            self._jit_finite = jax.jit(
                lambda l: jnp.all(jnp.isfinite(l),
                                  axis=tuple(range(1, l.ndim))))
        else:
            self._jit_finite = None
        # -- stall-free admission config -------------------------------
        if prefill_chunk < 0:
            raise ValueError(f"prefill_chunk must be >= 0, got "
                             f"{prefill_chunk}")
        chunk = min(int(prefill_chunk), self.pool.capacity)
        # chunk starts are multiples of the chunk width, so requiring
        # capacity % chunk == 0 guarantees start + chunk <= capacity for
        # every chunk — the row's dynamic-update-slice can never clamp
        # and smear the final columns. Auto-halve rather than error:
        # chunk width is a latency knob, not a correctness contract.
        while chunk > 1 and self.pool.capacity % chunk != 0:
            chunk //= 2
        self._stall_free = (chunk > 0 and policy == "continuous" and
                            getattr(engine, "_jit_prefill_chunk", None)
                            is not None)
        self.prefill_chunk = chunk if self._stall_free else 0
        if self._stall_free:
            budget = (2 * chunk if prefill_token_budget is None
                      else int(prefill_token_budget))
            if budget < chunk:
                raise ValueError(
                    f"prefill_token_budget ({budget}) must be >= "
                    f"prefill_chunk ({chunk}); a smaller budget could "
                    f"never schedule the in-flight chunk")
            self.prefill_token_budget = budget
        else:
            self.prefill_token_budget = None
        # prefix-hit seating rides the chunked-prefill path (a hit seats
        # PREFILLING at its uncached suffix), so it needs stall-free mode
        self._use_prefix = (self._paged and self.pool.prefix is not None
                            and self._stall_free)
        if self._paged:
            # build the paged gather/scatter jits now so _ensure_watch
            # wraps them before any traffic
            self.pool.bind_engine(engine)
        # FIFO of seated PREFILLING requests whose prompts are still
        # streaming in chunk by chunk; step() advances the head only
        self._prefill_queue: List[Request] = []
        self.temperature = cfg.temperature if temperature is None else temperature
        self.top_k = cfg.top_k if top_k is None else top_k
        self.top_p = cfg.top_p if top_p is None else top_p
        self._greedy = jnp.asarray(not do_sample)
        self._rng = jax.random.PRNGKey(seed)
        self._slot_req: dict = {}                      # slot -> Request
        self._current = np.zeros((num_slots,), np.int32)  # last token per slot
        # device twin of _current: decode/spec dispatch read it so a step
        # never blocks on the previous step's sampled token reaching the
        # host. The host copy is refreshed at the single end-of-step fetch.
        # device_put with the mesh's replicated sharding (not jnp.zeros)
        # so the array is COMMITTED and placed exactly like the jit
        # outputs that later replace it — an uncommitted or
        # single-device first arg would give _jit_cur_scatter a second
        # cache entry for the same shapes, a recompile the watchdog
        # rightly flags.
        # canonical placement for the twin: the pool's resolved ``index``
        # sharding (slots over `data` when the mesh and count allow,
        # replicated otherwise — so TP=1/DP=1 keeps today's placement
        # bitwise). EVERY producer of _cur_dev is pinned to it; GSPMD is
        # otherwise free to hand back the sampler's batch-sharded layout
        # and fork _jit_cur_scatter the first time an admission lands
        # after a decode (warmup can't sweep that ordering).
        self._cur_sharding = (
            self._pool_sharding("index", np.zeros((num_slots,), np.int32))
            if callable(self._pool_sharding) else self._rep_sharding())
        self._cur_dev = jax.device_put(
            np.zeros((num_slots,), np.int32), self._cur_sharding)
        self._jit_cur_scatter = jax.jit(
            lambda cur, tok, slots: cur.at[slots].set(tok, mode="drop"),
            out_shardings=self._cur_sharding)
        # after a verify step the new current token for row b is the last
        # *emitted* token: out[b, n_emit[b]-1] (n_emit >= 1 for live rows;
        # the max() guards masked rows, whose value is never surfaced)
        self._jit_spec_cur = jax.jit(
            lambda out, n_emit: jnp.take_along_axis(
                out, jnp.maximum(n_emit - 1, 0)[:, None],
                axis=1)[:, 0].astype(jnp.int32),
            out_shardings=self._cur_sharding)
        self._overlap = bool(overlap)
        # -- disaggregated prefill/decode role (ISSUE 19) --------------
        # "both" is the classic colocated engine. "prefill" runs
        # admission/chunked prefill only and parks each request once its
        # pages are full and its first token sampled (see
        # pending_handoffs); "decode" additionally accepts adopted
        # requests whose prefill ran elsewhere. Roles change NO jit
        # signature — every program is built and warmed identically, a
        # prefill engine simply never dispatches the decode ones.
        if role not in ("both", "prefill", "decode"):
            raise ValueError(f"role must be both|prefill|decode, "
                             f"got {role!r}")
        if role != "both" and not self._paged:
            raise ValueError("prefill/decode roles require paged_kv: "
                             "pages are the cross-replica handoff unit")
        self.role = role
        # prefill role: seated RUNNING requests whose prompts are fully
        # paged in and first token sampled, awaiting transfer to a
        # decode replica (they hold their slot+pages until adopted)
        self._handoff_ready: Optional[List[Request]] = \
            [] if role == "prefill" else None
        # pre-warm every reachable cur-scatter width NOW, before the
        # watchdog attaches below: singles scatter (1,) and batched
        # admissions scatter the power-of-two group buckets, a bounded
        # family warmup traffic cannot be relied on to sweep (an engine
        # warmed on sequential requests would otherwise compile its
        # first batched bucket under load)
        rep = self._rep_sharding()
        nb = 1
        while True:
            self._jit_cur_scatter(
                self._cur_dev,
                self._cur_commit(np.zeros((nb,), np.int32)),
                jnp.asarray(np.full((nb,), num_slots, np.int32)))
            if nb >= num_slots:
                break
            nb *= 2
        if self._spec is not None:
            self._jit_spec_cur(
                jax.device_put(np.zeros((num_slots, self._spec.k + 1),
                                        np.int32), rep),
                jax.device_put(np.ones((num_slots,), np.int32), rep))
        # deferred host work: (device_arrays, callback) pairs queued at
        # dispatch time and replayed — in dispatch order — after the one
        # blocking fetch in _drain_deferred at the end of step()
        self._deferred: List[Any] = []
        self.timers = SynchronizedWallClockTimer()
        self._next_id = 0
        self._ensure_watch()
        log_dist(f"ServingEngine: slots={num_slots} policy={policy} "
                 f"capacity={self.pool.capacity} "
                 f"max_queue_depth={max_queue_depth} "
                 f"admission={'stall-free chunk=%d budget=%d' % (self.prefill_chunk, self.prefill_token_budget) if self._stall_free else 'serial'}",
                 ranks=[0])

    # ------------------------------------------------------------------
    def _ensure_watch(self) -> None:
        """(Re-)attach the recompile watchdog to every jitted entry point.

        Idempotent and cheap (a handful of getattr/isinstance checks);
        called once per step because ``_jit_verify_k`` is created lazily
        on the first speculative verify and tests swap jits in and out."""
        wd = self.watchdog
        for attr in _WATCHED_ENGINE_JITS:
            wd.attach(self.engine, attr, name=f"InferenceEngine.{attr}")
        for attr in _WATCHED_POOL_JITS:
            wd.attach(self.pool, attr, name=f"SlotPool.{attr}")
        for attr in _WATCHED_SERVING_JITS:
            wd.attach(self, attr, name=f"ServingEngine.{attr}")
        if self._drafter is not None:
            # unwrap the fault-injection shim; the jit lives on the
            # real drafter
            drafter = getattr(self._drafter, "inner", self._drafter)
            for attr in _WATCHED_DRAFTER_JITS:
                wd.attach(drafter, attr, name=f"Drafter.{attr}")

    def end_warmup(self) -> None:
        """Declare warmup traffic over: from here on, any recompile counts
        against :attr:`watchdog` ``.recompiles`` (and raises in strict
        mode at the next step boundary)."""
        self.watchdog.end_warmup()

    # -- warmup signature manifest (graftcheck witness) -----------------
    def _signature_env(self) -> dict:
        """The serving config knobs that determine the reachable jit
        signature set — the ``configs`` entry graftcheck re-enumerates
        under when diffing a manifest (analysis/interp.py drivers)."""
        pool = self.pool
        return {
            "num_slots": int(pool.num_slots),
            "capacity": int(pool.capacity),
            "prefill_chunk": int(self.prefill_chunk or 0),
            "prefill_token_budget": int(self.prefill_token_budget or 0),
            "paged": bool(self._paged),
            "paged_kernel": str(getattr(pool, "kernel", "off"))
            if self._paged else "off",
            "paged_kernel_active": bool(getattr(pool, "kernel_active",
                                                False)),
            "page_size": int(getattr(pool, "page_size", 0) or 0),
            "num_pages": int(getattr(pool, "num_pages", 0) or 0),
            "pages_per_slot": int(getattr(pool, "pages_per_slot", 0) or 0),
            "top_k": int(self.top_k or 0),
            "top_p": float(self.top_p),
            "temperature": float(self.temperature),
            "greedy": bool(np.asarray(self._greedy)),
            "spec_k": int(self._spec.k) if self._spec is not None else 0,
            "guard_numerics": self._jit_finite is not None,
            "use_prefix": bool(self._use_prefix),
            "stall_free": bool(self._stall_free),
            "overlap": bool(self._overlap),
            # role never moves a traced shape (same warmups, same
            # programs; a prefill engine just skips the decode
            # dispatch) — recorded for arm attribution like the mesh
            "role": str(self.role),
            # mesh shape the caches/params were committed under. The
            # jitted entries keep their signatures across mesh shapes
            # (the tentpole invariant — only in/out shardings move), so
            # the interp drivers ignore these keys; they are recorded so
            # a manifest diff can attribute a mismatch to the arm that
            # produced it.
            "mesh_data": int(self._mesh_axis_size("data")),
            "mesh_model": int(self._mesh_axis_size("model")),
        }

    def _mesh_axis_size(self, axis: str) -> int:
        mesh = getattr(self.engine, "mesh", None)
        if mesh is None:
            return 1
        return int(dict(mesh.shape).get(axis, 1))

    def export_signatures(self, path: str, merge: bool = False,
                          extra: Optional[dict] = None) -> dict:
        """Write (or merge into) a ``signatures.json`` warmup manifest:
        ``{"version": 1, "configs": [env...], "programs": {name:
        [sorted sigs]}}``.

        ``merge=True`` unions with an existing file — bench rows run
        several serving arms against one shared inference engine, so
        the shared engine jits see every arm's traffic and the manifest
        is only meaningful as the union.  ``extra`` adds workload keys
        the config alone cannot know (vocab size, prompt-length sweep
        bounds)."""
        import json
        import os

        env = self._signature_env()
        if extra:
            env.update(extra)
        programs = self.watchdog.signature_manifest()
        doc = {"version": 1, "configs": [env], "programs": programs}
        if merge and os.path.exists(path):
            with open(path, encoding="utf-8") as fh:
                old = json.load(fh)
            configs = [c for c in old.get("configs", []) if c != env]
            doc["configs"] = configs + [env]
            merged = {k: set(v) for k, v in old.get("programs", {}).items()}
            for name, sigs in programs.items():
                merged.setdefault(name, set()).update(sigs)
            doc["programs"] = {name: sorted(sigs)
                               for name, sigs in sorted(merged.items())}
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(doc, fh, indent=2, sort_keys=True)
            fh.write("\n")
        return doc

    def set_tracer(self, tracer) -> None:
        """Swap the tracer in post-construction (e.g. a traced replay on
        an already-warmed server in ``bench.py --trace``)."""
        self.tracer = tracer
        self.timelines.tracer = tracer
        self.watchdog.tracer = tracer
        if self.slo is not None:
            self.slo.tracer = tracer

    def timeline(self, request_id: int):
        """Lifecycle events recorded for one request id (oldest first),
        or None if the id is unknown/evicted."""
        return self.timelines.get(request_id)

    def publish_telemetry(self) -> int:
        """Flush the metrics registry as ``telemetry/*`` monitor events
        on the current step axis; returns the number of events."""
        return self.registry.publish(self.metrics.monitor, self.step_id)

    # -- efficiency / goodput / flight recorder (ISSUE 8) --------------
    def _collect_telemetry_health(self) -> None:
        """Registry collector (runs at every snapshot/Prometheus
        scrape): pull-time counters that would be wasteful to push from
        the hot path — tracer ring totals/drops, JSONL sink write
        errors, flight-recorder activity."""
        g = self.registry.gauge
        g("telemetry/tracer_events_total").set(float(self.tracer.events_total))
        g("telemetry/tracer_dropped").set(float(self.tracer.dropped))
        mon = self.metrics.monitor
        jm = getattr(mon, "jsonl_monitor", None)
        if jm is None and hasattr(mon, "write_errors"):
            jm = mon          # a bare JSONLMonitor passed as the sink
        if jm is not None:
            g("monitor/jsonl_write_errors").set(
                float(getattr(jm, "write_errors", 0)))
        if self.recorder is not None:
            g("telemetry/flight_recorder_records").set(
                float(self.recorder.records_total))
            g("telemetry/postmortem_dumps").set(
                float(self.recorder.dump_count))

    @property
    def telemetry_overhead_s(self) -> float:
        """Host seconds spent in the ISSUE-8 instrumentation: the
        self-timed step-boundary block plus the cost model's per-call
        accounting and the SLO tracker's observe/on_step work (one-time
        AOT harvests are excluded — they are warmup, reported
        separately in ``costs.summary()['harvest_s']``)."""
        total = self._telemetry_ns / 1e9
        if self.costs is not None:
            total += self.costs.overhead_s
        if self.slo is not None:
            total += self.slo.overhead_s
        return total

    def _telemetry_step(self, wall: float, running_at_entry: int,
                        granted: List[Request],
                        finished: List[Request]) -> None:
        """Step-boundary efficiency/SLO/flight-recorder bookkeeping,
        self-timed so benches can report instrumentation overhead_pct
        honestly instead of diffing noisy wall clocks."""
        costs, slo, rec = self.costs, self.slo, self.recorder
        if costs is None and slo is None and rec is None:
            return
        t0 = time.perf_counter_ns()
        # the SLO tracker self-times its own methods; subtract its delta
        # from this envelope so telemetry_overhead_s never double-counts
        slo_ns0 = slo.overhead_ns if slo is not None else 0
        tokens = self._tokens_emitted - self._tokens_prev
        self._tokens_prev = self._tokens_emitted
        if slo is not None:
            if running_at_entry:
                slo.observe_gap(wall)
            slo.on_step(self.step_id)
        if costs is not None:
            costs.step_update(wall, tokens=tokens, tracer=self.tracer)
            if self.step_id % costs.kv_every == 0:
                costs.reconcile_kv(self.pool, monitor=self.metrics.monitor,
                                   step=self.step_id, tracer=self.tracer)
        if rec is not None:
            rec.record(self._step_record(wall, granted, finished))
        spent = time.perf_counter_ns() - t0
        if slo is not None:
            spent -= slo.overhead_ns - slo_ns0
        self._telemetry_ns += spent

    def _step_record(self, wall: float, granted: List[Request],
                     finished: List[Request]) -> dict:
        rec = {
            "step_id": self.step_id,
            "t_unix": time.time(),
            # the shared injected clock: fleet post-mortems align every
            # replica's ring on this axis, not the per-replica step_id
            "t": self._now(),
            "replica": self.replica_id,
            "wall_ms": wall * 1e3,
            "live": len(self._slot_req),
            "pending": self.scheduler.pending,
            "prefilling": len(self._prefill_queue),
            "free_slots": self.pool.free_count,
            "granted": [r.request_id for r in granted],
            "finished": [r.request_id for r in finished],
            "tokens_total": self._tokens_emitted,
            "load_state": (self._load.state.name
                           if self._load is not None else None),
            "alert_state": (self.slo.alert_state
                            if self.slo is not None else None),
        }
        if self._paged:
            rec["free_pages"] = self.pool.free_page_count
        return rec

    def _post_mortem(self, reason: str, error: Any = None,
                     extra: Optional[dict] = None) -> Optional[str]:
        """Write a flight-recorder post-mortem dump (no-op without a
        recorder or ``dump_dir``); never raises — the caller is already
        unwinding the real failure."""
        if self.recorder is None:
            return None
        try:
            return self.recorder.dump(
                reason, error=error, timelines=self.timelines,
                registry=self.registry, tracer=self.tracer, extra=extra)
        except Exception:       # pragma: no cover - defensive
            return None

    def debug_dump(self) -> dict:
        """Live statusz snapshot: the flight-recorder ring, open
        request timelines, registry, watchdog summary, every
        non-terminal request's host state, and (when enabled) the SLO
        and cost-model summaries — the same payload a post-mortem file
        wraps, served from a healthy process."""
        rec = self.recorder if self.recorder is not None \
            else FlightRecorder(capacity=1)
        out = rec.snapshot(timelines=self.timelines,
                           registry=self.registry, tracer=self.tracer)
        out.update(step_id=self.step_id, live=self.live_count,
                   pending=self.scheduler.pending,
                   requests=self._stuck_dump(),
                   load_state=(self._load.state.name
                               if self._load is not None else None),
                   watchdog=self.watchdog.summary(),
                   telemetry_overhead_s=self.telemetry_overhead_s)
        if self.slo is not None:
            out["slo"] = self.slo.snapshot()
        if self.costs is not None:
            out["costs"] = self.costs.summary()
        return out

    def efficiency_snapshot(self) -> dict:
        """Bench-facing rollup: cost-model MFU/bandwidth, SLO goodput +
        digest percentiles, KV HBM reconciliation, and instrumentation
        overhead (as a fraction of accumulated step wall)."""
        out: dict = {"telemetry_overhead_s": self.telemetry_overhead_s}
        wall = None
        if self.costs is not None:
            # pull-time freshness: the loop reconciles only every
            # kv_every steps, a snapshot should never serve stale drift
            self.costs.reconcile_kv(self.pool, step=self.step_id)
            cs = self.costs.summary()
            wall = cs["wall_s"]
            out["costs"] = cs
            out["mfu"] = cs["mfu"]
            out["bandwidth_util"] = cs["bandwidth_util"]
            hbm = cs["hbm"]
            out["hbm_drift"] = hbm.get("hbm_drift")
            out["hbm_peak_bytes"] = hbm.get("hbm_peak_bytes")
        if self.slo is not None:
            ss = self.slo.snapshot()
            out["slo"] = ss
            out["goodput_slo"] = ss["goodput_slo"]
            out["ttft_p99_ms"] = ss["ttft_p99_ms"]
            out["gap_p99_ms"] = ss["gap_p99_ms"]
            out["alert_state"] = ss["alert_state"]
        if not wall:
            # no cost model: fall back to the accumulated step wall so
            # overhead_pct is still honest on SLO-only configurations
            wall = self.step_wall_s
        if wall:
            out["overhead_pct"] = 100.0 * out["telemetry_overhead_s"] / wall
        return out

    def reset_efficiency_window(self) -> None:
        """Zero cost-model totals, SLO windows, and overhead clocks
        (harvested program costs are kept) — benches call this after
        warmup so efficiency numbers cover only the measured run."""
        if self.costs is not None:
            self.costs.reset_totals()
        if self.slo is not None:
            self.slo.reset()
        self._telemetry_ns = 0
        self.step_wall_s = 0.0
        self._tokens_prev = self._tokens_emitted

    def _chaos_corrupt_state(self) -> None:
        """Chaos-only (the ``state_corruption`` fault point):
        deliberately corrupt slot bookkeeping — a seated slot marked
        free, or a free slot dropped — so the ``check_invariants``
        audit and the flight recorder behind it are proven against REAL
        corruption. Only reachable through an armed FaultInjector."""
        if self._slot_req:
            self.pool._free_set.add(min(self._slot_req))
        elif self.pool._free_set:
            self.pool._free_set.discard(min(self.pool._free_set))
        self.tracer.instant("chaos/state_corruption")

    @property
    def live_count(self) -> int:
        return len(self._slot_req)

    @property
    def pending(self) -> int:
        return self.scheduler.pending

    def submit(self, prompt, max_new_tokens: int = 32,
               eos_token_id: Optional[int] = None,
               deadline_ms: Optional[float] = None,
               priority: Optional[str] = None,
               tenant: Optional[str] = None) -> Request:
        """Enqueue one generation request. Never raises on load: admission
        control marks the returned request ``REJECTED`` with a
        ``reject_reason`` (``"queue_full"``, ``"prompt_too_long"``,
        ``"rate_limited"``/``"tenant_quota"`` under tenant policies, or
        ``"retry_after"`` when overload or burn-rate shedding is active
        — then ``req.retry_after_s`` carries the backoff hint) so
        callers can shed or retry.

        ``priority``/``tenant`` (priority scheduling only) pick the
        request's class and rate-limit bucket; an unknown class raises
        ``ValueError``. Burn-rate shedding: when a class's SLO burn
        alert is at warn/page, submissions of STRICTLY LOWER classes are
        shed with ``retry_after`` — the error budget of a paying tier is
        defended by refusing work that would preempt it anyway.

        ``deadline_ms`` (or the engine-wide ``deadline_default_ms``)
        arms a TTL from submission: a request that can't finish in time
        retires with ``finish_reason="deadline"`` — out of the queue
        before ever costing a prefill, or out of its slot via the usual
        release/masking rollback."""
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got "
                             f"{max_new_tokens}")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        req = Request(self._next_id, prompt, max_new_tokens, eos_token_id)
        self._next_id += 1
        if self._priority is not None:
            req.priority_class = (priority if priority is not None
                                  else self._priority.default_class)
            self.scheduler.rank_of(req.priority_class)  # loud on unknown
        elif priority is not None:
            raise ValueError("priority classes require a priority-enabled "
                             "engine (pass priority=True/config to "
                             "ServingEngine / init_serving)")
        if tenant is not None:
            req.tenant = str(tenant)
        req.submit_time = self._now()
        ttl = deadline_ms if deadline_ms is not None \
            else self.deadline_default_ms
        if ttl is not None:
            if ttl <= 0:
                raise ValueError(f"deadline_ms must be > 0, got {ttl}")
            req.deadline_ms = float(ttl)
            req.deadline_time = req.submit_time + float(ttl) / 1e3
        if self._load is not None and self._load.state is LoadState.OVERLOADED:
            # overload shedding: stop feeding the queue before it melts;
            # rejected-with-retry_after is cheaper for everyone than an
            # accepted request that will blow its deadline anyway
            accepted, reason = False, RejectReason.RETRY_AFTER
            req.retry_after_s = self._degradation.retry_after_s
        elif self._shed_by_burn(req):
            accepted, reason = False, RejectReason.RETRY_AFTER
            if req.retry_after_s is None:
                req.retry_after_s = (
                    self._degradation.retry_after_s
                    if self._degradation is not None else 1.0)
        else:
            accepted, reason = self.scheduler.submit(req)
        self.timelines.record(req.request_id, "submitted",
                              prompt_len=req.prompt_len,
                              max_new_tokens=max_new_tokens,
                              priority_class=req.priority_class,
                              tenant=req.tenant)
        if not accepted:
            req.state = RequestState.REJECTED
            req.reject_reason = reason
            self.metrics.record_rejection(req)
            self.timelines.record(req.request_id, "rejected", terminal=True,
                                  reason=reason.value,
                                  retry_after_s=req.retry_after_s)
        elif self.slo is not None:
            # goodput denominator: every ADMITTED request counts against
            # the window, whether or not it ever finishes in time
            self.slo.observe_admitted(cls=req.priority_class)
        return req

    def _shed_floor(self) -> Optional[int]:
        """The lowest class rank still admitted under burn-rate
        shedding, or None when nothing is burning (or priority/SLO
        tracking is off). When class ``k``'s burn alert is warn/page,
        every class ranked strictly below ``k`` is shed — the floor is
        the highest-priority burning class's own rank."""
        if self._priority is None or self.slo is None:
            return None
        floor = None
        for cls, alert in self.slo.class_alerts.items():
            if alert in ("warn", "page"):
                try:
                    k = self.scheduler.rank_of(cls)
                except ValueError:
                    continue  # SLO classes need not all be sched classes
                floor = k if floor is None else min(floor, k)
        return floor

    def _shed_by_burn(self, req: Request) -> bool:
        floor = self._shed_floor()
        return floor is not None \
            and self.scheduler.rank_of(req.priority_class) > floor

    # ------------------------------------------------------------------
    @staticmethod
    def _rep_sharding():
        """Replicated NamedSharding on the global mesh — the placement
        every serving jit output carries, so host-built device arrays
        (the current-token twin) share a jit cache entry with them."""
        from ..parallel import mesh as mesh_mod
        return NamedSharding(mesh_mod.get_mesh(), PartitionSpec())

    def _cur_commit(self, arr):
        """Commit a current-token-family array (any width) to the same
        resolved slots placement the pool's ``index`` leaf carries —
        shape-aware, so a (1,) single-admission token stays replicated
        while a full-width batch shards with the pool. Pinning every
        producer keeps ``_jit_cur_scatter`` at one executable per width
        no matter what layout GSPMD picked for the sampler output."""
        if callable(self._pool_sharding):
            sh = self._pool_sharding("index", np.asarray(arr))
        else:
            sh = self._rep_sharding()
        return jax.device_put(arr, sh)

    def _sample_dev(self, logits):
        """Dispatch the sampler and return the token *device* array.

        No host sync happens here: callers stash the array (plus a
        closure that needs its host value) via :meth:`_defer`, and the
        single blocking fetch at the end of :meth:`step` replays every
        closure in dispatch order. Per-row sampling is independent
        (``categorical``/``argmax`` act row-wise on one split key), so
        batching rows from different call sites cannot change values."""
        self._rng, sub = jax.random.split(self._rng)
        return self.engine._jit_sample(
            logits, sub, jnp.asarray(self.temperature, jnp.float32),
            int(self.top_k), float(self.top_p), self._greedy)

    def _defer(self, arrays, callback) -> None:
        """Queue ``callback(*host_values)`` until the end-of-step fetch.

        ``arrays`` is a list of device arrays; the callback receives the
        same list with every element converted via ``np.asarray`` after
        the step's one ``block_until_ready``."""
        self._deferred.append((list(arrays), callback))

    def _drain_deferred(self, *, sync: bool = True) -> None:
        """The step's single device sync: block on every deferred array
        at once, then replay the queued host bookkeeping in dispatch
        order. ``serving/step_fetch`` times exactly the blocking wait."""
        if not self._deferred:
            return
        pending, self._deferred = self._deferred, []
        bundle = [a for arrays, _ in pending for a in arrays]
        if sync:
            timer = self.timers("serving/step_fetch")
            timer.start()
            # graftlint: allow[hot-loop-host-sync] -- the step's ONE deliberate sync: every deferred token/flag fetch collapses onto this block
            timer.stop(block_on=bundle)
        for arrays, callback in pending:
            callback(*[np.asarray(a) for a in arrays])

    @staticmethod
    def _bucket(n: int, cap: int) -> int:
        b = _MIN_PREFILL_BUCKET
        while b < n:
            b *= 2
        return min(b, cap)

    def _admit(self, req: Request, finished: List[Request]) -> None:
        eng = self.engine
        slot = self.pool.alloc()
        # rollback snapshot: a PREEMPTED request arrives carrying its
        # generated-so-far tokens and first-token stamp — a failed
        # re-admission must restore exactly that state, never wipe it
        n0 = len(req.output_tokens)
        admit0, first0 = req.admit_time, req.first_token_time
        try:
            if self.faults is not None:
                self.faults.check("admit_oom")
            seed = req.seed_tokens        # prompt, + outputs when resumed
            T = req.seed_len
            width = self._bucket(T, self.pool.capacity)
            ids = np.zeros((1, width), np.int32)
            ids[0, :T] = seed
            running_before = self._running_count()
            req.admit_time = self._now()
            with self.tracer.span("serving/admit", rid=req.request_id,
                                  tokens=T, width=width):
                logits, pre_cache = eng._jit_prefill_at(
                    eng.params, jnp.asarray(ids),
                    jnp.asarray(T - 1, jnp.int32))
                self.pool.admit(pre_cache, slot, T)
                with self.tracer.span("serving/sample"):
                    # dispatch only; the host value arrives at the
                    # end-of-step fetch
                    tok_dev = self._cur_commit(self._sample_dev(logits))
                self._cur_dev = self._jit_cur_scatter(
                    self._cur_dev, tok_dev, jnp.asarray([slot]))
            now = self._now()
            self.metrics.record_prefill(T, now - req.admit_time,
                                        blocking=running_before > 0)
            req.slot = slot
            self._slot_req[slot] = req
            req.state = RequestState.RUNNING
            req.last_admit_step = self.step_id
            self.timelines.record(req.request_id, "admitted", slot=slot,
                                  mode="bucketed")
            self.tracer.flow("s", "req", req.request_id)

            def _on_first_token(tok, req=req, slot=slot, n0=n0):
                token = int(tok[0])
                if req.first_token_time is None:
                    req.first_token_time = self._now()
                req.output_tokens.append(token)
                self._tokens_emitted += 1
                self._current[slot] = token
                if n0 == 0:
                    self.timelines.record(req.request_id, "first_token")
                self._maybe_retire(req, token, finished)

            self._defer([tok_dev], _on_first_token)
        except Exception:
            # undo the partial admission so the request can be re-queued
            # with no trace: the slot goes back and timing/output state
            # reverts to the pre-admission snapshot, so _abort_step sees
            # a clean QUEUED request (resumed ones keep their tokens)
            self._slot_req.pop(slot, None)
            self.pool.release(slot)
            req.state = RequestState.QUEUED
            req.slot = None
            req.admit_time = admit0
            req.first_token_time = first0
            del req.output_tokens[n0:]
            raise
        if self._use_prefix:
            # publish the freshly-prefilled full prompt pages (refcounted
            # past this slot's lifetime) for the next same-prefix request
            self.pool.cache_prefix(slot, seed)

    def _running_count(self) -> int:
        return sum(1 for r in self._slot_req.values()
                   if r.state is RequestState.RUNNING)

    def _admission_cost(self, req: Request) -> int:
        """Prefill tokens this grant charges against the step budget: the
        padded bucket width for a whole-seed admission, one chunk for a
        long seed (only its first chunk can run this step). Preempted
        requests are charged for prompt + generated-so-far — that is
        what re-admission actually prefills."""
        T = req.seed_len
        if T <= self.prefill_chunk:
            return self._bucket(T, self.pool.capacity)
        return self.prefill_chunk

    # -- paged KV: page accounting and prefix-hit seating --------------
    def _prefix_plan(self, hit_tokens: int, seed_len: int) -> int:
        """Where a prefix-hit admission starts prefilling. A full hit
        still re-prefills the LAST chunk (the final-chunk logits sample
        the first token, exactly like a cold chunked admission — bitwise
        parity); the start is aligned DOWN to a chunk multiple so every
        chunk keeps the start+chunk <= capacity invariant the chunk
        program's update-slice relies on."""
        C = max(self.prefill_chunk, 1)
        if hit_tokens >= seed_len:
            pos0 = seed_len - min(C, seed_len)
        else:
            pos0 = min(hit_tokens, seed_len)
        return (pos0 // C) * C

    def _page_cost(self, req: Request) -> int:
        """FRESH pages seating this request allocates right now: the
        pages covering its uncached suffix (CoW forks included; shared
        prefix pages are free — a refcount bump). Decode-time growth is
        deliberately NOT charged — that is the oversubscription bet,
        underwritten by trie eviction + pressure preemption."""
        ps = self.pool.page_size
        seed = req.seed_len
        pos0 = 0
        if self._use_prefix:
            hit = self.pool.prefix.peek(req.seed_tokens) * ps
            pos0 = self._prefix_plan(hit, seed)
        return (seed - 1) // ps - pos0 // ps + 1

    def _grant_page_budget(self) -> int:
        """Pages the grant may promise this step: free now, plus what
        trie eviction could reclaim without preempting anyone."""
        return self.pool.free_page_count + self.pool.evictable_page_count()

    def _ensure_pages(self, slot: int, start: int, end: int) -> None:
        """ensure_writable with the pressure valve: on PagePoolExhausted
        (free list empty AND trie eviction dry), preempt the youngest
        OTHER seated request — its pages come back to the free list —
        and retry. Only when no victim remains does the exhaustion
        propagate (a sizing bug: one request's footprint exceeds the
        whole pool, which the submit-time page check rejects)."""
        while True:
            try:
                self.pool.ensure_writable(slot, start, end)
                return
            except PagePoolExhausted:
                victims = [
                    r for r in select_victims(
                        list(self._slot_req.values()),
                        n=len(self._slot_req), current_step=self.step_id,
                        min_run_steps=0, class_rank=self._class_rank)
                    if r.slot != slot]
                if not victims:
                    raise
                self._preempt_req(victims[0], auto=True)

    def _ensure_decode_pages(self, width: int) -> None:
        """Back every RUNNING slot's next ``width`` write columns with
        exclusively-owned pages before the decode/verify dispatch.
        PREFILLING slots are skipped on purpose: their masked garbage
        writes hit unmapped entries (scatter drops them) or pages the
        seating already CoW-forked — allocating for garbage would waste
        pages under pressure."""
        for slot, req in list(self._slot_req.items()):
            if req.state is RequestState.RUNNING:
                idx = int(self.pool.starts[slot])
                self._ensure_pages(slot, idx, idx + width)

    def _admit_prefix_hit(self, req: Request) -> bool:
        """Try to seat ``req`` through the prefix cache: walk the trie,
        map the cached pages into a fresh slot for free, and enter the
        chunked-prefill path at the first uncached position. Returns
        False on a miss (caller falls through to the cold paths)."""
        pool = self.pool
        seed = req.seed_tokens
        seed_len = req.seed_len
        pages = pool.prefix.match(seed)
        hit = len(pages) * pool.page_size
        pos0 = self._prefix_plan(hit, seed_len)
        self.metrics.record_prefix(pos0, seed_len)
        if pos0 <= 0:
            return False     # nothing actually skipped: cold path
        now = self._now()    # before alloc: nothing may fail while the
        slot = pool.alloc()  # slot is held but not yet seated
        try:
            if self.faults is not None:
                self.faults.check("admit_oom")
            pool.reset_row(slot)
            pool.seat_prefix(slot, pages, pos0)
        except PagePoolExhausted:
            # the uncached suffix needs more fresh pages than remain:
            # release (unmapping anything seated so far) and retry next
            # step once eviction/preemption has freed pages
            pool.release(slot)
            req.state = RequestState.QUEUED
            req.slot = None
            self.scheduler.requeue_front([req])
            self.timelines.record(req.request_id, "requeued",
                                  reason="page_pressure")
            return True
        except Exception:
            pool.release(slot)
            req.state = RequestState.QUEUED
            req.slot = None
            raise
        req.admit_time = now
        req.slot = slot
        req.prefill_pos = pos0
        req.prefix_hit_tokens = pos0
        req.state = RequestState.PREFILLING
        req.last_admit_step = self.step_id
        self._slot_req[slot] = req
        self._prefill_queue.append(req)
        self.timelines.record(req.request_id, "admitted", slot=slot,
                              mode="prefix_hit")
        self.timelines.record(req.request_id, "prefix_hit",
                              hit_tokens=pos0, seed_len=seed_len)
        self.tracer.flow("s", "req", req.request_id)
        return True

    def _admit_stall_free(self, granted: List[Request],
                          finished: List[Request]) -> None:
        """Seat every granted request: long prompts become PREFILLING
        (their cache rows fill chunk by chunk in later steps), short
        prompts are grouped by padded bucket width and each group is
        prefilled + scattered in ONE batched dispatch."""
        groups: dict = {}
        for req in granted:
            if self._use_prefix and self._admit_prefix_hit(req):
                continue          # seated PREFILLING at its uncached
            #                       suffix (or re-queued under pressure)
            T = req.seed_len
            if T > self.prefill_chunk:
                now = self._now()
                slot = self.pool.alloc()
                try:
                    self.pool.reset_row(slot)
                except Exception:
                    # nothing seated yet: hand the slot straight back so
                    # a row-scrub failure cannot strand it
                    self.pool.release(slot)
                    raise
                req.admit_time = now
                req.slot = slot
                req.prefill_pos = 0
                req.state = RequestState.PREFILLING
                req.last_admit_step = self.step_id
                self._slot_req[slot] = req
                self._prefill_queue.append(req)
                self.timelines.record(req.request_id, "admitted", slot=slot,
                                      mode="chunked")
                self.tracer.flow("s", "req", req.request_id)
            else:
                groups.setdefault(self._bucket(T, self.pool.capacity),
                                  []).append(req)
        for width in sorted(groups):
            group = groups[width]
            if len(group) == 1:
                # singleton: the per-request path (no sentinel padding,
                # no scatter program) is strictly cheaper — the batched
                # dispatch only pays off when it coalesces ≥2 prompts
                self._admit(group[0], finished)
            else:
                self._admit_batch(group, width, finished)

    def _admit_batch(self, group: List[Request], width: int,
                     finished: List[Request]) -> None:
        """Batched bucketed admission: ``len(group)`` same-bucket prompts
        prefilled in one ``prefill_last`` dispatch at a power-of-two
        batch, then scattered into their slots by one jitted multi-row
        admit. Compile count: log2(num_slots) batch buckets x
        log2(max_seq_len) width buckets. Padding rows carry the slot
        sentinel ``num_slots`` (scatter drop-mode discards them)."""
        eng = self.engine
        n = len(group)
        nB = 1
        while nB < n:
            nB *= 2
        ids = np.zeros((nB, width), np.int32)
        last_pos = np.zeros((nB,), np.int32)
        slots = np.full((nB,), self.pool.num_slots, np.int32)
        lengths = np.zeros((nB,), np.int32)
        running_before = self._running_count()
        # rollback snapshots (preempted group members keep their tokens
        # and stamps if this dispatch dies — see _admit)
        n0s = [len(r.output_tokens) for r in group]
        stamps = [(r.admit_time, r.first_token_time) for r in group]
        try:
            if self.faults is not None:
                self.faults.check("admit_oom")
            for i, req in enumerate(group):
                T = req.seed_len
                ids[i, :T] = req.seed_tokens
                last_pos[i] = T - 1
                slots[i] = self.pool.alloc()
                lengths[i] = T
                req.admit_time = self._now()
            t0 = self._now()
            with self.tracer.span("serving/prefill_batch", n=n, width=width,
                                  batch=nB):
                logits, pre_cache = eng._jit_prefill_at(
                    eng.params, jnp.asarray(ids), jnp.asarray(last_pos))
                self.pool.admit_rows(pre_cache, slots, lengths)
                with self.tracer.span("serving/sample"):
                    # dispatch only; host values arrive at the
                    # end-of-step fetch
                    tokens_dev = self._cur_commit(self._sample_dev(logits))
                self._cur_dev = self._jit_cur_scatter(
                    self._cur_dev, tokens_dev, jnp.asarray(slots))
            now = self._now()
            self.metrics.record_prefill(int(lengths.sum()), now - t0,
                                        blocking=running_before > 0)
            for i, req in enumerate(group):
                slot = int(slots[i])
                req.slot = slot
                self._slot_req[slot] = req
                req.state = RequestState.RUNNING
                req.last_admit_step = self.step_id
                self.timelines.record(req.request_id, "admitted", slot=slot,
                                      mode="batched")
                self.tracer.flow("s", "req", req.request_id)
                if self._use_prefix:
                    self.pool.cache_prefix(slot, req.seed_tokens)

            def _on_batch_tokens(tokens, group=group, slots=slots, n0s=n0s):
                now = self._now()
                for i, req in enumerate(group):
                    token = int(tokens[i])
                    slot = int(slots[i])
                    if req.first_token_time is None:
                        req.first_token_time = now
                    req.output_tokens.append(token)
                    self._tokens_emitted += 1
                    self._current[slot] = token
                    if n0s[i] == 0:
                        self.timelines.record(req.request_id, "first_token")
                    self._maybe_retire(req, token, finished)

            self._defer([tokens_dev], _on_batch_tokens)
        except Exception:
            # roll the whole group back to clean QUEUED requests so
            # _abort_step re-queues them with no trace (resumed members
            # revert to their pre-admission snapshots)
            for i, req in enumerate(group):
                slot = int(slots[i])
                if slot < self.pool.num_slots:
                    self._slot_req.pop(slot, None)
                    self.pool.release(slot)
                req.state = RequestState.QUEUED
                req.slot = None
                req.admit_time, req.first_token_time = stamps[i]
                del req.output_tokens[n0s[i]:]
            raise

    def _prefill_chunk_step(self, finished: List[Request]) -> None:
        """Run AT MOST one bounded prefill chunk — for the head of the
        prefill queue — so per-step latency stays bounded by the token
        budget no matter how long the queued prompts are. The final
        chunk projects the prompt's true last position, samples the
        first token, and flips the request to RUNNING."""
        if not self._prefill_queue:
            return
        req = self._prefill_queue[0]
        slot = req.slot
        C = self.prefill_chunk
        pos = req.prefill_pos
        seed = req.seed_tokens            # prompt, + outputs when resumed
        seed_len = req.seed_len
        L = min(C, seed_len - pos)
        ids = np.zeros((1, C), np.int32)
        ids[0, :L] = seed[pos:pos + L]
        running_before = self._running_count()
        t0 = self._now()
        if self._paged:
            # the chunk's write window must land in owned pages BEFORE
            # the dispatch (allocating / CoW-forking under pressure may
            # preempt a victim — host work, so it happens outside jit)
            self._ensure_pages(slot, pos, pos + L)
        with self.tracer.span("serving/prefill_chunk", rid=req.request_id,
                              pos=pos, len=L):
            if self._paged:
                logits = self.pool.run_prefill_chunk(
                    self.engine, ids, slot, pos, L, L - 1)
            else:
                logits, cache = self.engine.prefill_chunk(
                    self.pool.cache, ids, slot, pos, L, L - 1)
                self.pool.cache = cache
        self.pool.starts[slot] = pos + L  # device index moved in-program
        req.prefill_pos = pos + L
        req.chunks += 1
        self.timelines.record(req.request_id, "prefill_chunk", pos=pos,
                              len=L)
        if req.prefill_pos >= seed_len:
            with self.tracer.span("serving/sample"):
                # dispatch only; host value arrives at the end-of-step
                # fetch
                tok_dev = self._cur_commit(self._sample_dev(logits))
            self._cur_dev = self._jit_cur_scatter(
                self._cur_dev, tok_dev, jnp.asarray([slot]))
            self.metrics.record_prefill(L, self._now() - t0,
                                        blocking=running_before > 0)
            self._prefill_queue.pop(0)
            req.state = RequestState.RUNNING
            req.last_admit_step = self.step_id
            if self._use_prefix:
                self.pool.cache_prefix(slot, seed)

            def _on_chunk_token(tok, req=req, slot=slot):
                token = int(tok[0])
                first = req.first_token_time is None
                if first:
                    req.first_token_time = self._now()
                req.output_tokens.append(token)
                self._tokens_emitted += 1
                self._current[slot] = token
                if first:
                    self.timelines.record(req.request_id, "first_token")
                self._maybe_retire(req, token, finished)

            self._defer([tok_dev], _on_chunk_token)
        else:
            # no sync: the chunk is enqueued and this step's decode
            # dispatch overlaps its host-side latency — the device
            # serializes them anyway, and step_gap captures the real
            # wall cost. Recorded time is therefore enqueue-side only.
            self.metrics.record_prefill(L, self._now() - t0,
                                        blocking=running_before > 0)

    def _maybe_retire(self, req: Request, token: int,
                      finished: List[Request]) -> None:
        if req.eos_token_id is not None and token == req.eos_token_id:
            req.finish_reason = FinishReason.EOS
        elif len(req.output_tokens) >= req.max_new_tokens:
            req.finish_reason = FinishReason.LENGTH
        elif req.slot is not None and \
                int(self.pool.starts[req.slot]) >= self.pool.capacity:
            # the slot's cache row is full: retire rather than silently
            # clamp-overwrite the last column on the next decode write
            req.finish_reason = FinishReason.LENGTH_CAP
        else:
            if self._handoff_ready is not None and \
                    req.state is RequestState.RUNNING:
                # prefill role: pages full, first token sampled — the
                # request now belongs to a decode replica. It stays
                # seated (slot + page references held) until the router
                # transfers it or a rollback path retires it.
                self._handoff_ready.append(req)
                # parked: prefill done but no decode home yet — the
                # completeness probe must not count this as done even
                # though the timeline is still open
                self.timelines.record(req.request_id, "handoff_ready",
                                      parked=True, slot=req.slot,
                                      journey=req.journey_id)
            return
        req.state = RequestState.FINISHED
        req.finish_time = self._now()
        self.pool.release(req.slot)
        del self._slot_req[req.slot]
        self._finish_record(req)
        finished.append(req)

    def _finish_record(self, req: Request) -> None:
        """Shared terminal bookkeeping for every FINISHED retirement
        (normal, length-capped, or deadline-expired): metrics, the flow
        arrow, and the terminal timeline event."""
        self.metrics.record_finish(req)
        if self.slo is not None:
            if req.finish_reason is FinishReason.CANCELLED:
                # a client cancellation is neither good nor bad service:
                # withdraw the admission instead of judging latencies
                self.slo.observe_cancel(cls=req.priority_class)
            else:
                ok = req.finish_reason in (FinishReason.EOS,
                                           FinishReason.LENGTH,
                                           FinishReason.LENGTH_CAP)
                e2e = (req.finish_time - req.submit_time
                       if req.finish_time is not None and
                       req.submit_time is not None else None)
                self.slo.observe_finish(ttft_s=req.ttft,
                                        per_token_s=req.per_token_latency,
                                        e2e_s=e2e, ok=ok,
                                        cls=req.priority_class)
        self.tracer.flow("f", "req", req.request_id)
        self.timelines.record(req.request_id, "finished", terminal=True,
                              reason=FinishReason.of(req.finish_reason).value,
                              new_tokens=len(req.output_tokens),
                              chunks=req.chunks,
                              spec_drafted=req.spec_drafted,
                              spec_accepted=req.spec_accepted)

    # -- disaggregated prefill/decode handoff (ISSUE 19) ---------------
    def pending_handoffs(self) -> List[Request]:
        """Prefill role: the seated RUNNING requests whose prefill is
        complete and first token sampled, ready for a decode replica.
        Non-destructive — a successful :meth:`adopt` on the destination
        followed by :meth:`finish_handoff` here removes an entry, so a
        request the router cannot place this step is simply retried."""
        return list(self._handoff_ready or ())

    def adopt(self, req: Request, src: "ServingEngine") -> dict:
        """Seat a request whose prefill ran on ANOTHER replica: copy its
        live pages across pools (one fixed-shape jitted transfer — see
        :meth:`PagedKVPool.import_pages`), seat them, and resume decode
        at the source's exact position. Pages the local prefix trie
        already holds for the request's prompt are mapped for free (a
        refcount bump) and only the uncached tail is moved — the
        prefix-affine dispatch payoff. The transferred pages are the
        same bits the source produced and the first token was already
        sampled from them, so greedy output is bitwise identical to a
        colocated run.

        On any failure nothing stays seated here (allocated pages are
        unwound on both pools) and the exception propagates — the
        router re-homes the request through the failover scrub.
        Returns transfer accounting: ``{"pages", "hit_pages", "bytes",
        "seconds"}``."""
        if self.role == "prefill":
            raise ValueError("adopt() needs a decode-capable replica "
                             "(role 'decode' or 'both')")
        if not self._paged or not getattr(src, "_paged", False):
            raise ValueError("adopt() requires paged KV on both replicas")
        if req.state is not RequestState.RUNNING or req.slot is None:
            raise ValueError(f"adopt() needs a seated RUNNING request; "
                             f"req {req.request_id} is {req.state.value}")
        pool, spool = self.pool, src.pool
        src_slot = req.slot
        seed = req.seed_tokens
        pos = int(spool.starts[src_slot])
        n_live = -(-pos // spool.page_size)
        src_pages = [int(p) for p in spool.table[src_slot, :n_live]]
        t0 = self._now()
        slot = pool.alloc()
        hit_pages: List[int] = []
        try:
            pool.reset_row(slot)
            if self._use_prefix:
                # local trie hit: map the cached prefix pages in place
                # of transferring them (their bits are identical — they
                # came off an earlier transfer or colocated prefill)
                hit_pages = pool.prefix.match(seed)[:n_live]
            if hit_pages:
                pool.map_prefix(slot, hit_pages, sync=False)
            dst_pages = pool.import_pages(spool, src_pages[len(hit_pages):])
        except Exception:
            # import_pages already unwound its own failure, so only
            # the slot (and any mapped prefix pages) needs releasing
            pool.release(slot)
            raise
        try:
            pool.seat_pages(slot, dst_pages, pos,
                            first_entry=len(hit_pages))
        except Exception:
            # seat_pages is atomic: on failure it took NONE of the
            # batch, so the whole import is ours to hand back
            pool.unref_pages(dst_pages)
            pool.release(slot)
            raise
        now = self._now()
        req.slot = slot
        req.last_admit_step = self.step_id
        if req.admit_time is None:
            req.admit_time = now
        self._slot_req[slot] = req
        # current-token twin: the source's last sampled token resumes
        # the decode loop here (width-1 scatter — a pre-warmed program)
        tok = int(req.output_tokens[-1])
        self._current[slot] = tok
        self._cur_dev = self._jit_cur_scatter(
            self._cur_dev,
            self._cur_commit(np.asarray([tok], np.int32)),
            jnp.asarray([slot]))
        if self._use_prefix:
            # publish the adopted prompt's full pages into THIS pool's
            # trie: the next same-prefix handoff routed here skips the
            # transfer for those pages entirely
            pool.cache_prefix(slot, seed)
        self.timelines.record(req.request_id, "adopted", slot=slot,
                              pages=len(dst_pages),
                              hit_pages=len(hit_pages),
                              src_replica=src.replica_id,
                              dst_replica=self.replica_id,
                              journey=req.journey_id)
        self.tracer.flow("s", "req", req.request_id)
        return {"pages": len(dst_pages), "hit_pages": len(hit_pages),
                "bytes": len(dst_pages) * pool.page_nbytes,
                "seconds": now - t0}

    def finish_handoff(self, req: Request, slot: int,
                       dst_replica: Optional[int] = None) -> None:
        """Prefill role: release the source seat AFTER a decode replica
        adopted the request. ``slot`` is the source slot (``req.slot``
        already points at the destination). The slot and its page
        references go back through the standard rollback — trie-cached
        prompt pages stay warm for the next same-prefix prompt — and
        the request's timeline HERE closes with a terminal hand-off
        event (it finishes on the adopting replica's timeline)."""
        if self._slot_req.get(slot) is not req:
            raise ValueError(f"finish_handoff: slot {slot} does not seat "
                             f"req {req.request_id}")
        del self._slot_req[slot]
        self.pool.release(slot)
        if self._handoff_ready:
            self._handoff_ready[:] = [r for r in self._handoff_ready
                                      if r is not req]
        self.timelines.record(req.request_id, "handed_off", terminal=True,
                              slot=slot, src_replica=self.replica_id,
                              dst_replica=dst_replica,
                              journey=req.journey_id)

    # -- resilience: eviction, deadlines, preemption -------------------
    def _evict_slot(self, req: Request) -> None:
        """Reclaim a seated request's slot through the rollback path:
        release the slot (its stale KV becomes masked padding, exactly
        like a rejected draft tail) and detach all seat state. The
        caller decides what the request becomes next (FINISHED on
        deadline, QUEUED on preemption, FAILED on poisoned logits)."""
        slot = req.slot
        del self._slot_req[slot]
        self.pool.release(slot)
        req.slot = None
        # identity filter, not remove(): value equality on requests would
        # elementwise-compare their numpy prompts
        self._prefill_queue[:] = [r for r in self._prefill_queue
                                  if r is not req]
        if self._handoff_ready:
            # a parked handoff that retires (deadline/cancel/preempt)
            # before any decode replica adopts it leaves the launchpad
            self._handoff_ready[:] = [r for r in self._handoff_ready
                                      if r is not req]

    def _expire_deadlines(self, finished: List[Request]) -> None:
        """Retire every request whose deadline has passed: queued ones
        before they cost a prefill, seated ones via slot eviction. Runs
        at the step boundary so a mid-step expiry can never interleave
        with a dispatch."""
        now = self._now()
        expired = self.scheduler.expire(now)
        for slot, req in list(self._slot_req.items()):
            if req.expired(now):
                self._evict_slot(req)
                expired.append(req)
        for req in expired:
            req.state = RequestState.FINISHED
            req.finish_reason = FinishReason.DEADLINE
            req.finish_time = now
            self._finish_record(req)
            finished.append(req)

    def preempt(self, request_id: int) -> Request:
        """Evict a seated (RUNNING or PREFILLING) request and re-queue it
        at the FRONT of the admission queue carrying its generated-so-far
        tokens. Re-admission prefills prompt + outputs through the
        existing bucketed/chunked paths — fixed shapes, zero new
        programs — and greedy output is bitwise identical to never having
        been preempted (see ``Request.seed_tokens``). Raises
        ``ValueError`` if the id is not currently seated."""
        for req in self._slot_req.values():
            if req.request_id == request_id:
                self._preempt_req(req, auto=False)
                return req
        raise ValueError(f"request {request_id} is not seated in a slot "
                         f"(only RUNNING/PREFILLING requests can be "
                         f"preempted)")

    def cancel(self, request_id: int) -> Optional[Request]:
        """Cancel a request by id — the client hung up or sent
        ``DELETE /v1/requests/{id}``. A QUEUED request is removed from
        the admission queue before it ever costs a prefill; a seated
        (RUNNING/PREFILLING) one is evicted through the preemption
        rollback (slot released, pages refcount-decremented, prefill
        queue filtered) and NOT re-queued. Either way the request
        retires ``FINISHED``/``cancelled`` with a terminal timeline
        event, and SLO accounting withdraws the admission (cancellation
        is neither good nor bad service). Returns the request, or None
        when the id is unknown or already terminal — a cancel racing
        the final token is normal, not an error."""
        for r in self.scheduler.queue:
            if r.request_id == request_id:
                # identity filter: deque.remove would still work (eq=False
                # means identity ==), but stay explicit like _evict_slot
                self.scheduler.queue = type(self.scheduler.queue)(
                    x for x in self.scheduler.queue if x is not r)
                return self._finish_cancel(r)
        for r in list(self._slot_req.values()):
            if r.request_id == request_id:
                slot = r.slot
                self._evict_slot(r)
                self.tracer.instant("serving/cancel", rid=r.request_id,
                                    slot=slot)
                return self._finish_cancel(r)
        return None

    def _finish_cancel(self, req: Request) -> Request:
        req.state = RequestState.FINISHED
        req.finish_reason = FinishReason.CANCELLED
        req.finish_time = self._now()
        self._finish_record(req)
        return req

    def _preempt_req(self, req: Request, auto: bool) -> None:
        slot = req.slot
        self._evict_slot(req)
        req.state = RequestState.QUEUED
        req.prefill_pos = 0       # a partial chunked prefill restarts
        req.admit_time = None
        req.preemptions += 1
        if auto:
            # pressure victims go to the BACK: re-queueing at the head
            # would hand the victim its own freed slot at the very next
            # grant — an infinite preempt/re-admit swap that generates
            # nothing. Tail requeue yields round-robin time-slicing with
            # the arrivals that caused the pressure.
            self.scheduler.requeue_back([req])
        else:
            self.scheduler.requeue_front([req])
        self.metrics.record_preemption(req)
        self.timelines.record(req.request_id, "preempted", slot=slot,
                              auto=auto, generated=len(req.output_tokens))
        self.tracer.instant("serving/preempt", rid=req.request_id,
                            slot=slot, auto=auto)

    def _auto_preempt(self) -> None:
        """Pressure valve: when the queue has outgrown the threshold and
        every slot is taken, evict ONE victim per step (youngest /
        least-progress first; must have held its slot for
        ``preempt_min_run_steps``). One per step is deliberate — paced
        eviction keeps the batch mostly busy while pressure drains.

        With paged KV, page starvation counts as pressure too: free
        slots are no help when the queue head's uncached suffix exceeds
        every page the pool could free without a preemption."""
        if (self.preempt_queue_threshold is None
                or self.scheduler.pending <= self.preempt_queue_threshold):
            return
        starved = self.pool.free_count == 0
        head = self.scheduler.head()
        if not starved and self._paged and head is not None:
            starved = (self._page_cost(head)
                       > self._grant_page_budget())
        if not starved:
            return
        victims = select_victims(
            list(self._slot_req.values()), n=1, current_step=self.step_id,
            min_run_steps=self.preempt_min_run_steps,
            class_rank=self._class_rank)
        for req in victims:
            self._preempt_req(req, auto=True)

    def _class_rank(self, req: Request) -> int:
        """Victim-selection key: a request's priority rank (0 = highest)
        under priority scheduling, 0 for everyone under plain FIFO."""
        if self._priority is None:
            return 0
        return self.scheduler.rank_of(req.priority_class)

    def _burn_preempt(self) -> None:
        """Burn-rate-driven preemption, the seated half of class
        shedding: while a class's burn alert is at warn/page
        (``_shed_floor``), requests of STRICTLY LOWER classes are not
        just refused at submit — if a protected-class request is
        waiting and the pool is starved (no free slot, or its pages
        exceed what a grant could allocate), one shed-class resident is
        evicted per step (paced like ``_auto_preempt``; tail-requeued so
        it resumes once the burn clears)."""
        floor = self._shed_floor()
        if floor is None:
            return
        head = self.scheduler.head_within(floor)
        if head is None:
            return  # nobody protected is waiting
        starved = self.pool.free_count == 0
        if not starved and self._paged:
            starved = self._page_cost(head) > self._grant_page_budget()
        if not starved:
            return  # normal admission will seat the protected head
        sheddable = [r for r in self._slot_req.values()
                     if self._class_rank(r) > floor]
        victims = select_victims(
            sheddable, n=1, current_step=self.step_id,
            min_run_steps=self.preempt_min_run_steps,
            class_rank=self._class_rank)
        for req in victims:
            self._preempt_req(req, auto=True)

    # ------------------------------------------------------------------
    def step(self) -> List[Request]:
        """One scheduler iteration: admit into free slots, then one decode
        (or draft+verify) step for every live slot. Returns the requests
        that finished.

        Exception-safe: if the engine throws mid-step, no slot leaks —
        granted-but-unadmitted requests go back to the head of the queue,
        requests whose KV state is unrecoverable are FAILED (reason
        ``"error"``), the pool is reset, and the error propagates."""
        finished: List[Request] = []
        self.step_id += 1
        self._ensure_watch()      # _jit_verify_k materializes lazily
        tracer = self.tracer
        t_step = self._now()
        running_at_entry = self._running_count()
        with tracer.span("serving/step", step=self.step_id):
            # boundary work first, outside the abort scope: expiring a
            # deadline or walking the load ladder touches no device
            # state, so a failure here must not FAIL innocent requests
            self._expire_deadlines(finished)
            self._update_load_state()
            self._auto_preempt()
            self._burn_preempt()
            tracer.counter("serving/occupancy", live=self.live_count,
                           pending=self.scheduler.pending)
            with tracer.span("serving/grant"):
                page_budget = self._grant_page_budget() if self._paged \
                    else None
                page_cost = self._page_cost if self._paged else None
                if self._stall_free:
                    # one chunk for the prefill-queue head will run this
                    # step; pre-charge it so admissions + chunk stay
                    # within budget
                    spent = self.prefill_chunk if self._prefill_queue else 0
                    granted = self.scheduler.grant(
                        self.pool.free_count, self.live_count,
                        token_budget=self._effective_prefill_budget(),
                        cost=self._admission_cost, spent=spent,
                        page_budget=page_budget, page_cost=page_cost)
                else:
                    granted = self.scheduler.grant(
                        self.pool.free_count, self.live_count,
                        page_budget=page_budget, page_cost=page_cost)
            try:
                decoded = False
                if self._overlap and self._running_count() \
                        and self.role != "prefill":
                    # pipelined order: the decode (or draft+verify) for
                    # the slots ALREADY running is dispatched first, so
                    # admission/prefill host bookkeeping below overlaps
                    # the in-flight device step. Slots admitted this
                    # step join the decode batch next step.
                    t0 = self._now()
                    if self._spec is not None:
                        self._spec_decode_step(finished, t0)
                    else:
                        self._decode_step(finished, t0)
                    decoded = True
                if self._stall_free:
                    self._admit_stall_free(granted, finished)
                    self._prefill_chunk_step(finished)
                else:
                    for req in granted:
                        self._admit(req, finished)
                if self.faults is not None:
                    # the host-exception and slow-dispatch points sit
                    # between admission and decode: requests are seated
                    # (worst case for the abort path) but no decode
                    # state has moved yet
                    self.faults.maybe_sleep("slow_dispatch")
                    self.faults.check("step_host_error")
                if not decoded and self._running_count() \
                        and self.role != "prefill":
                    t0 = self._now()
                    if self._spec is not None:
                        self._spec_decode_step(finished, t0)
                    else:
                        self._decode_step(finished, t0)
                # the step's ONE device sync: fetch every deferred
                # token/flag at once, then replay host bookkeeping in
                # dispatch order
                self._drain_deferred()
            except Exception:
                self._abort_step(granted)
                raise
        if self._paged:
            # per-step paging gauges (Prometheus export + dashboards):
            # occupancy and sharing level of the page pool
            free = self.pool.free_page_count
            shared = int(np.sum(self.pool.page_refs > 1))
            self.registry.gauge("paging/free_pages").set(float(free))
            self.registry.gauge("paging/pages_in_use").set(
                float(self.pool.num_pages - free))
            self.registry.gauge("paging/refcounted_pages").set(float(shared))
            tracer.counter("paging/pages", free=free,
                           in_use=self.pool.num_pages - free, shared=shared)
        if self.faults is not None and self.faults.fires("state_corruption"):
            # chaos: corrupt our own slot bookkeeping at the boundary so
            # check_invariants + the flight recorder face REAL damage
            self._chaos_corrupt_state()
        wall = self._now() - t_step
        self.step_wall_s += wall
        self._telemetry_step(wall, running_at_entry, granted, finished)
        # drain serving/step_fetch (the single-sync wait) into
        # timer/*_ms histograms alongside the rest of the step metrics
        self.timers.publish(self.registry)
        # strict-mode recompile gate sits at the step boundary: raising
        # mid-step would trigger _abort_step and FAIL innocent in-flight
        # requests, when the state is actually perfectly consistent
        try:
            self.watchdog.check()
        except RecompileAfterWarmupError as e:
            self._post_mortem("recompile_after_warmup", e)
            raise
        if self.step_wall_budget_ms is not None and \
                wall * 1e3 > self.step_wall_budget_ms:
            # per-step wall-time watchdog: flag, don't kill — one slow
            # step is an observability event; sustained slowness shows
            # up in step_gap p99 and drives the load-state machine
            self.metrics.record_step_overrun(wall, self.step_wall_budget_ms)
            tracer.instant("serving/step_overrun", wall_ms=wall * 1e3,
                           budget_ms=self.step_wall_budget_ms)
        if running_at_entry:
            # a running request waited through this WHOLE step for its
            # next token — the user-visible inter-token gap, admission
            # work included (what stall-free admission bounds)
            self.metrics.record_step_gap(wall)
        return finished

    def _effective_prefill_budget(self) -> Optional[int]:
        """The step's prefill token budget after degradation: PRESSURED
        halves it (floor: one chunk), OVERLOADED pins it at one chunk —
        admission slows before live decode latency does."""
        budget = self.prefill_token_budget
        if budget is None or self._load is None:
            return budget
        if self._load.state is LoadState.OVERLOADED:
            return max(self.prefill_chunk, 1)
        if self._load.state is LoadState.PRESSURED:
            return max(self.prefill_chunk, budget // 2)
        return budget

    def _update_load_state(self) -> None:
        if self._load is None:
            return
        cfg = self._degradation
        gaps = self.metrics.step_gaps[-cfg.window:]
        p99 = float(np.percentile(np.asarray(gaps), 99) * 1e3) \
            if gaps else None
        pending = self.scheduler.pending
        head = self.scheduler.head()
        if self._paged and head is not None and \
                self._page_cost(head) \
                > self._grant_page_budget():
            # page starvation is load even when the queue is short: an
            # oversubscribed pool that can't seat the queue head should
            # trip the ladder (and its retry_after shedding) just like
            # queue depth does, so degradation stays meaningful when
            # pages — not slots — are the scarce resource
            pending = max(pending, cfg.queue_pressured)
        moved = self._load.update(pending, p99, step=self.step_id)
        self.tracer.counter("serving/load_state",
                            level=int(self._load.state))
        if moved is not None:
            old, new = moved
            self.metrics.record_load_state(old, new)
            self.tracer.instant("serving/load_transition", old=old.name,
                                new=new.name, queue=self.scheduler.pending,
                                gap_p99_ms=p99)
            log_dist(f"ServingEngine: load {old.name} -> {new.name} "
                     f"(queue={self.scheduler.pending}, "
                     f"gap_p99_ms={p99})", ranks=[0])

    def _fail_slot(self, req: Request, reason: FinishReason) -> None:
        """Fail ONE seated request (poisoned logits): evict its slot via
        the rollback path and mark it FAILED, leaving every other slot's
        tokens from the same dispatch untouched."""
        self._evict_slot(req)
        req.state = RequestState.FAILED
        req.finish_reason = reason
        req.finish_time = self._now()
        self.metrics.record_failure(req)
        self.tracer.flow("f", "req", req.request_id)
        self.timelines.record(req.request_id, "failed", terminal=True,
                              reason=reason.value,
                              new_tokens=len(req.output_tokens))

    def _guard_rows(self, finite, running):
        """Replay half of the NaN/inf guard: given the fetched (B,) bool
        of per-row finiteness, return the survivors of ``running`` and
        fail the poisoned rows. Runs inside the deferred drain — the
        finite vector rode the step's one fetch instead of buying its
        own sync."""
        if finite is None:
            return running
        ok = [(slot, req) for slot, req in running if bool(finite[slot])]
        for slot, req in running:
            if not bool(finite[slot]) and \
                    req.state is RequestState.RUNNING:
                self._fail_slot(req, FinishReason.NUMERICAL_ERROR)
        return ok

    def _decode_step(self, finished: List[Request], t0: float) -> None:
        eng = self.engine
        if self._paged:
            # page the write column in BEFORE snapshotting the running
            # set: under pressure this can preempt a victim out of it
            self._ensure_decode_pages(1)
        running = [(slot, req) for slot, req in self._slot_req.items()
                   if req.state is RequestState.RUNNING]
        # device twin of the current-token vector: decode never waits for
        # the previous step's sampled tokens to round-trip the host
        tokens = self._cur_dev[:, None]
        pos = jnp.asarray(self.pool.positions())
        with self.tracer.span("serving/decode", live=len(running)):
            if self._paged:
                logits = self.pool.run_decode(eng, tokens, pos)
            else:
                logits, cache = eng._jit_decode(eng.params, self.pool.cache,
                                                tokens, pos)
        if self.faults is not None:
            logits, _ = self.faults.corrupt_logits(
                logits, [slot for slot, _ in running])
        # dispatch the finite check; the (B,) bool rides the step fetch
        finite_dev = (self._jit_finite(logits)
                      if self._jit_finite is not None and running else None)
        if not self._paged:
            self.pool.cache = cache
        if self._prefill_queue:
            # PREFILLING slots rode along as masked padding: the decode
            # program advanced every device index by 1, so overwrite from
            # the mirror (running rows +1, prefilling rows unchanged) —
            # same index-rollback trick speculative decoding uses
            deltas = np.zeros((self.pool.num_slots,), np.int32)
            for slot, _ in running:
                deltas[slot] = 1
            self.pool.advance(deltas)
        else:
            self.pool.advance(1)
        with self.tracer.span("serving/sample"):
            nxt_dev = self._sample_dev(logits)
        # full-batch overwrite: every row's next current token IS this
        # decode's sample for that row (non-running rows hold garbage a
        # masked decode row can never surface, and any later admission
        # scatter overwrites them); re-committed to the canonical slots
        # placement — a free transfer when GSPMD already chose it
        self._cur_dev = self._cur_commit(nxt_dev)

        def _on_decode(nxt, finite=None, running=running):
            live = self._guard_rows(finite, running)
            emitted = 0
            for slot, req in live:
                if req.state is not RequestState.RUNNING:
                    # retired by an earlier replay in this same drain
                    # (e.g. an admission token hit EOS); its decode row
                    # was masked padding
                    continue
                token = int(nxt[slot])
                req.output_tokens.append(token)
                self._current[slot] = token
                emitted += 1
                self._maybe_retire(req, token, finished)
            self._tokens_emitted += emitted
            self.metrics.record_decode_step(emitted, len(running),
                                            step_s=self._now() - t0)

        self._defer([nxt_dev] if finite_dev is None
                    else [nxt_dev, finite_dev], _on_decode)

    def _spec_decode_step(self, finished: List[Request], t0: float) -> None:
        """Draft K tokens per live slot, verify them all in ONE fixed-shape
        (num_slots, K+1) forward, keep each slot's accepted prefix + bonus
        token, and roll back rejected KV via the per-slot index."""
        eng = self.engine
        K = self._spec.k
        B = self.pool.num_slots
        if self._paged:
            # verify writes K+1 columns past every RUNNING slot's index;
            # page them in first (may preempt under pressure, so it runs
            # before the drafter snapshots the live set)
            self._ensure_decode_pages(K + 1)

        # PREFILLING slots keep histories[slot] = None: the drafter
        # proposes nothing for them (draft_len 0) and their deltas stay
        # 0 below, so verify's masked garbage writes are rolled back by
        # the index overwrite and later overwritten by their next chunk
        if self._load is not None and \
                self._load.state is LoadState.OVERLOADED:
            # degradation: suspend speculation WITHOUT changing a shape —
            # zero-length drafts flow through the same verify_k program
            # (draft_len 0 reduces it to plain decode per row), so the
            # suspension and the recovery are both recompile-free
            draft = np.zeros((B, K), np.int32)
            draft_len = np.zeros((B,), np.int32)
            t_draft = 0.0
        else:
            if self._deferred:
                # admissions sampled first tokens earlier THIS step (the
                # serial-order path): the drafter's host-side histories
                # need them, so settle the queue now. Steady-state decode
                # steps — and overlap mode, which dispatches spec before
                # admissions — never take this early drain, keeping the
                # hot loop at exactly one sync per step.
                self._drain_deferred()
            histories: List[Optional[np.ndarray]] = [None] * B
            for slot, req in self._slot_req.items():
                if req.state is RequestState.RUNNING:
                    histories[slot] = req.tokens()
            with self.tracer.span("serving/draft", k=K):
                draft, draft_len = self._drafter.propose(histories, K)
            draft = np.asarray(draft, np.int32)
            draft_len = np.clip(np.asarray(draft_len, np.int32), 0, K)
            t_draft = self._now() - t0

        # device twin feeds verify directly — no host round-trip for the
        # previous step's tokens
        tokens = jnp.concatenate(
            [self._cur_dev[:, None], jnp.asarray(draft)], axis=1)
        self._rng, sub = jax.random.split(self._rng)
        with self.tracer.span("serving/verify_k", k=K):
            if self._paged:
                out_dev, n_emit_dev = self.pool.run_verify(
                    eng, tokens,
                    jnp.asarray(self.pool.positions()), jnp.asarray(draft),
                    jnp.asarray(draft_len), sub,
                    jnp.asarray(self.temperature, jnp.float32),
                    self._greedy, int(self.top_k), float(self.top_p))
            else:
                cache, out_dev, n_emit_dev = eng.verify_k(
                    self.pool.cache, tokens,
                    jnp.asarray(self.pool.positions()), jnp.asarray(draft),
                    jnp.asarray(draft_len), sub,
                    jnp.asarray(self.temperature, jnp.float32),
                    self._greedy, int(self.top_k), float(self.top_p))
                self.pool.cache = cache
        # next step's current token per row is the last EMITTED one:
        # out[b, n_emit[b]-1] (n_emit >= 1 always for live rows)
        self._cur_dev = self._jit_spec_cur(out_dev, n_emit_dev)
        live = [(slot, req) for slot, req in self._slot_req.items()
                if req.state is RequestState.RUNNING]

        def _on_verify(out, n_emit, live=live, draft_len=draft_len):
            deltas = np.zeros((B,), np.int32)
            emitted = drafted = accepted = 0
            for slot, req in live:
                if req.state is not RequestState.RUNNING:
                    # retired by an earlier replay in this same drain;
                    # its verify row was masked padding
                    continue
                e = int(n_emit[slot])
                # the cache row holds e new positions regardless of how
                # many tokens the request actually consumes below: if
                # eos/budget truncates the emission, the request retires
                # this step, so the surplus becomes dead padding in a
                # freed slot
                deltas[slot] = e
                drafted += int(draft_len[slot])
                accepted += e - 1
                req.spec_drafted += int(draft_len[slot])
                req.spec_accepted += e - 1
                for token in out[slot, :e].tolist():
                    req.output_tokens.append(token)
                    self._current[slot] = token
                    emitted += 1
                    self._maybe_retire(req, token, finished)
                    if req.state is not RequestState.RUNNING:
                        break
            self.pool.advance(deltas)      # per-slot KV rollback
            self._tokens_emitted += emitted
            self.metrics.record_decode_step(
                emitted, len(live), drafted=drafted, accepted=accepted,
                draft_s=t_draft, step_s=self._now() - t0)

        self._defer([out_dev, n_emit_dev], _on_verify)

    def _abort_step(self, granted: List[Request]) -> None:
        """Mid-step exception recovery: never leak a slot. Requests the
        failed admission already rolled back to QUEUED re-join the queue
        head; PREFILLING requests lose only cache state that can be
        rebuilt from the prompt, so they are scrubbed and re-queued too
        (ahead of the granted ones — they are older); running requests
        lose their (possibly donated-away) KV state and are FAILED; the
        pool restarts from a fresh cache."""
        requeued = [r for r in granted if r.state is RequestState.QUEUED]
        self.scheduler.requeue_front(requeued)
        for req in requeued:
            self.timelines.record(req.request_id, "requeued",
                                  reason="admit_error")
        prefilling = sorted(
            (r for r in self._slot_req.values()
             if r.state is RequestState.PREFILLING),
            key=lambda r: r.request_id)
        for req in prefilling:
            del self._slot_req[req.slot]
            req.slot = None
            req.admit_time = None
            req.prefill_pos = 0
            # output_tokens are NOT cleared: a preempted request mid-
            # re-prefill owns real generated tokens — they are its seed,
            # rebuilt from scratch on the next admission
            self.timelines.record(req.request_id, "requeued",
                                  reason="step_error")
        self.scheduler.requeue_front(prefilling)
        self._prefill_queue[:] = []
        for req in self._slot_req.values():
            req.state = RequestState.FAILED
            req.finish_reason = FinishReason.ERROR
            req.finish_time = self._now()
            self.metrics.record_failure(req)
            self.timelines.record(req.request_id, "failed", terminal=True,
                                  reason=FinishReason.ERROR.value)
        self._slot_req.clear()
        if self._handoff_ready:
            self._handoff_ready.clear()  # every member was seated -> FAILED
        self._current[:] = 0
        # drop queued-but-unfetched host bookkeeping: its device arrays
        # belong to the aborted step's state, and its requests are now
        # FAILED/requeued either way
        self._deferred.clear()
        self._cur_dev = jax.device_put(
            np.zeros((self.pool.num_slots,), np.int32),
            self._cur_sharding)
        self.pool.reset()

    def run_until_drained(self, max_steps: Optional[int] = None,
                          stall_patience: int = 32) -> List[Request]:
        """Step until the queue and every slot are empty (or ``max_steps``).
        Every healthy step with live work either emits a token, advances
        a prefill by a full chunk, or changes the queue/slot population,
        and every prompt and budget is finite — so a progress signature
        that sits IDENTICAL for ``stall_patience`` consecutive steps can
        only mean a livelock (scheduler bug, budget deadlock, preemption
        thrash). Rather than hang forever, that raises
        :class:`~deepspeed_tpu.serving.resilience.ServingStalledError`
        carrying a dump of every stuck request's state."""
        out: List[Request] = []
        steps = 0
        last_sig = None
        still = 0
        while self.scheduler.pending or self._slot_req:
            out.extend(self.step())
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
            sig = self._progress_signature()
            if sig == last_sig:
                still += 1
                if still >= stall_patience:
                    dump = self._stuck_dump()
                    err = ServingStalledError(
                        f"no progress for {still} consecutive steps "
                        f"(step_id={self.step_id}, pending="
                        f"{self.scheduler.pending}, live="
                        f"{self.live_count}); stuck requests: {dump}",
                        dump=dump)
                    self._post_mortem("stalled", err,
                                      extra={"stuck": dump})
                    raise err
            else:
                still = 0
                last_sig = sig
        return out

    def _progress_signature(self) -> tuple:
        """Everything that must move for the drain loop to be making
        progress: queue/slot population, finished/failed totals, tokens
        generated and prefill positions of every seated request."""
        return (self.scheduler.pending, len(self._slot_req),
                len(self.metrics.finished), self.metrics.failed,
                tuple(sorted(
                    (r.request_id, r.state.value, len(r.output_tokens),
                     r.prefill_pos)
                    for r in self._slot_req.values())))

    def _stuck_dump(self) -> List[dict]:
        """Host-side state of every non-terminal request, for the
        ServingStalledError payload."""
        reqs = list(self._slot_req.values()) + list(self.scheduler.queue)
        return [{"request_id": r.request_id, "state": r.state.value,
                 "slot": r.slot, "prefill_pos": r.prefill_pos,
                 "seed_len": r.seed_len,
                 "new_tokens": len(r.output_tokens),
                 "max_new_tokens": r.max_new_tokens,
                 "preemptions": r.preemptions,
                 "deadline_ms": r.deadline_ms} for r in reqs]

    def check_invariants(self) -> None:
        """Audit the engine/pool/scheduler cross-bookkeeping; raises
        :class:`~deepspeed_tpu.serving.resilience.InvariantViolation`
        listing every violation (never just the first) if any state is
        inconsistent. The chaos suite calls this after every injected
        fault — the contract is that NO fault, wherever injected, may
        leak a slot or strand a request."""
        errors = list(self.pool.consistency_errors())
        seated = set(self._slot_req.keys())
        free = set(self.pool._free_set)
        overlap = seated & free
        if overlap:
            errors.append(f"slots both seated and free: {sorted(overlap)}")
        missing = set(range(self.pool.num_slots)) - seated - free
        if missing:
            errors.append(f"slots leaked (neither seated nor free): "
                          f"{sorted(missing)}")
        for slot, req in self._slot_req.items():
            if req.slot != slot:
                errors.append(f"slot map disagrees: _slot_req[{slot}] has "
                              f"req {req.request_id} with req.slot="
                              f"{req.slot}")
            if req.state not in (RequestState.RUNNING,
                                 RequestState.PREFILLING):
                errors.append(f"seated req {req.request_id} in state "
                              f"{req.state.value}")
        prefilling_ids = sorted(
            r.request_id for r in self._slot_req.values()
            if r.state is RequestState.PREFILLING)
        queue_ids = sorted(r.request_id for r in self._prefill_queue)
        if prefilling_ids != queue_ids:
            errors.append(f"PREFILLING seated requests {prefilling_ids} != "
                          f"prefill queue {queue_ids}")
        for r in self.scheduler.queue:
            if r.state is not RequestState.QUEUED:
                errors.append(f"queued req {r.request_id} in state "
                              f"{r.state.value}")
            if r.slot is not None:
                errors.append(f"queued req {r.request_id} still holds "
                              f"slot {r.slot}")
        if np.any(self.pool.starts < 0) or \
                np.any(self.pool.starts > self.pool.capacity):
            errors.append(f"cache starts out of [0, {self.pool.capacity}]: "
                          f"{self.pool.starts.tolist()}")
        for r in (self._handoff_ready or ()):
            # a parked handoff must still be a live seat HERE — anything
            # else means a retire/transfer path forgot to purge it
            if r.state is not RequestState.RUNNING or r.slot is None \
                    or self._slot_req.get(r.slot) is not r:
                errors.append(f"handoff-ready req {r.request_id} not "
                              f"seated RUNNING (state={r.state.value}, "
                              f"slot={r.slot})")
        if errors:
            err = InvariantViolation(errors)
            self._post_mortem("invariant_violation", err,
                              extra={"violations": errors})
            raise err

    def stats(self) -> dict:
        """Aggregate SLO snapshot (see ServingMetrics.snapshot); with
        paged KV a ``"paging"`` sub-dict carries the page-pool and
        prefix-cache counters (see PagedKVPool.page_stats)."""
        snap = self.metrics.snapshot()
        if self._paged:
            snap["paging"] = self.pool.page_stats()
        return snap
