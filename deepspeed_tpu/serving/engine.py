"""ServingEngine: request-level continuous batching over InferenceEngine.

``InferenceEngine.generate()`` is whole-batch synchronous — every
request must arrive together and the batch holds its slots until the
slowest member finishes. This front-end turns the same compiled
machinery (the jitted ``prefill_last`` and donated single-step decode)
into a server: requests arrive one at a time via :meth:`submit`, each
:meth:`step` admits queued prompts into free slots of the fixed-shape
:class:`~deepspeed_tpu.serving.slot_pool.SlotPool` and runs ONE decode
step for all live slots, and finished sequences retire immediately so
their slot goes back to work (Orca-style iteration-level scheduling;
PAPERS.md).

Shape discipline is what keeps this fast on TPU: the decode step always
runs at batch = ``num_slots`` with per-slot (B,) cache offsets, so slot
churn never changes a compiled program — dead slots ride along as
masked padding. Prompt prefills are right-padded to power-of-two
buckets and the true last position is projected via
``prefill_last(input_ids, last_pos)``, bounding prefill recompiles at
log2(max_seq_len) for arbitrary prompt lengths.
"""

from __future__ import annotations

import time
from typing import Any, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.logging import log_dist
from .metrics import ServingMetrics
from .request import Request, RequestState
from .scheduler import FIFOScheduler
from .slot_pool import SlotPool

_MIN_PREFILL_BUCKET = 16


class ServingEngine:
    """Continuous-batching server over a built
    :class:`~deepspeed_tpu.inference.engine.InferenceEngine`.

    Construct via :func:`deepspeed_tpu.init_serving`. Sampling knobs
    default to the inference config's (greedy unless ``do_sample``);
    they are server-global — per-request ``max_new_tokens`` and
    ``eos_token_id`` ride on the :class:`Request`.
    """

    def __init__(self, engine: Any, num_slots: int = 4,
                 max_queue_depth: int = 64, policy: str = "continuous",
                 do_sample: bool = False,
                 temperature: Optional[float] = None,
                 top_k: Optional[int] = None, top_p: Optional[float] = None,
                 seed: int = 0, monitor: Optional[Any] = None):
        self.engine = engine
        # materialize params + jits before sizing anything off the module
        engine._ensure_params(jnp.zeros((1, 2), jnp.int32))
        spec = engine.kv_cache_spec()
        if spec is None:
            raise ValueError(
                "serving requires the module to declare kv_cache_spec() "
                "(the slot pool allocates through it); the unified "
                "TransformerLM family does")
        if getattr(engine, "_jit_prefill_at", None) is None:
            raise ValueError(
                "serving requires the module to expose prefill_last("
                "input_ids, last_pos) for bucketed slot prefill")
        cfg = engine._config
        self.pool = SlotPool(spec, num_slots)
        self.scheduler = FIFOScheduler(num_slots, max_queue_depth,
                                       policy=policy,
                                       capacity=self.pool.capacity)
        self.metrics = ServingMetrics(monitor)
        self.temperature = cfg.temperature if temperature is None else temperature
        self.top_k = cfg.top_k if top_k is None else top_k
        self.top_p = cfg.top_p if top_p is None else top_p
        self._greedy = jnp.asarray(not do_sample)
        self._rng = jax.random.PRNGKey(seed)
        self._slot_req: dict = {}                      # slot -> Request
        self._current = np.zeros((num_slots,), np.int32)  # last token per slot
        self._next_id = 0
        self._now = time.perf_counter
        log_dist(f"ServingEngine: slots={num_slots} policy={policy} "
                 f"capacity={self.pool.capacity} "
                 f"max_queue_depth={max_queue_depth}", ranks=[0])

    # ------------------------------------------------------------------
    @property
    def live_count(self) -> int:
        return len(self._slot_req)

    @property
    def pending(self) -> int:
        return self.scheduler.pending

    def submit(self, prompt, max_new_tokens: int = 32,
               eos_token_id: Optional[int] = None) -> Request:
        """Enqueue one generation request. Never raises on load: admission
        control marks the returned request ``REJECTED`` with a
        ``reject_reason`` (``"queue_full"``, ``"prompt_too_long"``) so
        callers can shed or retry."""
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got "
                             f"{max_new_tokens}")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        req = Request(self._next_id, prompt, max_new_tokens, eos_token_id)
        self._next_id += 1
        req.submit_time = self._now()
        accepted, reason = self.scheduler.submit(req)
        if not accepted:
            req.state = RequestState.REJECTED
            req.reject_reason = reason
            self.metrics.record_rejection(req)
        return req

    # ------------------------------------------------------------------
    def _sample(self, logits) -> np.ndarray:
        self._rng, sub = jax.random.split(self._rng)
        return np.asarray(self.engine._jit_sample(
            logits, sub, jnp.asarray(self.temperature, jnp.float32),
            int(self.top_k), float(self.top_p), self._greedy))

    @staticmethod
    def _bucket(n: int, cap: int) -> int:
        b = _MIN_PREFILL_BUCKET
        while b < n:
            b *= 2
        return min(b, cap)

    def _admit(self, req: Request, finished: List[Request]) -> None:
        eng = self.engine
        slot = self.pool.alloc()
        T = req.prompt_len
        width = self._bucket(T, self.pool.capacity)
        ids = np.zeros((1, width), np.int32)
        ids[0, :T] = req.prompt
        req.admit_time = self._now()
        logits, pre_cache = eng._jit_prefill_at(
            eng.params, jnp.asarray(ids), jnp.asarray(T - 1, jnp.int32))
        self.pool.admit(pre_cache, slot, T)
        token = int(self._sample(logits)[0])   # device sync: token exists now
        req.first_token_time = self._now()
        req.state = RequestState.RUNNING
        req.slot = slot
        req.output_tokens.append(token)
        self._slot_req[slot] = req
        self._current[slot] = token
        self._maybe_retire(req, token, finished)

    def _maybe_retire(self, req: Request, token: int,
                      finished: List[Request]) -> None:
        if req.eos_token_id is not None and token == req.eos_token_id:
            req.finish_reason = "eos"
        elif len(req.output_tokens) >= req.max_new_tokens:
            req.finish_reason = "length"
        else:
            return
        req.state = RequestState.FINISHED
        req.finish_time = self._now()
        self.pool.release(req.slot)
        del self._slot_req[req.slot]
        self.metrics.record_finish(req)
        finished.append(req)

    # ------------------------------------------------------------------
    def step(self) -> List[Request]:
        """One scheduler iteration: admit into free slots, then one decode
        step for every live slot. Returns the requests that finished."""
        finished: List[Request] = []
        for req in self.scheduler.grant(self.pool.free_count,
                                        self.live_count):
            self._admit(req, finished)
        if self._slot_req:
            eng = self.engine
            tokens = jnp.asarray(self._current[:, None])
            pos = jnp.asarray(self.pool.positions())
            logits, cache = eng._jit_decode(eng.params, self.pool.cache,
                                            tokens, pos)
            self.pool.cache = cache
            self.pool.bump()
            nxt = self._sample(logits)
            for slot, req in list(self._slot_req.items()):
                token = int(nxt[slot])
                req.output_tokens.append(token)
                self._current[slot] = token
                self._maybe_retire(req, token, finished)
        return finished

    def run_until_drained(self, max_steps: Optional[int] = None
                          ) -> List[Request]:
        """Step until the queue and every slot are empty (or ``max_steps``).
        Every step with live work produces at least one token and every
        request's budget is finite, so this terminates."""
        out: List[Request] = []
        steps = 0
        while self.scheduler.pending or self._slot_req:
            out.extend(self.step())
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        return out

    def stats(self) -> dict:
        """Aggregate SLO snapshot (see ServingMetrics.snapshot)."""
        return self.metrics.snapshot()
