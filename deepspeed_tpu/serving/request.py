"""Request model for the serving subsystem.

A :class:`Request` is one user generation job moving through the
lifecycle ``QUEUED -> [PREFILLING ->] RUNNING -> FINISHED`` (or
``REJECTED`` straight out of admission control; ``PREFILLING`` is the
stall-free chunked-admission stage for prompts longer than the serving
engine's chunk width). The object doubles as the per-request SLO
record: the scheduler stamps wall-clock times at each transition and the
latency metrics (TTFT, queue wait, per-token latency) are derived
properties, so there is exactly one place timing truth lives.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import List, Optional

import numpy as np


class RequestState(enum.Enum):
    QUEUED = "queued"
    PREFILLING = "prefilling"  # seated in a slot, prompt streaming in by
    #                            bounded chunks (stall-free admission)
    RUNNING = "running"
    FINISHED = "finished"
    REJECTED = "rejected"
    FAILED = "failed"       # aborted by a mid-step engine exception


@dataclasses.dataclass
class Request:
    """One generation request plus its lifecycle/metric record.

    ``output_tokens`` includes every sampled token (the EOS token too,
    matching ``InferenceEngine.generate`` which returns the row through
    its first EOS). Timing fields are ``time.perf_counter`` stamps set
    by the serving engine; they are ``None`` until the corresponding
    transition happens.
    """

    request_id: int
    prompt: np.ndarray                      # (T,) int32
    max_new_tokens: int
    eos_token_id: Optional[int] = None

    state: RequestState = RequestState.QUEUED
    reject_reason: Optional[str] = None     # "queue_full" | "prompt_too_long"
    finish_reason: Optional[str] = None     # "eos" | "length" | "length_cap"
    #                                         | "error"
    slot: Optional[int] = None
    prefill_pos: int = 0                    # prompt tokens already written
    #                                         into the slot (chunked prefill)
    output_tokens: List[int] = dataclasses.field(default_factory=list)

    # telemetry counters (per-request lifecycle accounting)
    chunks: int = 0                         # chunked-prefill dispatches run
    spec_drafted: int = 0                   # draft tokens proposed for this
    #                                         request's slot
    spec_accepted: int = 0                  # draft tokens accepted

    submit_time: Optional[float] = None
    admit_time: Optional[float] = None      # prefill issued (slot granted)
    first_token_time: Optional[float] = None
    finish_time: Optional[float] = None

    @property
    def prompt_len(self) -> int:
        return int(np.shape(self.prompt)[0])

    def tokens(self) -> np.ndarray:
        """Prompt + generated tokens, the ``generate()``-shaped row."""
        return np.concatenate(
            [np.asarray(self.prompt, np.int32),
             np.asarray(self.output_tokens, np.int32)])

    # -- derived SLO metrics (seconds; None until the inputs exist) ----
    @property
    def queue_wait(self) -> Optional[float]:
        if self.submit_time is None or self.admit_time is None:
            return None
        return self.admit_time - self.submit_time

    @property
    def ttft(self) -> Optional[float]:
        """Time to first token: submit -> first sampled token."""
        if self.submit_time is None or self.first_token_time is None:
            return None
        return self.first_token_time - self.submit_time

    @property
    def per_token_latency(self) -> Optional[float]:
        """Mean decode latency per token AFTER the first (the steady-state
        inter-token gap users see while a response streams)."""
        if self.first_token_time is None or self.finish_time is None:
            return None
        n = len(self.output_tokens)
        if n <= 1:
            return 0.0
        return (self.finish_time - self.first_token_time) / (n - 1)
