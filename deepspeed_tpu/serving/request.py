"""Request model for the serving subsystem.

A :class:`Request` is one user generation job moving through the
lifecycle ``QUEUED -> [PREFILLING ->] RUNNING -> FINISHED`` (or
``REJECTED`` straight out of admission control; ``PREFILLING`` is the
stall-free chunked-admission stage for prompts longer than the serving
engine's chunk width; preemption sends a seated request back to
``QUEUED`` carrying its generated-so-far tokens). The object doubles as
the per-request SLO record: the scheduler stamps wall-clock times at
each transition and the latency metrics (TTFT, queue wait, per-token
latency) are derived properties, so there is exactly one place timing
truth lives.

Terminal reasons are CLOSED ENUMS (:class:`FinishReason`,
:class:`RejectReason`), not free-form strings: every monitor event,
stats key and timeline attribute derives from them, and
:class:`~deepspeed_tpu.serving.metrics.ServingMetrics` validates each
recorded reason against the enum so a typo'd reason fails loudly at the
emit site instead of silently forking a new metrics series. Both enums
are ``str`` subclasses, so ``req.finish_reason == "eos"`` keeps
working everywhere.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import List, Optional, Union

import numpy as np


class RequestState(enum.Enum):
    QUEUED = "queued"
    PREFILLING = "prefilling"  # seated in a slot, prompt streaming in by
    #                            bounded chunks (stall-free admission)
    RUNNING = "running"
    FINISHED = "finished"
    REJECTED = "rejected"
    FAILED = "failed"       # aborted by a mid-step engine exception


class FinishReason(str, enum.Enum):
    """Why a request left via FINISHED (or FAILED — the error reasons).

    ``str`` mixin: members compare and format as their values, so
    existing ``finish_reason == "eos"`` comparisons and f-string tags
    are unchanged.
    """

    EOS = "eos"                          # emitted its eos_token_id
    LENGTH = "length"                    # hit max_new_tokens
    LENGTH_CAP = "length_cap"            # cache row full (capacity)
    DEADLINE = "deadline"                # per-request deadline expired
    CANCELLED = "cancelled"              # client cancelled / disconnected
    ERROR = "error"                      # mid-step engine exception
    NUMERICAL_ERROR = "numerical_error"  # NaN/inf logits in this slot

    __str__ = str.__str__  # "eos", not "FinishReason.EOS" (py<3.11 quirk)

    @classmethod
    def of(cls, value: Union[str, "FinishReason"]) -> "FinishReason":
        """Validate/coerce; raises ``ValueError`` on unknown reasons."""
        return cls(value)


class RejectReason(str, enum.Enum):
    """Why admission control refused a submission."""

    QUEUE_FULL = "queue_full"            # bounded queue at depth
    PROMPT_TOO_LONG = "prompt_too_long"  # can never fit the KV capacity
    RETRY_AFTER = "retry_after"          # shed by overload degradation or
    #                                      burn-rate class shedding;
    #                                      retry_after_s carries the hint
    RATE_LIMITED = "rate_limited"        # tenant token bucket empty
    TENANT_QUOTA = "tenant_quota"        # tenant queue quota reached

    __str__ = str.__str__

    @classmethod
    def of(cls, value: Union[str, "RejectReason"]) -> "RejectReason":
        return cls(value)


@dataclasses.dataclass(eq=False)  # identity semantics: a generated __eq__
#                                   would elementwise-compare numpy prompts
#                                   (ambiguous truth) and drop hashability
class Request:
    """One generation request plus its lifecycle/metric record.

    ``output_tokens`` includes every sampled token (the EOS token too,
    matching ``InferenceEngine.generate`` which returns the row through
    its first EOS). Timing fields are ``time.perf_counter`` stamps set
    by the serving engine; they are ``None`` until the corresponding
    transition happens.
    """

    request_id: int
    prompt: np.ndarray                      # (T,) int32
    max_new_tokens: int
    eos_token_id: Optional[int] = None

    # -- multi-tenancy --------------------------------------------------
    priority_class: str = "default"         # scheduling class; rank order
    #                                         comes from PriorityConfig
    tenant: str = "default"                 # rate-limit / quota bucket

    state: RequestState = RequestState.QUEUED
    reject_reason: Optional[RejectReason] = None
    finish_reason: Optional[FinishReason] = None
    slot: Optional[int] = None
    prefill_pos: int = 0                    # seed tokens already written
    #                                         into the slot (chunked prefill)
    output_tokens: List[int] = dataclasses.field(default_factory=list)

    # -- fleet trace context --------------------------------------------
    # minted by ReplicaRouter.submit and carried across every replica
    # boundary (handoff, page transfer, failover) so each home's
    # Tracer/TimelineStore stamps the same journey; None on a bare
    # single-engine deployment
    journey_id: Optional[int] = None
    hop: int = 0                            # replica-boundary crossings

    # -- resilience -----------------------------------------------------
    deadline_ms: Optional[float] = None     # TTL from submit; None = none
    deadline_time: Optional[float] = None   # absolute perf_counter stamp
    retry_after_s: Optional[float] = None   # backoff hint on RETRY_AFTER
    preemptions: int = 0                    # times bounced back to QUEUED
    last_admit_step: int = -1               # engine step_id of last seating

    # telemetry counters (per-request lifecycle accounting)
    prefix_hit_tokens: int = 0              # seed tokens skipped at seating
    #                                         via the paged prefix cache
    chunks: int = 0                         # chunked-prefill dispatches run
    spec_drafted: int = 0                   # draft tokens proposed for this
    #                                         request's slot
    spec_accepted: int = 0                  # draft tokens accepted

    submit_time: Optional[float] = None
    admit_time: Optional[float] = None      # prefill issued (slot granted)
    first_token_time: Optional[float] = None
    finish_time: Optional[float] = None

    @property
    def prompt_len(self) -> int:
        return int(np.shape(self.prompt)[0])

    def tokens(self) -> np.ndarray:
        """Prompt + generated tokens, the ``generate()``-shaped row."""
        return np.concatenate(
            [np.asarray(self.prompt, np.int32),
             np.asarray(self.output_tokens, np.int32)])

    # -- preemption resume ---------------------------------------------
    @property
    def seed_tokens(self) -> np.ndarray:
        """What admission must prefill into a slot: the prompt, plus —
        after a preemption — everything generated so far. The last
        generated token has never been fed through the model (the
        decode loop feeds it next), so re-prefilling the FULL history
        and sampling at its last position produces exactly the token
        the next decode step would have: greedy output is bitwise
        identical across preemptions."""
        return self.tokens() if self.output_tokens else \
            np.asarray(self.prompt, np.int32)

    @property
    def seed_len(self) -> int:
        return self.prompt_len + len(self.output_tokens)

    def expired(self, now: float) -> bool:
        """Deadline passed? (False when no deadline is set.)"""
        return self.deadline_time is not None and now >= self.deadline_time

    # -- derived SLO metrics (seconds; None until the inputs exist) ----
    @property
    def queue_wait(self) -> Optional[float]:
        if self.submit_time is None or self.admit_time is None:
            return None
        return self.admit_time - self.submit_time

    @property
    def ttft(self) -> Optional[float]:
        """Time to first token: submit -> first sampled token."""
        if self.submit_time is None or self.first_token_time is None:
            return None
        return self.first_token_time - self.submit_time

    @property
    def per_token_latency(self) -> Optional[float]:
        """Mean decode latency per token AFTER the first (the steady-state
        inter-token gap users see while a response streams)."""
        if self.first_token_time is None or self.finish_time is None:
            return None
        n = len(self.output_tokens)
        if n <= 1:
            return 0.0
        return (self.finish_time - self.first_token_time) / (n - 1)
