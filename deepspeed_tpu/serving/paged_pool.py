"""Paged KV pool: fixed-size pages + per-slot page tables + refcounted
copy-on-write prefix sharing over the serving slot pool.

Terminology map (for readers coming from the reference systems):

* **vLLM PagedAttention** — our *page* is vLLM's KV *block*
  (``page_size`` token columns of K/V across every layer); the
  ``(num_slots, max_pages_per_slot)`` int32 *page table* is vLLM's
  per-sequence block table; the free-page heap is the block allocator;
  ``num_pages < num_slots * max_pages_per_slot`` is oversubscription —
  slots reserve nothing, so HBM holds *actual* tokens, not worst-case
  rows.
* **SGLang RadixAttention** — the token-keyed
  :class:`~deepspeed_tpu.serving.prefix_cache.PrefixCache` trie is the
  radix tree; a page's refcount counts (slots mapping it) + (trie
  nodes caching it); admission walks the trie and maps shared pages
  for free, prefilling only the uncached suffix; the first divergent
  WRITE into a shared page triggers copy-on-write (one jitted
  page-to-page copy, then the writer's table entry swings to the
  fresh copy).

Shape discipline is identical to the contiguous
:class:`~deepspeed_tpu.serving.slot_pool.SlotPool`: physical storage is
ONE statically-shaped pytree — k/v ``(L, num_pages, KV, cache_d,
page_size)`` — and every jitted entry (decode, ``verify_k``,
``prefill_chunk``, batched admission) is a gather → existing traced
attention program → scatter composition:
:meth:`KVCacheSpec.dense_from_pages` reassembles the dense ``(L, B, KV,
cache_d, max_seq_len)`` view the compiled attention already consumes
(so the math — and greedy output — is BITWISE identical to the
contiguous pool), and only the columns the step actually wrote are
scattered back by page id. Page churn, prefix hits, CoW forks and
preempt/resume are all data movement inside the same buffers: zero
post-warmup recompiles, watchdog-enforced. The transient dense view is
scratch the compiler can schedule; the *persistent* HBM footprint is
the page pool — which is the served-requests-per-GB lever. (A fused
Pallas paged-attention kernel that skips the dense rematerialization is
the natural follow-up; the pool/table/refcount contract here is
layout-compatible with it.)

Composition with the int8 packed cache (BASELINE.md): the page pool
allocates through the same module-declared ``KVCacheSpec``, so
quantized (int8, or int32-packed with ``cache_d = head_dim // 4``)
columns page exactly like full-precision ones, with per-column scales
paged alongside — paging multiplies with the 4x packed-footprint win
rather than replacing it.

Sentinel convention: table entry ``num_pages`` means "unmapped". The
gather reads sentinel entries with a clip-mode take (arbitrary real
page — harmless, a slot's mapped region always covers its live
``[0, index)`` columns and attention masks the rest), and the scatter
drops sentinel writes (``mode="drop"``), so a dead or padding row can
never touch a real page.
"""

from __future__ import annotations

import heapq
from typing import Any, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .prefix_cache import PrefixCache
from .slot_pool import SlotPool


class PagePoolExhausted(RuntimeError):
    """No free page and nothing evictable: the caller must preempt a
    victim (freeing its pages) and retry, or fail the allocation."""


#: mirror of ``ops.attention.paged_attention.MAX_QUERY_ROWS`` as a local
#: literal so graftcheck can decide the verify-width gate statically;
#: ``bind_engine`` asserts the two stay equal
_KERNEL_MAX_QUERY_ROWS = 8


class PagedKVPool(SlotPool):
    """Drop-in :class:`SlotPool` with paged storage and prefix caching.

    The host-side API (``alloc``/``release``/``advance``/``starts``/
    ``admit``/``admit_rows``/``reset``/``consistency_errors``) is the
    SlotPool contract; the jitted decode/verify/chunk entries live HERE
    (``run_decode``/``run_verify``/``run_prefill_chunk``) because they
    compose the engine's traced model functions with the pool's
    gather/scatter — the serving engine dispatches to them when paging
    is on.
    """

    def __init__(self, spec: Any, num_slots: int,
                 num_pages: Optional[int] = None, page_size: int = 64,
                 sharding: Any = None, prefix_cache: bool = True,
                 kernel: str = "auto"):
        if kernel not in ("auto", "on", "off"):
            raise ValueError(f"kernel must be auto|on|off, got {kernel!r}")
        capacity = int(spec.max_seq_len)
        page_size = int(page_size)
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        if capacity % page_size != 0:
            raise ValueError(
                f"page_size ({page_size}) must divide the KV capacity "
                f"({capacity}) so page tables tile the positions axis "
                f"exactly")
        self.page_size = page_size
        self.pages_per_slot = capacity // page_size
        P = (num_slots * self.pages_per_slot if num_pages is None
             else int(num_pages))
        if P < 1:
            raise ValueError(f"num_pages must be >= 1, got {P}")
        self.num_pages = P
        # -- host page bookkeeping (device truth: cache_store["table"]) --
        self.page_refs = np.zeros((P,), np.int32)
        self._free_pages = list(range(P))
        heapq.heapify(self._free_pages)   # smallest page first: deterministic
        self._free_page_set = set(self._free_pages)
        self.table = np.full((num_slots, self.pages_per_slot), P, np.int32)
        self.cow_copies = 0
        self.page_evictions = 0
        self.registry = None              # optional MetricsRegistry
        self.prefix = PrefixCache(page_size) if prefix_cache else None
        super().__init__(spec, num_slots, sharding=sharding)
        # engine-bound gather/scatter jits (built on first bind_engine;
        # the copy-page program needs nothing from the engine)
        self._engine = None
        self._paged_decode_jit = None
        self._paged_verify_jit = None
        self._paged_chunk_jit = None
        # fused paged-attention kernel selection (ISSUE 13): "off" keeps
        # the gather→dense-attention→scatter composition everywhere;
        # "on" forces the in-place page-table kernel (interpret mode
        # off-TPU — the bitwise-parity/CI configuration); "auto" uses
        # the kernel on TPU only. The dense composition remains the
        # oracle and fallback either way (chunked prefill always uses
        # it — chunk widths exceed the kernel's query-row limit).
        self.kernel = kernel
        self._paged_decode_kernel_jit = None
        self._paged_verify_kernel_jit = None
        self._jit_copy_page = jax.jit(self._copy_page_body,
                                      donate_argnums=(0,))
        # the cross-pool transfer is two programs, not one: replicas
        # live on DISJOINT meshes, and no single jit can span two
        # device sets — the source gathers the page batch on ITS
        # devices, the block hops meshes via an explicit device_put
        # (the "wire"), and the destination scatters on its own
        self._jit_gather_pages = jax.jit(self._gather_pages_body)
        self._jit_scatter_pages = jax.jit(self._scatter_pages_body,
                                          donate_argnums=(0,))
        self._admit_rows_jit = jax.jit(self._paged_admit_rows,
                                       donate_argnums=(0,))

    # ------------------------------------------------------------------
    # state containers
    # ------------------------------------------------------------------
    def _fresh_cache(self):
        """Zeroed page pool + sentinel table, committed like the dense
        pool (see SlotPool._fresh_cache for why commitment matters)."""
        cs = self.spec.paged_cache(self.num_pages, self.page_size)
        cs["index"] = jnp.zeros((self.num_slots,), jnp.int32)
        cs["table"] = jnp.full((self.num_slots, self.pages_per_slot),
                               self.num_pages, jnp.int32)
        if self._sharding is not None:
            cs = {k: self._place_leaf(k, v) for k, v in cs.items()}
        return {"cache_store": cs}

    def _table_from_mirror(self):
        tbl = jnp.array(self.table, copy=True)
        if self._sharding is not None:
            tbl = self._place_leaf("table", tbl)
        return tbl

    def _sync_table(self) -> None:
        """Rebuild the device page table from the host mirror (same
        committed-leaf discipline as ``_index_from_mirror``)."""
        cs = dict(self.cache["cache_store"])
        cs["table"] = self._table_from_mirror()
        self.cache = {"cache_store": cs}

    def _inc(self, name: str, amount: float = 1.0) -> None:
        if self.registry is not None:
            self.registry.counter(name).inc(amount)

    # ------------------------------------------------------------------
    # page refcounting / allocation
    # ------------------------------------------------------------------
    @property
    def free_page_count(self) -> int:
        return len(self._free_pages)

    def evictable_page_count(self) -> int:
        """Pages reclaimable WITHOUT preempting anyone (trie-only refs)."""
        return self.prefix.evictable_pages(self) \
            if self.prefix is not None else 0

    def ref_page(self, pid: int) -> None:
        if not 0 <= pid < self.num_pages:
            raise ValueError(f"page {pid} out of range [0, {self.num_pages})")
        if self.page_refs[pid] <= 0:
            raise RuntimeError(f"ref_page({pid}) on a free page (allocator "
                               f"bug: free pages have no owner to share)")
        self.page_refs[pid] += 1

    def unref_page(self, pid: int) -> bool:
        """Drop one reference; returns True when the page became free."""
        if not 0 <= pid < self.num_pages:
            raise ValueError(f"page {pid} out of range [0, {self.num_pages})")
        if pid in self._free_page_set or self.page_refs[pid] <= 0:
            raise RuntimeError(f"double free of page {pid} (already free; "
                               f"pool/trie bug)")
        self.page_refs[pid] -= 1
        if self.page_refs[pid] == 0:
            heapq.heappush(self._free_pages, pid)
            self._free_page_set.add(pid)
            return True
        return False

    def alloc_page(self) -> int:
        """Pop a free page (refcount set to 1 for the caller's mapping).
        Under pressure, least-recently-matched trie-only pages are
        reclaimed first; raises :class:`PagePoolExhausted` when even the
        trie has nothing to give — the caller's cue to preempt."""
        if not self._free_pages and self.prefix is not None:
            freed = self.prefix.evict(self, 1)
            if freed:
                self.page_evictions += freed
                self._inc("paging/evictions", freed)
        if not self._free_pages:
            raise PagePoolExhausted(
                f"page pool exhausted: {self.num_pages} pages all "
                f"referenced and nothing evictable")
        pid = heapq.heappop(self._free_pages)
        self._free_page_set.discard(pid)
        self.page_refs[pid] = 1
        return pid

    # ------------------------------------------------------------------
    # slot mapping (the mutable side of the page table)
    # ------------------------------------------------------------------
    def _unmap_slot(self, slot: int) -> None:
        sent = self.num_pages
        for pid in self.table[slot]:
            if pid != sent:
                self.unref_page(int(pid))
        self.table[slot, :] = sent

    def release(self, slot: int) -> None:
        """Free the slot AND unreference its pages: exclusively-owned
        pages (generated suffix, CoW forks) return to the free pool
        immediately; trie-cached prompt pages stay warm for the next
        request with the same prefix."""
        super().release(slot)         # range + double-free validation
        self._unmap_slot(slot)
        self._sync_table()

    def reset(self) -> None:
        self.page_refs[:] = 0
        self._free_pages = list(range(self.num_pages))
        heapq.heapify(self._free_pages)
        self._free_page_set = set(self._free_pages)
        self.table[:] = self.num_pages
        if self.prefix is not None:
            # the cached pages died with the pool; a fresh trie (not
            # clear()) avoids walking unref_page over freed state
            self.prefix = PrefixCache(self.page_size)
        super().reset()

    def reset_row(self, slot: int) -> None:
        self._unmap_slot(slot)
        super().reset_row(slot)
        self._sync_table()

    def ensure_writable(self, slot: int, start: int, end: int,
                        sync: bool = True) -> int:
        """Make positions ``[start, end)`` of ``slot`` safely writable
        BEFORE a jitted step writes them: unmapped pages are allocated;
        shared pages (refcount > 1) are copy-on-write forked — one
        jitted page copy, table entry swung to the fork, old page
        unref'd. Returns the number of CoW copies performed. May raise
        :class:`PagePoolExhausted` (already-made mappings stay valid;
        the caller preempts a victim and retries)."""
        if end <= start:
            return 0
        end = min(end, self.capacity)
        sent = self.num_pages
        ncow = 0
        changed = False
        for p in range(start // self.page_size,
                       (end - 1) // self.page_size + 1):
            pid = int(self.table[slot, p])
            if pid == sent:
                self.table[slot, p] = self.alloc_page()
                changed = True
            elif self.page_refs[pid] > 1:
                fork = self.alloc_page()
                try:
                    cs = self._jit_copy_page(self.cache["cache_store"],
                                             jnp.asarray(pid, jnp.int32),
                                             jnp.asarray(fork, jnp.int32))
                except Exception:
                    # copy dispatch died before the fork was mapped:
                    # return it to the free list (fresh refcount is 1)
                    # instead of stranding it until the next reset()
                    self.unref_page(fork)
                    raise
                self.cache = {"cache_store": cs}
                self.table[slot, p] = fork
                self.unref_page(pid)
                ncow += 1
                changed = True
        if changed and sync:
            self._sync_table()
        if ncow:
            self.cow_copies += ncow
            self._inc("paging/cow_copies", ncow)
        return ncow

    def map_prefix(self, slot: int, page_ids: Sequence[int],
                   sync: bool = True) -> None:
        """Map a trie hit's pages into the slot's table (positions
        ``[0, len(page_ids) * page_size)``) — the near-zero-cost half of
        a prefix hit: one refcount bump per page, no prefill."""
        for i, pid in enumerate(page_ids):
            if self.table[slot, i] != self.num_pages:
                raise RuntimeError(f"map_prefix over occupied entry "
                                   f"({slot}, {i})")
            self.ref_page(int(pid))
            self.table[slot, i] = int(pid)
        if sync and len(page_ids):
            self._sync_table()

    def seat_prefix(self, slot: int, page_ids: Sequence[int],
                    prefill_pos: int) -> None:
        """Seat a prefix-hit admission: map the shared pages, position
        the chunked prefill at ``prefill_pos``, and up-front CoW every
        mapped page at or beyond it. The eager CoW matters: decode steps
        interleave with chunked prefill and write (masked) garbage at
        the slot's index each dispatch — those writes must never land in
        a page another request still reads."""
        self.map_prefix(slot, page_ids, sync=False)
        hit_len = len(page_ids) * self.page_size
        self.starts[slot] = prefill_pos
        self.ensure_writable(slot, prefill_pos,
                             max(hit_len, prefill_pos + 1), sync=False)
        cs = dict(self.cache["cache_store"])
        cs["index"] = self._index_from_mirror()
        cs["table"] = self._table_from_mirror()
        self.cache = {"cache_store": cs}

    def cache_prefix(self, slot: int, tokens) -> int:
        """Publish the slot's freshly-prefilled FULL prompt pages into
        the prefix trie (called once per request when its prefill
        completes). Only full pages are cached — the trailing partial
        page keeps taking this slot's decode writes."""
        if self.prefix is None:
            return 0
        n_full = int(np.asarray(tokens).reshape(-1).shape[0]) \
            // self.page_size
        if n_full == 0:
            return 0
        pages = [int(p) for p in self.table[slot, :n_full]]
        if any(p == self.num_pages for p in pages):
            raise RuntimeError(f"cache_prefix: slot {slot} prompt pages "
                               f"not fully mapped: {pages}")
        return self.prefix.insert(tokens, pages, self)

    # ------------------------------------------------------------------
    # cross-pool page transfer (disaggregated prefill -> decode handoff)
    # ------------------------------------------------------------------
    @property
    def page_nbytes(self) -> int:
        """Bytes one page occupies across every cache leaf (what a
        cross-pool transfer moves per page)."""
        cs = self.cache["cache_store"]
        return sum(int(np.prod(cs[k].shape)) * cs[k].dtype.itemsize
                   // self.num_pages
                   for k in ("k", "v", "k_scale", "v_scale") if k in cs)

    def import_pages(self, src_pool: "PagedKVPool",
                     src_page_ids: Sequence[int]) -> List[int]:
        """Copy ``src_page_ids`` out of ANOTHER pool's storage into
        freshly allocated pages here — the device half of a
        disaggregated prefill->decode handoff. One fixed-shape jitted
        gather + one donated scatter per call (id vectors sentinel-
        padded to ``pages_per_slot``, the block hopping meshes between
        them), so every transfer — any page count, any replica pair —
        reuses the same two compiled programs.

        Ownership contract: the returned destination pages carry
        refcount 1 OWNED BY THE CALLER until :meth:`seat_pages` maps
        them into a slot's table. The source pool's references are
        untouched — the source slot's ``release()`` drops them exactly
        once, after the copy. On ANY failure (allocation or copy
        dispatch) every destination page allocated so far is unref'd
        before the exception propagates (the :meth:`ensure_writable`
        unwind template), so a mid-transfer death leaks nothing on
        either pool."""
        ids = [int(p) for p in src_page_ids]
        if len(ids) > self.pages_per_slot:
            raise ValueError(
                f"import_pages: {len(ids)} pages exceed pages_per_slot "
                f"({self.pages_per_slot}) — a transfer moves at most one "
                f"slot's table per call")
        if (src_pool.page_size != self.page_size
                or src_pool.num_pages != self.num_pages
                or src_pool.pages_per_slot != self.pages_per_slot):
            raise ValueError(
                f"import_pages needs identical page geometry on both "
                f"pools (one compiled transfer program); got src="
                f"{src_pool.num_pages}x{src_pool.page_size} vs dst="
                f"{self.num_pages}x{self.page_size}")
        for pid in ids:
            if pid in src_pool._free_page_set \
                    or src_pool.page_refs[pid] <= 0:
                raise ValueError(f"import_pages: source page {pid} is "
                                 f"free (nothing to copy)")
        dst: List[int] = []
        try:
            for _ in ids:
                dst.append(self.alloc_page())
            src_vec = np.full((self.pages_per_slot,),
                              src_pool.num_pages, np.int32)
            dst_vec = np.full((self.pages_per_slot,),
                              self.num_pages, np.int32)
            src_vec[:len(ids)] = ids
            dst_vec[:len(dst)] = dst
            cs = self._dispatch_transfer(src_pool, src_vec, dst_vec)
        except Exception:
            # unwind: pages allocated for a transfer that never landed
            # go straight back to the free list (fresh refcount is 1)
            self.unref_pages(dst)
            raise
        self.cache = {"cache_store": cs}
        self._inc("paging/pages_imported", len(dst))
        return dst

    def unref_pages(self, page_ids: Sequence[int]) -> None:
        """Drop one reference on each page — the bulk unwind of an
        :meth:`import_pages` batch whose seating failed (the caller
        still owns every page in the batch; :meth:`seat_pages` is
        atomic, so failure means NONE were taken)."""
        for pid in page_ids:
            self.unref_page(int(pid))

    def _land_block(self, block: dict) -> dict:
        """Move a gathered page block onto THIS pool's devices — the
        wire hop of a disaggregated transfer (replicas live on disjoint
        meshes; a same-mesh handoff makes this a no-op). Placement goes
        through :meth:`_place_leaf` so the block the scatter sees here
        is committed exactly like the block its bind-time precompile
        saw — the difference between zero and one executable."""
        return {k: self._place_leaf(k, v) for k, v in block.items()}

    def _dispatch_transfer(self, src_pool: "PagedKVPool",
                           src_vec, dst_vec):
        """The traced dispatch of a cross-pool transfer: id vectors
        arrive already sentinel-padded to ``pages_per_slot``, so every
        call replays the SAME two compiled programs — the source pool's
        gather, then (after the block hops onto this pool's devices)
        this pool's donated scatter (graftcheck drives exactly this
        method)."""
        block = src_pool._jit_gather_pages(
            src_pool.cache["cache_store"], jnp.asarray(src_vec))
        block = self._land_block(block)
        return self._jit_scatter_pages(
            self.cache["cache_store"], block, jnp.asarray(dst_vec))

    def seat_pages(self, slot: int, page_ids: Sequence[int],
                   prefill_pos: int, first_entry: int = 0) -> None:
        """Seat imported pages into ``slot`` at ``prefill_pos``: the
        slot's table TAKES the caller's :meth:`import_pages` references
        (no refcount bump — ownership transfers to the table) and
        index+table republish in one rebind (the :meth:`seat_prefix`
        idiom). ``first_entry`` offsets the table entries — a
        prefix-affine adopt maps trie-hit pages at ``[0, first_entry)``
        via :meth:`map_prefix` and seats only the transferred tail
        here. The decode loop resumes exactly where the source
        replica's prefill stopped."""
        ids = [int(p) for p in page_ids]
        need = -(-int(prefill_pos) // self.page_size)
        if first_entry + len(ids) < need:
            raise ValueError(
                f"seat_pages: {first_entry}+{len(ids)} pages cannot back "
                f"prefill_pos={prefill_pos} (live region needs {need})")
        # validate EVERYTHING before the first table write: seating is
        # atomic, so a caller's unwind never has to ask which pages a
        # half-failed seat already took
        for i, pid in enumerate(ids):
            if self.table[slot, first_entry + i] != self.num_pages:
                raise RuntimeError(f"seat_pages over occupied entry "
                                   f"({slot}, {first_entry + i})")
            if pid in self._free_page_set or self.page_refs[pid] <= 0:
                raise RuntimeError(f"seat_pages: page {pid} is free "
                                   f"(import its data first)")
        for i, pid in enumerate(ids):
            self.table[slot, first_entry + i] = pid
        self.starts[slot] = int(prefill_pos)
        cs = dict(self.cache["cache_store"])
        cs["index"] = self._index_from_mirror()
        cs["table"] = self._table_from_mirror()
        self.cache = {"cache_store": cs}

    # ------------------------------------------------------------------
    # jitted gather/scatter programs
    # ------------------------------------------------------------------
    @staticmethod
    def _copy_page_body(cs: dict, src, dst):
        """One page-to-page K/V copy (the CoW fork), all layers in one
        program; src/dst are traced scalars so one compile covers every
        page pair."""
        out = dict(cs)
        for key in ("k", "v", "k_scale", "v_scale"):
            if key not in cs:
                continue
            leaf = cs[key]
            page = jax.lax.dynamic_slice_in_dim(leaf, src, 1, 1)
            out[key] = jax.lax.dynamic_update_slice_in_dim(leaf, page,
                                                           dst, 1)
        return out

    @staticmethod
    def _gather_pages_body(src_cs: dict, src_ids):
        """Source half of a cross-pool transfer (the prefill->decode
        handoff): pull the sentinel-padded page batch out of the source
        pool's storage as one fixed-width (``pages_per_slot``) block
        per leaf — the transfer's wire format. A sentinel id clip-reads
        an arbitrary real page; its paired sentinel destination entry
        drops the write on the other side, so ONE compile covers every
        transfer size — the same trick the admission scatter uses.
        Runs on the SOURCE pool's devices."""
        return {key: jnp.take(src_cs[key], src_ids, axis=1, mode="clip")
                for key in ("k", "v", "k_scale", "v_scale")
                if key in src_cs}

    @staticmethod
    def _scatter_pages_body(dst_cs: dict, block: dict, dst_ids):
        """Destination half: seat the gathered block at ``dst_ids``
        (sentinel entries drop), all layers in one donated in-place
        program. Runs on the DESTINATION pool's devices — the block
        arrived via :meth:`_land_block`."""
        out = dict(dst_cs)
        for key in ("k", "v", "k_scale", "v_scale"):
            if key not in dst_cs:
                continue
            out[key] = dst_cs[key].at[:, dst_ids].set(
                block[key].astype(dst_cs[key].dtype), mode="drop")
        return out

    def _scatter_cols(self, pool: dict, dense: dict, tables, positions):
        """Traced: write the dense view's columns at ``positions``
        ((B, W) absolute positions, aligned with the dense batch) back
        into the page pool through per-row page ``tables`` ((B,
        max_pages_per_slot)). Out-of-range positions and sentinel table
        entries scatter with ``mode="drop"`` — they touch nothing."""
        ps = self.page_size
        maxP = self.pages_per_slot
        sent = self.num_pages
        pidx = positions // ps
        valid = (positions >= 0) & (positions < maxP * ps)
        pages = jnp.take_along_axis(tables, jnp.clip(pidx, 0, maxP - 1),
                                    axis=1)
        pages = jnp.where(valid, pages, sent)
        offs = positions % ps
        out = dict(pool)
        for key in ("k", "v"):
            leaf = dense[key]                     # (L, B, KV, cd, S)
            vals = jnp.take_along_axis(
                leaf, positions[None, :, None, None, :], axis=4,
                mode="clip")
            vals = vals.transpose(1, 4, 0, 2, 3)  # (B, W, L, KV, cd)
            out[key] = pool[key].at[:, pages, :, :, offs].set(
                vals.astype(pool[key].dtype), mode="drop")
        for key in ("k_scale", "v_scale"):
            if key not in pool:
                continue
            leaf = dense[key]                     # (L, B, KV, S)
            vals = jnp.take_along_axis(
                leaf, positions[None, :, None, :], axis=3, mode="clip")
            vals = vals.transpose(1, 3, 0, 2)     # (B, W, L, KV)
            out[key] = pool[key].at[:, pages, :, offs].set(
                vals.astype(pool[key].dtype), mode="drop")
        return out

    def _paged_admit_rows(self, pool: dict, pre: dict, rows_tables,
                          slots, lengths):
        """Batched paged admission: scatter every column of the (full-
        capacity) prefill cache through host-passed per-row tables.
        Padding rows are ALL-sentinel tables (not just a sentinel slot
        id — indexing the device table with a clamped sentinel slot
        would alias a real slot's pages), so their writes drop."""
        S = self.capacity
        nB = rows_tables.shape[0]
        pos = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None, :],
                               (nB, S))
        out = self._scatter_cols(pool, pre, rows_tables, pos)
        out["index"] = pool["index"].at[slots].set(
            jnp.asarray(lengths, jnp.int32), mode="drop")
        out["table"] = pool["table"]
        return out

    def bind_engine(self, engine: Any) -> None:
        """Build the jitted decode/verify/chunk wrappers over the
        engine's traced model functions. Composition, not duplication:
        the SAME ``decode_fn`` / verify body / ``prefill_chunk`` method
        the contiguous path compiles runs against the gathered dense
        view, which is what makes paged greedy output bitwise identical
        to the contiguous pool. Idempotent per engine (rebinding would
        shed the recompile watchdog's wrappers)."""
        if self._engine is engine and self._paged_decode_jit is not None:
            return
        if getattr(engine, "_decode_fn", None) is None:
            raise ValueError("PagedKVPool.bind_engine needs a built "
                             "InferenceEngine (LM module with decode())")
        from ..inference.engine import _filter_logits
        from .spec_decode.verify import make_verify_fn

        self._engine = engine
        spec = self.spec
        decode_fn = engine._decode_fn
        verify_body = make_verify_fn(decode_fn, _filter_logits)
        module = getattr(engine, "_serve_module", None) or engine.module
        dequant = engine._dequant
        chunk_gen = getattr(module, "prefill_chunk", None)
        scatter = self._scatter_cols

        def dense_cache(cs):
            dense = spec.dense_from_pages(cs, cs["table"])
            dense["index"] = cs["index"]
            return {"cache_store": dense}

        def paged_decode(params, cs, token, pos):
            logits, new = decode_fn(params, dense_cache(cs), token, pos)
            ncs = new["cache_store"]
            W = cs["index"][:, None]          # one column written per row
            out = scatter(cs, ncs, cs["table"], W)
            out["index"] = ncs["index"]
            out["table"] = cs["table"]
            return logits, out

        def paged_verify(params, cs, tokens, pos, draft, draft_len, rng,
                         temperature, greedy, top_k, top_p):
            new, out_tok, n_emit = verify_body(
                params, dense_cache(cs), tokens, pos, draft, draft_len,
                rng, temperature, greedy, top_k, top_p)
            ncs = new["cache_store"]
            K1 = tokens.shape[1]              # K+1 columns written per row
            W = cs["index"][:, None] + \
                jnp.arange(K1, dtype=jnp.int32)[None, :]
            out = scatter(cs, ncs, cs["table"], W)
            out["index"] = ncs["index"]
            out["table"] = cs["table"]
            return out, out_tok, n_emit

        def paged_chunk(params, cs, ids, row_table, slot, start, length,
                        last_idx):
            # gather ONE slot's dense row from its pages, run the
            # window-masked chunk, scatter back only the chunk window
            vals = {k: v for k, v in cs.items()
                    if k not in ("index", "table")}
            dense = spec.dense_from_pages(vals, row_table[None])
            dense["index"] = start[None]
            out, vars_ = module.apply(
                {"params": dequant(params),
                 "cache": {"cache_store": dense}},
                ids, start[None], last_idx, method=chunk_gen,
                mutable=["cache"])
            new = vars_["cache"]["cache_store"]
            C = ids.shape[1]
            W = start[None, None] + \
                jnp.arange(C, dtype=jnp.int32)[None, :]       # (1, C)
            outcs = scatter(cs, new, row_table[None], W)
            outcs["index"] = cs["index"].at[slot].set(
                start + jnp.asarray(length, jnp.int32), mode="drop")
            outcs["table"] = cs["table"]
            return out, outcs

        self._paged_decode_jit = jax.jit(paged_decode, donate_argnums=(1,))
        self._paged_verify_jit = jax.jit(paged_verify, donate_argnums=(1,),
                                         static_argnums=(9, 10))
        self._paged_chunk_jit = (jax.jit(paged_chunk, donate_argnums=(1,))
                                 if chunk_gen is not None else None)

        # -- fused paged-attention kernel entries (ISSUE 13) -----------
        # Same jit signatures as the dense compositions above, but the
        # model step runs ``decode_paged``: column writes scatter through
        # the page table at the source and the Pallas kernel reads pages
        # in place — the dense (L, B, KV, cd, S) scratch view is never
        # built. Greedy decode output is bitwise-identical (the kernel's
        # per-page online-softmax blocking matches decode_attention at
        # block_s=page_size; see ops/attention/paged_attention.py).
        if self.kernel_active \
                and getattr(module, "decode_paged", None) is not None:
            from ..ops.attention.paged_attention import MAX_QUERY_ROWS
            if MAX_QUERY_ROWS != _KERNEL_MAX_QUERY_ROWS:
                raise RuntimeError(
                    f"_KERNEL_MAX_QUERY_ROWS={_KERNEL_MAX_QUERY_ROWS} "
                    f"drifted from kernel MAX_QUERY_ROWS={MAX_QUERY_ROWS}")

            def kernel_decode_fn(params, cache, token, pos):
                cs = cache["cache_store"]
                vals = {k: v for k, v in cs.items() if k != "table"}
                logits, vars_ = module.apply(
                    {"params": dequant(params),
                     "cache": {"cache_store": vals}},
                    token, pos, cs["table"], method=module.decode_paged,
                    mutable=["cache"])
                new = dict(vars_["cache"]["cache_store"])
                new["table"] = cs["table"]
                return logits, {"cache_store": new}

            def kernel_decode(params, cs, token, pos):
                logits, new = kernel_decode_fn(params,
                                               {"cache_store": cs},
                                               token, pos)
                return logits, new["cache_store"]

            kernel_verify_body = make_verify_fn(kernel_decode_fn,
                                                _filter_logits)

            def kernel_verify(params, cs, tokens, pos, draft, draft_len,
                              rng, temperature, greedy, top_k, top_p):
                new, out_tok, n_emit = kernel_verify_body(
                    params, {"cache_store": cs}, tokens, pos, draft,
                    draft_len, rng, temperature, greedy, top_k, top_p)
                return new["cache_store"], out_tok, n_emit

            self._paged_decode_kernel_jit = jax.jit(kernel_decode,
                                                    donate_argnums=(1,))
            self._paged_verify_kernel_jit = jax.jit(
                kernel_verify, donate_argnums=(1,), static_argnums=(9, 10))
        # pre-compile the CoW copy program with a no-op self-copy: the
        # first real fork can land arbitrarily late (a prefix hit on a
        # page some earlier request published), easily after warmup
        # traffic ends — and the strict watchdog rightly counts ANY
        # post-warmup compile
        zero = jnp.asarray(0, jnp.int32)
        self.cache = {"cache_store": self._jit_copy_page(
            self.cache["cache_store"], zero, zero)}
        # same treatment for both halves of the cross-pool transfer: a
        # decode-role replica sees its first page import whenever the
        # router's first handoff lands — typically long after warmup
        # traffic ends — and a prefill-role replica's gather fires at
        # the same moment from the other side. All-sentinel id vectors
        # make the pair a no-op (the clip-gather reads garbage, every
        # scatter write drops); the block rides _land_block so its
        # committed placement here matches what a real transfer ships.
        sent_ids = jax.device_put(jnp.full((self.pages_per_slot,),
                                           self.num_pages, jnp.int32))
        block = self._land_block(self._jit_gather_pages(
            self.cache["cache_store"], sent_ids))
        self.cache = {"cache_store": self._jit_scatter_pages(
            self.cache["cache_store"], block, sent_ids)}

    # ------------------------------------------------------------------
    # jitted entry points (the serving engine dispatches here when paged)
    # ------------------------------------------------------------------
    @property
    def kernel_active(self) -> bool:
        """Whether decode/verify dispatch to the fused paged-attention
        kernel ("on": always, interpret mode off-TPU; "auto": TPU only;
        "off": never — dense gather/scatter composition everywhere)."""
        if self.kernel == "off":
            return False
        if self.kernel == "on":
            return True
        return jax.default_backend() == "tpu"

    def run_decode(self, engine: Any, tokens, pos):
        """One masked decode step for every slot over paged storage;
        updates the pool state in place and returns the logits."""
        self.bind_engine(engine)
        # direct attribute dispatch on both arms (not `fn = a or b;
        # fn(...)`): the watchdog and graftcheck identify watched
        # programs by the attribute the call goes through; each arm
        # rebinds self.cache immediately — its cache operand is donated
        if self._paged_decode_kernel_jit is not None:
            logits, cs = self._paged_decode_kernel_jit(
                engine.params, self.cache["cache_store"], tokens, pos)
            self.cache = {"cache_store": cs}
        else:
            logits, cs = self._paged_decode_jit(
                engine.params, self.cache["cache_store"], tokens, pos)
            self.cache = {"cache_store": cs}
        return logits

    def run_verify(self, engine: Any, tokens, pos, draft, draft_len, rng,
                   temperature, greedy, top_k: int, top_p: float):
        """Speculative verify over paged storage (same semantics as
        ``InferenceEngine.verify_k``); returns ``(out, n_emit)``. The
        fused kernel handles K+1 query rows up to its sublane-tile limit
        (``_KERNEL_MAX_QUERY_ROWS``); wider verify chunks stay on the
        dense composition."""
        self.bind_engine(engine)
        use_kernel = self._paged_verify_kernel_jit is not None \
            and tokens.shape[1] <= _KERNEL_MAX_QUERY_ROWS
        if use_kernel:
            cs, out, n_emit = self._paged_verify_kernel_jit(
                engine.params, self.cache["cache_store"], tokens, pos,
                draft, draft_len, rng, temperature, greedy, int(top_k),
                float(top_p))
            self.cache = {"cache_store": cs}
        else:
            cs, out, n_emit = self._paged_verify_jit(
                engine.params, self.cache["cache_store"], tokens, pos,
                draft, draft_len, rng, temperature, greedy, int(top_k),
                float(top_p))
            self.cache = {"cache_store": cs}
        return out, n_emit

    def run_prefill_chunk(self, engine: Any, ids, slot: int, start: int,
                          length: int, last_idx: int):
        """One bounded prefill chunk into ``slot``'s pages at offset
        ``start`` (pages covering the window must already be writable —
        the engine calls :meth:`ensure_writable` first). Returns the
        chunk's (1, 1, V) logits."""
        self.bind_engine(engine)
        if self._paged_chunk_jit is None:
            raise ValueError("run_prefill_chunk requires a module with "
                             "prefill_chunk(); the TransformerLM family "
                             "has one")
        logits, cs = self._paged_chunk_jit(
            engine.params, self.cache["cache_store"],
            jnp.asarray(ids, jnp.int32), jnp.asarray(self.table[slot]),
            jnp.asarray(slot, jnp.int32), jnp.asarray(start, jnp.int32),
            jnp.asarray(length, jnp.int32),
            jnp.asarray(last_idx, jnp.int32))
        self.cache = {"cache_store": cs}
        return logits

    # ------------------------------------------------------------------
    # admission (SlotPool API, paged storage)
    # ------------------------------------------------------------------
    def _admit_scatter(self, prefill_cache: dict, slots: np.ndarray,
                       lengths: np.ndarray) -> None:
        nB = len(slots)
        rows = np.full((nB, self.pages_per_slot), self.num_pages, np.int32)
        for i, s in enumerate(slots):
            if s < self.num_slots:
                rows[i] = self.table[s]
        self._sync_table()       # publish ensure_writable's new mappings
        self.cache = {"cache_store": self._admit_rows_jit(
            self.cache["cache_store"], prefill_cache["cache_store"],
            jnp.asarray(rows), jnp.asarray(slots), jnp.asarray(lengths))}
        real = slots < self.num_slots
        self.starts[slots[real]] = lengths[real]

    def admit(self, prefill_cache: dict, slot: int, length: int) -> None:
        if length > self.capacity:
            raise ValueError(f"sequence length {length} exceeds slot "
                             f"capacity {self.capacity}")
        self.ensure_writable(slot, 0, length, sync=False)
        self._admit_scatter(prefill_cache,
                            np.asarray([slot], np.int32),
                            np.asarray([length], np.int32))

    def admit_rows(self, prefill_cache: dict, slots, lengths) -> None:
        slots = np.asarray(slots, np.int32)
        lengths = np.asarray(lengths, np.int32)
        if slots.shape != lengths.shape or slots.ndim != 1:
            raise ValueError(f"admit_rows needs matching 1-D slots/lengths; "
                             f"got {slots.shape} vs {lengths.shape}")
        real = slots < self.num_slots
        if np.any(lengths[real] > self.capacity):
            raise ValueError(f"sequence length {int(lengths[real].max())} "
                             f"exceeds slot capacity {self.capacity}")
        for s, T in zip(slots[real], lengths[real]):
            self.ensure_writable(int(s), 0, int(T), sync=False)
        self._admit_scatter(prefill_cache, slots, lengths)

    # ------------------------------------------------------------------
    # audit / stats
    # ------------------------------------------------------------------
    def page_stats(self) -> dict:
        free = len(self._free_pages)
        stats = {"pages_total": self.num_pages,
                 "pages_free": free,
                 "pages_in_use": self.num_pages - free,
                 "refcounted_pages": int(np.sum(self.page_refs > 1)),
                 "cow_copies": self.cow_copies,
                 "page_evictions": self.page_evictions,
                 "page_size": self.page_size}
        if self.prefix is not None:
            stats.update(
                prefix_hits=self.prefix.hits,
                prefix_misses=self.prefix.misses,
                prefix_hit_tokens=self.prefix.hit_tokens,
                prefix_nodes=self.prefix.num_nodes,
                prefix_evictable_pages=self.evictable_page_count())
        return stats

    def consistency_errors(self) -> List[str]:
        """SlotPool's audit plus the page bookkeeping invariants: the
        free-page heap/set mirrors agree, every refcount equals the
        references actually held (table entries + trie nodes), zero-ref
        pages are exactly the free ones, free slots map nothing, and
        every live slot's ``[0, index)`` columns are page-backed."""
        errors = super().consistency_errors()
        P, sent = self.num_pages, self.num_pages
        if len(self._free_pages) != len(self._free_page_set):
            errors.append(f"free page heap ({len(self._free_pages)}) and "
                          f"set ({len(self._free_page_set)}) sizes differ")
        if set(self._free_pages) != self._free_page_set:
            errors.append("free page heap and set mirrors disagree")
        if len(set(self._free_pages)) != len(self._free_pages):
            errors.append("duplicate pages in free heap (double free)")
        bad = [p for p in self._free_page_set if not 0 <= p < P]
        if bad:
            errors.append(f"free pages out of range: {sorted(bad)}")
        held = np.zeros((P,), np.int64)
        for pid in self.table.reshape(-1):
            pid = int(pid)
            if pid == sent:
                continue
            if not 0 <= pid < P:
                errors.append(f"table references page {pid} out of range")
                continue
            held[pid] += 1
        if self.prefix is not None:
            for pid, c in self.prefix.page_counts().items():
                if not 0 <= pid < P:
                    errors.append(f"trie references page {pid} out of range")
                else:
                    held[pid] += c
        mism = np.nonzero(held != self.page_refs)[0]
        if len(mism):
            show = mism[:8].tolist()
            errors.append(
                f"page refcounts disagree with held references at pages "
                f"{show}: refs={self.page_refs[mism][:8].tolist()} "
                f"held={held[mism][:8].tolist()}")
        zero_ref = set(np.nonzero(self.page_refs == 0)[0].tolist())
        if zero_ref != self._free_page_set:
            errors.append(
                f"zero-ref pages != free pages: only-zero-ref="
                f"{sorted(zero_ref - self._free_page_set)[:8]} "
                f"only-free={sorted(self._free_page_set - zero_ref)[:8]}")
        for slot in range(self.num_slots):
            row = self.table[slot]
            if slot in self._free_set:
                if np.any(row != sent):
                    errors.append(f"free slot {slot} still maps pages "
                                  f"{row[row != sent].tolist()}")
                continue
            n_live = -(-int(self.starts[slot]) // self.page_size)
            if np.any(row[:n_live] == sent):
                errors.append(
                    f"slot {slot} live region [0, {int(self.starts[slot])})"
                    f" has unmapped pages: row={row[:n_live].tolist()}")
        return errors
