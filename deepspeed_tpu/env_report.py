"""Environment / capability report.

Capability parity with reference ``deepspeed/env_report.py`` + ``bin/
ds_report`` — prints framework, JAX/XLA, device, and native-op build
status. Run as ``python -m deepspeed_tpu.env_report``.
"""

from __future__ import annotations

import importlib
import os
import shutil
import sys

GREEN = "\033[92m"
RED = "\033[91m"
YELLOW = "\033[93m"
END = "\033[0m"
OKAY = f"{GREEN}[OKAY]{END}"
NO = f"{RED}[NO]{END}"
WARNING = f"{YELLOW}[WARNING]{END}"


def _version(mod_name: str):
    try:
        mod = importlib.import_module(mod_name)
        return getattr(mod, "__version__", "unknown")
    except ImportError:
        return None


def op_report():
    """Native-op availability (the analog of the reference's op-compat
    table over op_builder)."""
    rows = []
    from .ops.op_builder import available_ops

    for name, status in available_ops().items():
        rows.append((name, OKAY if status else NO))
    return rows


def debug_report():
    import jax

    rows = [
        ("deepspeed_tpu", _version("deepspeed_tpu") or "dev"),
        ("jax", jax.__version__),
        ("jaxlib", _version("jaxlib")),
        ("flax", _version("flax")),
        ("optax", _version("optax")),
        ("orbax", _version("orbax.checkpoint")),
        ("numpy", _version("numpy")),
        ("python", sys.version.split()[0]),
        ("platform", jax.default_backend()),
        ("devices", ", ".join(str(d) for d in jax.devices())),
        ("g++", shutil.which("g++") or "not found"),
        ("XLA_FLAGS", os.environ.get("XLA_FLAGS", "")),
    ]
    return rows


def main():
    print("-" * 70)
    print("DeepSpeed-TPU general environment info:")
    print("-" * 70)
    for k, v in debug_report():
        print(f"{k:<20} {v}")
    print("-" * 70)
    print("native/compiled ops:")
    print("-" * 70)
    try:
        for name, status in op_report():
            print(f"{name:<20} {status}")
    except Exception as e:
        print(f"op report unavailable: {e}")
    print("-" * 70)


def cli_main():
    main()


if __name__ == "__main__":
    main()
