"""Module injection, TPU-native.

The reference rewrites module *objects*: per-model policies select fused CUDA
containers (``module_inject/replace_module.py:283 replace_transformer_layer``)
and AutoTP swaps ``nn.Linear`` for ``LinearLayer``/``LinearAllreduce``
(``module_inject/auto_tp.py:13``, ``module_inject/layers.py:15,32``). On TPU
nothing needs rewriting — XLA already fuses, and tensor parallelism is a
*sharding annotation*. So "injection" here produces :class:`ShardingRules`:

* :func:`get_policy_rules` — per-family explicit rules (the policy path);
* :func:`auto_tp_rules` — shape/name-heuristic classification of an arbitrary
  param pytree (the AutoTP path): down/output projections are row-parallel
  (their input dim sharded ⇒ XLA inserts the allreduce the reference's
  LinearAllreduce does by hand), everything else column-parallel.
"""

from __future__ import annotations

import re
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from ..parallel.mesh import MODEL_AXIS
from ..runtime.zero.policy import ShardingRules, _path_str

# name fragments marking the SECOND linear of a pair (row-parallel: shard the
# input dim, allreduce output) — mirrors auto_tp.py's allreduce-linear
# heuristics (o_proj/out_proj/down_proj/dense_4h_to_h/fc2/...)
ROW_PARALLEL_PAT = re.compile(
    r"(o_proj|out_proj|down_proj|dense_4h_to_h|attention/dense|fc2|proj_out"
    r"|c_proj|wo)(/|$)", re.IGNORECASE)
EMBED_PAT = re.compile(r"(embedding|wte|embed_tokens)(/|$)", re.IGNORECASE)
POS_EMBED_PAT = re.compile(r"(wpe|embed_pos|position)", re.IGNORECASE)


def auto_tp_rules(params: Any, tp_size: int,
                  exclude: Sequence[str] = ()) -> ShardingRules:
    """Infer tensor-parallel sharding rules for an arbitrary param pytree
    (≅ AutoTP, reference module_inject/auto_tp.py:13).

    Classification per leaf (rightmost dims; leading dims — e.g. a scanned
    layer stack — stay unsharded):
      - embeddings: vocab-parallel (dim -2 over model) unless positional;
      - kernels matching ROW_PARALLEL_PAT: input dim (-2) over model;
      - other >=2D kernels: output dim (-1) over model, plus their biases;
      - anything indivisible by ``tp_size``: replicated (the reference
        likewise falls back to no-TP for odd shapes).
    """
    import jax

    rules: List[Tuple[str, tuple]] = []
    leaves = jax.tree_util.tree_flatten_with_path(params)[0]
    for path, leaf in leaves:
        p = _path_str(path)
        if any(x in p for x in exclude):
            continue
        shape = np.shape(leaf)
        nd = len(shape)
        spec: Optional[tuple] = None
        if EMBED_PAT.search(p) and not POS_EMBED_PAT.search(p) and nd >= 2:
            if shape[-2] % tp_size == 0:
                spec = (None,) * (nd - 2) + (MODEL_AXIS, None)
        elif p.endswith("kernel") and nd >= 2:
            if ROW_PARALLEL_PAT.search(p):
                if shape[-2] % tp_size == 0:
                    spec = (None,) * (nd - 2) + (MODEL_AXIS, None)
            else:
                if shape[-1] % tp_size == 0:
                    spec = (None,) * (nd - 1) + (MODEL_AXIS,)
        elif p.endswith("bias") and nd >= 1 and not ROW_PARALLEL_PAT.search(p):
            if shape[-1] % tp_size == 0:
                spec = (None,) * (nd - 1) + (MODEL_AXIS,)
        if spec is not None:
            rules.append((re.escape(p) + "$", spec))
    return ShardingRules(rules)


def get_policy_rules(model: Any) -> Optional[ShardingRules]:
    """Explicit per-family rules when the model type is known (≅ the policy/
    container path, reference module_inject/replace_policy.py)."""
    from ..models.gpt2 import GPT2LMHeadModel, gpt2_sharding_rules
    from ..models.transformer_lm import TransformerLM, transformer_sharding_rules

    if isinstance(model, TransformerLM):
        return ShardingRules(transformer_sharding_rules())
    if isinstance(model, GPT2LMHeadModel):
        return ShardingRules(gpt2_sharding_rules())
    return None


def replace_module(model: Any, params: Any = None, tp_size: int = 1,
                   injection_policy=None) -> ShardingRules:
    """Top-level injection entry (≅ replace_transformer_layer /
    replace_module, reference module_inject/replace_module.py:283,751):
    policy rules when the family is known, AutoTP otherwise."""
    if injection_policy:
        pairs = injection_policy.items() if hasattr(injection_policy, "items") \
            else injection_policy
        return ShardingRules(list(pairs))
    rules = get_policy_rules(model)
    if rules is not None:
        return rules
    if params is None:
        raise ValueError("AutoTP needs the param pytree for unknown models")
    return auto_tp_rules(params, tp_size)
