"""GPT-2 as a PipelineModule — the 3D-parallel (DP × PP × TP) flagship.

Capability parity target: the reference's Megatron-GPT2 pipeline configs
(``PipeModelDataParallelTopology``, reference pipe/topology.py:244, and the
GPT2 model tests under tests/model/Megatron_GPT2). Blocks reuse
``models/gpt2.Block``; the head is untied (NeoX-style) so stages stay
homogeneous.
"""

from __future__ import annotations

from typing import Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..parallel.mesh import MODEL_AXIS, PIPE_AXIS
from ..runtime.pipe.module import LayerSpec, PipelineModule
from .gpt2 import Block, GPT2Config


class GPT2Embed(nn.Module):
    """Stage-0 embedding (wte + wpe) consuming the micro-batch dict."""

    config: GPT2Config

    @nn.compact
    def __call__(self, micro_batch):
        cfg = self.config
        ids = micro_batch["input_ids"]
        wte = nn.Embed(cfg.vocab_size, cfg.n_embd, dtype=cfg.dtype, name="wte")
        wpe = nn.Embed(cfg.n_positions, cfg.n_embd, dtype=cfg.dtype, name="wpe")
        T = ids.shape[-1]
        return wte(ids) + wpe(jnp.arange(T)[None, :])


class GPT2Head(nn.Module):
    """Final LN + untied LM head producing logits."""

    config: GPT2Config

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        x = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype,
                         name="ln_f")(x)
        return nn.Dense(cfg.vocab_size, use_bias=False, dtype=jnp.float32,
                        name="lm_head")(x.astype(jnp.float32))


def gpt2_lm_loss(logits, micro_batch):
    """Shifted causal cross-entropy; -100/-1 labels are ignored."""
    input_ids = micro_batch["input_ids"]
    labels = micro_batch.get("labels", input_ids) \
        if hasattr(micro_batch, "get") else input_ids
    logits = logits[:, :-1]
    targets = labels[:, 1:]
    mask = (targets >= 0).astype(jnp.float32)
    targets = jnp.maximum(targets, 0)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return (nll * mask).sum() / jnp.maximum(mask.sum(), 1.0)


def gpt2_pipe_module(config: GPT2Config, num_stages: int,
                     activation_checkpoint_interval: int = 1) -> PipelineModule:
    layers: Tuple = tuple(
        [LayerSpec(GPT2Embed, config)]
        + [LayerSpec(Block, config)] * config.n_layer
        + [LayerSpec(GPT2Head, config)])
    return PipelineModule(layers=layers, loss_fn=gpt2_lm_loss,
                          num_stages=num_stages,
                          activation_checkpoint_interval=activation_checkpoint_interval)


def gpt2_pipe_sharding_rules():
    """Composed pipe × tensor-parallel rules for the stacked block params
    (rank 4: stage, local_layer, in, out). Specific TP rules first; the
    trailing blocks/ rule pipe-shards everything else (LN params, etc.)."""
    M, P = MODEL_AXIS, PIPE_AXIS
    return [
        (r"attn/qkv/kernel", (P, None, None, M)),   # column parallel
        (r"attn/proj/kernel", (P, None, M, None)),  # row parallel
        (r"mlp/fc/kernel", (P, None, None, M)),     # column parallel
        (r"mlp/proj/kernel", (P, None, M, None)),   # row parallel
        (r"attn/qkv/bias", (P, None, M)),
        (r"mlp/fc/bias", (P, None, M)),
        (r"wte/embedding", (M, None)),              # vocab-parallel embedding
        (r"lm_head/kernel", (None, M)),             # column-parallel head
        (r"blocks/", (P,)),
    ]
