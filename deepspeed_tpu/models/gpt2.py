"""GPT-2 family, TPU-first.

The flagship model for the Megatron-GPT2 / GPT-2 baseline configs
(reference tests/model/Megatron_GPT2, BASELINE.json "GPT-2 125M ZeRO-1").
Architecture notes (not a port — reference has no JAX model zoo):

* Transformer blocks run under ``nn.scan`` — one set of stacked block params
  with a leading layer dimension. This is the TPU-idiomatic layout: one
  compiled block body (fast compiles at depth), and under ZeRO-3 the
  per-layer slices of the stacked params are gathered layer-by-layer inside
  the scan, reproducing the reference's module-granular gather/release
  (stage3.py fetch/release hooks) as a compiler-scheduled pipeline.
* ``remat`` enables activation checkpointing around each block
  (≅ runtime/activation_checkpointing/checkpointing.py:708).
* Tensor-parallel sharding is declared, not coded: ``gpt2_sharding_rules``
  maps parameter paths to mesh axes (Megatron-style column/row splits);
  the engine's ZeroShardingPolicy composes ZeRO axes on top.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..parallel.mesh import MODEL_AXIS


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    n_positions: int = 1024
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    dropout: float = 0.0
    layer_norm_epsilon: float = 1e-5
    dtype: Any = jnp.bfloat16  # activation/compute dtype
    remat: bool = False  # activation checkpointing per block
    # remat policy: "full" recomputes everything; "dots" saves matmul
    # outputs and recomputes only elementwise ops (cheaper recompute,
    # jax.checkpoint_policies.dots_with_no_batch_dims_saveable)
    remat_policy: str = "full"
    # Pallas flash kernel: True | False | "auto" (on-TPU when seq >= the
    # measured crossover — BASELINE.md; off elsewhere)
    use_flash_attention: Any = "auto"
    # sequence/context parallelism over the `seq` mesh axis:
    # None | "ring" (ppermute KV rotation) | "ulysses" (all-to-all head swap)
    sequence_parallel: Optional[str] = None
    # lax.scan unroll factor over the stacked blocks: >1 lets XLA schedule
    # across layer boundaries (scan steps otherwise materialize the carry
    # and serialize); costs compile time proportionally
    scan_unroll: int = 1
    # block-sparse attention: a SparsityConfig (ops/sparse_attention) —
    # every attention layer computes only the layout's blocks via the
    # fused Pallas kernel (gather formulation off-TPU / fine granules).
    # The model-level analog of the reference's SparseAttentionUtils
    # module swap (module_inject; docs/_posts/2020-09-09-sparse-attention.md)
    sparse_attention: Optional[Any] = None
    # fused LayerNorm->matmul Pallas kernel for the ln_1->qkv and ln_2->fc
    # pairs (ops/transformer/ln_linear.py — the TPU analog of the
    # reference's fused transformer-block kernel). True | False | "auto".
    # The parameter tree is identical either way. "auto" currently
    # resolves to OFF: the round-5 flagship A/B measured the fused kernel
    # at 0.91x XLA's composition (40.9k -> 37.3k tok/s at 350M/seq1024 —
    # benchmarks/model_bench_results.json; XLA's matmul pipelining +
    # multi-output fusions beat hand fusion at these shapes). Kept as an
    # explicit option and parity-tested; does not compose with model
    # parallelism (the Pallas call is not GSPMD-partitionable)
    fused_ln_linear: Any = "auto"
    # streaming cross-entropy: >0 computes the LM loss in T-chunks of
    # this size without materializing the (B, T, V) logits tensor
    # (ops/transformer/chunked_xent.py). Measured ~free at the flagship
    # (-0.3%) and lets previously-OOM configs compile (350M mbs16, 774M
    # dots_plain) — but did NOT unlock a better operating point at
    # either size (BASELINE.md 774M section). 0 = dense loss.
    loss_chunk: int = 0


# sizes for the standard family
GPT2_SIZES = {
    "gpt2-125m": dict(n_embd=768, n_layer=12, n_head=12),
    "gpt2-medium": dict(n_embd=1024, n_layer=24, n_head=16),
    "gpt2-large": dict(n_embd=1280, n_layer=36, n_head=20),
    "gpt2-xl": dict(n_embd=1600, n_layer=48, n_head=25),
    "gpt2-1.3b": dict(n_embd=2048, n_layer=24, n_head=16),
}


def gpt2_config(name: str = "gpt2-125m", **overrides) -> GPT2Config:
    return GPT2Config(**{**GPT2_SIZES[name], **overrides})


def gpt2_sharding_rules():
    """Megatron-style TP rules as (path-regex, PartitionSpec entries).

    Scanned block params carry a leading layer dim (axis 0 = None).
    The TPU-native analog of the reference's injection policies / AutoTP
    layer classification (module_inject/auto_tp.py:13,
    module_inject/layers.py:15,32): column-parallel for QKV & MLP-in,
    row-parallel for attn-out & MLP-out, vocab-parallel embedding.
    """
    M = MODEL_AXIS
    return [
        (r"wte/embedding", (M, None)),          # vocab-parallel embedding
        (r"wpe/embedding", (None, None)),
        (r"attn/qkv/kernel", (None, None, M)),  # column parallel (layer dim first)
        (r"attn/proj/kernel", (None, M, None)),  # row parallel
        (r"mlp/fc/kernel", (None, None, M)),    # column parallel
        (r"mlp/proj/kernel", (None, M, None)),  # row parallel
        (r"attn/qkv/bias", (None, M)),
        (r"mlp/fc/bias", (None, M)),
    ]


def _use_fused_ln(cfg) -> bool:
    """Fused ln->matmul gate. "auto" resolves OFF (the measured flagship
    A/B has XLA's composition 1.10x the fused kernel — GPT2Config note);
    explicit True demands the kernel and raises under model parallelism
    (the Pallas call is not GSPMD-partitionable) — silently downgrading a
    demanded kernel would mis-attribute benchmarks."""
    if cfg.fused_ln_linear is False:
        return False
    from ..parallel.mesh import get_model_parallel_world_size

    if cfg.fused_ln_linear is True:
        if get_model_parallel_world_size() > 1:
            raise ValueError(
                "fused_ln_linear=True does not compose with model "
                "parallelism (the Pallas call is not GSPMD-partitionable); "
                "use fused_ln_linear='auto' to fall back automatically")
        return True
    # "auto" = off: the measured A/B has XLA's composition 1.10x faster
    # than the fused kernel at the flagship shape (see GPT2Config note)
    return False


class _LNParams(nn.Module):
    """LayerNorm parameters only (same names/shapes/init as nn.LayerNorm);
    the computation itself runs inside the fused ln_linear kernel."""

    @nn.compact
    def __call__(self, c: int):
        scale = self.param("scale", nn.initializers.ones, (c,))
        bias = self.param("bias", nn.initializers.zeros, (c,))
        return scale, bias


class _DenseParams(nn.Module):
    """nn.Dense parameters only (same names/shapes/init); the matmul runs
    inside the fused ln_linear kernel."""

    features: int

    @nn.compact
    def __call__(self, c: int):
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (c, self.features))
        bias = self.param("bias", nn.initializers.zeros, (self.features,))
        return kernel, bias


class CausalSelfAttention(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, x, deterministic: bool = True, ln=None):
        cfg = self.config
        B, T, C = x.shape
        H = cfg.n_head
        if ln is not None:
            # fused path: x arrives pre-LN; ln_1's params come from the
            # Block and the LN+qkv matmul run as one Pallas kernel
            from ..ops.transformer.ln_linear import ln_linear

            kernel, bias = _DenseParams(3 * C, name="qkv")(C)
            qkv = ln_linear(x, ln[0], ln[1], kernel, bias,
                            eps=cfg.layer_norm_epsilon)
        else:
            qkv = nn.Dense(3 * C, dtype=cfg.dtype, name="qkv")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        use_flash = cfg.use_flash_attention
        if use_flash == "auto":
            from ..ops.attention.flash_attention import use_flash_by_default

            use_flash = use_flash_by_default(T) and cfg.dropout == 0
        q = q.reshape(B, T, H, C // H)
        k = k.reshape(B, T, H, C // H)
        v = v.reshape(B, T, H, C // H)

        if cfg.sparse_attention is not None:
            if cfg.sequence_parallel:
                raise ValueError("sparse_attention does not compose with "
                                 "sequence_parallel (the layout is over the "
                                 "full sequence)")
            if cfg.dropout > 0 and not deterministic:
                raise ValueError("sparse_attention does not support "
                                 "attention-probability dropout")
            import numpy as np

            from ..ops.sparse_attention.pallas_kernel import (
                block_sparse_flash_attention,
                supports_pallas,
            )

            scfg = cfg.sparse_attention
            layout = np.asarray(scfg.make_layout(T))
            if supports_pallas(scfg.block, T) and \
                    jax.default_backend() == "tpu":
                y = block_sparse_flash_attention(
                    q, k, v, layout, scfg.block, causal=True)
            else:
                # exact gather formulation (CPU tests / fine granules)
                from ..ops.sparse_attention.sparse_self_attention import (
                    block_sparse_attention,
                )

                y = block_sparse_attention(q, k, v, layout, scfg.block,
                                           causal=True)
        elif cfg.sequence_parallel:
            if cfg.sequence_parallel not in ("ring", "ulysses"):
                raise ValueError(
                    f"sequence_parallel must be 'ring' or 'ulysses', "
                    f"got {cfg.sequence_parallel!r}")
            if cfg.dropout > 0:
                raise ValueError(
                    "sequence_parallel does not support attention-probability "
                    "dropout (dropout>0)")
            from ..ops.attention.sequence_parallel import (
                ring_attention,
                ulysses_attention,
            )
            from ..parallel.mesh import get_model_parallel_world_size

            head_axes = MODEL_AXIS if get_model_parallel_world_size() > 1 else None
            if cfg.sequence_parallel == "ring":
                if cfg.use_flash_attention is True:
                    raise ValueError(
                        "sequence_parallel='ring' computes its own blockwise "
                        "softmax; use_flash_attention only composes with "
                        "'ulysses'")
                y = ring_attention(q, k, v, causal=True, head_axes=head_axes)
            else:
                attn_fn = None
                if use_flash:
                    from ..ops.attention.flash_attention import flash_attention

                    def attn_fn(q, k, v, *, causal, scale):
                        return flash_attention(q, k, v, causal=causal, scale=scale)
                y = ulysses_attention(q, k, v, causal=True, head_axes=head_axes,
                                      attn_fn=attn_fn)
        elif use_flash:
            if cfg.dropout > 0 and cfg.use_flash_attention is True:
                raise ValueError(
                    "use_flash_attention does not support attention-probability "
                    "dropout (dropout>0); use the dense path or dropout=0")
            from ..ops.attention.flash_attention import flash_attention

            y = flash_attention(q, k, v, causal=True)
        else:
            scale = 1.0 / jnp.sqrt(C // H).astype(cfg.dtype)
            att = jnp.einsum("bthd,bshd->bhts", q, k) * scale
            mask = jnp.tril(jnp.ones((T, T), dtype=bool))
            att = jnp.where(mask[None, None], att, jnp.finfo(att.dtype).min)
            att = jax.nn.softmax(att.astype(jnp.float32), axis=-1).astype(cfg.dtype)
            if cfg.dropout > 0:
                att = nn.Dropout(cfg.dropout)(att, deterministic=deterministic)
            y = jnp.einsum("bhts,bshd->bthd", att, v)
        y = y.reshape(B, T, C)
        y = nn.Dense(C, dtype=cfg.dtype, name="proj")(y)
        if cfg.dropout > 0:
            y = nn.Dropout(cfg.dropout)(y, deterministic=deterministic)
        return y


class MLP(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, x, deterministic: bool = True, ln=None):
        cfg = self.config
        if ln is not None:
            from ..ops.transformer.ln_linear import ln_linear

            kernel, bias = _DenseParams(4 * cfg.n_embd, name="fc")(cfg.n_embd)
            h = ln_linear(x, ln[0], ln[1], kernel, bias,
                          eps=cfg.layer_norm_epsilon)
        else:
            h = nn.Dense(4 * cfg.n_embd, dtype=cfg.dtype, name="fc")(x)
        h = jax.nn.gelu(h, approximate=True)
        h = nn.Dense(cfg.n_embd, dtype=cfg.dtype, name="proj")(h)
        if cfg.dropout > 0:
            h = nn.Dropout(cfg.dropout)(h, deterministic=deterministic)
        return h


class Block(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        cfg = self.config
        if _use_fused_ln(cfg):
            # same parameter tree as the unfused path (_LNParams/_DenseParams
            # register identical names/shapes/init); LN rides the matmul
            ln1 = _LNParams(name="ln_1")(cfg.n_embd)
            x = x + CausalSelfAttention(cfg, name="attn")(
                x, deterministic, ln=ln1)
            ln2 = _LNParams(name="ln_2")(cfg.n_embd)
            x = x + MLP(cfg, name="mlp")(x, deterministic, ln=ln2)
            return x
        x = x + CausalSelfAttention(cfg, name="attn")(
            nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype,
                         name="ln_1")(x), deterministic)
        x = x + MLP(cfg, name="mlp")(
            nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype,
                         name="ln_2")(x), deterministic)
        return x


class _ScanBody(nn.Module):
    """scan body: (carry, broadcast deterministic) → (carry, None)."""

    config: GPT2Config

    @nn.compact
    def __call__(self, x, deterministic):
        block_cls = Block
        if self.config.remat:
            policy = None
            if self.config.remat_policy == "dots_plain":
                # dots WITHOUT the named attention/ln saves — A/B isolation
                # for the save-vs-recompute tradeoff (saving out/lse costs
                # ~20 MB x n_layer of live memory at the flagship shape)
                policy = jax.checkpoint_policies.\
                    dots_with_no_batch_dims_saveable
            elif self.config.remat_policy == "dots":
                # dots policy + named attention-kernel outputs: saves matmul
                # outputs AND the flash/sparse kernel's (out, lse), so the
                # backward pass reuses the attention forward instead of
                # re-running the kernel (ATTN_SAVE_NAMES tags in
                # ops/attention/flash_attention.py)
                from ..ops.attention.flash_attention import ATTN_SAVE_NAMES
                from ..ops.transformer.ln_linear import LN_SAVE_NAMES

                policy = jax.checkpoint_policies.save_from_both_policies(
                    jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
                    jax.checkpoint_policies.save_only_these_names(
                        *ATTN_SAVE_NAMES, *LN_SAVE_NAMES))
            block_cls = nn.remat(Block, prevent_cse=False,
                                 static_argnums=(2,), policy=policy)
        x = block_cls(self.config, name="block")(x, deterministic)
        return x, None


class GPT2LMHeadModel(nn.Module):
    """Causal LM with tied embedding head.

    ``__call__(batch)`` returns the mean cross-entropy loss — the engine's
    model convention. ``batch`` = {"input_ids": (B,T) int32,
    optional "labels": (B,T), optional "attention_mask": (B,T)}.
    """

    config: GPT2Config

    def setup(self):
        cfg = self.config
        self.wte = nn.Embed(cfg.vocab_size, cfg.n_embd, dtype=cfg.dtype, name="wte")
        self.wpe = nn.Embed(cfg.n_positions, cfg.n_embd, dtype=cfg.dtype, name="wpe")
        self.blocks = nn.scan(
            _ScanBody,
            variable_axes={"params": 0},
            split_rngs={"params": True, "dropout": True},
            length=cfg.n_layer,
            in_axes=nn.broadcast,
            metadata_params={nn.PARTITION_NAME: "layers"},
            unroll=cfg.scan_unroll,
        )(cfg, name="blocks")
        self.ln_f = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype,
                                 name="ln_f")

    def hidden(self, input_ids, deterministic: bool = True):
        """Final hidden states (B, T, C) before the tied-head projection."""
        B, T = input_ids.shape
        pos = jnp.arange(T)[None, :]
        x = self.wte(input_ids) + self.wpe(pos)
        # nn.scan carries (x,) through the stacked blocks
        x, _ = self.blocks(x, deterministic)
        return self.ln_f(x)

    def logits(self, input_ids, deterministic: bool = True):
        x = self.hidden(input_ids, deterministic)
        # tied head: project onto embedding matrix
        return self.wte.attend(x.astype(jnp.float32))

    def __call__(self, batch, deterministic: bool = False):
        cfg = self.config
        input_ids = batch["input_ids"]
        labels = batch.get("labels", input_ids) if hasattr(batch, "get") else input_ids
        targets = labels[:, 1:]
        mask = (targets >= 0).astype(jnp.float32)  # -100/-1 = ignore
        targets = jnp.maximum(targets, 0)
        if cfg.loss_chunk:
            # streaming loss: never materialize the (B, T, V) logits.
            # The projection runs in cfg.dtype, exactly like Embed.attend
            # (which promotes both operands to the module dtype).
            from ..ops.transformer.chunked_xent import chunked_softmax_xent

            x = self.hidden(input_ids, deterministic)[:, :-1]
            nll_sum = chunked_softmax_xent(
                x, self.wte.embedding, targets, mask, cfg.loss_chunk,
                compute_dtype=cfg.dtype)
            return nll_sum / jnp.maximum(mask.sum(), 1.0)
        logits = self.logits(input_ids, deterministic)
        # causal shift: predict token t+1
        logits = logits[:, :-1]
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        return (nll * mask).sum() / jnp.maximum(mask.sum(), 1.0)
