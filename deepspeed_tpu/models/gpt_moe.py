"""Mixture-of-Experts GPT (Megatron-GPT-MoE family).

Covers the reference's MoE model containers
(``module_inject/containers/megatron_gpt_moe.py`` / ``base_moe.py``) and
the DeepSpeed-MoE NLG recipe (alternating dense/MoE transformer blocks,
docs/_posts/2021-12-09-deepspeed-moe-nlg.md): a causal LM whose MLPs are
:class:`deepspeed_tpu.moe.MoE` layers on every ``moe_every``-th block
(PR-MoE-style pyramid via ``num_experts`` per MoE block). Expert
parallelism comes from the global mesh's ``expert`` axis; the engine folds
the gate aux loss via the (loss, aux) tuple convention.

Blocks are a Python loop (not nn.scan) because dense and MoE blocks have
different parameter structures — the stack depth of MoE models is modest
and per-block remat keeps activation memory flat.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Sequence, Union

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..moe import MoE
from .gpt2 import CausalSelfAttention, GPT2Config


@dataclasses.dataclass
class GPTMoEConfig:
    vocab_size: int = 50257
    n_positions: int = 1024
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    moe_every: int = 2                 # every k-th block is MoE (NLG recipe)
    num_experts: Union[int, Sequence[int]] = 8  # int, or per-MoE-block list
    k: int = 1                         # top-k gating
    capacity_factor: float = 1.25
    drop_tokens: bool = True
    # random token selection when dropping at capacity (the reference's
    # use_rts, sharded_moe.py: breaks position bias; draws the "gating"
    # rng in train mode). False = deterministic position-order dropping
    use_rts: bool = True
    # "auto" (einsum for k=1, index for k>=2 — the measured per-k policy),
    # "index" (scatter/gather), or "einsum" (the reference's dense one-hot
    # dispatch) — see moe/layer.py and BASELINE.md round-5 MoE rows
    moe_dispatch_mode: str = "auto"
    # PR-MoE residual blend (arXiv:2201.05596): dense expert + learned
    # per-token coefficient alongside each MoE block
    use_residual: bool = False
    aux_loss_weight: float = 0.01
    dropout: float = 0.0
    layer_norm_epsilon: float = 1e-5
    dtype: Any = jnp.float32
    remat: bool = False


def _attention_config(cfg: "GPTMoEConfig") -> GPT2Config:
    """Reuse the GPT-2 attention (flash / sequence-parallel paths and
    dropout wiring included) instead of duplicating it."""
    return GPT2Config(vocab_size=cfg.vocab_size, n_positions=cfg.n_positions,
                      n_embd=cfg.n_embd, n_layer=cfg.n_layer,
                      n_head=cfg.n_head, dropout=cfg.dropout,
                      layer_norm_epsilon=cfg.layer_norm_epsilon,
                      dtype=cfg.dtype)


class _Block(nn.Module):
    config: GPTMoEConfig
    use_moe: bool
    num_experts: int

    @nn.compact
    def __call__(self, x, deterministic: bool):
        cfg = self.config
        ln1 = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype,
                           name="ln_1")
        ln2 = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype,
                           name="ln_2")
        x = x + CausalSelfAttention(_attention_config(cfg), name="attn")(
            ln1(x), deterministic)
        aux = jnp.asarray(0.0, jnp.float32)
        if self.use_moe:
            moe_out, aux, _ = MoE(
                hidden_size=cfg.n_embd, num_experts=self.num_experts,
                k=cfg.k, capacity_factor=cfg.capacity_factor,
                drop_tokens=cfg.drop_tokens, use_rts=cfg.use_rts,
                dispatch_mode=cfg.moe_dispatch_mode,
                use_residual=cfg.use_residual, dtype=cfg.dtype,
                name="moe")(
                    ln2(x), deterministic=deterministic)
            x = x + moe_out
        else:
            h = nn.Dense(4 * cfg.n_embd, dtype=cfg.dtype, name="mlp_fc")(
                ln2(x))
            h = jax.nn.gelu(h, approximate=True)
            h = nn.Dense(cfg.n_embd, dtype=cfg.dtype, name="mlp_proj")(h)
            if cfg.dropout > 0:
                h = nn.Dropout(cfg.dropout)(h, deterministic=deterministic)
            x = x + h
        return x, aux


class GPTMoEModel(nn.Module):
    """Causal LM with alternating dense/MoE blocks —
    ``__call__(batch) -> (loss, aux_loss)`` (engine convention)."""

    config: GPTMoEConfig

    def _experts_for_block(self, moe_index: int) -> int:
        ne = self.config.num_experts
        if isinstance(ne, int):
            return ne
        return int(ne[min(moe_index, len(ne) - 1)])

    @nn.compact
    def __call__(self, batch, deterministic: bool = False):
        cfg = self.config
        ids = batch["input_ids"]
        B, T = ids.shape
        wte = nn.Embed(cfg.vocab_size, cfg.n_embd, dtype=cfg.dtype,
                       name="wte")
        x = wte(ids)
        pos = jnp.broadcast_to(jnp.arange(T)[None], (B, T))
        x = x + nn.Embed(cfg.n_positions, cfg.n_embd, dtype=cfg.dtype,
                         name="wpe")(pos)

        aux_total = jnp.asarray(0.0, jnp.float32)
        moe_index = 0
        block_cls = _Block
        if cfg.remat:
            block_cls = nn.remat(_Block, prevent_cse=False,
                                 static_argnums=(2,))
        for i in range(cfg.n_layer):
            use_moe = cfg.moe_every > 0 and (i % cfg.moe_every ==
                                             cfg.moe_every - 1)
            n_exp = self._experts_for_block(moe_index) if use_moe else 0
            x, aux = block_cls(cfg, use_moe, n_exp,
                               name=f"block_{i}")(x, deterministic)
            if use_moe:
                aux_total = aux_total + aux
                moe_index += 1

        x = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype,
                         name="ln_f")(x)
        logits = wte.attend(x.astype(jnp.float32))

        # same shifted-target convention as GPT2LMHeadModel (gpt2.py:246):
        # honor batch["labels"] when present
        labels = batch.get("labels", ids) if hasattr(batch, "get") else ids
        targets = labels[:, 1:]
        logp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
        token_ll = jnp.take_along_axis(logp, targets[..., None],
                                       axis=-1)[..., 0]
        loss = -jnp.mean(token_ll)
        return loss, cfg.aux_loss_weight * aux_total
