"""Unified decoder-only transformer covering the reference's model families.

The reference ships 17 per-model injection "policies/containers"
(``deepspeed/module_inject/containers/``: gpt2, gptj, gptneo(x), llama, opt,
bloom, megatron, bert, ...) plus fused inference modules
(``model_implementations/transformers/ds_transformer.py:19`` and the
``ds_bloom/ds_gpt/ds_opt/ds_megatron_gpt`` variants). TPU-native, those
collapse into ONE parameterized flax module: every family is a point in a
small feature space (position encoding × norm × activation × residual
topology × GQA), and XLA fuses what the reference hand-fused in CUDA.

Families are presets of :class:`TransformerConfig` (see ``FAMILY_PRESETS``):

=============  ========  =========  ========  ===================
family         pos_emb   norm       act       notes
=============  ========  =========  ========  ===================
gpt2           learned   layernorm  gelu      tied head, qkv bias
gpt-neo        learned   layernorm  gelu      local attn ignored
gptj           rotary    layernorm  gelu      parallel residual
gpt-neox       rotary    layernorm  gelu      parallel residual, rotary_pct
llama          rotary    rmsnorm    swiglu    no biases, untied head, GQA
opt            learned   layernorm  relu      tied head
bloom          alibi     layernorm  gelu      embedding layernorm
megatron-gpt   learned   layernorm  gelu
=============  ========  =========  ========  ===================

KV-cache decoding uses the flax ``cache`` variable collection: ``prefill``
writes the prompt's K/V at positions [0, T), ``decode`` appends one position
via ``lax.dynamic_update_slice`` and attends over the static-shape cache with
a validity mask — static shapes keep XLA happy (the reference's
inference_context.h workspace is the moral equivalent).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Optional, Tuple, Union

import flax.linen as nn
import jax
import jax.numpy as jnp



@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 50257
    max_seq_len: int = 2048
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    n_kv_head: Optional[int] = None     # < n_head ⇒ grouped-query attention
    pos_emb: str = "learned"            # learned | rotary | alibi | none
    rotary_pct: float = 1.0             # fraction of head_dim rotated (neox)
    rope_theta: float = 10000.0
    norm: str = "layernorm"             # layernorm | rmsnorm
    activation: str = "gelu"            # gelu | relu | swiglu
    mlp_ratio: float = 4.0
    parallel_residual: bool = False     # gptj/neox: x + attn(ln1 x) + mlp(ln2 x)
    qkv_bias: bool = True
    mlp_bias: bool = True
    embed_layernorm: bool = False       # bloom
    tie_word_embeddings: bool = True
    layer_norm_epsilon: float = 1e-5
    dropout: float = 0.0
    dtype: Any = jnp.bfloat16
    use_flash_attention: Any = "auto"   # True | False | "auto" (Pallas flash
    # for the full-context forward on TPU from the tuned crossover length;
    # alibi and train-mode attention dropout stay on the einsum path)
    remat: bool = False
    decode_kernel: str = "auto"         # auto | on | off (fused Pallas decode)
    decode_block: Optional[int] = None  # pin the fused decode kernel's block
    # granule (STATIC int). The paged-attention kernel's position block is
    # one page, so a dense arm pinned to decode_block=page_size runs the
    # SAME online-softmax blocking — the bitwise-parity oracle for the
    # paged kernel (ops/attention/paged_attention.py). None keeps the
    # allocation-based default (pick_block_s).
    kv_cache_quant: bool = False        # int8 KV cache (per-row scales):
    # halves the cache's HBM traffic — the resource decode is bound by —
    # and halves KV memory, doubling the servable context per chip
    kv_cache_packed: Optional[bool] = None  # store the int8 cache in an
    # int32 container (pack_int8_sublanes: 4 head-dim rows per word, the
    # TPU's own sublane byte order, so the kernel unpacks with a free
    # pltpu.bitcast). Same bytes in a natively-tiled dtype — insurance
    # against Mosaic's (4,1)-packed s8 layout-conversion copies (the
    # round-4/5 capacity killer; the positions-minor layout + carry-DUS
    # scan fixed the measured cases, and packed/plain now measure equal —
    # BASELINE.md round-5 capacity ladder). Only meaningful with
    # kv_cache_quant; requires head_dim % 4 == 0. Tri-state: None (auto,
    # the default) packs when head_dim allows and warns once when it
    # can't; True requires a packable head_dim (raises otherwise);
    # False keeps the plain int8 container.
    int8_weights: bool = False          # serve with int8-at-rest Dense kernels
    int8_kernel: str = "auto"           # auto | on | off (Pallas dequant-GEMM)
    int8_head: bool = False             # quantize lm_head too (off: the vocab
    # projection — the largest single accuracy lever — stays full precision,
    # matching the ZeRO-Inference streamed tier and reference practice)
    loss_chunk: int = 0                 # streaming cross-entropy: >0 computes
    # the LM loss in T-chunks of this size without materializing the
    # (B, T, V) logits (ops/transformer/chunked_xent.py); 0 = dense loss

    @property
    def head_dim(self) -> int:
        return self.n_embd // self.n_head

    @property
    def kv_heads(self) -> int:
        return self.n_kv_head or self.n_head


FAMILY_PRESETS = {
    "gpt2": dict(pos_emb="learned", norm="layernorm", activation="gelu"),
    "gpt-neo": dict(pos_emb="learned", norm="layernorm", activation="gelu"),
    "gptj": dict(pos_emb="rotary", norm="layernorm", activation="gelu",
                 parallel_residual=True, tie_word_embeddings=False),
    "gpt-neox": dict(pos_emb="rotary", rotary_pct=0.25, norm="layernorm",
                     activation="gelu", parallel_residual=True,
                     tie_word_embeddings=False),
    "llama": dict(pos_emb="rotary", norm="rmsnorm", activation="swiglu",
                  qkv_bias=False, mlp_bias=False, tie_word_embeddings=False,
                  layer_norm_epsilon=1e-6),
    "opt": dict(pos_emb="learned", norm="layernorm", activation="relu"),
    "bloom": dict(pos_emb="alibi", norm="layernorm", activation="gelu",
                  embed_layernorm=True),
    "megatron-gpt": dict(pos_emb="learned", norm="layernorm", activation="gelu"),
}


def transformer_config(family: str, **overrides) -> TransformerConfig:
    """Build a config from a family preset (≅ picking an injection policy,
    reference module_inject/replace_policy.py)."""
    if family not in FAMILY_PRESETS:
        raise ValueError(f"unknown family {family!r}; know {sorted(FAMILY_PRESETS)}")
    return TransformerConfig(**{**FAMILY_PRESETS[family], **overrides})


def transformer_logical_axes():
    """LOGICAL axis annotations for this module's parameter paths (≅ t5x
    ``param_with_axes`` metadata, expressed as path patterns so the flax
    modules stay annotation-free). Works for every family preset (paths
    are family-invariant). Scanned blocks carry a leading ``layers`` dim;
    ``heads`` is the fused heads*head_dim projection width and ``ffn``
    the MLP hidden width."""
    return [
        (r"embed_tokens/embedding", ("vocab", "embed")),
        (r"embed_pos/embedding", ("positions", "embed")),
        (r"attn/(q_proj|k_proj|v_proj)/kernel", ("layers", "embed", "heads")),
        (r"attn/o_proj/kernel", ("layers", "heads", "embed")),
        (r"attn/(q_proj|k_proj|v_proj)/bias", ("layers", "heads")),
        (r"mlp/(up_proj|gate_proj)/kernel", ("layers", "embed", "ffn")),
        (r"mlp/(up_proj|gate_proj)/bias", ("layers", "ffn")),
        (r"mlp/down_proj/kernel", ("layers", "ffn", "embed")),
        (r"lm_head/kernel", ("embed", "vocab")),
    ]


def transformer_sharding_rules(rules=None):
    """Megatron-style TP rules for this module's parameter paths — the
    AutoTP analog (reference module_inject/auto_tp.py:13): column-parallel
    up-projections, row-parallel down-projections, vocab-parallel
    embedding. Derived by resolving :func:`transformer_logical_axes`
    through the ``parallel/`` axis-rules table (``rules`` overrides the
    default) so one table swap re-partitions the module; the default
    table reproduces the historical hard-coded placement exactly
    (pinned by tests/unit/parallel/test_axis_rules.py)."""
    from ..parallel.axis_rules import default_axis_rules

    rules = rules if rules is not None else default_axis_rules()
    return [(pat, rules.spec_entries(axes))
            for pat, axes in transformer_logical_axes()]


def _dense(cfg: TransformerConfig, features: int, *, use_bias: bool,
           name: str, dtype=None):
    """nn.Dense, or its int8-at-rest serving twin when ``cfg.int8_weights``
    — params become int8 kernel + f32 per-channel scale consumed by the
    Pallas dequant-GEMM (ops/quantization); the inference engine's
    quantization tier builds that tree from a bf16 checkpoint."""
    if cfg.int8_weights:
        from ..ops.quantization import QuantDense

        return QuantDense(features, use_bias=use_bias, dtype=dtype or cfg.dtype,
                          kernel_mode=cfg.int8_kernel, name=name)
    return nn.Dense(features, use_bias=use_bias, dtype=dtype or cfg.dtype,
                    name=name)


def _norm(cfg: TransformerConfig, name: str):
    if cfg.norm == "rmsnorm":
        return nn.RMSNorm(epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype, name=name)
    return nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype, name=name)


def _rotate_half(x):
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([-x2, x1], axis=-1)


def apply_rotary(x, positions, *, rotary_dim: int, theta: float):
    """NeoX-style rotary embedding on the first ``rotary_dim`` channels.
    x: (B, T, H, D); positions: (B, T) absolute token positions."""
    rot, rest = x[..., :rotary_dim], x[..., rotary_dim:]
    inv_freq = 1.0 / (theta ** (jnp.arange(0, rotary_dim, 2, dtype=jnp.float32)
                                / rotary_dim))
    angles = positions[..., None].astype(jnp.float32) * inv_freq  # (B,T,rd/2)
    angles = jnp.concatenate([angles, angles], axis=-1)[:, :, None, :]  # (B,T,1,rd)
    cos, sin = jnp.cos(angles), jnp.sin(angles)
    rot32 = rot.astype(jnp.float32)
    out = rot32 * cos + _rotate_half(rot32) * sin
    return jnp.concatenate([out.astype(x.dtype), rest], axis=-1)


def alibi_slopes(n_head: int) -> jnp.ndarray:
    """Per-head ALiBi slopes (Press et al.), matching the reference's alibi
    computation used for bloom (csrc attention alibi path)."""
    def pow2_slopes(n):
        start = 2.0 ** (-(2.0 ** -(math.log2(n) - 3)))
        return [start * (start ** i) for i in range(n)]

    if math.log2(n_head).is_integer():
        return jnp.asarray(pow2_slopes(n_head), jnp.float32)
    closest = 2 ** math.floor(math.log2(n_head))
    base = pow2_slopes(closest)
    extra = pow2_slopes(2 * closest)[0::2][: n_head - closest]
    return jnp.asarray(base + extra, jnp.float32)


class CachedAttention(nn.Module):
    """Multi-head / grouped-query attention with optional KV cache.

    Modes (``decode`` is a static tri-state):
      - ``False`` — training / no-cache forward: full causal
        self-attention.
      - ``"prefill"`` — writes the prompt's k/v into the ``cache``
        collection (k, v, cache_index) and attends over the FRESH
        prompt k/v (start == 0 contract): O(T) attention memory, never
        the (B, H, T, max_seq_len) allocated-cache tensor. Use for the
        first multi-token call.
      - ``True`` — reads+updates the cache; 1-token decode takes the
        fused Pallas kernel, multi-token (chunked decode at unknown
        start) takes the window-masked einsum over the cache.
    """

    config: TransformerConfig

    def _use_flash(self, seq_len: int, deterministic: bool) -> bool:
        """Route the full-context (non-decode) forward through the Pallas
        flash kernel. ``auto``: on TPU from the tuned crossover length;
        ``True`` forces it (interpret mode off-TPU — for tests). ALiBi has
        no flash bias hook and attention-probability dropout has no kernel
        equivalent — those stay on the einsum path (forcing raises)."""
        cfg = self.config
        use = cfg.use_flash_attention
        if use is False or use == "off":
            return False
        alibi_ok = cfg.pos_emb != "alibi"
        drop_ok = cfg.dropout == 0 or deterministic
        if use == "auto":
            from ..ops.attention.flash_attention import use_flash_by_default

            return use_flash_by_default(seq_len) and alibi_ok and drop_ok
        if not alibi_ok:
            raise ValueError("use_flash_attention=True does not compose with "
                             "pos_emb='alibi' (no bias hook in the kernel)")
        if not drop_ok:
            raise ValueError("use_flash_attention=True does not support "
                             "attention-probability dropout in train mode")
        return True

    def _use_decode_kernel(self, cache_len: int,
                           deterministic: bool = True) -> bool:
        """Route 1-token decode through the fused Pallas kernel. ``auto``:
        on TPU with a kernel-compatible cache length; ``on`` forces it
        (interpret mode off-TPU — for tests); ``off`` keeps the jnp path.
        Attention-probability dropout (train-mode decode) has no kernel
        equivalent — that combination stays on the jnp path."""
        from ..ops.attention.decode_attention import pick_block_s

        cfg = self.config
        if cfg.decode_kernel == "off":
            return False
        if cfg.dropout > 0 and not deterministic:
            return False
        if pick_block_s(cache_len) < 8:
            return False
        if cfg.decode_kernel == "on":
            return True
        return jax.default_backend() == "tpu"

    def _paged_decode_step(self, q, k, v, kv_cache, positions,
                           deterministic):
        """Decode/verify step over PAGED storage: write this step's K/V
        columns straight into the page pool through the table (sentinel
        entries drop — the ``_scatter_cols`` discipline, applied at the
        source) and attend via the fused paged kernel. The value bytes
        written and the attention math match the dense path exactly
        (same quantize/pack pipeline, kernel compute copied op-for-op
        from the dense decode kernel), which is what keeps paged-kernel
        greedy output bitwise-identical to the dense oracle."""
        cfg = self.config
        B, T, H, D = q.shape
        kv_packed = kv_cache_spec(cfg)[2]
        from ..ops.attention.paged_attention import (
            MAX_QUERY_ROWS,
            paged_decode_attention,
        )

        assert T <= MAX_QUERY_ROWS, \
            (f"paged-kernel decode handles T <= {MAX_QUERY_ROWS} query "
             f"rows (plain decode and speculative verify); T={T} callers "
             f"take the dense-composition path")
        start = kv_cache["start"]
        assert jnp.ndim(start) == 1, \
            "paged decode is slot-pooled: start must be (B,)"
        table = kv_cache["table"]                  # (B, pages_per_slot)
        P = kv_cache["k"].shape[0]
        ps = kv_cache["k"].shape[-1]
        maxP = table.shape[1]
        new_cache = {key: val for key, val in kv_cache.items()
                     if key not in ("start", "table")}

        # column writes through the table (mode="drop" for sentinels)
        pos_w = positions.astype(jnp.int32)               # (B, T) absolute
        pidx = pos_w // ps
        valid = (pos_w >= 0) & (pos_w < maxP * ps)
        pages = jnp.take_along_axis(table, jnp.clip(pidx, 0, maxP - 1),
                                    axis=1)
        pages = jnp.where(valid, pages, P)
        offs = pos_w % ps
        k_rows = k.astype(cfg.dtype).transpose(0, 2, 1, 3)  # (B, KV, T, D)
        v_rows = v.astype(cfg.dtype).transpose(0, 2, 1, 3)
        scales = {}
        if cfg.kv_cache_quant:
            from ..ops.attention.decode_attention import (
                pack_int8_sublanes,
                quantize_kv_rows,
            )

            k_rows, k_sc = quantize_kv_rows(k_rows)       # scales (B,KV,T)
            v_rows, v_sc = quantize_kv_rows(v_rows)
            for key, sc in (("k_scale", k_sc), ("v_scale", v_sc)):
                buf = kv_cache[key]                       # (P, KV, ps)
                new_cache[key] = buf.at[pages, :, offs].set(
                    sc.transpose(0, 2, 1).astype(buf.dtype), mode="drop")
            scales = dict(k_scale_pages=new_cache["k_scale"],
                          v_scale_pages=new_cache["v_scale"])
        k_cols = k_rows.transpose(0, 1, 3, 2)             # (B, KV, D, T)
        v_cols = v_rows.transpose(0, 1, 3, 2)
        if kv_packed:
            from ..ops.attention.decode_attention import pack_int8_sublanes

            k_cols = pack_int8_sublanes(k_cols)           # (B, KV, D//4, T)
            v_cols = pack_int8_sublanes(v_cols)
        for key, cols in (("k", k_cols), ("v", v_cols)):
            buf = kv_cache[key]                           # (P, KV, cd, ps)
            vals = cols.transpose(0, 3, 1, 2)             # (B, T, KV, cd)
            new_cache[key] = buf.at[pages, :, :, offs].set(
                vals.astype(buf.dtype), mode="drop")

        slopes = alibi_slopes(H) if cfg.pos_emb == "alibi" else None
        y = paged_decode_attention(
            q.astype(cfg.dtype), new_cache["k"], new_cache["v"], table,
            start, alibi_slopes=slopes, **scales)
        y = y.astype(cfg.dtype).reshape(B, T, H * D)
        o_proj = _dense(cfg, self.config.n_embd, use_bias=cfg.qkv_bias,
                        name="o_proj")
        return o_proj(y), new_cache

    @nn.compact
    def __call__(self, x, *, decode: Union[bool, str] = False,
                 deterministic: bool = True, kv_cache=None,
                 block_hint=None):
        cfg = self.config
        B, T, C = x.shape
        H, KV, D = cfg.n_head, cfg.kv_heads, cfg.head_dim
        dense = lambda feats, name: _dense(  # noqa: E731
            cfg, feats, use_bias=cfg.qkv_bias, name=name)
        q = dense(H * D, "q_proj")(x).reshape(B, T, H, D)
        k = dense(KV * D, "k_proj")(x).reshape(B, T, KV, D)
        v = dense(KV * D, "v_proj")(x).reshape(B, T, KV, D)

        kv_packed = kv_cache_spec(cfg)[2]
        if decode:
            # This layer's KV-cache slice arrives as an ARGUMENT (dict
            # with k/v [+ scales] and the shared ``start``) and the
            # updated slice is RETURNED — the stacked cache rides the
            # layer scan's carry with per-layer dynamic-update-slices,
            # the one pattern XLA reliably keeps in place at any size.
            # (The previous design — per-layer flax cache variables,
            # nn.scan variable_axes — lowers to a scan whose xs/ys pair
            # double-buffers the quantized cache above ~100 MB:
            # BASELINE.md round-5 capacity section.)
            assert kv_cache is not None, "decode needs the kv_cache slice"
            # ``start`` is scalar () for batch-uniform decode (generate),
            # or (B,) for slot-pooled decode where every sequence sits at
            # its own cache offset (serving/ continuous batching)
            start = kv_cache["start"]
            per_slot = jnp.ndim(start) == 1
            positions = (start[:, None] if per_slot else start) \
                + jnp.arange(T)[None, :]
        else:
            start = jnp.zeros((), jnp.int32)
            positions = jnp.broadcast_to(jnp.arange(T)[None, :], (B, T))

        if cfg.pos_emb == "rotary":
            rd = int(cfg.rotary_pct * D) // 2 * 2
            q = apply_rotary(q, positions, rotary_dim=rd, theta=cfg.rope_theta)
            k = apply_rotary(k, positions, rotary_dim=rd, theta=cfg.rope_theta)

        if decode and kv_cache is not None and "table" in kv_cache:
            # Paged decode: this layer's K/V live in the PAGE POOL
            # ((P, KV, cache_d, page_size), no batch axis) and both the
            # column writes and the attention read resolve positions
            # through the per-slot page table — no dense per-slot view is
            # ever materialized (the gather→attend→scatter round-trip the
            # fused kernel eliminates; ops/attention/paged_attention.py).
            return self._paged_decode_step(q, k, v, kv_cache, positions,
                                           deterministic)

        kv_scales = None  # set on the quantized-cache einsum fallback
        # "fresh" attention = causal over the just-computed k/v. True for
        # the training forward AND for prefill (start == 0 contract): the
        # prompt's causal window IS the fresh k/v, so prefill must NOT
        # attend over the allocated cache — the (B, H, T, S) score tensor
        # that implies OOM-crashed the worker at T=4096 / S=8192.
        fresh = (not decode) or (decode == "prefill" and T > 1)
        new_cache = None
        o_proj = _dense(cfg, C, use_bias=cfg.qkv_bias, name="o_proj")
        if decode:
            k_rows = k.astype(cfg.dtype).transpose(0, 2, 1, 3)  # (B,KV,T,D)
            v_rows = v.astype(cfg.dtype).transpose(0, 2, 1, 3)
            new_cache = dict(kv_cache)

            def store(buf, new):
                """Write the new positions-minor columns at each row's
                offset: one DUS for scalar start; per-slot (B,) starts
                vmap the DUS over the batch (lowers to a scatter — each
                slot writes at its own cache offset)."""
                if per_slot:
                    return jax.vmap(
                        lambda c, n, s: jax.lax.dynamic_update_slice(
                            c, n, (0,) * (c.ndim - 1) + (s,)))(buf, new,
                                                               start)
                return jax.lax.dynamic_update_slice(
                    buf, new, (0,) * (buf.ndim - 1) + (start,))

            if cfg.kv_cache_quant:
                from ..ops.attention.decode_attention import (
                    pack_int8_sublanes,
                    quantize_kv_rows,
                )

                k_rows, k_sc = quantize_kv_rows(k_rows)
                v_rows, v_sc = quantize_kv_rows(v_rows)
                new_cache["k_scale"] = store(kv_cache["k_scale"], k_sc)
                new_cache["v_scale"] = store(kv_cache["v_scale"], v_sc)
            # positions-minor store: new rows become (B, KV, D, T) columns
            k_cols = k_rows.transpose(0, 1, 3, 2)
            v_cols = v_rows.transpose(0, 1, 3, 2)
            if kv_packed:
                k_cols = pack_int8_sublanes(k_cols)  # (B, KV, D//4, T)
                v_cols = pack_int8_sublanes(v_cols)
            new_cache["k"] = store(kv_cache["k"], k_cols)
            new_cache["v"] = store(kv_cache["v"], v_cols)
            if T == 1 and self._use_decode_kernel(cfg.max_seq_len,
                                                  deterministic):
                # fused Pallas decode attention (reference softmax_context,
                # pt_binding.cpp:1910-1975): length masking + softmax +
                # value reduction in one pass over the cache; int8 caches
                # pass their per-row scales straight through
                from ..ops.attention.decode_attention import (
                    decode_attention,
                    pick_block_s,
                )

                slopes = alibi_slopes(H) if cfg.pos_emb == "alibi" else None
                scales = dict(k_scale=new_cache["k_scale"],
                              v_scale=new_cache["v_scale"]) \
                    if cfg.kv_cache_quant else {}
                # block_hint (static, from the caller's known generation
                # budget) shrinks the block granule to the LIVE length
                # instead of the allocated capacity — cache reads are
                # block-granular, so this is pure saved bandwidth
                y = decode_attention(
                    q[:, 0].astype(cfg.dtype), new_cache["k"],
                    new_cache["v"], start + 1, alibi_slopes=slopes,
                    block_s=pick_block_s(
                        cfg.max_seq_len,
                        preferred=(block_hint if block_hint is not None
                                   else cfg.decode_block)), **scales)
                y = y.astype(cfg.dtype).reshape(B, 1, H * D)
                return o_proj(y), new_cache
            if not fresh:
                # chunked decode (decode=True, T > 1, start unknown):
                # attend over the allocated cache with a window mask
                k_all, v_all = new_cache["k"], new_cache["v"]
                S = cfg.max_seq_len
                if kv_packed:
                    from ..ops.attention.decode_attention import \
                        unpack_int8_sublanes

                    k_all = unpack_int8_sublanes(k_all)
                    v_all = unpack_int8_sublanes(v_all)
                # the shared einsum below expects (B, KV, S, D)
                k_all = k_all.transpose(0, 1, 3, 2)
                v_all = v_all.transpose(0, 1, 3, 2)
                if cfg.kv_cache_quant:
                    # do NOT dequantize the cache (a full-size bf16 copy —
                    # multiple GB at long S); fold the per-row scales into
                    # the score and probability tensors, as the kernel does
                    kv_scales = (new_cache["k_scale"], new_cache["v_scale"])
                # row t may see cache slots [0, start+t]; per-slot starts
                # make the mask batch-dependent: (B, T, S) instead of (T, S)
                if per_slot:
                    mask = (jnp.arange(S)[None, None, :]
                            <= (start[:, None]
                                + jnp.arange(T)[None, :])[:, :, None])
                else:
                    mask = (jnp.arange(S)[None, :]
                            <= (start + jnp.arange(T))[:, None])
        if fresh:
            if self._use_flash(T, deterministic):
                # fused Pallas flash attention for the full-context forward
                # (and, via its custom_vjp, the streamed/resident backward) —
                # O(T) memory instead of the (B, H, T, T) logits tensor
                from ..ops.attention.flash_attention import flash_attention

                k_f, v_f = k, v
                if KV != H:
                    k_f = jnp.repeat(k, H // KV, axis=2)
                    v_f = jnp.repeat(v, H // KV, axis=2)
                y = flash_attention(q.astype(cfg.dtype),
                                    k_f.astype(cfg.dtype),
                                    v_f.astype(cfg.dtype), causal=True)
                y = y.astype(cfg.dtype).reshape(B, T, H * D)
                return o_proj(y), new_cache
            k_all = k.transpose(0, 2, 1, 3)  # (B, KV, T, D)
            v_all = v.transpose(0, 2, 1, 3)
            S = T
            mask = jnp.tril(jnp.ones((T, T), dtype=bool))

        if KV != H:
            rep = H // KV
            k_all = jnp.repeat(k_all, rep, axis=1)
            v_all = jnp.repeat(v_all, rep, axis=1)
            if kv_scales is not None:
                kv_scales = tuple(jnp.repeat(s, rep, axis=1)
                                  for s in kv_scales)

        scale = 1.0 / math.sqrt(D)
        # int8 cache: the s8->f32 cast does NOT fuse into the dot on TPU
        # (measured: full fp32 cache copies, BASELINE.md round-5 KV
        # section), so the quantized path casts to the compute dtype
        # instead — int8 is exact in bf16, the copy is half the bytes,
        # and the dot still accumulates in f32. The per-row scales apply
        # to the (B,H,T,S) score/probability tensors.
        if kv_scales is not None:
            att = jnp.einsum("bthd,bhsd->bhts", q.astype(cfg.dtype),
                             k_all.astype(cfg.dtype),
                             preferred_element_type=jnp.float32) * scale
            att = att * kv_scales[0][:, :, None, :]
        else:
            att = jnp.einsum("bthd,bhsd->bhts", q.astype(jnp.float32),
                             k_all.astype(jnp.float32)) * scale
        if cfg.pos_emb == "alibi":
            slopes = alibi_slopes(H)  # (H,)
            if decode and jnp.ndim(start) == 1:
                # per-slot decode: relative key offsets differ per batch row
                rel = (jnp.arange(S)[None, None, :]
                       - (start[:, None] + jnp.arange(T)[None, :])[:, :, None])
                att = att + slopes[None, :, None, None] * rel[:, None]
            else:
                kpos = jnp.arange(S)[None, :]
                qpos = (start + jnp.arange(T))[:, None]
                att = att + slopes[None, :, None, None] \
                    * (kpos - qpos)[None, None]
        att = jnp.where(mask[None, None] if mask.ndim == 2 else mask[:, None],
                        att, -1e30)
        att = jax.nn.softmax(att, axis=-1)
        if cfg.dropout > 0:
            att = nn.Dropout(cfg.dropout)(att, deterministic=deterministic)
        if kv_scales is not None:
            att = att * kv_scales[1][:, :, None, :]
            y = jnp.einsum("bhts,bhsd->bthd", att.astype(cfg.dtype),
                           v_all.astype(cfg.dtype),
                           preferred_element_type=jnp.float32)
        else:
            y = jnp.einsum("bhts,bhsd->bthd", att,
                           v_all.astype(jnp.float32))
        y = y.astype(cfg.dtype)
        y = y.reshape(B, T, H * D)
        return o_proj(y), new_cache


class TransformerMLP(nn.Module):
    config: TransformerConfig

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        cfg = self.config
        hidden = int(cfg.mlp_ratio * cfg.n_embd)
        if cfg.activation == "swiglu":
            # llama sizing: 2/3 * 4d rounded — callers control via mlp_ratio
            gate = _dense(cfg, hidden, use_bias=cfg.mlp_bias, name="gate_proj")(x)
            up = _dense(cfg, hidden, use_bias=cfg.mlp_bias, name="up_proj")(x)
            h = jax.nn.silu(gate) * up
        else:
            h = _dense(cfg, hidden, use_bias=cfg.mlp_bias, name="up_proj")(x)
            h = jax.nn.gelu(h, approximate=True) if cfg.activation == "gelu" \
                else jax.nn.relu(h)
        h = _dense(cfg, cfg.n_embd, use_bias=cfg.mlp_bias, name="down_proj")(h)
        if cfg.dropout > 0:
            h = nn.Dropout(cfg.dropout)(h, deterministic=deterministic)
        return h


class TransformerBlock(nn.Module):
    config: TransformerConfig

    @nn.compact
    def __call__(self, x, decode: Union[bool, str] = False,
                 deterministic: bool = True, kv_cache=None,
                 block_hint=None):
        cfg = self.config
        a, new_cache = CachedAttention(cfg, name="attn")(
            _norm(cfg, "ln_1")(x), decode=decode, deterministic=deterministic,
            kv_cache=kv_cache, block_hint=block_hint)
        if cfg.parallel_residual:
            m = TransformerMLP(cfg, name="mlp")(_norm(cfg, "ln_2")(x), deterministic)
            return x + a + m, new_cache
        x = x + a
        m = TransformerMLP(cfg, name="mlp")(_norm(cfg, "ln_2")(x), deterministic)
        return x + m, new_cache


class _ScanBlock(nn.Module):
    """One scanned layer. The carry is ``(x, cache, start, layer_idx)``:
    the STACKED (L-leading) KV cache rides the carry and each layer
    dynamic-slices its own entry and dynamic-update-slices it back — the
    carry-DUS pattern XLA keeps in place at any size, unlike scanned
    cache VARIABLES whose xs/ys accumulator pair double-buffers the
    quantized cache above ~100 MB (BASELINE.md round-5 capacity
    section)."""

    config: TransformerConfig

    @nn.compact
    def __call__(self, carry, decode, deterministic, block_hint):
        x, cache, start, li = carry
        cls = TransformerBlock
        if self.config.remat:
            cls = nn.remat(cls, prevent_cse=False,
                           static_argnums=(2, 3, 5))
        block = cls(self.config, name="block")
        if cache is None:
            x, _ = block(x, decode, deterministic, None, block_hint)
            return (x, None, start, li), None
        # "table" is the POOL-WIDE page table (slots, pages_per_slot) —
        # shared by every layer, so it rides the slice whole and is never
        # written back (the paged branch returns k/v pages only)
        kv_slice = {key: jax.lax.dynamic_index_in_dim(val, li, 0,
                                                      keepdims=False)
                    for key, val in cache.items() if key != "table"}
        kv_slice["start"] = start
        if "table" in cache:
            kv_slice["table"] = cache["table"]
        x, new_slice = block(x, decode, deterministic, kv_slice, block_hint)
        cache = {key: (val if key == "table"
                       else jax.lax.dynamic_update_slice_in_dim(
                           val, new_slice[key][None], li, 0))
                 for key, val in cache.items()}
        return (x, cache, start, li + 1), None


_PACK_DISABLED_WARNED: set = set()


def kv_cache_spec(cfg: TransformerConfig):
    """The single source of truth for the KV-cache container: returns
    ``(cache_dtype, cache_d, kv_packed)`` — the per-layer k/v arrays are
    (B, KV, cache_d, max_seq_len). Used by CachedAttention (reads/
    writes), _CacheStore (allocation) and make_layer_kv_cache
    (ZeRO-Inference allocation) so the layout can never drift apart."""
    D = cfg.head_dim
    if cfg.kv_cache_quant and cfg.kv_cache_packed is not False and D % 4 != 0:
        if cfg.kv_cache_packed is True:
            raise ValueError(
                f"kv_cache_packed=True requires head_dim % 4 == 0 (the int32 "
                f"container packs 4 head-dim rows per word); head_dim={D}. "
                f"Use kv_cache_packed=None (auto) or False, or pad n_embd.")
        if D not in _PACK_DISABLED_WARNED:  # auto: warn once per head_dim
            _PACK_DISABLED_WARNED.add(D)
            from ..utils.logging import logger

            logger.warning(
                f"int32 KV-cache packing disabled: head_dim={D} is not a "
                f"multiple of 4; falling back to the plain int8 container "
                f"(risk: Mosaic's (4,1)-packed s8 carry layout — see "
                f"kv_cache_packed in TransformerConfig)")
    kv_packed = (cfg.kv_cache_quant and cfg.kv_cache_packed is not False
                 and D % 4 == 0)
    if kv_packed:
        return jnp.int32, D // 4, True
    if cfg.kv_cache_quant:
        return jnp.int8, D, False
    return cfg.dtype, D, False


@dataclasses.dataclass(frozen=True)
class KVCacheSpec:
    """Module-declared KV-cache allocation contract: everything an engine
    needs to size, allocate and bound a cache WITHOUT inferring layout
    from pytree leaf shapes (ADVICE r5). ``stacked_cache``/``layer_cache``
    build zeroed containers in the exact layout CachedAttention reads and
    writes; the serving slot pool allocates through this (batch dim =
    slots) and ``InferenceEngine.generate`` takes ``max_seq_len`` as the
    authoritative capacity."""

    n_layer: int
    kv_heads: int
    head_dim: int          # logical per-head width
    cache_d: int           # stored sublane dim (head_dim, or //4 packed)
    dtype: Any
    max_seq_len: int
    quantized: bool
    packed: bool

    def layer_cache(self, batch_size: int) -> dict:
        """Zeroed single-layer k/v dict: (B, KV, cache_d, S) [+ scales]."""
        shape = (batch_size, self.kv_heads, self.cache_d, self.max_seq_len)
        cache = {"k": jnp.zeros(shape, self.dtype),
                 "v": jnp.zeros(shape, self.dtype)}
        if self.quantized:
            sshape = (batch_size, self.kv_heads, self.max_seq_len)
            cache["k_scale"] = jnp.zeros(sshape, jnp.float32)
            cache["v_scale"] = jnp.zeros(sshape, jnp.float32)
        return cache

    def stacked_cache(self, batch_size: int) -> dict:
        """Zeroed L-stacked cache dict matching the ``cache_store`` flax
        variables: k/v (L, B, KV, cache_d, S) [+ scales (L, B, KV, S)],
        plus a per-sequence ``index`` (B,) int32 — the vector-start form
        CachedAttention accepts for slot-pooled decode."""
        L = self.n_layer
        shape = (L, batch_size, self.kv_heads, self.cache_d,
                 self.max_seq_len)
        cache = {"k": jnp.zeros(shape, self.dtype),
                 "v": jnp.zeros(shape, self.dtype),
                 "index": jnp.zeros((batch_size,), jnp.int32)}
        if self.quantized:
            sshape = (L, batch_size, self.kv_heads, self.max_seq_len)
            cache["k_scale"] = jnp.zeros(sshape, jnp.float32)
            cache["v_scale"] = jnp.zeros(sshape, jnp.float32)
        return cache

    # -- paged KV (PagedAttention-style block pool) --------------------
    def paged_cache(self, num_pages: int, page_size: int) -> dict:
        """Zeroed PAGE-POOL k/v arrays: the positions axis is split into
        ``num_pages`` physical pages of ``page_size`` columns each, with
        NO batch axis — k/v (L, P, KV, cache_d, page_size) [+ scales
        (L, P, KV, page_size)]. A per-slot page table maps logical
        positions to pages; :meth:`dense_from_pages` reassembles the
        ``stacked_cache`` layout the attention kernels consume. Same
        dtype/packing tiers as the contiguous container (int8/packed
        cache columns page exactly like full-precision ones)."""
        shape = (self.n_layer, num_pages, self.kv_heads, self.cache_d,
                 page_size)
        cache = {"k": jnp.zeros(shape, self.dtype),
                 "v": jnp.zeros(shape, self.dtype)}
        if self.quantized:
            sshape = (self.n_layer, num_pages, self.kv_heads, page_size)
            cache["k_scale"] = jnp.zeros(sshape, jnp.float32)
            cache["v_scale"] = jnp.zeros(sshape, jnp.float32)
        return cache

    def dense_from_pages(self, paged: dict, table) -> dict:
        """Traced paged-attention GATHER: reassemble the dense
        ``(L, B, KV, cache_d, max_seq_len)`` view of a page pool from a
        ``(B, max_pages_per_slot)`` int32 page table, so the existing
        attention programs (decode / verify / chunked prefill) run
        UNCHANGED over paged storage — bitwise-identical math, static
        shapes, zero new attention kernels. Unmapped entries carry the
        sentinel ``num_pages``; the clip-mode gather reads an arbitrary
        real page there, which is safe because a slot's mapped region
        always covers its live ``[0, index)`` columns and attention
        masks everything beyond (the same alive-masking that makes dead
        slots free). ``table`` rows must span exactly
        ``max_seq_len // page_size`` pages."""
        B, max_pages = table.shape
        flat = table.reshape(-1)
        out = {}
        for key in ("k", "v"):
            leaf = paged[key]                       # (L, P, KV, cd, ps)
            L, _, KV, cd, ps = leaf.shape
            g = jnp.take(leaf, flat, axis=1, mode="clip")
            g = g.reshape(L, B, max_pages, KV, cd, ps)
            out[key] = g.transpose(0, 1, 3, 4, 2, 5).reshape(
                L, B, KV, cd, max_pages * ps)
        if self.quantized:
            for key in ("k_scale", "v_scale"):
                leaf = paged[key]                   # (L, P, KV, ps)
                L, _, KV, ps = leaf.shape
                g = jnp.take(leaf, flat, axis=1, mode="clip")
                g = g.reshape(L, B, max_pages, KV, ps)
                out[key] = g.transpose(0, 1, 3, 2, 4).reshape(
                    L, B, KV, max_pages * ps)
        return out


def make_kv_cache_spec(cfg: TransformerConfig) -> KVCacheSpec:
    cache_dtype, cache_d, packed = kv_cache_spec(cfg)
    return KVCacheSpec(n_layer=cfg.n_layer, kv_heads=cfg.kv_heads,
                       head_dim=cfg.head_dim, cache_d=cache_d,
                       dtype=cache_dtype, max_seq_len=cfg.max_seq_len,
                       quantized=cfg.kv_cache_quant, packed=packed)


def make_layer_kv_cache(cfg: TransformerConfig, batch_size: int) -> dict:
    """Zeroed SINGLE-LAYER KV cache dict — the explicit functional form
    of one _CacheStore slice, for callers that stream layers one at a
    time (ZeRO-Inference) and thread the cache themselves. Add a
    ``start`` scalar before passing to TransformerBlock."""
    return make_kv_cache_spec(cfg).layer_cache(batch_size)


class _CacheStore(nn.Module):
    """Owns the STACKED (n_layer-leading) KV-cache arrays as top-level
    flax variables in the ``cache`` collection. The stack rides the
    layer scan's CARRY (see _ScanBlock) rather than scanned per-layer
    variables; this module is only the flax-variable home that keeps the
    engine-facing contract (prefill/decode with ``mutable=["cache"]``,
    cache an opaque pytree) unchanged. Call once to READ (returns the
    value dict + start), again with ``new_values``/``new_index`` to
    WRITE the post-scan state back."""

    config: TransformerConfig

    @nn.compact
    def __call__(self, batch_size, new_values=None, new_index=None):
        cfg = self.config
        L, KV = cfg.n_layer, cfg.kv_heads
        cache_dtype, cache_d, _ = kv_cache_spec(cfg)
        shape = (L, batch_size, KV, cache_d, cfg.max_seq_len)
        ck = self.variable("cache", "k", jnp.zeros, shape, cache_dtype)
        cv = self.variable("cache", "v", jnp.zeros, shape, cache_dtype)
        values = {"k": ck.value, "v": cv.value}
        if cfg.kv_cache_quant:
            sshape = (L, batch_size, KV, cfg.max_seq_len)
            cks = self.variable("cache", "k_scale", jnp.zeros, sshape,
                                jnp.float32)
            cvs = self.variable("cache", "v_scale", jnp.zeros, sshape,
                                jnp.float32)
            values.update(k_scale=cks.value, v_scale=cvs.value)
        cidx = self.variable("cache", "index",
                             lambda: jnp.zeros((), jnp.int32))
        if new_values is not None:
            ck.value = new_values["k"]
            cv.value = new_values["v"]
            if cfg.kv_cache_quant:
                cks.value = new_values["k_scale"]
                cvs.value = new_values["v_scale"]
            cidx.value = new_index
        return values, cidx.value


class TransformerLM(nn.Module):
    """Causal LM over any family preset. Training convention matches the
    engine (``__call__(batch) -> loss``); inference uses ``prefill``/
    ``decode`` with the ``cache`` collection."""

    config: TransformerConfig

    def kv_cache_spec(self) -> KVCacheSpec:
        """Module-declared KV-cache contract (shape/dtype/capacity of the
        ``cache_store`` variables). Engines size and bound caches from
        THIS — not from inferring axis positions off pytree leaves — and
        the serving slot pool allocates through it (batch dim = slots).
        Safe to call on an unbound module: reads only ``self.config``."""
        return make_kv_cache_spec(self.config)

    def setup(self):
        cfg = self.config
        self.embed_tokens = nn.Embed(cfg.vocab_size, cfg.n_embd, dtype=cfg.dtype,
                                     name="embed_tokens")
        if cfg.pos_emb == "learned":
            self.embed_pos = nn.Embed(cfg.max_seq_len, cfg.n_embd, dtype=cfg.dtype,
                                      name="embed_pos")
        if cfg.embed_layernorm:
            self.embed_ln = _norm(cfg, "embed_ln")
        self.blocks = nn.scan(
            _ScanBlock,
            variable_axes={"params": 0},
            split_rngs={"params": True, "dropout": True},
            length=cfg.n_layer,
            in_axes=(nn.broadcast, nn.broadcast, nn.broadcast),
            metadata_params={nn.PARTITION_NAME: "layers"},
        )(cfg, name="blocks")
        self.cache_store = _CacheStore(cfg, name="cache_store")
        self.ln_f = _norm(cfg, "ln_f")
        if not cfg.tie_word_embeddings:
            head_cfg = cfg if (cfg.int8_head or not cfg.int8_weights) else \
                dataclasses.replace(cfg, int8_weights=False)
            self.lm_head = _dense(head_cfg, cfg.vocab_size, use_bias=False,
                                  dtype=jnp.float32, name="lm_head")

    def _transform(self, input_ids, positions, decode, deterministic,
                   block_hint=None, head=True, paged_table=None):
        cfg = self.config
        B, T = input_ids.shape
        x = self.embed_tokens(input_ids)
        if cfg.pos_emb == "learned":
            x = x + self.embed_pos(positions)
        if cfg.embed_layernorm:
            x = self.embed_ln(x)
        if decode:
            cache, start = self.cache_store(B)
            if paged_table is not None:
                # paged-kernel decode: the cache_store variables hold the
                # PAGE POOL (L, P, KV, cd, page_size) — provided-cache
                # shapes pass through — and the shared page table joins
                # the carry so every layer resolves positions through it
                # (stripped before writeback; see _ScanBlock)
                cache = dict(cache, table=paged_table)
            carry = (x, cache, start, jnp.zeros((), jnp.int32))
            (x, cache, _, _), _ = self.blocks(carry, decode, deterministic,
                                              block_hint)
            if paged_table is not None:
                cache = {key: val for key, val in cache.items()
                         if key != "table"}
            self.cache_store(B, new_values=cache, new_index=start + T)
        else:
            carry = (x, None, jnp.zeros((), jnp.int32),
                     jnp.zeros((), jnp.int32))
            (x, _, _, _), _ = self.blocks(carry, decode, deterministic,
                                          block_hint)
        x = self.ln_f(x)
        if not head:
            return x  # pre-projection hidden states (streaming loss path)
        return self._project_head(x)

    def _project_head(self, x):
        """The ONE vocabulary-projection path (scoring, generation
        prefill and decode all route here)."""
        if self.config.tie_word_embeddings:
            return self.embed_tokens.attend(x.astype(jnp.float32))
        return self.lm_head(x.astype(jnp.float32))

    def logits(self, input_ids, deterministic: bool = True):
        B, T = input_ids.shape
        pos = jnp.broadcast_to(jnp.arange(T)[None], (B, T))
        return self._transform(input_ids, pos, False, deterministic)

    def prefill(self, input_ids):
        """Run the prompt, filling the KV cache. Call with
        ``mutable=["cache"]``. Returns (B, T, V) logits. The "prefill"
        mode contract (start == 0) lets attention run over the fresh
        prompt k/v (flash for long prompts) instead of the allocated
        cache — O(T) memory in the prompt, not O(T x max_seq_len)."""
        B, T = input_ids.shape
        pos = jnp.broadcast_to(jnp.arange(T)[None], (B, T))
        return self._transform(input_ids, pos, "prefill", True)

    def prefill_last(self, input_ids, last_pos=None):
        """Prefill variant for GENERATION: fills the cache but projects
        only the LAST position onto the vocabulary, returning (B, 1, V)
        logits. Sampling uses only the last position, and the full
        (B, T, V) fp32 logits are the largest prefill allocation
        (~0.8 GB at B=8/T=512/V=50k — measured as the binding constraint
        on the 32k serving row, BASELINE.md); scoring callers keep
        ``prefill``.

        ``last_pos`` (scalar or (B,) int32, optional) selects WHICH
        position to project instead of T-1 — the serving path right-pads
        prompts to a shape bucket (bounded prefill recompiles) and
        projects the true last prompt token; causal attention keeps that
        position's hidden state independent of the right padding."""
        B, T = input_ids.shape
        pos = jnp.broadcast_to(jnp.arange(T)[None], (B, T))
        x = self._transform(input_ids, pos, "prefill", True, head=False)
        if last_pos is None:
            x = x[:, -1:]
        else:
            idx = jnp.broadcast_to(jnp.asarray(last_pos, jnp.int32), (B,))
            x = jax.vmap(lambda xb, i: jax.lax.dynamic_slice_in_dim(
                xb, i, 1, 0))(x, idx)
        return self._project_head(x)

    def prefill_chunk(self, input_ids, start_pos, last_idx):
        """Chunked serving prefill: process a fixed-width (B, C) token
        chunk AGAINST the allocated cache at per-slot offsets and project
        only ``last_idx`` onto the vocabulary, returning (B, 1, V).

        This is ``decode``'s multi-token path (window-masked attention
        over the allocated cache — row ``t`` of the chunk sees cache
        positions ``[0, start + t]``, which IS the causal mask against
        already-written positions), with ``prefill_last``'s head
        discipline (one projected position instead of the (B, C, V)
        logits tensor). Long prompts stream through it C tokens at a
        time, so per-step serving latency is bounded by the chunk width
        instead of the longest queued prompt (Sarathi-style stall-free
        chunked prefill; PAPERS.md). Call with ``mutable=["cache"]``;
        ``start_pos`` is scalar or (B,) — the serving path passes the
        slot's current prefill offset. Right-padding in the final
        partial chunk writes masked garbage past the true length
        (invisible to attention once the caller sets the slot index to
        the true length, exactly like the bucketed ``prefill_last``)."""
        B, T = input_ids.shape
        off = start_pos[:, None] if jnp.ndim(start_pos) == 1 else start_pos
        pos = off + jnp.broadcast_to(jnp.arange(T)[None], (B, T))
        x = self._transform(input_ids, pos, True, True, head=False)
        idx = jnp.broadcast_to(jnp.asarray(last_idx, jnp.int32), (B,))
        x = jax.vmap(lambda xb, i: jax.lax.dynamic_slice_in_dim(
            xb, i, 1, 0))(x, idx)
        return self._project_head(x)

    def decode(self, input_ids, start_pos, block_hint=None):
        """One (or few) token step against the cache; ``start_pos`` is the
        current cache length — scalar for a B-uniform batch, or (B,) for
        slot-pooled decode where every sequence sits at its own offset
        (continuous batching). Call with ``mutable=["cache"]``.
        ``block_hint`` (STATIC int) overrides the fused kernel's block
        granule — an explicit expert option; engine.generate keeps the
        allocation-based default after a budget-derived hint measured
        net-negative (grid overhead dominates dead-row reads;
        BASELINE.md round-5 KV e2e section)."""
        B, T = input_ids.shape
        off = start_pos[:, None] if jnp.ndim(start_pos) == 1 else start_pos
        pos = off + jnp.broadcast_to(jnp.arange(T)[None], (B, T))
        return self._transform(input_ids, pos, True, True, block_hint)

    def decode_paged(self, input_ids, start_pos, table):
        """Fused paged-kernel decode step: like :meth:`decode`, but the
        provided ``cache`` collection holds the PAGE POOL arrays
        (``KVCacheSpec.paged_cache`` layout — k/v (L, P, KV, cache_d,
        page_size), no batch axis) and ``table`` is the (B,
        pages_per_slot) int32 page table (sentinel = num_pages). Column
        writes scatter through the table and attention reads pages in
        place inside the fused kernel — no dense per-slot view is ever
        materialized. ``start_pos`` must be the per-slot (B,) cache
        lengths; handles 1 <= T <= MAX_QUERY_ROWS query rows (plain
        decode and speculative verify). Call with ``mutable=["cache"]``;
        greedy output is bitwise-identical to the dense-oracle
        :meth:`decode` over ``dense_from_pages`` of the same pool."""
        B, T = input_ids.shape
        off = start_pos[:, None] if jnp.ndim(start_pos) == 1 else start_pos
        pos = off + jnp.broadcast_to(jnp.arange(T)[None], (B, T))
        return self._transform(input_ids, pos, True, True,
                               paged_table=table)

    def __call__(self, batch, deterministic: bool = False):
        cfg = self.config
        input_ids = batch["input_ids"]
        labels = batch.get("labels", input_ids) if hasattr(batch, "get") \
            else input_ids
        targets = labels[:, 1:]
        mask = (targets >= 0).astype(jnp.float32)
        targets = jnp.maximum(targets, 0)
        if cfg.loss_chunk:
            # streaming loss: never materialize the (B, T, V) logits
            from ..ops.transformer.chunked_xent import chunked_softmax_xent

            B, T = input_ids.shape
            pos = jnp.broadcast_to(jnp.arange(T)[None], (B, T))
            x = self._transform(input_ids, pos, False, deterministic,
                                head=False)[:, :-1]
            if cfg.tie_word_embeddings:
                # Embed.attend promotes both operands to cfg.dtype; the
                # embedding table is never quantized (quantize_lm_params
                # converts only Dense kernels), so int8_weights+int8_head
                # is fine here — the guard below is untied-only
                w, cd = self.embed_tokens.embedding, cfg.dtype
            else:
                if cfg.int8_weights and cfg.int8_head:
                    raise ValueError(
                        "loss_chunk does not compose with an int8-quantized "
                        "untied lm_head (QuantDense stores an int8 kernel + "
                        "scale; the streaming loss reads a plain kernel). "
                        "Serve int8 with the dense loss, keep the head fp32, "
                        "or tie the embeddings.")
                if self.is_initializing():
                    # create the head's params (the streaming path reads
                    # the kernel without calling the module)
                    self.lm_head(jnp.zeros((1, x.shape[-1]), jnp.float32))
                w = self.lm_head.variables["params"]["kernel"].T
                cd = jnp.float32  # the lm_head Dense computes in fp32
            nll_sum = chunked_softmax_xent(
                x, w, targets, mask, cfg.loss_chunk, compute_dtype=cd)
            return nll_sum / jnp.maximum(mask.sum(), 1.0)
        logits = self.logits(input_ids, deterministic)
        logits = logits[:, :-1]
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        return (nll * mask).sum() / jnp.maximum(mask.sum(), 1.0)
