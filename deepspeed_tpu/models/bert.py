"""BERT-family encoder models.

Covers the reference's encoder model families
(``module_inject/containers/bert.py`` / ``distil_bert.py``, the
``DeepSpeedTransformerLayer`` training kernel whose numerics are tested
against the HF BERT layer in
``tests/unit/ops/accelerators/test_accelerator_forward.py``, and the
BERT-pretraining benchmark surface of
``docs/_tutorials/bert-pretraining.md``). TPU-first: bidirectional flash
attention (Pallas), bf16-friendly, scanned encoder stack with remat; MLM
(+ optional NSP) pretraining loss follows the engine's
``__call__(batch) -> loss`` convention.

Family presets: ``bert`` (post-layernorm, learned positions, token types),
``distil-bert`` (no token types, no pooler, half depth).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..ops.attention.flash_attention import flash_attention


@dataclasses.dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    layer_norm_eps: float = 1e-12
    use_token_type: bool = True
    use_pooler: bool = True
    dtype: Any = jnp.float32
    remat: bool = False


BERT_SIZES = {
    "bert-base": dict(hidden_size=768, num_hidden_layers=12,
                      num_attention_heads=12, intermediate_size=3072),
    "bert-large": dict(hidden_size=1024, num_hidden_layers=24,
                       num_attention_heads=16, intermediate_size=4096),
    "distil-bert": dict(hidden_size=768, num_hidden_layers=6,
                        num_attention_heads=12, intermediate_size=3072,
                        use_token_type=False, use_pooler=False),
}


def bert_config(name: str = "bert-base", **overrides) -> BertConfig:
    return BertConfig(**{**BERT_SIZES[name], **overrides})


class BertSelfAttention(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, x, mask_bias, deterministic: bool):
        cfg = self.config
        h = cfg.num_attention_heads
        d = cfg.hidden_size // h
        qkv = nn.Dense(3 * cfg.hidden_size, dtype=cfg.dtype, name="qkv")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        B, T = x.shape[:2]

        def heads(t):
            return t.reshape(B, T, h, d)

        needs_dropout = cfg.attention_probs_dropout_prob > 0 and \
            not deterministic
        if mask_bias is None and not needs_dropout:
            out = flash_attention(heads(q), heads(k), heads(v), causal=False)
        else:
            if mask_bias is None:
                # dropout needs materialized probs — bias-path with a zero
                # mask so attention dropout is NOT silently skipped
                mask_bias = jnp.zeros((1, 1, 1, 1), jnp.float32)
            # padding masks need the bias path — plain jnp attention; XLA
            # fuses it well for the short-seq encoder regime
            scale = 1.0 / math.sqrt(d)
            logits = jnp.einsum("bthd,bshd->bhts", heads(q), heads(k)) * scale
            logits = logits + mask_bias
            probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
            probs = probs.astype(x.dtype)
            if cfg.attention_probs_dropout_prob > 0 and not deterministic:
                probs = nn.Dropout(cfg.attention_probs_dropout_prob)(
                    probs, deterministic=False)
            out = jnp.einsum("bhts,bshd->bthd", probs, heads(v))
        out = out.reshape(B, T, cfg.hidden_size)
        return nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="output")(out)


class BertLayer(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, x, mask_bias, deterministic: bool):
        cfg = self.config
        attn = BertSelfAttention(cfg, name="attention")(
            x, mask_bias, deterministic)
        if cfg.hidden_dropout_prob > 0 and not deterministic:
            attn = nn.Dropout(cfg.hidden_dropout_prob)(
                attn, deterministic=False)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         name="attention_ln")(x + attn)
        y = nn.Dense(cfg.intermediate_size, dtype=cfg.dtype,
                     name="intermediate")(x)
        y = nn.gelu(y)
        y = nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="output")(y)
        if cfg.hidden_dropout_prob > 0 and not deterministic:
            y = nn.Dropout(cfg.hidden_dropout_prob)(y, deterministic=False)
        return nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                            name="output_ln")(x + y)


class BertModel(nn.Module):
    """Encoder: returns (sequence_output, pooled_output|None)."""

    config: BertConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 deterministic: bool = True):
        cfg = self.config
        B, T = input_ids.shape
        x = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                     name="word_embeddings")(input_ids)
        pos = jnp.broadcast_to(jnp.arange(T)[None], (B, T))
        x = x + nn.Embed(cfg.max_position_embeddings, cfg.hidden_size,
                         dtype=cfg.dtype, name="position_embeddings")(pos)
        if cfg.use_token_type:
            if token_type_ids is None:
                token_type_ids = jnp.zeros_like(input_ids)
            x = x + nn.Embed(cfg.type_vocab_size, cfg.hidden_size,
                             dtype=cfg.dtype,
                             name="token_type_embeddings")(token_type_ids)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         name="embeddings_ln")(x)
        if cfg.hidden_dropout_prob > 0 and not deterministic:
            x = nn.Dropout(cfg.hidden_dropout_prob)(x, deterministic=False)

        mask_bias = None
        if attention_mask is not None:
            mask_bias = jnp.where(attention_mask[:, None, None, :] > 0,
                                  0.0, -1e9).astype(jnp.float32)

        layer = BertLayer
        if cfg.remat:
            layer = nn.remat(BertLayer, static_argnums=(3,))
        for i in range(cfg.num_hidden_layers):
            x = layer(cfg, name=f"layer_{i}")(x, mask_bias, deterministic)

        pooled = None
        if cfg.use_pooler:
            pooled = jnp.tanh(nn.Dense(cfg.hidden_size, dtype=cfg.dtype,
                                       name="pooler")(x[:, 0]))
        return x, pooled


class BertForPreTraining(nn.Module):
    """MLM (+ optional NSP) pretraining — ``__call__(batch) -> loss``.

    batch keys: ``input_ids``, optional ``attention_mask``,
    ``token_type_ids``, ``mlm_labels`` (-100 = unmasked), and optional
    ``next_sentence_label`` when the pooler is on.
    """

    config: BertConfig

    @nn.compact
    def __call__(self, batch, deterministic: bool = False):
        cfg = self.config
        seq_out, pooled = BertModel(cfg, name="bert")(
            batch["input_ids"], batch.get("attention_mask"),
            batch.get("token_type_ids"), deterministic=deterministic)
        h = nn.Dense(cfg.hidden_size, dtype=cfg.dtype,
                     name="mlm_transform")(seq_out)
        h = nn.gelu(h)
        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         name="mlm_ln")(h)
        logits = nn.Dense(cfg.vocab_size, dtype=jnp.float32,
                          name="mlm_head")(h)

        labels = batch["mlm_labels"]
        mask = (labels >= 0).astype(jnp.float32)
        safe_labels = jnp.maximum(labels, 0)
        logp = jax.nn.log_softmax(logits, axis=-1)
        token_ll = jnp.take_along_axis(logp, safe_labels[..., None],
                                       axis=-1)[..., 0]
        mlm_loss = -jnp.sum(token_ll * mask) / jnp.maximum(jnp.sum(mask), 1)

        loss = mlm_loss
        if cfg.use_pooler and "next_sentence_label" in batch:
            nsp_logits = nn.Dense(2, dtype=jnp.float32,
                                  name="nsp_head")(pooled)
            nsp_lp = jax.nn.log_softmax(nsp_logits, axis=-1)
            nsp_loss = -jnp.mean(jnp.take_along_axis(
                nsp_lp, batch["next_sentence_label"][:, None], axis=-1))
            loss = loss + nsp_loss
        return loss
