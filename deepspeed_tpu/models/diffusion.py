"""Diffusion model family: CLIP text encoder, conditional UNet, VAE.

The last three of the reference's 17 injection families
(``module_inject/containers/{clip,unet,vae}.py`` wrapping diffusers
modules with fused kernels + CUDA graphs). TPU-native equivalents are
first-class flax modules — XLA fuses what the reference's spatial kernels
(``csrc/spatial/``, see ``ops/spatial.py``) fuse by hand, and the whole
denoise step compiles to one program (the CUDA-graph analog):

* :class:`CLIPTextEncoder` — causal transformer text encoder
  (containers/clip.py's attention surface: qkv fused when dims match).
* :class:`UNet2DCondition` — timestep-embedded conv UNet with self- and
  cross-attention transformer blocks at each resolution
  (containers/unet.py: to_q/to_k/to_v[/to_out] attention layout).
* :class:`AutoencoderVAE` — conv encoder/decoder with the reparameterized
  latent (containers/vae.py's DSVAE surface: encode/decode entry points).

``diffusion_sharding_rules`` gives the tensor-parallel placements the
reference's policies encode (qkv/ff column-parallel, out-proj
row-parallel).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..parallel.mesh import MODEL_AXIS


# ---------------------------------------------------------------------------
# CLIP text encoder (containers/clip.py)
# ---------------------------------------------------------------------------
@dataclass
class CLIPConfig:
    vocab_size: int = 49408
    max_positions: int = 77
    width: int = 512
    layers: int = 8
    heads: int = 8
    dtype: Any = jnp.float32


class _CLIPBlock(nn.Module):
    cfg: CLIPConfig

    @nn.compact
    def __call__(self, x, mask):
        cfg = self.cfg
        H = cfg.heads
        D = cfg.width // H
        h = nn.LayerNorm(dtype=cfg.dtype, name="ln_1")(x)
        # fused qkv — the container's concat when q/k/v widths match
        qkv = nn.Dense(3 * cfg.width, dtype=cfg.dtype, name="qkv")(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        B, T, _ = h.shape
        q = q.reshape(B, T, H, D)
        k = k.reshape(B, T, H, D)
        v = v.reshape(B, T, H, D)
        att = jnp.einsum("bthd,bshd->bhts", q, k) / math.sqrt(D)
        att = jnp.where(mask[None, None], att, jnp.finfo(jnp.float32).min)
        att = jax.nn.softmax(att.astype(jnp.float32), axis=-1).astype(cfg.dtype)
        h = jnp.einsum("bhts,bshd->bthd", att, v).reshape(B, T, cfg.width)
        x = x + nn.Dense(cfg.width, dtype=cfg.dtype, name="out_proj")(h)
        h = nn.LayerNorm(dtype=cfg.dtype, name="ln_2")(x)
        h = nn.Dense(4 * cfg.width, dtype=cfg.dtype, name="fc1")(h)
        h = h * jax.nn.sigmoid(1.702 * h)  # quick-gelu (CLIP)
        return x + nn.Dense(cfg.width, dtype=cfg.dtype, name="fc2")(h)


class CLIPTextEncoder(nn.Module):
    """Causal CLIP text tower → (B, T, width) hidden states (the
    conditioning input of the UNet)."""

    cfg: CLIPConfig = field(default_factory=CLIPConfig)

    @nn.compact
    def __call__(self, input_ids):
        cfg = self.cfg
        B, T = input_ids.shape
        x = nn.Embed(cfg.vocab_size, cfg.width, dtype=cfg.dtype,
                     name="token_embedding")(input_ids)
        pos = self.param("position_embedding", nn.initializers.normal(0.01),
                         (cfg.max_positions, cfg.width), cfg.dtype)
        x = x + pos[None, :T]
        mask = jnp.tril(jnp.ones((T, T), bool))
        for i in range(cfg.layers):
            x = _CLIPBlock(cfg, name=f"block_{i}")(x, mask)
        return nn.LayerNorm(dtype=cfg.dtype, name="ln_final")(x)


# ---------------------------------------------------------------------------
# conditional UNet (containers/unet.py)
# ---------------------------------------------------------------------------
@dataclass
class UNetConfig:
    in_channels: int = 4
    out_channels: int = 4
    block_channels: Sequence[int] = (64, 128)
    layers_per_block: int = 1
    attention_heads: int = 4
    cross_attention_dim: int = 512
    norm_groups: int = 8
    dtype: Any = jnp.float32


def timestep_embedding(t, dim: int, max_period: float = 10000.0):
    """Sinusoidal timestep embedding (diffusers convention)."""
    half = dim // 2
    freqs = jnp.exp(-math.log(max_period) *
                    jnp.arange(half, dtype=jnp.float32) / half)
    args = t.astype(jnp.float32)[:, None] * freqs[None]
    return jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)


class _ResnetBlock(nn.Module):
    cfg: UNetConfig
    out_ch: int

    @nn.compact
    def __call__(self, x, temb):
        cfg = self.cfg
        h = nn.GroupNorm(num_groups=cfg.norm_groups, dtype=cfg.dtype,
                         name="norm1")(x)
        h = jax.nn.silu(h)
        h = nn.Conv(self.out_ch, (3, 3), padding="SAME", dtype=cfg.dtype,
                    name="conv1")(h)
        tproj = nn.Dense(self.out_ch, dtype=cfg.dtype, name="time_emb_proj")(
            jax.nn.silu(temb))
        skip = x if x.shape[-1] == self.out_ch else nn.Conv(
            self.out_ch, (1, 1), dtype=cfg.dtype, name="conv_shortcut")(x)
        h = h + tproj[:, None, None, :]
        h = nn.GroupNorm(num_groups=cfg.norm_groups, dtype=cfg.dtype,
                         name="norm2")(h)
        h = jax.nn.silu(h)
        h = nn.Conv(self.out_ch, (3, 3), padding="SAME", dtype=cfg.dtype,
                    name="conv2")(h)
        return h + skip


class _CrossAttnBlock(nn.Module):
    """Self-attention + cross-attention + geglu ff over flattened spatial
    tokens (the containers/unet.py attention surface: to_q/to_k/to_v +
    to_out)."""

    cfg: UNetConfig
    channels: int

    def _attention(self, x, context, name):
        cfg = self.cfg
        H = cfg.attention_heads
        D = self.channels // H
        B, N, _ = x.shape
        q = nn.Dense(self.channels, use_bias=False, dtype=cfg.dtype,
                     name=f"{name}_to_q")(x).reshape(B, N, H, D)
        k = nn.Dense(self.channels, use_bias=False, dtype=cfg.dtype,
                     name=f"{name}_to_k")(context)
        v = nn.Dense(self.channels, use_bias=False, dtype=cfg.dtype,
                     name=f"{name}_to_v")(context)
        M = context.shape[1]
        k = k.reshape(B, M, H, D)
        v = v.reshape(B, M, H, D)
        att = jnp.einsum("bnhd,bmhd->bhnm", q, k) / math.sqrt(D)
        att = jax.nn.softmax(att.astype(jnp.float32), axis=-1).astype(cfg.dtype)
        y = jnp.einsum("bhnm,bmhd->bnhd", att, v).reshape(B, N, self.channels)
        return nn.Dense(self.channels, dtype=cfg.dtype,
                        name=f"{name}_to_out")(y)

    @nn.compact
    def __call__(self, x, context):
        cfg = self.cfg
        B, Hh, Ww, C = x.shape
        tokens = x.reshape(B, Hh * Ww, C)
        h = nn.LayerNorm(dtype=cfg.dtype, name="norm_self")(tokens)
        tokens = tokens + self._attention(h, h, "attn1")
        h = nn.LayerNorm(dtype=cfg.dtype, name="norm_cross")(tokens)
        ctx = nn.Dense(self.channels, dtype=cfg.dtype,
                       name="context_proj")(context)
        tokens = tokens + self._attention(h, ctx, "attn2")
        h = nn.LayerNorm(dtype=cfg.dtype, name="norm_ff")(tokens)
        # geglu feed-forward (diffusers)
        gate = nn.Dense(4 * self.channels, dtype=cfg.dtype, name="ff_gate")(h)
        val = nn.Dense(4 * self.channels, dtype=cfg.dtype, name="ff_val")(h)
        h = val * jax.nn.gelu(gate)
        tokens = tokens + nn.Dense(self.channels, dtype=cfg.dtype,
                                   name="ff_out")(h)
        return tokens.reshape(B, Hh, Ww, C)


class UNet2DCondition(nn.Module):
    """Conditional denoising UNet: ``(latents NHWC, timesteps (B,),
    encoder_hidden_states (B, M, ctx_dim)) -> noise prediction NHWC``."""

    cfg: UNetConfig = field(default_factory=UNetConfig)

    @nn.compact
    def __call__(self, sample, timesteps, encoder_hidden_states):
        cfg = self.cfg
        ch0 = cfg.block_channels[0]
        temb = timestep_embedding(timesteps, ch0)
        temb = nn.Dense(4 * ch0, dtype=cfg.dtype, name="time_fc1")(temb)
        temb = nn.Dense(4 * ch0, dtype=cfg.dtype,
                        name="time_fc2")(jax.nn.silu(temb))

        h = nn.Conv(ch0, (3, 3), padding="SAME", dtype=cfg.dtype,
                    name="conv_in")(sample)
        skips = [h]
        # down path
        for bi, ch in enumerate(cfg.block_channels):
            for li in range(cfg.layers_per_block):
                h = _ResnetBlock(cfg, ch, name=f"down_{bi}_res_{li}")(h, temb)
                h = _CrossAttnBlock(cfg, ch, name=f"down_{bi}_attn_{li}")(
                    h, encoder_hidden_states)
                skips.append(h)
            if bi < len(cfg.block_channels) - 1:
                h = nn.Conv(ch, (3, 3), strides=(2, 2), padding="SAME",
                            dtype=cfg.dtype, name=f"down_{bi}_downsample")(h)
                skips.append(h)
        # mid
        mid_ch = cfg.block_channels[-1]
        h = _ResnetBlock(cfg, mid_ch, name="mid_res_1")(h, temb)
        h = _CrossAttnBlock(cfg, mid_ch, name="mid_attn")(
            h, encoder_hidden_states)
        h = _ResnetBlock(cfg, mid_ch, name="mid_res_2")(h, temb)
        # up path
        for bi, ch in reversed(list(enumerate(cfg.block_channels))):
            for li in range(cfg.layers_per_block + 1):
                skip = skips.pop()
                h = jnp.concatenate([h, skip], axis=-1)
                h = _ResnetBlock(cfg, ch, name=f"up_{bi}_res_{li}")(h, temb)
                h = _CrossAttnBlock(cfg, ch, name=f"up_{bi}_attn_{li}")(
                    h, encoder_hidden_states)
            if bi > 0:
                B, Hh, Ww, C = h.shape
                h = jax.image.resize(h, (B, Hh * 2, Ww * 2, C), "nearest")
                h = nn.Conv(ch, (3, 3), padding="SAME", dtype=cfg.dtype,
                            name=f"up_{bi}_upsample")(h)
        h = nn.GroupNorm(num_groups=cfg.norm_groups, dtype=cfg.dtype,
                         name="norm_out")(h)
        return nn.Conv(cfg.out_channels, (3, 3), padding="SAME",
                       dtype=cfg.dtype, name="conv_out")(jax.nn.silu(h))


# ---------------------------------------------------------------------------
# VAE (containers/vae.py)
# ---------------------------------------------------------------------------
@dataclass
class VAEConfig:
    in_channels: int = 3
    latent_channels: int = 4
    base_channels: int = 32
    norm_groups: int = 8
    scaling_factor: float = 0.18215
    dtype: Any = jnp.float32


class AutoencoderVAE(nn.Module):
    """Conv VAE with the diffusers entry points: ``encode`` → (mean,
    logvar), ``decode`` latents → image, ``__call__`` = full
    reconstruction (training surface)."""

    cfg: VAEConfig = field(default_factory=VAEConfig)

    def setup(self):
        cfg = self.cfg
        c = cfg.base_channels
        self.enc = [
            nn.Conv(c, (3, 3), padding="SAME", dtype=cfg.dtype,
                    name="enc_in"),
            nn.Conv(c * 2, (3, 3), strides=(2, 2), padding="SAME",
                    dtype=cfg.dtype, name="enc_down1"),
            nn.Conv(c * 4, (3, 3), strides=(2, 2), padding="SAME",
                    dtype=cfg.dtype, name="enc_down2"),
        ]
        self.enc_norm = nn.GroupNorm(num_groups=cfg.norm_groups,
                                     dtype=cfg.dtype, name="enc_norm")
        self.to_moments = nn.Conv(2 * cfg.latent_channels, (1, 1),
                                  dtype=cfg.dtype, name="to_moments")
        self.from_latent = nn.Conv(c * 4, (1, 1), dtype=cfg.dtype,
                                   name="from_latent")
        self.dec = [
            nn.ConvTranspose(c * 2, (4, 4), strides=(2, 2), padding="SAME",
                             dtype=cfg.dtype, name="dec_up1"),
            nn.ConvTranspose(c, (4, 4), strides=(2, 2), padding="SAME",
                             dtype=cfg.dtype, name="dec_up2"),
        ]
        self.dec_norm = nn.GroupNorm(num_groups=cfg.norm_groups,
                                     dtype=cfg.dtype, name="dec_norm")
        self.dec_out = nn.Conv(cfg.in_channels, (3, 3), padding="SAME",
                               dtype=cfg.dtype, name="dec_out")

    def encode(self, images) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Raw (unscaled) latent distribution — scale AFTER sampling
        (diffusers convention: latents = sample(dist) * scaling_factor)."""
        h = images
        for conv in self.enc:
            h = jax.nn.silu(conv(h))
        h = self.enc_norm(h)
        moments = self.to_moments(h)
        mean, logvar = jnp.split(moments, 2, axis=-1)
        return mean, logvar

    def decode(self, latents) -> jnp.ndarray:
        h = self.from_latent(latents / self.cfg.scaling_factor)
        for conv in self.dec:
            h = jax.nn.silu(conv(h))
        h = self.dec_norm(h)
        return jnp.tanh(self.dec_out(h))

    def __call__(self, images, rng=None):
        mean, logvar = self.encode(images)
        if rng is not None:
            sample = mean + jnp.exp(0.5 * logvar) * \
                jax.random.normal(rng, mean.shape, mean.dtype)
        else:
            sample = mean
        # scaling applies to the SAMPLED latent, keeping noise consistent
        # with the distribution the logvar describes
        return self.decode(sample * self.cfg.scaling_factor), mean, logvar


def diffusion_sharding_rules():
    """Tensor-parallel placements for the diffusion family (the policy
    content of containers/{clip,unet,vae}.py): attention qkv / q,k,v and
    ff in-projections column-parallel; out-projections row-parallel;
    convs replicated (spatial ops shard over batch)."""
    M = MODEL_AXIS
    return [
        (r"(qkv|to_q|to_k|to_v|fc1|ff_gate|ff_val)/kernel", (None, M)),
        (r"(qkv|fc1|ff_gate|ff_val)/bias", (M,)),
        (r"(out_proj|to_out|fc2|ff_out)/kernel", (M, None)),
    ]
