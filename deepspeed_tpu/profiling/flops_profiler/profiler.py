"""Flops profiler.

Capability parity with reference
``deepspeed/profiling/flops_profiler/profiler.py:23 FlopsProfiler`` — but
TPU-first. The reference monkey-patches ``torch.nn.functional`` entry points
with flop-counting wrappers (profiler.py:444-700) because torch is eager. In
JAX the whole computation is available *as data*: we trace the step function
to a jaxpr and walk it, counting FLOPs/MACs per primitive and attributing
them to the flax module that issued them via the ``name_stack``
(flax wraps every module method in ``jax.named_scope``). Totals are
cross-checked against XLA's own ``Compiled.cost_analysis()``.

Public surface (reference parity):
  * ``FlopsProfiler(model)`` with ``start_profile / stop_profile /
    get_total_flops / get_total_macs / get_total_params /
    get_total_duration / print_model_profile / end_profile``
  * ``get_model_profile(model, args=...)`` one-shot helper
    (reference profiler.py:1117)

Differences (documented, inherent to XLA): per-module *latency* is not
observable after fusion — the per-module tree reports flops/macs/params and
flops share instead; wall latency and achieved FLOPS are reported for the
whole compiled step.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...utils.logging import logger


# ---------------------------------------------------------------------------
# per-primitive flop models
# ---------------------------------------------------------------------------
def _size(aval) -> int:
    return int(np.prod(aval.shape)) if aval.shape else 1


def _dot_general_flops(eqn) -> Tuple[int, int]:
    """MACs/FLOPs for dot_general: batch * M * N * K MACs."""
    lhs, rhs = eqn.invars[0].aval, eqn.invars[1].aval
    dnums = eqn.params["dimension_numbers"]
    (lc, rc), (lb, rb) = dnums
    batch = int(np.prod([lhs.shape[i] for i in lb])) if lb else 1
    contract = int(np.prod([lhs.shape[i] for i in lc])) if lc else 1
    m = int(np.prod([lhs.shape[i] for i in range(len(lhs.shape))
                     if i not in lc and i not in lb]))
    n = int(np.prod([rhs.shape[i] for i in range(len(rhs.shape))
                     if i not in rc and i not in rb]))
    macs = batch * m * n * contract
    return macs, 2 * macs


def _conv_flops(eqn) -> Tuple[int, int]:
    lhs, rhs = eqn.invars[0].aval, eqn.invars[1].aval
    out = eqn.outvars[0].aval
    # output positions × (kernel volume × in-channels) MACs; rhs holds
    # (out_ch, in_ch/g, *kernel) after dimension_numbers normalization — use
    # total kernel size / out_channels for generality
    dn = eqn.params["dimension_numbers"]
    out_spatial_and_batch = _size(out)
    kernel_elems = _size(rhs)
    out_ch_dim = dn.rhs_spec[0]
    out_ch = rhs.shape[out_ch_dim]
    macs = out_spatial_and_batch * (kernel_elems // max(out_ch, 1))
    return macs, 2 * macs


# elementwise primitives: 1 flop per output element
_ELEMENTWISE = {
    "add", "sub", "mul", "div", "max", "min", "neg", "abs", "exp", "log",
    "tanh", "logistic", "rsqrt", "sqrt", "pow", "integer_pow", "erf",
    "exp2", "log1p", "expm1", "cbrt", "sin", "cos", "erf_inv",
    "and", "or", "xor", "not", "ge", "gt", "le", "lt", "eq", "ne",
    "select_n", "clamp", "sign", "floor", "ceil", "round", "rem",
    "nextafter", "atan2",
}
# reduction primitives: 1 flop per *input* element
_REDUCTIONS = {
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod", "reduce_and",
    "reduce_or", "argmax", "argmin", "cumsum", "cumlogsumexp", "cummax",
    "cummin", "cumprod", "reduce_precision", "logsumexp",
}
def _eqn_cost(eqn) -> Tuple[int, int]:
    """Returns (macs, flops) of one jaxpr equation (non-recursive prims)."""
    name = eqn.primitive.name
    if name == "dot_general":
        return _dot_general_flops(eqn)
    if name == "conv_general_dilated":
        return _conv_flops(eqn)
    if name in _ELEMENTWISE:
        return 0, sum(_size(v.aval) for v in eqn.outvars)
    if name in _REDUCTIONS:
        return 0, sum(_size(v.aval) for v in eqn.invars
                      if hasattr(v, "aval") and v.aval.shape)
    if name == "scatter_add":
        return 0, sum(_size(v.aval) for v in eqn.invars[1:2])
    return 0, 0


def _scope_of(eqn) -> str:
    """Module path from the equation's name stack (flax named_scopes)."""
    try:
        stack = eqn.source_info.name_stack
        s = str(stack)
        return s if s else ""
    except Exception:
        return ""


def count_jaxpr_flops(jaxpr, scale: int = 1,
                      tree: Optional[Dict[str, List[int]]] = None,
                      prefix: str = "") -> Tuple[int, int]:
    """Walk a (closed) jaxpr recursively, returning (macs, flops) and filling
    ``tree`` with per-scope aggregates. ``scale`` multiplies costs inside
    ``scan``/``while`` bodies by their trip count where it is static."""
    if hasattr(jaxpr, "jaxpr"):
        jaxpr = jaxpr.jaxpr
    total_macs = 0
    total_flops = 0
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        sub_scale = scale
        subjaxprs = []
        if name == "scan":
            subjaxprs = [eqn.params["jaxpr"]]
            sub_scale = scale * int(eqn.params.get("length", 1))
        elif name == "while":
            # trip count unknowable statically; count body once
            subjaxprs = [eqn.params["body_jaxpr"], eqn.params["cond_jaxpr"]]
        elif name == "cond":
            # count the most expensive branch (re-walked with the tree so
            # its flops are attributed to scopes, not just the totals)
            branches = eqn.params.get("branches", ())
            if branches:
                costs = [count_jaxpr_flops(b, 1) for b in branches]
                best = max(range(len(costs)), key=lambda i: costs[i][1])
                scope = _scope_of(eqn) or prefix
                bm, bf = count_jaxpr_flops(branches[best], scale, tree, scope)
                total_macs += bm
                total_flops += bf
            continue
        elif "jaxpr" in eqn.params:  # pjit/custom_jvp/custom_vjp/remat/closed_call
            subjaxprs = [eqn.params["jaxpr"]]
        elif "call_jaxpr" in eqn.params:
            subjaxprs = [eqn.params["call_jaxpr"]]
        elif "fun_jaxpr" in eqn.params:
            subjaxprs = [eqn.params["fun_jaxpr"]]

        if subjaxprs:
            scope = _scope_of(eqn) or prefix
            for sj in subjaxprs:
                m, f = count_jaxpr_flops(sj, sub_scale, tree, scope)
                total_macs += m
                total_flops += f
            continue

        macs, flops = _eqn_cost(eqn)
        macs *= scale
        flops *= scale
        total_macs += macs
        total_flops += flops
        if tree is not None and flops:
            scope = _scope_of(eqn) or prefix
            # aggregate into every ancestor scope so the tree rolls up
            parts = [p for p in scope.split("/") if p] if scope else []
            paths = [""] + ["/".join(parts[:i + 1]) for i in range(len(parts))]
            for p in paths:
                ent = tree.setdefault(p, [0, 0])
                ent[0] += macs
                ent[1] += flops
    return total_macs, total_flops


# ---------------------------------------------------------------------------
# parameter counting per module scope
# ---------------------------------------------------------------------------
def _param_tree(params) -> Dict[str, int]:
    out: Dict[str, int] = {}
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    for path, leaf in flat:
        parts = []
        for k in path:
            key = getattr(k, "key", getattr(k, "idx", None))
            if key is not None:
                parts.append(str(key))
        n = int(np.prod(np.shape(leaf))) if np.shape(leaf) else 1
        paths = [""] + ["/".join(parts[:i + 1]) for i in range(len(parts))]
        for p in paths:
            out[p] = out.get(p, 0) + n
    return out


def _num_to_string(num: float, units=None, precision: int = 2) -> str:
    if units is None:
        if num >= 1e12:
            return f"{num / 1e12:.{precision}f} T"
        if num >= 1e9:
            return f"{num / 1e9:.{precision}f} G"
        if num >= 1e6:
            return f"{num / 1e6:.{precision}f} M"
        if num >= 1e3:
            return f"{num / 1e3:.{precision}f} K"
        return f"{num:.{precision}f} "
    return f"{num:.{precision}f} {units}"


class FlopsProfiler:
    """Profiles a jitted step function or a flax model's apply.

    Usage (engine-integrated, reference engine.py:1688):
        prof = FlopsProfiler(model=model)
        prof.start_profile()
        ... run fn through prof.profile(fn, *args) or attach to engine ...
        prof.print_model_profile()
    """

    def __init__(self, model=None, ds_engine=None):
        self.model = model
        self.ds_engine = ds_engine
        self.started = False
        self._macs = 0
        self._flops = 0
        self._params = 0
        self._duration = 0.0
        self._tree: Dict[str, List[int]] = {}
        self._param_scopes: Dict[str, int] = {}
        self._xla_flops: Optional[float] = None
        self._xla_bytes: Optional[float] = None

    # -- reference API ----------------------------------------------------
    def start_profile(self, ignore_list=None) -> None:
        self.started = True
        self._tree = {}
        self._macs = self._flops = 0
        self._duration = 0.0

    def stop_profile(self) -> None:
        pass  # analysis happens in profile(); kept for API parity

    def reset_profile(self) -> None:
        self.start_profile()

    def end_profile(self) -> None:
        self.started = False

    # -- core -------------------------------------------------------------
    def profile(self, fn: Callable, *args, static_argnums=(),
                run: bool = True, **kwargs) -> Dict[str, Any]:
        """Trace/compile ``fn(*args)``; fill flops tree; optionally run and
        time it. Returns a summary dict."""
        closed = jax.make_jaxpr(fn, static_argnums=static_argnums)(*args, **kwargs)
        self._tree = {}
        self._macs, self._flops = count_jaxpr_flops(closed, tree=self._tree)

        # XLA's own view (total only) as a cross-check. Only when the caller
        # intends to run the program anyway — compiling a 20B-param graph
        # purely for cost_analysis would stall training at profile_step.
        self._xla_flops = self._xla_bytes = None
        if run:
            try:
                compiled = jax.jit(fn, static_argnums=static_argnums) \
                    .lower(*args, **kwargs).compile()
                ca = compiled.cost_analysis()
                if isinstance(ca, (list, tuple)):
                    ca = ca[0] if ca else {}
                self._xla_flops = float(ca.get("flops", 0.0)) or None
                self._xla_bytes = float(ca.get("bytes accessed", 0.0)) or None
            except Exception:  # cost analysis unavailable on some backends
                compiled = jax.jit(fn, static_argnums=static_argnums)
            out = compiled(*args, **kwargs)
            jax.block_until_ready(out)
            t0 = time.perf_counter()
            out = compiled(*args, **kwargs)
            jax.block_until_ready(out)
            self._duration = time.perf_counter() - t0

        # params: first arg that looks like a pytree of arrays named 'params'
        for a in args:
            if isinstance(a, dict) or hasattr(a, "keys"):
                self._param_scopes = _param_tree(a)
                self._params = self._param_scopes.get("", 0)
                break
        return {
            "flops": self._flops,
            "macs": self._macs,
            "params": self._params,
            "duration": self._duration,
            "xla_flops": self._xla_flops,
            "xla_bytes_accessed": self._xla_bytes,
        }

    # -- getters (reference parity) ---------------------------------------
    def get_total_flops(self, as_string: bool = False):
        return _num_to_string(self._flops) + "FLOPs" if as_string else self._flops

    def get_total_macs(self, as_string: bool = False):
        return _num_to_string(self._macs) + "MACs" if as_string else self._macs

    def get_total_params(self, as_string: bool = False):
        return _num_to_string(self._params) + "params" if as_string else self._params

    def get_total_duration(self, as_string: bool = False):
        return f"{self._duration * 1e3:.2f} ms" if as_string else self._duration

    # -- reports ----------------------------------------------------------
    def print_model_profile(self, profile_step: int = 1, module_depth: int = -1,
                            top_modules: int = 3, detailed: bool = True,
                            output_file: Optional[str] = None) -> str:
        lines: List[str] = []
        lines.append("-" * 72)
        lines.append("DeepSpeed-TPU Flops Profiler")
        lines.append("-" * 72)
        lines.append(f"profile step:                   {profile_step}")
        lines.append(f"params:                         {_num_to_string(self._params)}")
        lines.append(f"fwd MACs:                       {_num_to_string(self._macs)}MACs")
        lines.append(f"fwd flops:                      {_num_to_string(self._flops)}FLOPs")
        if self._xla_flops:
            lines.append(f"XLA cost-analysis flops:        "
                         f"{_num_to_string(self._xla_flops)}FLOPs")
        if self._xla_bytes:
            lines.append(f"XLA bytes accessed:             "
                         f"{_num_to_string(self._xla_bytes)}B")
        if self._duration:
            lines.append(f"step latency:                   {self._duration * 1e3:.2f} ms")
            lines.append(f"achieved FLOPS:                 "
                         f"{_num_to_string(self._flops / self._duration)}FLOPS")

        if detailed and self._tree:
            lines.append("")
            lines.append("per-module breakdown (depth-aggregated, by named_scope):")
            scopes = {k: v for k, v in self._tree.items() if k}
            by_depth: Dict[int, List[Tuple[str, List[int]]]] = {}
            for k, v in scopes.items():
                by_depth.setdefault(k.count("/"), []).append((k, v))
            max_depth = max(by_depth) if by_depth else 0
            depth_limit = max_depth if module_depth < 0 else module_depth
            for d in sorted(by_depth):
                if d > depth_limit:
                    break
                top = sorted(by_depth[d], key=lambda kv: -kv[1][1])[:top_modules]
                lines.append(f"  depth {d}:")
                for name, (macs, flops) in top:
                    share = 100.0 * flops / max(self._flops, 1)
                    lines.append(
                        f"    {name:<48s} {_num_to_string(flops)}FLOPs "
                        f"({share:.1f}%)")
        report = "\n".join(lines)
        if jax.process_index() == 0:  # rank-gated like log_dist(ranks=[0])
            if output_file:
                with open(output_file, "w") as f:
                    f.write(report)
            else:
                logger.info("\n" + report)
        return report


def get_model_profile(model, args=None, kwargs=None, print_profile: bool = True,
                      detailed: bool = True, module_depth: int = -1,
                      top_modules: int = 3, as_string: bool = False,
                      output_file: Optional[str] = None, seed: int = 0):
    """One-shot profile of a flax model's forward — reference
    ``get_model_profile`` (profiler.py:1117). ``args`` are the model inputs
    (after params); params are initialized internally."""
    import jax.random as jrandom

    args = args or ()
    kwargs = kwargs or {}
    rng = jrandom.PRNGKey(seed)
    variables = model.init({"params": rng, "dropout": rng}, *args, **kwargs)
    params = variables["params"]

    def fwd(p, *a):
        return model.apply({"params": p}, *a, **kwargs)

    prof = FlopsProfiler(model=model)
    prof.start_profile()
    prof.profile(fwd, params, *args)
    if print_profile:
        prof.print_model_profile(module_depth=module_depth,
                                 top_modules=top_modules, detailed=detailed,
                                 output_file=output_file)
    flops, macs, params_n = prof.get_total_flops(as_string), \
        prof.get_total_macs(as_string), prof.get_total_params(as_string)
    prof.end_profile()
    return flops, macs, params_n
