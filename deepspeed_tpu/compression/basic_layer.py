"""Compression math + compressed flax layers.

Capability parity with reference ``deepspeed/compression/basic_layer.py``
(LinearLayer_Compress :121, Conv2dLayer_Compress :404, Embedding_Compress
:611, and the TP Row/Col compressed linears :767,802). Two surfaces:

* pure jnp transforms (``quantize_weight``, ``prune_*_mask``) used by the
  scheduler to compress parameters inside the compiled train step;
* :class:`LinearLayerCompress` / :class:`EmbeddingCompress` flax modules
  that additionally fake-quantize *activations* on the forward pass
  (activation_quantization needs to live in the layer). TP variants are
  the same modules with GSPMD shardings on the kernel — row/col splits are
  sharding annotations on TPU, not separate classes.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..runtime.quantize import quantize_highbit


# --------------------------------------------------------------------------
# weight transforms (jittable; used by the scheduler)
# --------------------------------------------------------------------------
def quantize_weight(w: jnp.ndarray, bits: int, groups: int = 1,
                    q_type: str = "symmetric",
                    rounding: str = "nearest",
                    rng: Optional[jax.Array] = None) -> jnp.ndarray:
    return quantize_highbit(w, bits, groups, q_type, rounding, rng)


def sparse_l1_mask(w: jnp.ndarray, dense_ratio: float) -> jnp.ndarray:
    """Unstructured: keep the top ``dense_ratio`` fraction by |w| —
    reference SPARSE_PRUNING_METHOD_L1."""
    flat = jnp.abs(w).reshape(-1)
    thresh = jnp.quantile(flat, 1.0 - dense_ratio)
    return (jnp.abs(w) >= thresh).astype(w.dtype)


def row_prune_mask(w: jnp.ndarray, dense_ratio: float) -> jnp.ndarray:
    """Structured: keep rows (output features = last dim in flax kernels)
    with the largest L1 norm — reference ROW_PRUNING."""
    axis = tuple(range(w.ndim - 1))
    scores = jnp.sum(jnp.abs(w), axis=axis)
    n = w.shape[-1]
    k = max(1, int(n * dense_ratio))
    thresh = jnp.sort(scores)[n - k]
    return (scores >= thresh).astype(w.dtype)  # (out_features,)


def head_prune_mask(w: jnp.ndarray, dense_ratio: float,
                    num_heads: int) -> jnp.ndarray:
    """Structured: rank attention heads by the L1 norm of their slice of
    the output-projection weight — reference HEAD_PRUNING. ``w`` is the
    attention output kernel (in_features = heads*head_dim first dim for
    flax (in, out))."""
    in_features = w.shape[0]
    head_dim = in_features // num_heads
    per_head = jnp.sum(jnp.abs(w.reshape(num_heads, head_dim, -1)),
                       axis=(1, 2))
    k = max(1, int(num_heads * dense_ratio))
    thresh = jnp.sort(per_head)[num_heads - k]
    head_mask = (per_head >= thresh).astype(w.dtype)
    return jnp.repeat(head_mask, head_dim)  # (in_features,)


def channel_prune_mask(w: jnp.ndarray, dense_ratio: float) -> jnp.ndarray:
    """Structured: conv output channels by L1 norm — reference
    CHANNEL_PRUNING. Flax conv kernels are (kh, kw, in, out)."""
    return row_prune_mask(w, dense_ratio)


# --------------------------------------------------------------------------
# activation quantization (lives in the forward pass)
# --------------------------------------------------------------------------
def quantize_activation(x: jnp.ndarray, bits: int = 8,
                        q_type: str = "asymmetric",
                        range_calibration: str = "dynamic") -> jnp.ndarray:
    """Dynamic-range fake-quant of activations — reference
    activation_quantization with range_calibration=dynamic; ``static``
    calibration would use recorded ranges (the dynamic path subsumes it
    numerically and needs no calibration pass)."""
    q_range = 2 ** bits
    x_min = jnp.min(x, axis=-1, keepdims=True)
    x_max = jnp.max(x, axis=-1, keepdims=True)
    if q_type == "symmetric":
        scale = 2 * jnp.maximum(jnp.abs(x_min), jnp.abs(x_max)) / q_range
        scale = jnp.where(scale == 0, 1.0, scale)
        return jnp.clip(jnp.round(x / scale), -(q_range >> 1),
                        (q_range >> 1) - 1) * scale
    scale = (x_max - x_min) / q_range
    scale = jnp.where(scale == 0, 1.0, scale)
    zero = jnp.round(x_min / scale) * scale
    return jnp.clip(jnp.round((x - zero) / scale), 0, q_range - 1) * scale \
        + zero


class LinearLayerCompress(nn.Module):
    """Dense layer with optional activation fake-quant on input and weight
    fake-quant on the fly — reference LinearLayer_Compress. Weight-side
    *training-time* compression normally comes from the scheduler transform;
    the in-layer path serves QAT-style usage."""

    features: int
    use_bias: bool = True
    act_bits: Optional[int] = None
    act_q_type: str = "asymmetric"
    weight_bits: Optional[int] = None
    weight_q_groups: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (x.shape[-1], self.features), self.dtype)
        if self.weight_bits is not None:
            kernel = quantize_weight(kernel, self.weight_bits,
                                     self.weight_q_groups)
        if self.act_bits is not None:
            x = quantize_activation(x, self.act_bits, self.act_q_type)
        y = x @ kernel
        if self.use_bias:
            y = y + self.param("bias", nn.initializers.zeros,
                               (self.features,), self.dtype)
        return y


class EmbeddingCompress(nn.Module):
    """Embedding with weight fake-quant — reference Embedding_Compress."""

    num_embeddings: int
    features: int
    weight_bits: Optional[int] = None
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, ids):
        table = self.param("embedding", nn.initializers.normal(0.02),
                           (self.num_embeddings, self.features), self.dtype)
        if self.weight_bits is not None:
            table = quantize_weight(table, self.weight_bits)
        return jnp.take(table, ids, axis=0)


class ConvLayerCompress(nn.Module):
    """Conv with weight/activation fake-quant and sparse/channel pruning on
    the forward pass — reference Conv2dLayer_Compress (basic_layer.py:404).
    Flax kernel layout (kh, kw, in, out): channel pruning masks the last
    (output-channel) dim."""

    features: int
    kernel_size: tuple = (3, 3)
    strides: tuple = (1, 1)
    padding: str = "SAME"
    use_bias: bool = True
    act_bits: Optional[int] = None
    act_q_type: str = "asymmetric"
    weight_bits: Optional[int] = None
    weight_q_groups: int = 1
    sparse_dense_ratio: Optional[float] = None
    channel_dense_ratio: Optional[float] = None
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        in_ch = x.shape[-1]
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            self.kernel_size + (in_ch, self.features),
                            self.dtype)
        if self.weight_bits is not None:
            kernel = quantize_weight(kernel, self.weight_bits,
                                     self.weight_q_groups)
        if self.sparse_dense_ratio is not None:
            kernel = kernel * sparse_l1_mask(kernel, self.sparse_dense_ratio)
        ch_mask = None
        if self.channel_dense_ratio is not None:
            ch_mask = channel_prune_mask(kernel, self.channel_dense_ratio)
            kernel = kernel * ch_mask
        if self.act_bits is not None:
            x = quantize_activation(x, self.act_bits, self.act_q_type)
        y = jax.lax.conv_general_dilated(
            x, kernel, window_strides=self.strides, padding=self.padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros,
                              (self.features,), self.dtype)
            if ch_mask is not None:
                bias = bias * ch_mask
            y = y + bias
        return y


class BNCompress(nn.Module):
    """BatchNorm whose scale/bias follow a channel-pruning mask — reference
    BNLayer_Compress (basic_layer.py:611). Pass the producing conv's channel
    mask so normalization of pruned channels is inert."""

    use_running_average: bool = True
    momentum: float = 0.9
    epsilon: float = 1e-5
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, channel_mask: Optional[jnp.ndarray] = None):
        y = nn.BatchNorm(use_running_average=self.use_running_average,
                         momentum=self.momentum, epsilon=self.epsilon,
                         dtype=self.dtype, name="bn")(x)
        if channel_mask is not None:
            y = y * channel_mask
        return y


class ColumnParallelLinearCompress(LinearLayerCompress):
    """Column-parallel compressed linear — reference
    ColumnParallelLinear_Compress (basic_layer.py:767). On TPU the TP split
    is a sharding annotation: kernel (in, out) sharded (None, model); the
    output stays sharded over ``model`` for a following row-parallel layer.
    Compression math is inherited unchanged — masks/fake-quant are
    elementwise and commute with GSPMD sharding."""

    @nn.compact
    def __call__(self, x):
        y = super().__call__(x)
        from ..parallel import mesh as mesh_mod

        if mesh_mod.has_mesh():
            from jax.sharding import NamedSharding, PartitionSpec

            # leading dims UNCONSTRAINED so data-parallel batch sharding
            # survives; only the feature dim is pinned to the model axis
            U = PartitionSpec.UNCONSTRAINED
            y = jax.lax.with_sharding_constraint(
                y, NamedSharding(mesh_mod.get_mesh(),
                                 PartitionSpec(*([U] * (y.ndim - 1)
                                                 + [mesh_mod.MODEL_AXIS]))))
        return y


class RowParallelLinearCompress(LinearLayerCompress):
    """Row-parallel compressed linear — reference RowParallelLinear_Compress
    (basic_layer.py:802): kernel (in, out) sharded (model, None); XLA inserts
    the partial-sum reduction the reference does with an explicit
    all-reduce."""

    @nn.compact
    def __call__(self, x):
        y = super().__call__(x)
        from ..parallel import mesh as mesh_mod

        if mesh_mod.has_mesh():
            from jax.sharding import NamedSharding, PartitionSpec

            # feature dim replicated (the partial-sum reduction point);
            # leading dims unconstrained to preserve batch sharding
            U = PartitionSpec.UNCONSTRAINED
            y = jax.lax.with_sharding_constraint(
                y, NamedSharding(mesh_mod.get_mesh(),
                                 PartitionSpec(*([U] * (y.ndim - 1)
                                                 + [None]))))
        return y


def compression_tp_rules():
    """Sharding rules for the TP compressed linears (≅ the reference's
    explicit column/row weight splits)."""
    from ..parallel.mesh import MODEL_AXIS

    return [
        (r"col_parallel.*/kernel", (None, MODEL_AXIS)),
        (r"col_parallel.*/bias", (MODEL_AXIS,)),
        (r"row_parallel.*/kernel", (MODEL_AXIS, None)),
    ]
