"""Compression orchestration.

Capability parity with reference ``deepspeed/compression/compress.py`` —
``init_compression`` (:100), ``redundancy_clean`` (:148) and the
knowledge-distillation ``student_initialization`` (:192). The reference
swaps nn.Modules for compressed variants; on TPU the compiled train step
applies an equivalent **pure parameter transform** each step (fake-quant +
pruning masks, schedule-gated on the step counter with ``jnp.where`` so a
single compiled program covers the whole schedule).
"""

from __future__ import annotations

import re
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.logging import log_dist
from .basic_layer import (
    channel_prune_mask,
    head_prune_mask,
    quantize_weight,
    row_prune_mask,
    sparse_l1_mask,
)
from .config import CompressionConfig


def _leaf_path(path) -> str:
    return ".".join(str(getattr(k, "key", getattr(k, "idx", k)))
                    for k in path)


def _progressive_bits(step, start_bits: int, target_bits: int,
                      period: int):
    """Bits halve toward the target every ``period`` steps after the
    schedule starts (reference MoQ-style quantization_period)."""
    if period <= 0:
        return jnp.asarray(target_bits, jnp.float32)
    halvings = jnp.floor(step.astype(jnp.float32) / period)
    bits = jnp.maximum(float(target_bits),
                       jnp.floor(start_bits / 2.0 ** halvings))
    return bits


def build_compression_transform(
        config: CompressionConfig
) -> Callable[[Any, jnp.ndarray], Any]:
    """Returns a jittable ``transform(params, step) -> params`` applying
    every configured technique to its matched parameters."""
    wq = config.technique_groups("weight_quantization")
    sp = config.technique_groups("sparse_pruning")
    rp = config.technique_groups("row_pruning")
    hp = config.technique_groups("head_pruning")
    cp = config.technique_groups("channel_pruning")

    def transform(params, step):
        step = jnp.asarray(step)

        def visit(path, p):
            key = _leaf_path(path)
            if jnp.ndim(p) < 2:
                return p
            out = p
            for g in wq:
                if not g.matches(key):
                    continue
                start = int(g.params.get("start_bits", 16))
                target = int(g.params.get("target_bits", 8))
                period = int(g.params.get("quantization_period", 1))
                active = step >= g.schedule_offset
                bits = _progressive_bits(
                    jnp.maximum(step - g.schedule_offset, 0),
                    start, target, period)
                # static bits per branch: evaluate at target bits (the
                # asymptotic state) and at start bits, pick by schedule —
                # intermediate bit levels are covered by re-jit only when
                # the period divides step ranges; in-jit we blend the two
                # end states like fp16_mixed_quantize does
                q_target = quantize_weight(
                    out, target, int(g.shared.get("quantize_groups", 1)),
                    g.shared.get("quantization_type", "symmetric"))
                ratio = jnp.clip((jnp.asarray(start, jnp.float32) - bits) /
                                 max(start - target, 1), 0.0, 1.0)
                out = jnp.where(active,
                                (1.0 - ratio) * out + ratio * q_target, out)
            for g in sp:
                if g.matches(key):
                    dense_ratio = float(g.params.get("dense_ratio", 0.5))
                    mask = sparse_l1_mask(out, dense_ratio)
                    out = jnp.where(step >= g.schedule_offset, out * mask,
                                    out)
            for g in rp:
                if g.matches(key):
                    dense_ratio = float(g.params.get("dense_ratio", 0.5))
                    mask = row_prune_mask(out, dense_ratio)
                    out = jnp.where(step >= g.schedule_offset, out * mask,
                                    out)
            for g in hp:
                if g.matches(key):
                    ratio = float(g.params.get("dense_ratio", 0.5))
                    heads = int(g.params.get("num_heads", 1))
                    mask = head_prune_mask(out, ratio, heads)
                    out = jnp.where(step >= g.schedule_offset,
                                    out * mask[:, None], out)
            for g in cp:
                if g.matches(key) and jnp.ndim(p) == 4:
                    ratio = float(g.params.get("dense_ratio", 0.5))
                    mask = channel_prune_mask(out, ratio)
                    out = jnp.where(step >= g.schedule_offset, out * mask,
                                    out)
            return out

        return jax.tree_util.tree_map_with_path(visit, params)

    return transform


def init_compression(config: Dict[str, Any]) -> Tuple[CompressionConfig,
                                                      Callable]:
    """Parse a ds_config-style dict (or just its ``compression_training``
    block) and return (config, transform) — reference init_compression
    wraps the model; here the transform plugs into the engine's compiled
    step (engine reads ``compression_training`` itself)."""
    block = config.get("compression_training", config)
    cc = CompressionConfig(block)
    log_dist(f"compression: {len(cc.groups)} groups "
             f"({[g.technique + '/' + g.name for g in cc.groups]})",
             ranks=[0])
    return cc, build_compression_transform(cc)


def redundancy_clean(params: Any, config: CompressionConfig) -> Any:
    """Materialize the final pruning decisions (hard zeros) — reference
    redundancy_clean. Quantization groups also collapse to their target
    bits. For physical dim reduction see :func:`shrink_params`."""
    transform = build_compression_transform(config)
    return transform(params, jnp.asarray(10 ** 9))


def shrink_params(params: Any, config: CompressionConfig,
                  couplings: Optional[Dict[str, List[str]]] = None) -> Any:
    """Physically remove row/head-pruned units — the reference's
    ``fix_compression(..., dim_reduction=True)`` (helper.py:207) path.

    Row pruning drops output features of the matched kernel (last dim) and
    its bias; each path in ``couplings[matched_path]`` then has the SAME
    kept-indices sliced from its input dim (dim 0) — the reference does this
    mask hand-off between a pruned layer and its consumer inside
    redundancy_clean. Head pruning shrinks the attention output projection's
    input dim by whole heads.

    Returns a new (host, numpy) param tree with smaller arrays; pair it with
    a model built at the reduced width. Output parity with the masked big
    model is asserted in tests/unit/compression/.
    """
    couplings = couplings or {}
    # compute masks from (and emit) the CLEANED params so the kept-index
    # sets agree exactly with redundancy_clean's masks — ranking rows on
    # raw weights could diverge when quantization reorders near-threshold
    # rows, breaking the shrink/mask parity guarantee
    params = redundancy_clean(params, config)
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        flat[_leaf_path(path)] = np.asarray(leaf)

    keep: Dict[str, np.ndarray] = {}      # path -> kept OUTPUT indices
    keep_in: Dict[str, np.ndarray] = {}   # path -> kept INPUT indices

    for g in config.technique_groups("row_pruning"):
        ratio = float(g.params.get("dense_ratio", 0.5))
        for key, w in flat.items():
            if not key.endswith("kernel") or not g.matches(key):
                continue
            mask = np.asarray(row_prune_mask(jnp.asarray(w), ratio))
            idx = np.nonzero(mask)[0]
            keep[key] = idx
            keep[key.rsplit(".", 1)[0] + ".bias"] = idx
            for consumer in couplings.get(key, []):
                keep_in[consumer] = idx

    for g in config.technique_groups("head_pruning"):
        ratio = float(g.params.get("dense_ratio", 0.5))
        heads = int(g.params.get("num_heads", 1))
        for key, w in flat.items():
            if not key.endswith("kernel") or not g.matches(key):
                continue
            mask = np.asarray(head_prune_mask(jnp.asarray(w), ratio, heads))
            idx = np.nonzero(mask)[0]
            keep_in[key] = idx
            for producer in couplings.get(key, []):
                # the qkv/value projection feeding these heads loses the
                # same units from its OUTPUT dim
                keep[producer] = idx
                keep[producer.rsplit(".", 1)[0] + ".bias"] = idx

    def visit(path, leaf):
        key = _leaf_path(path)
        out = np.asarray(leaf)
        if key in keep:
            out = np.take(out, keep[key], axis=out.ndim - 1)
        if key in keep_in and out.ndim >= 2:
            # input-feature axis: dim 0 for (in, out) linears, dim ndim-2
            # for conv (kh, kw, in, out) layouts
            out = np.take(out, keep_in[key], axis=out.ndim - 2)
        return out

    return jax.tree_util.tree_map_with_path(visit, params)


def student_initialization(student_params: Any, teacher_params: Any,
                           config: Dict[str, Any]) -> Any:
    """Layer-reduction student init — reference compress.py:192. Copies
    ``teacher_layer`` (list of teacher layer indices) onto the student's
    consecutive layers, plus ``other_module_name`` subtrees verbatim.

    Layer params are matched by rewriting path components that contain the
    layer index (e.g. ``layers_3`` ← ``layers_9``)."""
    lr = config.get("layer_reduction", config)
    teacher_layers: List[int] = list(lr.get("teacher_layer", []))
    module_name = lr.get("module_name_prefix", "")

    def rename(path_str: str, student_idx: int, teacher_idx: int) -> str:
        return re.sub(rf"(_|\.){student_idx}(\.|$|_)",
                      rf"\g<1>{teacher_idx}\g<2>", path_str, count=1)

    flat_teacher = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(teacher_params)[0]:
        flat_teacher[_leaf_path(path)] = leaf

    def visit(path, leaf):
        key = _leaf_path(path)
        if module_name and not key.startswith(module_name):
            return flat_teacher.get(key, leaf)
        for student_idx, teacher_idx in enumerate(teacher_layers):
            m = re.search(rf"(^|[._]){student_idx}([._]|$)", key)
            if m:
                teacher_key = rename(key, student_idx, teacher_idx)
                if teacher_key in flat_teacher and \
                        np.shape(flat_teacher[teacher_key]) == np.shape(leaf):
                    return flat_teacher[teacher_key]
        return flat_teacher.get(key, leaf)

    return jax.tree_util.tree_map_with_path(visit, student_params)
