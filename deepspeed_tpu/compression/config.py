"""Compression config parsing.

Capability parity with reference ``deepspeed/compression/config.py`` +
``constants.py`` — parses the ``compression_training`` JSON block:
techniques (weight/activation quantization, sparse/row/head/channel
pruning, layer_reduction), each with ``shared_parameters`` and
``different_groups`` of {params, modules} entries. Unmodified reference
configs must parse.
"""

from __future__ import annotations

from typing import Any, Dict, List

TECHNIQUES = (
    "weight_quantization",
    "activation_quantization",
    "sparse_pruning",
    "row_pruning",
    "head_pruning",
    "channel_pruning",
)


class CompressionGroup:
    """One ``different_groups`` entry of a technique."""

    def __init__(self, technique: str, name: str, params: Dict[str, Any],
                 modules: List[str], shared: Dict[str, Any]):
        self.technique = technique
        self.name = name
        self.params = dict(params)
        self.modules = list(modules)
        self.shared = dict(shared)

    @property
    def schedule_offset(self) -> int:
        return int(self.shared.get("schedule_offset", 0))

    def matches(self, param_path: str) -> bool:
        """Reference matching: module-name substring (modules=["*"] matches
        everything). Separator-agnostic: flax scopes are written with "/" or
        "." interchangeably."""
        path = param_path.replace("/", ".")
        for pattern in self.modules:
            if pattern == "*" or pattern.replace("/", ".") in path:
                return True
        return False

    def __repr__(self):
        return (f"CompressionGroup({self.technique}/{self.name}, "
                f"modules={self.modules})")


class CompressionConfig:
    def __init__(self, compression_training: Dict[str, Any]):
        self.raw = dict(compression_training or {})
        self.groups: List[CompressionGroup] = []
        for technique in TECHNIQUES:
            block = self.raw.get(technique)
            if not block:
                continue
            shared = dict(block.get("shared_parameters", {}))
            if not shared.get("enabled", False):
                continue
            for name, group in block.get("different_groups", {}).items():
                self.groups.append(CompressionGroup(
                    technique, name, group.get("params", {}),
                    group.get("modules", ["*"]), shared))
        lr = self.raw.get("layer_reduction", {})
        self.layer_reduction_enabled = bool(lr.get("enabled", False))
        self.layer_reduction = dict(lr)

    def technique_groups(self, technique: str) -> List[CompressionGroup]:
        return [g for g in self.groups if g.technique == technique]

    @property
    def enabled(self) -> bool:
        return bool(self.groups) or self.layer_reduction_enabled
