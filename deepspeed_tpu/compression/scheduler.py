"""Compression scheduler.

Capability parity with reference ``deepspeed/compression/scheduler.py`` —
tracks training steps and reports which technique groups are active. In
this framework the schedule gating runs *inside* the compiled train step
(jnp.where on the step counter, see compress.build_compression_transform);
this class is the eager-side mirror for user introspection and for driving
``redundancy_clean`` at the right moment.
"""

from __future__ import annotations

from typing import Dict, List

from ..utils.logging import log_dist
from .config import CompressionConfig


class CompressionScheduler:
    def __init__(self, config: CompressionConfig):
        self.config = config
        self.training_steps = 0
        self._announced: Dict[str, bool] = {}

    def step(self, step_zero_check: bool = False) -> None:
        self.training_steps += 1
        for g in self.config.groups:
            key = f"{g.technique}/{g.name}"
            if not self._announced.get(key) and \
                    self.training_steps >= g.schedule_offset:
                self._announced[key] = True
                log_dist(f"compression group {key} active from step "
                         f"{self.training_steps}", ranks=[0])

    def active_groups(self) -> List[str]:
        return [f"{g.technique}/{g.name}" for g in self.config.groups
                if self.training_steps >= g.schedule_offset]
