from .basic_layer import (
    EmbeddingCompress,
    LinearLayerCompress,
    quantize_activation,
    quantize_weight,
)
from .compress import (
    build_compression_transform,
    init_compression,
    redundancy_clean,
    student_initialization,
)
from .config import CompressionConfig
from .scheduler import CompressionScheduler

__all__ = [
    "CompressionConfig", "CompressionScheduler", "init_compression",
    "redundancy_clean", "student_initialization",
    "build_compression_transform", "LinearLayerCompress",
    "EmbeddingCompress", "quantize_weight", "quantize_activation",
]
