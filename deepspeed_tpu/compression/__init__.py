from .basic_layer import (
    BNCompress,
    ColumnParallelLinearCompress,
    ConvLayerCompress,
    EmbeddingCompress,
    LinearLayerCompress,
    RowParallelLinearCompress,
    compression_tp_rules,
    quantize_activation,
    quantize_weight,
)
from .compress import (
    build_compression_transform,
    init_compression,
    redundancy_clean,
    shrink_params,
    student_initialization,
)
from .config import CompressionConfig
from .scheduler import CompressionScheduler

__all__ = [
    "CompressionConfig", "CompressionScheduler", "init_compression",
    "redundancy_clean", "shrink_params", "student_initialization",
    "build_compression_transform", "LinearLayerCompress",
    "EmbeddingCompress", "ConvLayerCompress", "BNCompress",
    "ColumnParallelLinearCompress", "RowParallelLinearCompress",
    "compression_tp_rules", "quantize_weight", "quantize_activation",
]
