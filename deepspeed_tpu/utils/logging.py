"""Rank-aware logging.

Capability parity with the reference's ``deepspeed/utils/logging.py`` (logger
factory at utils/logging.py:20, ``log_dist`` rank-filtered logging at
utils/logging.py:75), re-expressed for a JAX multi-process world where the
process index comes from ``jax.process_index()`` rather than torch.distributed.
"""

from __future__ import annotations

import functools
import logging
import os
import sys
from typing import Iterable, Optional

LOG_LEVEL_DEFAULT = logging.INFO

log_levels = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "critical": logging.CRITICAL,
}


class LoggerFactory:
    @staticmethod
    def create_logger(name: str = "DeepSpeedTPU", level: int = LOG_LEVEL_DEFAULT) -> logging.Logger:
        if name is None:
            raise ValueError("name for logger cannot be None")
        formatter = logging.Formatter(
            "[%(asctime)s] [%(levelname)s] [%(name)s:%(lineno)d:%(funcName)s] %(message)s")
        logger_ = logging.getLogger(name)
        logger_.setLevel(level)
        logger_.propagate = False
        if not logger_.handlers:
            ch = logging.StreamHandler(stream=sys.stdout)
            ch.setLevel(level)
            ch.setFormatter(formatter)
            logger_.addHandler(ch)
        return logger_


logger = LoggerFactory.create_logger(
    level=log_levels.get(os.environ.get("DSTPU_LOG_LEVEL", "info").lower(), LOG_LEVEL_DEFAULT))


def _process_index() -> int:
    try:
        import jax

        return jax.process_index()
    except Exception:
        return int(os.environ.get("RANK", "0"))


@functools.lru_cache(None)
def warning_once(msg: str):
    logger.warning(msg)


def log_dist(message: str, ranks: Optional[Iterable[int]] = None, level: int = logging.INFO) -> None:
    """Log ``message`` only on the listed process ranks (``[-1]`` or None = all).

    Mirrors the semantics of reference ``log_dist`` (utils/logging.py:75) with
    JAX process indices standing in for torch.distributed ranks.
    """
    my_rank = _process_index()
    ranks = list(ranks) if ranks is not None else []
    should_log = not ranks or (-1 in ranks) or (my_rank in ranks)
    if should_log:
        logger.log(level, f"[Rank {my_rank}] {message}")


def print_rank_0(message: str) -> None:
    if _process_index() == 0:
        print(message, flush=True)
