"""Profiler range annotation.

Capability parity with reference ``deepspeed/utils/nvtx.py:9
instrument_w_nvtx`` — wraps a function in a named profiler range. On TPU
the range shows up in xprof/perfetto traces via
``jax.profiler.TraceAnnotation`` and inside compiled programs via
``jax.named_scope`` (which also names HLO ops for the flops profiler's
per-module attribution).
"""

from __future__ import annotations

import contextlib
import functools


def instrument_w_nvtx(func):
    """Decorator: execute ``func`` inside a named trace range."""
    import jax

    name = getattr(func, "__qualname__", getattr(func, "__name__", "fn"))

    @functools.wraps(func)
    def wrapped(*args, **kwargs):
        with jax.profiler.TraceAnnotation(name), jax.named_scope(name):
            return func(*args, **kwargs)

    return wrapped


def range_push(name: str) -> None:
    """Eager range begin — reference signature (range_pop takes no args);
    delegates to the accelerator's stack-managed implementation."""
    from ..accelerator import get_accelerator

    get_accelerator().range_push(name)


def range_pop() -> None:
    from ..accelerator import get_accelerator

    get_accelerator().range_pop()


@contextlib.contextmanager
def trace_range(name: str):
    """with trace_range("phase"): ... — xprof-visible range that is ALSO a
    jax.named_scope, so ops traced inside attribute to this name in the
    flops profiler's per-module tree (same visibility as
    ``instrument_w_nvtx``)."""
    import jax

    with jax.profiler.TraceAnnotation(name), jax.named_scope(name):
        yield
