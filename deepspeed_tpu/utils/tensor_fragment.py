"""Debug/introspection access to sharded training state.

Capability parity with reference ``deepspeed/utils/tensor_fragment.py`` —
the ``safe_get_full_*`` / ``safe_set_full_*`` APIs (:48,:91,:107,:124) that
give users whole-tensor views of ZeRO-partitioned params, grads and
optimizer state regardless of sharding. Under GSPMD a "fragment" is just a
shard of a ``jax.Array``; ``jax.device_get`` assembles the full logical
tensor, and ``device_put`` against the engine's shardings re-partitions on
set. The fragment *address map* the reference needs (tensor_fragment.py:144
``get_hp_fragment_mapping``) is carried by the array's sharding itself.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np


def _lookup(tree: Any, path: str):
    node = tree
    for part in path.replace(".", "/").split("/"):
        if node is None:
            return None
        if isinstance(node, dict):
            node = node.get(part)
        else:
            node = getattr(node, part, None)
    return node


def _set(tree: Any, path: str, value) -> bool:
    parts = path.replace(".", "/").split("/")
    node = tree
    for part in parts[:-1]:
        if not isinstance(node, dict) or part not in node:
            return False
        node = node[part]
    if isinstance(node, dict) and parts[-1] in node:
        node[parts[-1]] = value
        return True
    return False


def safe_get_full_fp32_param(engine, param_path: str) -> Optional[np.ndarray]:
    """Full fp32 master weights of one param (reference :48)."""
    import jax

    if engine.state is None:
        return None
    if engine._offload_opt is not None:
        # under offload the fp32 master lives host-side; the device params
        # are the downcast compute copy — never return those as "fp32".
        # read_leaf fetches O(leaf) from the NVMe tier when swapped out.
        key = param_path.replace(".", "/")
        if key in engine._offload_opt.master:
            return engine._offload_opt.read_leaf("master", key)
    source = engine.state.get("master") or engine.state["params"]
    leaf = _lookup(source, param_path)
    return None if leaf is None else \
        np.asarray(jax.device_get(leaf), np.float32)


def safe_get_full_grad(engine, param_path: str) -> Optional[np.ndarray]:
    """Full gradient from the eager-path accumulator (reference :91). The
    fused train_batch consumes grads inside the compiled step — use the
    forward/backward API when grads must be inspected."""
    import jax

    if engine._grad_acc is None:
        return None
    leaf = _lookup(engine._grad_acc, param_path)
    return None if leaf is None else np.asarray(jax.device_get(leaf))


def safe_get_full_optimizer_state(engine, param_path: str,
                                  optim_state_key: str
                                  ) -> Optional[np.ndarray]:
    """Full optimizer moment for one param (reference :107).
    ``optim_state_key``: exp_avg | exp_avg_sq."""
    import jax

    if engine._offload_opt is not None:
        kind = {"exp_avg": "m", "exp_avg_sq": "v"}.get(optim_state_key)
        if kind is None:
            return None
        return engine._offload_opt.read_leaf(
            kind, param_path.replace(".", "/"))
    if engine.state is None or engine.state.get("opt_state") is None:
        return None
    opt = engine.state["opt_state"]
    sub = getattr(opt, optim_state_key, None)
    if sub is None and hasattr(opt, "_asdict"):
        sub = opt._asdict().get(optim_state_key)
    if sub is None:
        return None
    leaf = _lookup(sub, param_path)
    return None if leaf is None else np.asarray(jax.device_get(leaf))


def safe_set_full_fp32_param(engine, param_path: str, value) -> bool:
    """Overwrite one param's master (and compute) weights (reference
    :124 set API)."""
    import jax
    import jax.numpy as jnp

    if engine.state is None:
        return False
    host_master = jax.device_get(engine.state.get("master")) \
        if engine.state.get("master") is not None else None
    host_params = jax.device_get(engine.state["params"])
    ok = False
    if host_master is not None and _set(host_master, param_path,
                                        np.asarray(value, np.float32)):
        engine.state["master"] = jax.device_put(
            host_master, engine._shardings["master"])
        ok = True
    leaf = _lookup(host_params, param_path)
    if leaf is not None:
        cast = np.asarray(value).astype(np.asarray(leaf).dtype)
        if _set(host_params, param_path, cast):
            engine.state["params"] = jax.device_put(
                host_params, engine._shardings["params"])
            ok = True
    if engine._offload_opt is not None:
        if engine._offload_opt.write_leaf(
                "master", param_path.replace(".", "/"), value):
            ok = True
    return ok
