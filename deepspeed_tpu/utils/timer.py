"""Wall-clock and throughput timers.

Capability parity with reference ``deepspeed/utils/timer.py`` —
``SynchronizedWallClockTimer`` (:33) and ``ThroughputTimer`` (:153).

**Dispatch vs compute.** JAX dispatch is asynchronous: a jitted call
returns as soon as the program is enqueued, so a host-side timer around
it measures *dispatch*, not compute. The accelerator's bare
``synchronize()`` (no tensors) only round-trips a tiny transfer, which
does NOT wait for enqueued compute — the reference's
``cuda.synchronize()`` has no cheap TPU analogue. Timers that wrap
jitted calls must therefore pass the call's outputs to
``stop(block_on=...)``, which ``jax.block_until_ready``-s them before
reading the clock. Construct the timer with ``barrier=True`` to make
that mandatory: a ``stop()`` without ``block_on`` then raises instead
of silently recording a dispatch time.

``SynchronizedWallClockTimer.publish(registry)`` drains every timer's
recorded intervals into ``timer/{name}_ms`` histograms on a
:class:`~deepspeed_tpu.telemetry.MetricsRegistry`.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from .logging import log_dist

FORWARD_MICRO_TIMER = "fwd_microstep"
FORWARD_GLOBAL_TIMER = "fwd"
BACKWARD_MICRO_TIMER = "bwd_microstep"
BACKWARD_GLOBAL_TIMER = "bwd"
STEP_MICRO_TIMER = "step_microstep"
STEP_GLOBAL_TIMER = "step"
TRAIN_BATCH_TIMER = "train_batch"


class SynchronizedWallClockTimer:
    class Timer:
        def __init__(self, name: str, barrier: bool = False):
            self.name_ = name
            self.barrier = barrier
            self.elapsed_ = 0.0
            self.started_ = False
            self.start_time = 0.0
            self.records: List[float] = []

        def _sync(self):
            from ..accelerator import get_accelerator

            try:
                get_accelerator().synchronize()
            except Exception:
                pass

        def start(self):
            assert not self.started_, f"{self.name_} timer has already been started"
            self._sync()
            self.start_time = time.time()
            self.started_ = True

        def stop(self, reset: bool = False, record: bool = True,
                 block_on=None):
            """Stop the timer. ``block_on`` takes the jitted call's
            outputs (any pytree) and waits for them to actually exist
            before reading the clock — without it, async dispatch makes
            the recorded interval a dispatch time (see module doc).
            ``barrier=True`` timers refuse to record without it."""
            assert self.started_, "timer is not started"
            if block_on is not None:
                import jax

                jax.block_until_ready(block_on)
            elif self.barrier and record:
                raise RuntimeError(
                    f"timer '{self.name_}' was constructed with "
                    f"barrier=True: stop() needs block_on=<jitted "
                    f"outputs>, otherwise it times dispatch, not compute")
            else:
                self._sync()
            elapsed = time.time() - self.start_time
            if reset:
                self.elapsed_ = elapsed
            else:
                self.elapsed_ += elapsed
            if record:
                self.records.append(elapsed * 1000.0)
            self.started_ = False

        def reset(self):
            self.elapsed_ = 0.0
            self.started_ = False

        def elapsed(self, reset: bool = True) -> float:
            started = self.started_
            if started:
                self.stop(record=False)
            elapsed = self.elapsed_
            if reset:
                self.reset()
            if started:
                self.start()
            return elapsed

        def mean(self) -> float:
            return sum(self.records) / len(self.records) if self.records else 0.0

    def __init__(self):
        self.timers: Dict[str, SynchronizedWallClockTimer.Timer] = {}

    def __call__(self, name: str,
                 barrier: bool = False) -> "SynchronizedWallClockTimer.Timer":
        if name not in self.timers:
            self.timers[name] = self.Timer(name, barrier=barrier)
        return self.timers[name]

    def publish(self, registry, clear: bool = True) -> int:
        """Drain every timer's recorded intervals into ``timer/{name}_ms``
        histograms on a telemetry ``MetricsRegistry``; returns the number
        of observations moved (drained so repeat publishes never
        double-count)."""
        moved = 0
        for name, timer in self.timers.items():
            if not timer.records:
                continue
            hist = registry.histogram(f"timer/{name}_ms")
            for ms in timer.records:
                hist.observe(ms)
            moved += len(timer.records)
            if clear:
                del timer.records[:]
        return moved

    def log(self, names: List[str], normalizer: float = 1.0, reset: bool = True,
            memory_breakdown: bool = False, ranks: Optional[List[int]] = None):
        assert normalizer > 0.0
        string = "time (ms)"
        for name in names:
            if name in self.timers:
                elapsed = self.timers[name].elapsed(reset=reset) * 1000.0 / normalizer
                string += f" | {name}: {elapsed:.2f}"
        log_dist(string, ranks=ranks or [0])

    def get_mean(self, names: List[str], normalizer: float = 1.0, reset: bool = True):
        assert normalizer > 0.0
        return {name: self.timers[name].mean() / normalizer
                for name in names if name in self.timers}


class ThroughputTimer:
    """samples/sec tracker (reference utils/timer.py:153)."""

    def __init__(self, batch_size: int, start_step: int = 2, steps_per_output: int = 50,
                 monitor_memory: bool = False, logging_fn=None):
        self.start_time = 0.0
        self.end_time = 0.0
        self.started = False
        self.batch_size = max(batch_size, 1)
        self.start_step = start_step
        self.epoch_count = 0
        self.micro_step_count = 0
        self.global_step_count = 0
        self.total_elapsed_time = 0.0
        self.step_elapsed_time = 0.0
        self.steps_per_output = steps_per_output
        self.monitor_memory = monitor_memory
        self.logging = logging_fn or (lambda msg: log_dist(msg, ranks=[0]))
        self.initialized = False

    def update_epoch_count(self):
        self.epoch_count += 1
        self.micro_step_count = 0

    def _init_timer(self):
        self.initialized = True

    def start(self):
        self._init_timer()
        self.started = True
        if self.global_step_count >= self.start_step:
            from ..accelerator import get_accelerator

            try:
                get_accelerator().synchronize()
            except Exception:
                pass
            self.start_time = time.time()

    def stop(self, global_step: bool = False, report_speed: bool = True,
             block_on=None):
        """``block_on`` — the step's jitted outputs; waits for compute to
        finish before reading the clock (see module doc on dispatch)."""
        if not self.started:
            return
        self.started = False
        self.micro_step_count += 1
        if global_step:
            self.global_step_count += 1
        if self.start_time > 0 and self.global_step_count > self.start_step:
            if block_on is not None:
                import jax

                jax.block_until_ready(block_on)
            else:
                from ..accelerator import get_accelerator

                try:
                    get_accelerator().synchronize()
                except Exception:
                    pass
            self.end_time = time.time()
            duration = self.end_time - self.start_time
            self.total_elapsed_time += duration
            self.step_elapsed_time += duration
            if global_step and report_speed and \
                    self.global_step_count % self.steps_per_output == 0:
                self.logging(
                    f"epoch={self.epoch_count}/micro_step={self.micro_step_count}/"
                    f"global_step={self.global_step_count}, "
                    f"RunningAvgSamplesPerSec={self.avg_samples_per_sec():.2f}, "
                    f"CurrSamplesPerSec={self.batch_size / duration:.2f}")
                self.step_elapsed_time = 0.0

    def avg_samples_per_sec(self) -> float:
        if self.global_step_count > self.start_step and self.total_elapsed_time > 0:
            samples = self.batch_size * (self.global_step_count - self.start_step)
            return samples / self.total_elapsed_time
        return 0.0
