"""Shared byte-packing for layer streaming (ZeRO-Inference + param offload).

One transformer layer's param tree travels host↔device as ONE contiguous
byte buffer: per-transfer latency (host↔device link round-trips) would
otherwise dominate the stream for trees with many small leaves. Leaves are
re-sliced on device by a traced bitcast unpack (an HBM-local copy).

Used by ``inference/zero_inference.py`` (serving stream) and
``runtime/zero/param_offload.py`` (training stream) — the wire-dtype rule,
packing and unpack must stay byte-identical between them, which is why
they live here.
"""

from __future__ import annotations

from typing import Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np


class LayerWireFormat:
    """Leaf metadata + pack/unpack for one layer's param tree.

    ``keep_dtype`` (path, leaf) -> bool: leaves that keep their storage
    dtype on the wire (e.g. quantization "scale" rows); every other float
    leaf converts to ``compute_dtype``; non-floats always keep storage.
    """

    def __init__(self, layer_tree, compute_dtype,
                 keep_dtype: Optional[Callable] = None):
        self.compute_dtype = np.dtype(compute_dtype)
        leaves_wp, self.treedef = \
            jax.tree_util.tree_flatten_with_path(layer_tree)

        def wire_dtype(path, leaf):
            d = np.asarray(leaf).dtype
            if not jnp.issubdtype(d, jnp.floating):
                return d
            if keep_dtype is not None and keep_dtype(path, leaf):
                return d
            return self.compute_dtype

        self.shapes: List[tuple] = [np.shape(l) for _, l in leaves_wp]
        self.wire_dtypes = [wire_dtype(p, l) for p, l in leaves_wp]
        self.nbytes = [int(np.prod(s)) * d.itemsize
                       for s, d in zip(self.shapes, self.wire_dtypes)]
        self.total_nbytes = sum(self.nbytes)

    @property
    def uniform_dtype(self) -> Optional[np.dtype]:
        """The single wire dtype when every leaf shares one (the training
        stream: all params ride as compute dtype), else None. Uniform
        layers should ship as a TYPED buffer and unpack with plain
        slice+reshape — the byte-path's ``u8[N, itemsize]`` bitcast
        reshape is padded to the 128-lane tile on real TPUs (observed 64x
        HBM blowup at compile on a 0.5 GB layer)."""
        first = self.wire_dtypes[0] if self.wire_dtypes else None
        for d in self.wire_dtypes:
            if d != first:
                return None
        return first

    def unpack_typed(self, flat):
        """Traced: (total_elems,) uniform-dtype buffer -> leaf tree via
        slice+reshape (no bitcast, no tiling pathologies)."""
        itemsize = self.uniform_dtype.itemsize
        offs, leaves = 0, []
        for shape, nb in zip(self.shapes, self.nbytes):
            n = nb // itemsize
            leaves.append(flat[offs:offs + n].reshape(shape))
            offs += n
        return jax.tree_util.tree_unflatten(self.treedef, leaves)

    def pack_into(self, layer_tree, buf: np.ndarray) -> None:
        """Host: flatten + convert + concatenate into ``buf`` (uint8)."""
        leaves = jax.tree_util.tree_leaves(layer_tree)
        offs = 0
        for leaf, wdt, nb in zip(leaves, self.wire_dtypes, self.nbytes):
            buf[offs:offs + nb] = \
                np.asarray(leaf, wdt).reshape(-1).view(np.uint8)
            offs += nb

    def unpack(self, flat):
        """Traced: packed byte buffer -> leaf tree (HBM-local bitcasts)."""
        offs, leaves = 0, []
        for shape, wdt, nb in zip(self.shapes, self.wire_dtypes,
                                  self.nbytes):
            seg = flat[offs:offs + nb]
            jdt = jnp.dtype(wdt)
            if jdt.itemsize > 1:
                seg = jax.lax.bitcast_convert_type(
                    seg.reshape(-1, jdt.itemsize), jdt)
            else:
                seg = jax.lax.bitcast_convert_type(seg, jdt)
            leaves.append(seg.reshape(shape))
            offs += nb
        return jax.tree_util.tree_unflatten(self.treedef, leaves)

    def unpack_host(self, buf: np.ndarray):
        """Host-side inverse of :meth:`pack_into` (checkpoint reads)."""
        offs, out = 0, []
        for shape, wdt, nb in zip(self.shapes, self.wire_dtypes,
                                  self.nbytes):
            out.append(buf[offs:offs + nb].view(wdt).reshape(shape).copy())
            offs += nb
        return jax.tree_util.tree_unflatten(self.treedef, out)
