"""Deferred / device-targeted initialization context.

Capability parity with reference ``deepspeed/utils/init_on_device.py:12
OnDevice`` — construct a model "on meta" (shapes only, no memory) or on a
chosen device/dtype. JAX equivalents: ``device="meta"`` wraps
``jax.eval_shape`` (abstract init — the flax idiom for huge models whose
real params come from a checkpoint); a concrete device pins
``jax.default_device``.
"""

from __future__ import annotations

import contextlib
from typing import Any, Optional


class OnDevice:
    """with OnDevice(dtype=jnp.bfloat16, device="meta"): params = init(...)

    * ``device="meta"`` — exposes :meth:`abstract_init`; inside the context
      ``init(module, *args)`` returns shape/dtype structs with zero memory.
    * other device — params created inside land on that device.
    """

    _active: Optional["OnDevice"] = None

    def __init__(self, dtype=None, device: Any = "meta", enabled: bool = True):
        self.dtype = dtype
        self.device = device
        self.enabled = enabled
        self._ctx = None

    def __enter__(self):
        OnDevice._active = self
        if self.enabled and self.device not in (None, "meta"):
            import jax

            self._ctx = jax.default_device(self.device)
            self._ctx.__enter__()
        return self

    def __exit__(self, *exc):
        OnDevice._active = None
        if self._ctx is not None:
            self._ctx.__exit__(*exc)
            self._ctx = None
        return False

    # -- meta-mode helpers -------------------------------------------------
    def abstract_init(self, module, *args, rngs=None, **kwargs):
        """Shapes-only init (zero device memory) — usable to build
        shardings / checkpoint restore targets for models too big to
        materialize."""
        import jax

        rngs = rngs or {"params": jax.random.PRNGKey(0)}

        def go(*a, **kw):
            return module.init(rngs, *a, **kw)

        out = jax.eval_shape(go, *args, **kwargs)
        if self.dtype is not None:
            out = jax.tree_util.tree_map(
                lambda s: jax.ShapeDtypeStruct(
                    s.shape, self.dtype) if hasattr(s, "shape") else s, out)
        return out


def on_device_init(module, *args, dtype=None, **kwargs):
    """One-shot helper: abstract (meta) init of a flax module."""
    with OnDevice(dtype=dtype, device="meta") as ctx:
        return ctx.abstract_init(module, *args, **kwargs)
