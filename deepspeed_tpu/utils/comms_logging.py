"""Per-op communication latency/bandwidth records.

Capability parity with reference ``deepspeed/utils/comms_logging.py`` —
``CommsLogger`` (:61) and the algorithmic/bus bandwidth math (:28). Bandwidth
formulas are the standard collective-cost model: for an all-reduce over n
ranks, bus bytes = 2·(n-1)/n · size, etc.
"""

from __future__ import annotations

import math
from collections import defaultdict
from typing import Dict, List

from .logging import log_dist


def get_caller_func(frame_depth: int = 3) -> str:
    import sys

    return sys._getframe(frame_depth).f_code.co_name


def convert_size(size_bytes: float) -> str:
    if size_bytes == 0:
        return "0B"
    size_name = ("B", "KB", "MB", "GB", "TB", "PB")
    i = int(math.floor(math.log(size_bytes, 1024)))
    p = math.pow(1024, i)
    s = round(size_bytes / p, 2)
    return f"{s} {size_name[i]}"


def calc_bw_log(comm_op: str, size: int, duration: float, n: int) -> tuple:
    """(algbw, busbw) in Gbps. ``n`` = ranks participating."""
    duration = max(duration, 1e-9)
    if comm_op in ("all_to_all_single", "all_to_all"):
        tput = size / duration
        busbw = (size / duration) * ((n - 1) / n) if n > 1 else size / duration
    elif comm_op in ("all_gather", "all_gather_into_tensor", "reduce_scatter",
                     "reduce_scatter_tensor", "all_gather_object"):
        size *= n
        tput = size / duration
        busbw = (size / duration) * ((n - 1) / n) if n > 1 else size / duration
    elif comm_op in ("all_reduce", "inference_all_reduce"):
        tput = size * 2 / duration
        busbw = (size / duration) * (2 * (n - 1) / n) if n > 1 else size / duration
    else:  # send/recv/broadcast/reduce/barrier
        tput = size / duration
        busbw = tput
    # bytes/sec → Gbps
    return tput * 8 / 1e9, busbw * 8 / 1e9


class CommsLogger:
    def __init__(self, enabled: bool = False, prof_all: bool = True, prof_ops=None,
                 verbose: bool = False, debug: bool = False):
        self.enabled = enabled
        self.prof_all = prof_all
        self.prof_ops = prof_ops or []
        self.verbose = verbose
        self.debug = debug
        self.comms_dict: Dict[str, Dict[int, List]] = defaultdict(dict)

    def configure(self, comms_config) -> None:
        self.enabled = comms_config.comms_logger_enabled
        if self.enabled:
            self.verbose = comms_config.comms_logger.verbose
            self.debug = comms_config.comms_logger.debug
            self.prof_ops = comms_config.comms_logger.prof_ops
            self.prof_all = comms_config.comms_logger.prof_all

    def start_profiling_comms(self):
        self.enabled = True

    def stop_profiling_comms(self):
        self.enabled = False

    def append(self, raw_name: str, record_name: str, latency: float, msg_size: int,
               n_ranks: int) -> None:
        algbw, busbw = calc_bw_log(raw_name, msg_size, latency, n_ranks)
        if record_name in self.comms_dict:
            if msg_size in self.comms_dict[record_name]:
                self.comms_dict[record_name][msg_size][0] += 1
                self.comms_dict[record_name][msg_size][1].append(latency)
                self.comms_dict[record_name][msg_size][2].append(algbw)
                self.comms_dict[record_name][msg_size][3].append(busbw)
            else:
                self.comms_dict[record_name][msg_size] = [1, [latency], [algbw], [busbw]]
        else:
            self.comms_dict[record_name][msg_size] = [1, [latency], [algbw], [busbw]]
        if self.verbose:
            log_dist(
                f"comm op: {record_name} | time (ms): {latency * 1e3:.2f} | "
                f"msg size: {convert_size(msg_size)} | algbw (Gbps): {algbw:.2f} | "
                f"busbw (Gbps): {busbw:.2f}", [0])

    def log_all(self, print_log: bool = True, show_straggler: bool = False):
        from copy import deepcopy

        lines = [f"{'Comm. Op': <20}{'Message Size': <20}{'Count': <20}"
                 f"{'Total Latency(ms)': <20}{'Avg Latency(ms)': <20}"
                 f"{'tput_avg (Gbps)': <20}{'busbw_avg (Gbps)': <20}"]
        out = deepcopy(self.comms_dict)
        for record_name, entries in out.items():
            lines.append(record_name)
            for msg_size, vals in sorted(entries.items()):
                count, latencies, algbws, busbws = vals
                total_lat = sum(latencies)
                avg_lat = total_lat / count
                avg_algbw = sum(algbws) / count
                avg_busbw = sum(busbws) / count
                lines.append(
                    f"{' ': <20}{convert_size(msg_size): <20}{count: <20}"
                    f"{total_lat * 1e3: <20.2f}{avg_lat * 1e3: <20.2f}"
                    f"{avg_algbw: <20.2f}{avg_busbw: <20.2f}")
        summary = "\n".join(lines)
        if print_log:
            log_dist("\n" + summary, [0])
        return out
