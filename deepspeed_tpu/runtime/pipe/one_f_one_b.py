"""1F1B pipelined gradient executor.

Executes the ``TrainSchedule`` instruction stream (schedule.py:147; reference
``deepspeed/runtime/pipe/schedule.py:189-257`` and ``engine.py:1293
_exec_schedule``) as ONE compiled SPMD loop:

* Each *tick* of a ``lax.scan`` performs, on every stage simultaneously, one
  ForwardPass (of micro ``t - stage``) and one BackwardPass (of micro
  ``t - 2(S-1) + stage``) — the steady-state 1F1B interleave. Warmup
  (forwards only valid) and drain (backwards only valid) fall out of the
  micro-id validity masks; the reference expresses the same thing as
  per-stage instruction lists.
* SendActivation/RecvActivation = one ``jnp.roll`` (+1) of the stage-sharded
  activation buffer per tick; SendGrad/RecvGrad = one roll (−1) of the
  cotangent buffer. XLA lowers both to ``collective-permute`` between
  neighboring stages over the ``pipe`` mesh axis — the reference's
  ``p2p.send/recv`` without the tensor-meta handshake (shapes are static).
* BackwardPass is a manual ``jax.vjp`` of the stage's block chain at the
  SAVED stage input (the activation-checkpointed recompute the reference
  gets from pipelined activation checkpointing). Saved inputs live in a ring
  buffer of capacity 2S−1 (+1 scratch slot for masked writes) — the 1F1B
  memory signature: outstanding activations bounded by the stage depth, NOT
  by the number of micro-batches (GPipe autodiff transpose stores one carry
  per tick ⇒ linear in M).
* LoadMicroBatch/embedding (first stage) and head+loss (last stage) are
  differentiated per tick with vjps restricted to their param subtrees; the
  loss cotangent is seeded with the fp16 loss scale.
* ReduceTiedGrads: tied params (``tied_*``) are visible to both the embed
  and head subtrees; both vjp contributions accumulate into the same slot
  and GSPMD inserts the cross-stage reduction (reference engine.py:225).
* ReduceGrads/OptimizerStep happen in the engine after this function
  returns, exactly like the reference's final-step instructions.

Total ticks = M + 2(S−1): M steady-state ticks are fully utilized (one F
and one B each, both valid); the 2(S−1) ramp ticks carry masked work — the
pipeline bubble. See BASELINE.md for the measured bubble/memory tradeoff vs
the GPipe executor (kept as ``pipeline.schedule = "gpipe"``).
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp

from ...parallel import mesh as mesh_mod
from ...parallel.mesh import PIPE_AXIS


def forward_micro_ids(t, stage_ids, num_stages):
    """ForwardPass micro id per stage at tick ``t`` (invalid outside [0, M))."""
    del num_stages
    return t - stage_ids


def backward_micro_ids(t, stage_ids, num_stages):
    """BackwardPass micro id per stage at tick ``t``."""
    return t - 2 * (num_stages - 1) + stage_ids


def total_ticks(num_micro_batches, num_stages):
    return num_micro_batches + 2 * (num_stages - 1)


def _constrain_pipe(x, mb_dim: int = 1):
    """Pin dim 0 of a (S, ...) buffer to the pipe axis and the micro-batch
    dim to the batch axes, when a mesh is active."""
    if not mesh_mod.has_mesh():
        return x
    from jax.sharding import NamedSharding, PartitionSpec

    entries: list = [PIPE_AXIS] + [None] * (mb_dim - 1)
    if x.ndim > mb_dim:
        entries.append(tuple(mesh_mod.batch_axes()))
    sh = NamedSharding(mesh_mod.get_mesh(), PartitionSpec(*entries))
    return jax.lax.with_sharding_constraint(x, sh)


def make_1f1b_grads(module) -> Callable:
    """Build ``grads_fn(params, stacked_batch, rng, scale, deterministic)``
    returning ``(loss_sum, grads, n_valid_micros)`` for a PipelineModule.

    ``grads`` is the SUM over micro-batches of loss-scale-seeded gradients
    (the engine divides by ``scale * denom`` in finalize).
    """
    S = module.num_stages
    pre_specs, block_specs, post_specs = module._split_specs()
    spec0 = block_specs[0]
    n_local = len(block_specs) // S

    from .module import block_call_mode

    call_mode = block_call_mode(spec0.typename)
    block = spec0.build()

    def chain(stage_params, x, keys, deterministic):
        """Forward through one stage's n_local blocks (scan over leaf dim 0)."""

        def body(h, xs):
            layer_params, key = xs
            rngs = {"dropout": key, "gating": jax.random.fold_in(key, 1)}
            if call_mode == "decode_det":
                # inference-capable blocks (x, decode, deterministic, ...):
                # pin decode=False for training so the deterministic flag
                # can't land in the decode slot positionally
                h = block.apply({"params": layer_params}, h, False,
                                deterministic, rngs=rngs)
            elif call_mode == "det":
                h = block.apply({"params": layer_params}, h, deterministic,
                                rngs=rngs)
            else:
                h = block.apply({"params": layer_params}, h, rngs=rngs)
            if isinstance(h, tuple):
                h = h[0]  # (x, new_cache) blocks: drop the dead aux entry
            return h, None

        h, _ = jax.lax.scan(body, x, (stage_params, keys))
        return h

    from .module import PipelineModule  # avoid cycle at import time

    def _subtree(params, prefixes):
        return {k: v for k, v in params.items()
                if any(k.startswith(p) for p in prefixes)}

    def grads_fn(params, stacked_batch, rng, scale, deterministic=True):
        leaves = jax.tree_util.tree_leaves(stacked_batch)
        M = leaves[0].shape[0]
        R = 2 * S  # ring capacity: max outstanding = 2(S-1)+1 < 2S; +scratch

        blocks_params = params["pipe"]["blocks"]["block"]
        pre_sub = _subtree(params, ("pre_", "tied_"))
        post_sub = _subtree(params, ("post_", "tied_"))

        def merged(sub):
            rest = {k: jax.lax.stop_gradient(v) for k, v in params.items()
                    if k not in sub}
            return {**rest, **sub}

        def embed_fn(sub, micro):
            return module.apply({"params": merged(sub)}, micro,
                                method=PipelineModule._embed)

        def head_fn(sub, y, micro):
            return module.apply({"params": merged(sub)}, y, micro,
                                method=PipelineModule._head_loss)

        def micro_at(i):
            i = jnp.clip(i, 0, M - 1)
            return jax.tree_util.tree_map(
                lambda x: jax.lax.dynamic_index_in_dim(x, i, 0, keepdims=False),
                stacked_batch)

        # probe shapes with an abstract embed (no FLOPs at trace time)
        feat = jax.eval_shape(embed_fn, pre_sub, micro_at(0))
        zero_f32 = lambda t: jax.tree_util.tree_map(  # noqa: E731
            lambda p: jnp.zeros(p.shape, jnp.float32), t)

        stage_ids = jnp.arange(S)
        x_roll0 = _constrain_pipe(jnp.zeros((S,) + feat.shape, feat.dtype))
        g_roll0 = _constrain_pipe(jnp.zeros((S,) + feat.shape, jnp.float32))
        ring0 = _constrain_pipe(jnp.zeros((S, R + 1) + feat.shape, feat.dtype),
                                mb_dim=2)

        carry0 = dict(
            x_roll=x_roll0, g_roll=g_roll0, ring=ring0,
            d_blocks=zero_f32(blocks_params),
            d_pre=zero_f32(pre_sub), d_post=zero_f32(post_sub),
            loss_sum=jnp.zeros((), jnp.float32))

        def micro_keys(micro_ids):
            """Per-stage rng keys derived from (micro id, stage) — NOT the
            tick — so the backward recompute of micro m at stage s re-runs
            the exact stochastic branch (dropout, MoE gating noise) its
            forward took, ticks apart."""
            return jax.vmap(lambda s, m: jax.random.split(
                jax.random.fold_in(jax.random.fold_in(rng, jnp.clip(
                    m, 0, M - 1)), s), n_local))(stage_ids, micro_ids)

        def tick(carry, t):
            f_id = forward_micro_ids(t, stage_ids, S)
            b_id = backward_micro_ids(t, stage_ids, S)
            valid_f = (f_id >= 0) & (f_id < M)
            valid_b = (b_id >= 0) & (b_id < M)
            keys_f = micro_keys(f_id)
            keys_b = micro_keys(b_id)

            # -- LoadMicroBatch + stage-0 embed (recomputed in bwd below) --
            x0 = embed_fn(jax.lax.stop_gradient(pre_sub), micro_at(f_id[0]))
            x_in = carry["x_roll"].at[0].set(x0.astype(carry["x_roll"].dtype))

            # -- ForwardPass on every stage --
            y = jax.vmap(chain, in_axes=(0, 0, 0, None))(
                blocks_params, x_in, keys_f, deterministic)

            # save stage inputs for the backward recompute; masked ticks
            # write to the scratch slot R so live slots are never clobbered
            slot = jnp.where(valid_f, f_id % R, R)
            ring = jax.vmap(
                lambda ring_s, sl, xs: ring_s.at[sl].set(xs))(
                    carry["ring"], slot, x_in)

            # -- last stage: head + loss (+ seed cotangent with loss scale) --
            h_micro = micro_at(f_id[S - 1])
            loss, head_pull = jax.vjp(
                head_fn, post_sub, y[S - 1].astype(feat.dtype), h_micro)
            seed = jnp.where(valid_f[S - 1], scale, 0.0).astype(jnp.float32)
            d_post_t, g_last, _ = head_pull(seed.astype(loss.dtype))
            loss_sum = carry["loss_sum"] + jnp.where(
                valid_f[S - 1], loss.astype(jnp.float32), 0.0)

            # -- BackwardPass: vjp of the chain at the SAVED input --
            g_in = carry["g_roll"].at[S - 1].set(g_last.astype(jnp.float32))
            b_slot = jnp.where(valid_b, b_id % R, R)
            x_saved = jax.vmap(
                lambda ring_s, sl: jax.lax.dynamic_index_in_dim(
                    ring_s, sl, 0, keepdims=False))(ring, b_slot)

            def stage_bwd(sp, xs, g, ks):
                _, pull = jax.vjp(
                    lambda sp_, x_: chain(sp_, x_, ks, deterministic), sp, xs)
                dsp, dx = pull(g.astype(xs.dtype))
                return dsp, dx

            dsp, dx = jax.vmap(stage_bwd)(blocks_params, x_saved,
                                          g_in, keys_b)
            mask = valid_b.astype(jnp.float32)
            d_blocks = jax.tree_util.tree_map(
                lambda a, g: a + g.astype(jnp.float32)
                * mask.reshape((S,) + (1,) * (g.ndim - 1)),
                carry["d_blocks"], dsp)

            # -- stage 0: backward through the embed for this micro --
            g0 = dx[0].astype(jnp.float32) * mask[0]
            _, embed_pull = jax.vjp(
                lambda sub: embed_fn(sub, micro_at(b_id[0])), pre_sub)
            (d_pre_t,) = embed_pull(g0.astype(feat.dtype))
            d_pre = jax.tree_util.tree_map(
                lambda a, g: a + g.astype(jnp.float32), carry["d_pre"], d_pre_t)
            d_post = jax.tree_util.tree_map(
                lambda a, g: a + g.astype(jnp.float32),
                carry["d_post"], d_post_t)

            # -- SendActivation (+1) / SendGrad (−1) collective permutes --
            x_roll = _constrain_pipe(jnp.roll(y, 1, axis=0))
            g_roll = _constrain_pipe(jnp.roll(
                dx.astype(jnp.float32)
                * mask.reshape((S,) + (1,) * (dx.ndim - 1)), -1, axis=0))

            new_carry = dict(carry, x_roll=x_roll, g_roll=g_roll,
                             ring=_constrain_pipe(ring, mb_dim=2),
                             d_blocks=d_blocks,
                             d_pre=d_pre, d_post=d_post, loss_sum=loss_sum)
            return new_carry, None

        final, _ = jax.lax.scan(tick, carry0, jnp.arange(total_ticks(M, S)))

        # assemble the full gradient tree: blocks + pre/post/tied subtrees
        # (tied keys get contributions from BOTH embed and head vjps)
        grads = {}
        for k in params:
            if k == "pipe":
                grads[k] = {"blocks": {"block": final["d_blocks"]}}
            else:
                g_p = final["d_pre"].get(k)
                g_q = final["d_post"].get(k)
                if g_p is not None and g_q is not None:
                    grads[k] = jax.tree_util.tree_map(
                        lambda a, b: a + b, g_p, g_q)
                else:
                    grads[k] = g_p if g_p is not None else g_q
        return final["loss_sum"] / M, grads, float(M)

    return grads_fn
