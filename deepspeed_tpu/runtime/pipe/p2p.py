"""Stage-to-stage transfer primitives.

Capability parity with reference ``deepspeed/runtime/pipe/p2p.py`` (send/recv/
isend/irecv between adjacent stages, :23,30). On TPU there is no eager P2P:
stage transfer inside the compiled pipeline is ``jnp.roll`` on the
pipe-sharded buffer (→ XLA collective-permute; see module.py), and these
helpers provide the explicit-collective form for shard_map code paths.
"""

from __future__ import annotations

from jax import lax

from ...parallel.mesh import PIPE_AXIS


def _ring_perm(n: int, shift: int = 1):
    return [(i, (i + shift) % n) for i in range(n)]


def send_to_next_stage(x, num_stages: int):
    """Rotate activations one stage forward (stage i → i+1) inside a
    shard_map over the pipe axis (≅ p2p.send of activations)."""
    return lax.ppermute(x, PIPE_AXIS, _ring_perm(num_stages, 1))


def send_to_prev_stage(x, num_stages: int):
    """Rotate gradients one stage backward (stage i → i-1) — the transpose
    direction (≅ p2p.send of grads)."""
    return lax.ppermute(x, PIPE_AXIS, _ring_perm(num_stages, -1))


def can_send_recv() -> bool:
    return True
