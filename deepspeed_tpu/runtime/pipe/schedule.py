"""Pipeline instruction schedules.

Capability parity with reference ``deepspeed/runtime/pipe/schedule.py`` —
``PipeSchedule`` ABC, ``InferenceSchedule`` (:135), ``TrainSchedule`` 1F1B
(:189,197-257), ``DataParallelSchedule`` (:327) and the ``PipeInstruction``
vocabulary.

On TPU the *executed* schedule is a compiled SPMD loop. The default
executor (``one_f_one_b.py``) runs THIS ``TrainSchedule`` stream: per tick
each stage performs the schedule's ForwardPass and BackwardPass micro ids,
activations/cotangents move by collective-permute (Send/Recv instructions),
and conformance of the executed order against these streams is asserted in
``tests/unit/runtime/pipe/test_one_f_one_b.py``. The ``"gpipe"`` executor
(module.py) uses them as its tick-count specification only.
"""

from __future__ import annotations

from typing import List


class PipeInstruction:
    """Base instruction (≅ reference schedule.py PipeInstruction)."""

    def __init__(self, **kwargs):
        self.name = self.__class__.__name__
        self.kwargs = kwargs
        for k, v in kwargs.items():
            setattr(self, k, v)

    def __repr__(self):
        if self.kwargs:
            args = ", ".join(f"{k}={v}" for k, v in self.kwargs.items())
            return f"{self.name}({args})"
        return self.name

    def __eq__(self, other):
        return repr(self) == repr(other)


class OptimizerStep(PipeInstruction):
    pass


class ReduceGrads(PipeInstruction):
    pass


class ReduceTiedGrads(PipeInstruction):
    pass


class LoadMicroBatch(PipeInstruction):
    pass


class ForwardPass(PipeInstruction):
    pass


class BackwardPass(PipeInstruction):
    pass


class SendActivation(PipeInstruction):
    pass


class RecvActivation(PipeInstruction):
    pass


class SendGrad(PipeInstruction):
    pass


class RecvGrad(PipeInstruction):
    pass


class PipeSchedule:
    """Yields lists of instructions per step (≅ reference PipeSchedule)."""

    def __init__(self, micro_batches: int, stages: int, stage_id: int):
        self.micro_batches = micro_batches
        self.stages = stages
        self.stage_id = stage_id
        self.prev_stage = self.stage_id - 1
        self.next_stage = self.stage_id + 1

    def steps(self):
        raise NotImplementedError

    def num_pipe_buffers(self) -> int:
        return self.micro_batches

    @property
    def stage(self):
        return self.stage_id

    @property
    def num_stages(self):
        return self.stages

    @property
    def is_first_stage(self):
        return self.stage_id == 0

    @property
    def is_last_stage(self):
        return self.stage_id == self.stages - 1

    def _valid_micro_batch(self, micro_batch_id: int) -> bool:
        return 0 <= micro_batch_id < self.micro_batches

    def _valid_stage(self, stage_id: int) -> bool:
        return 0 <= stage_id < self.stages

    def __iter__(self):
        return iter(self.steps())


class InferenceSchedule(PipeSchedule):
    """Forward-only pipelining (≅ reference schedule.py:135)."""

    def steps(self):
        total_steps = self.micro_batches + self.stages - 1
        cmds_per_step = []
        for step_id in range(total_steps):
            micro_batch_id = step_id - self.stage_id
            cmds: List[PipeInstruction] = []
            if self._valid_micro_batch(micro_batch_id):
                if self.is_first_stage:
                    cmds.append(LoadMicroBatch(buffer_id=micro_batch_id % 2))
                else:
                    cmds.append(RecvActivation(buffer_id=micro_batch_id % 2))
                cmds.append(ForwardPass(buffer_id=micro_batch_id % 2))
                if not self.is_last_stage:
                    cmds.append(SendActivation(buffer_id=micro_batch_id % 2))
            cmds_per_step.append(cmds)
        return cmds_per_step

    def num_pipe_buffers(self) -> int:
        return 2


class TrainSchedule(PipeSchedule):
    """1F1B training schedule (≅ reference schedule.py:189).

    Steady state interleaves one forward with one backward per step; warmup
    fills the pipeline with forwards, cooldown drains with backwards.
    """

    def steps(self):
        total_steps = 2 * (self.micro_batches + self.stages - 1)
        cmds_per_step = []
        prev_micro_batch_id = -1
        for step_id in range(total_steps):
            micro_batch_id, is_forward = self._step_to_micro_batch(step_id)
            cmds: List[PipeInstruction] = []

            # exchange activations/grads with neighbors
            if self._valid_micro_batch(prev_micro_batch_id):
                if is_forward:
                    if self._valid_stage(self.prev_stage):
                        cmds.append(SendGrad(buffer_id=self._buffer_idx(
                            prev_micro_batch_id)))
                else:
                    if self._valid_stage(self.next_stage):
                        cmds.append(SendActivation(buffer_id=self._buffer_idx(
                            prev_micro_batch_id)))
            if self._valid_micro_batch(micro_batch_id):
                if is_forward:
                    if self._valid_stage(self.prev_stage):
                        cmds.append(RecvActivation(buffer_id=self._buffer_idx(
                            micro_batch_id)))
                else:
                    if self._valid_stage(self.next_stage):
                        cmds.append(RecvGrad(buffer_id=self._buffer_idx(
                            micro_batch_id)))

            # compute
            if self._valid_micro_batch(micro_batch_id):
                if is_forward:
                    if self.is_first_stage or self.is_last_stage:
                        cmds.append(LoadMicroBatch(buffer_id=self._buffer_idx(
                            micro_batch_id)))
                    cmds.append(ForwardPass(buffer_id=self._buffer_idx(
                        micro_batch_id)))
                else:
                    cmds.append(BackwardPass(buffer_id=self._buffer_idx(
                        micro_batch_id)))

            # last step: reduce + optimizer
            if step_id == total_steps - 1:
                cmds.append(ReduceTiedGrads())
                cmds.append(ReduceGrads())
                cmds.append(OptimizerStep())

            prev_micro_batch_id = micro_batch_id
            cmds_per_step.append(cmds)
        return cmds_per_step

    def _step_to_micro_batch(self, step_id):
        if _is_even(step_id) and _is_even(self.stage_id):
            micro_batch_id = self._even_step_forward_id(step_id)
            is_forward = True
        elif _is_odd(step_id) and _is_odd(self.stage_id):
            micro_batch_id = self._odd_step_forward_id(step_id)
            is_forward = True
        elif _is_even(step_id) and _is_odd(self.stage_id):
            micro_batch_id = self._even_step_backward_id(step_id)
            is_forward = False
        elif _is_odd(step_id) and _is_even(self.stage_id):
            micro_batch_id = self._odd_step_backward_id(step_id)
            is_forward = False
        else:
            raise AssertionError("unreachable")
        return micro_batch_id, is_forward

    def _even_step_forward_id(self, step_id):
        base = step_id // 2
        return int(base - self.stage_id // 2)

    def _odd_step_forward_id(self, step_id):
        base = (step_id - 1) // 2
        return int(base - self.stage_id // 2)

    def _even_step_backward_id(self, step_id):
        base = step_id // 2
        return int(base - self.stages + (self.stage_id + 1) // 2)

    def _odd_step_backward_id(self, step_id):
        base = ((step_id - 1) // 2) - self.stages + 1
        return int(base + self.stage_id // 2)

    def _buffer_idx(self, micro_batch_id):
        assert self._valid_micro_batch(micro_batch_id)
        return micro_batch_id % self.num_pipe_buffers()

    def num_pipe_buffers(self) -> int:
        buffers = min(self.stages - self.stage_id, self.micro_batches)
        return max(2, buffers)


class DataParallelSchedule(PipeSchedule):
    """Degenerate single-stage schedule (≅ reference schedule.py:327)."""

    def steps(self):
        cmds_per_step = []
        for step_id in range(self.micro_batches):
            cmds = [LoadMicroBatch(buffer_id=0), ForwardPass(buffer_id=0),
                    BackwardPass(buffer_id=0)]
            if step_id == self.micro_batches - 1:
                cmds.extend([ReduceGrads(), OptimizerStep()])
            cmds_per_step.append(cmds)
        return cmds_per_step

    def num_pipe_buffers(self) -> int:
        return 1


def _is_even(x: int) -> bool:
    return x % 2 == 0


def _is_odd(x: int) -> bool:
    return x % 2 != 0


# ----------------------------------------------------------------------
# planned-schedule tracing
# ----------------------------------------------------------------------
def schedule_trace(schedule_cls, micro_batches: int, stages: int,
                   tick_us: float = 100.0) -> dict:
    """Render a schedule's PLANNED instruction streams as a Chrome
    trace-event object: one track per stage, the tick index as a
    synthetic time axis (``tick_us`` fake µs per tick), one complete
    span per instruction (a tick with k instructions subdivides into k
    equal slices).

    Planned, not executed: the 1F1B executor compiles the whole stream
    into ONE ``lax.scan``, so there is no host-side instruction loop to
    instrument — per-tick wall times live inside XLA. The plan view is
    still the thing you stare at to understand bubble structure
    (warmup/steady/cooldown shape, send/recv pairing) and it is exactly
    what the executor runs (conformance is pinned by the 1F1B tests).
    """
    events = [{"name": "process_name", "ph": "M", "pid": 0, "tid": 0,
               "args": {"name": f"{schedule_cls.__name__} plan "
                                f"(mb={micro_batches}, stages={stages})"}}]
    for stage_id in range(stages):
        sched = schedule_cls(micro_batches, stages, stage_id)
        events.append({"name": "thread_name", "ph": "M", "pid": 0,
                       "tid": stage_id,
                       "args": {"name": f"stage {stage_id}"}})
        for tick, cmds in enumerate(sched.steps()):
            if not cmds:
                continue
            slot = tick_us / len(cmds)
            for j, cmd in enumerate(cmds):
                events.append({
                    "name": cmd.name, "ph": "X", "pid": 0, "tid": stage_id,
                    "ts": tick * tick_us + j * slot, "dur": slot,
                    "args": {"tick": tick, **cmd.kwargs}})
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"synthetic_time":
                          f"1 schedule tick = {tick_us:g} fake us"}}


def export_schedule_trace(schedule_cls, micro_batches: int, stages: int,
                          path: str, tick_us: float = 100.0) -> int:
    """Write :func:`schedule_trace` as Perfetto-loadable JSON; returns
    the event count."""
    import json

    trace = schedule_trace(schedule_cls, micro_batches, stages,
                           tick_us=tick_us)
    with open(path, "w") as f:
        json.dump(trace, f)
    return len(trace["traceEvents"])
