"""Pipeline-parallel module expression.

Capability parity with reference ``deepspeed/runtime/pipe/module.py`` —
``LayerSpec`` (:29), ``TiedLayerSpec`` (:76), ``PipelineModule`` (:85) with
layer partitioning (:353). TPU-native execution model:

The reference materializes only the local stage's layers per rank and moves
activations with P2P sends. Here the whole network lives as ONE parameter
tree: the homogeneous transformer blocks are stacked ``(S, L/S, ...)`` —
outer dim sharded over the ``pipe`` mesh axis (each stage stores only its
chunk) — and the microbatch loop rotates a stage-sharded activation buffer
with ``jnp.roll`` along the pipe-sharded dim, which XLA lowers to a
``collective-permute`` between neighboring stages (the reference's
``p2p.send/recv``, runtime/pipe/p2p.py). The whole GPipe loop (warmup +
steady state + drain = M + S - 1 ticks, matching ``TrainSchedule``'s
forward tick count) is inside the one compiled train step; the backward
schedule is the autodiff transpose (reverse collective-permutes), and
per-tick ``remat`` bounds activation memory like the reference's
activation-checkpointed pipeline.

Tied layers: ``TiedLayerSpec`` reuses one module instance (e.g. the
embedding used again as the LM head). Tied params are replicated across
``pipe`` and GSPMD sums their gradient contributions — the reference's
tied-weight allreduce (pipe/engine.py:225) is implicit.

Constraint: the repeated middle run of specs must be homogeneous (same
class/kwargs) with total count divisible by the stage count — the standard
LLM case. Heterogeneous pipelines raise with guidance.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ...parallel.mesh import PIPE_AXIS
from ...utils.logging import logger


class LayerSpec:
    """Deferred layer constructor (≅ reference module.py:29)."""

    def __init__(self, typename, *module_args, **module_kwargs):
        self.typename = typename
        self.module_args = module_args
        self.module_kwargs = module_kwargs
        if not issubclass(typename, nn.Module):
            raise RuntimeError("LayerSpec only supports flax nn.Module types")

    def build(self, name: Optional[str] = None) -> nn.Module:
        kwargs = dict(self.module_kwargs)
        if name is not None:
            kwargs["name"] = name
        return self.typename(*self.module_args, **kwargs)

    def signature(self) -> Tuple:
        return (self.typename, self.module_args, tuple(sorted(
            (k, repr(v)) for k, v in self.module_kwargs.items())))

    def __repr__(self):
        return f"LayerSpec({self.typename.__name__})"


class TiedLayerSpec(LayerSpec):
    """Layer whose params are shared across occurrences by key
    (≅ reference module.py:76)."""

    def __init__(self, key: str, typename, *module_args, forward_fn=None,
                 **module_kwargs):
        super().__init__(typename, *module_args, **module_kwargs)
        self.key = key
        self.forward_fn = forward_fn


def block_call_mode(typename: type) -> str:
    """How the pipeline executors invoke a block — shared by the GPipe and
    1F1B executors so both pass flags identically:

    * ``"decode_det"`` — ``__call__(self, x, decode, deterministic, ...)``,
      the inference-capable TransformerBlock family: executors pin
      ``decode=False`` (training) and thread ``deterministic`` into the
      right slot (passing it positionally would land in ``decode``).
    * ``"det"`` — ``__call__(self, x, deterministic)``: the flag is the
      second argument.
    * ``"plain"`` — ``__call__(self, x)``.
    """
    import inspect

    try:
        sig = inspect.signature(typename.__call__)
    except (TypeError, ValueError):
        return "plain"
    names = [p.name for p in sig.parameters.values()
             if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)]
    names = names[1:]  # drop self
    if "decode" in names and "deterministic" in names:
        return "decode_det"
    return "det" if len(names) >= 2 else "plain"


def block_passes_deterministic(typename: type) -> bool:
    """Back-compat shim for the old boolean call-mode probe."""
    return block_call_mode(typename) == "det"


def partition_balanced(weights: Sequence[float], num_parts: int) -> List[int]:
    """Split ``weights`` into ``num_parts`` contiguous chunks minimizing the
    max chunk weight (≅ reference ds_utils.partition_balanced used by
    PipelineModule._partition_layers). Returns part boundaries of length
    num_parts+1."""
    n = len(weights)
    prefix = [0.0]
    for w in weights:
        prefix.append(prefix[-1] + w)

    def parts_ok(limit: float) -> Optional[List[int]]:
        bounds = [0]
        start = 0
        for _ in range(num_parts):
            end = start
            while end < n and prefix[end + 1] - prefix[start] <= limit:
                end += 1
            if end == start:  # single item exceeds limit
                return None
            bounds.append(end)
            start = end
            if end == n:
                break
        if bounds[-1] != n:
            return None
        while len(bounds) < num_parts + 1:
            bounds.append(n)
        return bounds

    lo = max(weights) if weights else 0.0
    hi = prefix[-1]
    best = parts_ok(hi)
    for _ in range(50):
        mid = (lo + hi) / 2
        cand = parts_ok(mid)
        if cand is not None:
            best, hi = cand, mid
        else:
            lo = mid
    return best


class _PipeScanBody(nn.Module):
    """nn.scan body adapter: user blocks return x (or ``(x, aux)``); scan
    needs (carry, out)."""

    block_cls: type
    block_args: Tuple = ()
    block_kwargs: Tuple = ()  # sorted (key, value) pairs — hashable for flax
    remat: bool = True

    call_mode: str = "plain"  # see block_call_mode

    @nn.compact
    def __call__(self, x, deterministic=True):
        cls = self.block_cls
        if self.remat:
            static = {"det": (2,), "decode_det": (2, 3)}.get(self.call_mode, ())
            cls = nn.remat(cls, prevent_cse=False, static_argnums=static)
        block = cls(*self.block_args, **dict(self.block_kwargs), name="block")
        if self.call_mode == "decode_det":
            x = block(x, False, deterministic)
        elif self.call_mode == "det":
            x = block(x, deterministic)
        else:
            x = block(x)
        if isinstance(x, tuple):
            # inference-capable blocks return (x, new_cache); in training
            # (decode=False, no cache threaded) the aux entry is dead —
            # keep only the activation so the scan carry structure holds
            x = x[0]
        return x, None


class _PipeTick(nn.Module):
    """One pipeline tick: inject micro at stage 0, run every stage's local
    blocks, emit the last stage's output, rotate the buffer. Head/loss run
    at the PipelineModule level (keeps tied modules in one scope)."""

    block_cls: type
    block_args: Tuple = ()
    block_kwargs: Tuple = ()
    remat: bool = True
    num_stages: int = 1
    num_blocks: int = 1
    call_mode: str = "plain"

    def setup(self):
        L, S = self.num_blocks, self.num_stages
        inner = nn.scan(
            _PipeScanBody,
            variable_axes={"params": 0},
            split_rngs={"params": True, "dropout": True},
            length=L // S,
            in_axes=nn.broadcast,  # deterministic flag
            metadata_params={nn.PARTITION_NAME: "layers"},
        )
        self.blocks = nn.vmap(
            inner,
            variable_axes={"params": 0},
            split_rngs={"params": True, "dropout": True},
            in_axes=(0, None), out_axes=0,
            metadata_params={nn.PARTITION_NAME: PIPE_AXIS},
        )(block_cls=self.block_cls, block_args=self.block_args,
          block_kwargs=self.block_kwargs, remat=self.remat,
          call_mode=self.call_mode, name="blocks")

    def __call__(self, carry, t, embedded, deterministic):
        state = carry
        S = self.num_stages
        M = embedded.shape[0]
        inject = jax.lax.dynamic_index_in_dim(
            embedded, jnp.minimum(t, M - 1), axis=0, keepdims=False)
        x0 = jnp.where(t < M, inject, state[0])
        state = state.at[0].set(x0)
        y, _ = self.blocks(state, deterministic)  # (S, mb, ...) per stage
        state = jnp.roll(y, 1, axis=0)  # stage i output → stage i+1 input
        # emit last stage's output (valid for micro t-S+1 once t >= S-1)
        return state, y[S - 1]


class PipelineModule(nn.Module):
    """Express a model as a sequence of layers pipelined over stages.

    ``__call__(stacked_batch)`` consumes the micro-batch-stacked batch
    (leading dim = num_micro_batches) and returns the mean loss.

    Fields:
      layers: tuple of LayerSpec — [pre..., block×L (homogeneous), post...]
      loss_fn: (final_activations, micro_batch) -> scalar loss
      num_stages: pipe-parallel degree (must match the mesh's pipe axis)
      embed_fn_name: method on pre modules producing block input from batch
      activation_checkpoint_interval: remat the tick body when > 0
    """

    layers: Tuple
    loss_fn: Callable
    num_stages: int
    partition_method: str = "parameters"
    activation_checkpoint_interval: int = 1
    input_key: str = "input_ids"

    def _split_specs(self):
        specs = list(self.layers)
        sigs = [s.signature() for s in specs]
        # longest homogeneous run = the pipelined blocks
        best_start, best_len = 0, 0
        i = 0
        while i < len(specs):
            j = i
            while j < len(specs) and sigs[j] == sigs[i]:
                j += 1
            if j - i > best_len:
                best_start, best_len = i, j - i
            i = j
        if best_len < self.num_stages:
            raise ValueError(
                f"PipelineModule needs a homogeneous middle run of >= num_stages "
                f"({self.num_stages}) identical LayerSpecs to pipeline; got run of "
                f"{best_len}. Heterogeneous pipelines are not supported by the "
                f"SPMD executor — make the repeated block a single module class.")
        if best_len % self.num_stages != 0:
            raise ValueError(
                f"block count {best_len} not divisible by num_stages "
                f"{self.num_stages}")
        return (specs[:best_start], specs[best_start:best_start + best_len],
                specs[best_start + best_len:])

    def setup(self):
        pre_specs, block_specs, post_specs = self._split_specs()
        tied: Dict[str, nn.Module] = {}  # local: flax freezes dict attributes

        def build(spec, idx, where):
            if isinstance(spec, TiedLayerSpec):
                if spec.key not in tied:
                    tied[spec.key] = spec.build(name=f"tied_{spec.key}")
                return tied[spec.key]
            return spec.build(name=f"{where}_{idx}")

        self.pre_layers = [build(s, i, "pre") for i, s in enumerate(pre_specs)]
        self.post_layers = [build(s, i, "post") for i, s in enumerate(post_specs)]
        self._post_specs = tuple(post_specs)

        spec0 = block_specs[0]
        call_mode = block_call_mode(spec0.typename)
        # lifted scan over ticks: params broadcast across iterations
        self.ticks = nn.scan(
            _PipeTick,
            variable_broadcast="params",
            split_rngs={"params": False, "dropout": True},
            in_axes=(0, nn.broadcast, nn.broadcast),
            out_axes=0,
        )(block_cls=spec0.typename, block_args=spec0.module_args,
          block_kwargs=tuple(sorted(spec0.module_kwargs.items())),
          remat=bool(self.activation_checkpoint_interval),
          num_stages=self.num_stages, num_blocks=len(block_specs),
          call_mode=call_mode, name="pipe")
        self._num_blocks = len(block_specs)

    def _embed(self, micro_batch):
        x = micro_batch
        for layer in self.pre_layers:
            x = layer(x)
        return x

    def _head_loss(self, x, micro_batch):
        for spec, layer in zip(self._post_specs, self.post_layers):
            fwd = getattr(spec, "forward_fn", None)
            x = fwd(layer, x) if fwd is not None else layer(x)
        return self.loss_fn(x, micro_batch)

    def __call__(self, stacked_batch, deterministic: bool = True):
        S = self.num_stages
        leaves = jax.tree_util.tree_leaves(stacked_batch)
        M = leaves[0].shape[0]

        def micro_at(i):
            return jax.tree_util.tree_map(lambda x: x[i], stacked_batch)

        # embed all micros up front (pre params replicated over pipe; this
        # compute is tiny vs the blocks and keeps the tick body homogeneous).
        # Unrolled per-micro rather than jax.vmap'd: submodule calls inside a
        # raw jax transform trip flax's trace-level check (JaxTransformError)
        # — the lifted-transform rule; M is small and static so unrolling is
        # the simplest legal form
        embedded = jnp.stack([self._embed(micro_at(i)) for i in range(M)])
        feat_shape = embedded.shape[1:]

        state0 = jnp.zeros((S,) + feat_shape, embedded.dtype)
        ts = jnp.arange(M + S - 1)
        _, ys = self.ticks(state0, ts, embedded, deterministic)
        # last stage emits micro m's output at tick m + S - 1
        outputs = ys[S - 1:]  # (M, mb, ...)

        # head + loss at module level: tied modules (e.g. embedding reused as
        # LM head via TiedLayerSpec.forward_fn) share one scope here
        losses = jnp.stack([self._head_loss(outputs[i], micro_at(i))
                            for i in range(M)])
        return jnp.mean(losses)

    def num_pipeline_ticks(self, num_micro_batches: int) -> int:
        """forward ticks per global step = M + S - 1 (matches
        InferenceSchedule's step count for the same M, S)."""
        return num_micro_batches + self.num_stages - 1


def pipe_sharding_rules():
    """Sharding rule placing the stacked block params on the pipe axis
    (dim 0 = stage). Specs are padded with None to each param's rank."""
    return [(r"blocks/", (PIPE_AXIS,))]
