"""Pipeline-parallel training engine.

Capability parity with reference ``deepspeed/runtime/pipe/engine.py:42
PipelineEngine``: ``train_batch``/``eval_batch`` over micro-batch schedules,
DP gradient reduction, tied-weight grads, ZeRO-composition rules. Two
executors, selected by ``pipeline.schedule``:

* ``"1f1b"`` (default) — ``one_f_one_b.make_1f1b_grads`` executes the
  ``TrainSchedule`` instruction stream (reference engine.py:1293
  ``_exec_schedule``) as a compiled tick loop with interleaved fwd/bwd and
  a constant-in-M activation ring; conformance is asserted against the
  schedule in ``tests/unit/runtime/pipe/test_one_f_one_b.py``.
* ``"gpipe"`` — the compiled SPMD forward roll in
  ``PipelineModule.__call__`` with the autodiff transpose as backward.

Differences from the reference, by construction:
* activation sends/recvs = collective-permutes emitted from ``jnp.roll`` on
  the pipe-sharded buffer; the tensor-meta handshake (engine.py:795) is
  unnecessary (shapes are static under jit);
* tied-weight grad allreduce (engine.py:225) is implicit (tied params are
  replicated over pipe, GSPMD sums contributions);
* DP grad reduction / ZeRO sharding compose exactly as in the base engine
  (the pipe axis is just another mesh axis to the ZeRO policy).

The reference restricts ZeRO to stage<=1 under pipelining (engine.py:1386);
here stage 1 is the recommended pairing and stages 2/3 are permitted but
warned (grads/params shard over data while flowing through the pipe loop).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...parallel import mesh as mesh_mod
from ...utils.logging import log_dist, logger
from ..engine import DeepSpeedEngine
from ..zero.policy import ShardingRules
from .module import PipelineModule, pipe_sharding_rules


class PipelineEngine(DeepSpeedEngine):
    def __init__(self, model: PipelineModule, config=None, model_parameters=None,
                 training_data=None, lr_scheduler=None, collate_fn=None, mesh=None,
                 sharding_rules=None, **kwargs):
        assert isinstance(model, PipelineModule), \
            "PipelineEngine requires a PipelineModule (reference parity)"
        self.num_stages = model.num_stages

        if mesh is None and not mesh_mod.has_mesh():
            cfg_mesh = (config.get("mesh", {}) if isinstance(config, dict) else {})
            mesh = mesh_mod.initialize_mesh(
                data=cfg_mesh.get("data", -1), model=cfg_mesh.get("model", 1),
                pipe=self.num_stages, expert=cfg_mesh.get("expert", 1),
                seq=cfg_mesh.get("seq", 1))

        rules = list(pipe_sharding_rules())
        if sharding_rules is not None:
            rules = list(getattr(sharding_rules, "raw_rules", [])) + rules
        merged_rules = ShardingRules(rules)

        super().__init__(model=model, config=config, model_parameters=model_parameters,
                         training_data=training_data, lr_scheduler=lr_scheduler,
                         collate_fn=collate_fn, mesh=mesh,
                         sharding_rules=merged_rules, **kwargs)

        pipe_world = mesh_mod.get_pipe_parallel_world_size()
        assert pipe_world == self.num_stages, (
            f"mesh pipe axis ({pipe_world}) != PipelineModule.num_stages "
            f"({self.num_stages})")
        if self.zero_optimization_stage() > 1:
            logger.warning(
                "ZeRO stage>1 with pipeline parallelism: supported by the GSPMD "
                "formulation but the reference restricts to stage<=1; validate "
                "memory/perf for your config")
        self.micro_batches = self.gradient_accumulation_steps()
        log_dist(f"PipelineEngine: stages={self.num_stages} "
                 f"micro_batches={self.micro_batches}", ranks=[0])

    # the pipelined loss consumes ALL micro-batches in one call
    def _make_grads_fn(self, micro_grads, constrain_grads, scale_value, gas):
        schedule = self._config.pipeline.schedule
        if schedule == "1f1b" and self._user_loss_fn:
            # the 1F1B executor differentiates PipelineModule.loss_fn at the
            # last stage; a user-supplied whole-model loss_fn only composes
            # with the autodiff (gpipe) executor
            logger.warning(
                "pipeline.schedule=1f1b ignores a user-supplied loss_fn; "
                "falling back to the gpipe executor (set PipelineModule."
                "loss_fn to use 1f1b)")
            schedule = "gpipe"
        if schedule == "1f1b":
            from .one_f_one_b import make_1f1b_grads

            pipe_grads = make_1f1b_grads(self.module)

            def grads_fn(state, stacked_batch):
                params = state["params"]
                scale = scale_value(state)
                rng = jax.random.fold_in(state["rng"], state["step"])
                loss, grads, denom = pipe_grads(params, stacked_batch, rng,
                                                scale)
                grads = constrain_grads(grads, params)
                return loss, grads, denom

            return grads_fn

        assert schedule == "gpipe", \
            f"unknown pipeline.schedule {schedule!r} (1f1b | gpipe)"
        loss_fn = self._loss_fn

        def grads_fn(state, stacked_batch):
            params = state["params"]
            scale = scale_value(state)
            rng = jax.random.fold_in(state["rng"], state["step"])

            def scaled_loss(p):
                loss = loss_fn(p, stacked_batch, rng)
                # seed with scale*gas so grads follow the engine-wide
                # SUM-over-micros convention (denom = gas) — keeps the
                # prescale_gradients branch identical across executors
                return (loss * scale * gas).astype(jnp.float32), loss

            grads, loss = jax.grad(scaled_loss, has_aux=True)(params)
            grads = constrain_grads(grads, params)
            return loss, grads, float(gas)

        return grads_fn

    def _init_params_from_batch(self, batch):
        if self._params_host is not None:
            return self._params_host
        rng = jax.random.PRNGKey(self._rng_seed)
        # pipeline module consumes (M, mb, ...); init with M=1
        stacked = jax.tree_util.tree_map(lambda x: np.asarray(x)[None], batch)
        variables = self.module.init({"params": rng, "dropout": rng}, stacked)
        return variables["params"]

    # --- reference parity: PipelineEngine only supports train/eval batch ---
    def forward(self, *args, **kwargs):
        raise RuntimeError(
            "PipelineEngine does not support forward(); use train_batch() / "
            "eval_batch() (reference pipe/engine.py parity)")

    __call__ = forward

    def backward(self, *args, **kwargs):
        raise RuntimeError(
            "PipelineEngine does not support backward(); use train_batch()")

    def step(self, *args, **kwargs):
        raise RuntimeError(
            "PipelineEngine does not support step(); use train_batch()")

    def is_gradient_accumulation_boundary(self) -> bool:
        return True

    def eval_batch(self, data_iter=None, batch=None):
        """Forward-only pipelined evaluation (≅ reference eval_batch)."""
        if data_iter is None and batch is None and self.training_dataloader is not None:
            data_iter = iter(self.training_dataloader)
        source = data_iter if data_iter is not None else batch
        stacked = self._stack_micro_batches(source)
        if self.state is None:
            first = jax.tree_util.tree_map(lambda x: x[0], stacked)
            self._build_state(self._init_params_from_batch(first))
        if not hasattr(self, "_jit_eval"):
            self._jit_eval = self.eval_batch_fn()
        return self._jit_eval(self.state["params"], stacked)
