"""Inference weight quantization.

Capability parity with reference ``deepspeed/runtime/weight_quantizer.py``
(``WeightQuantization``, 153 LoC) — an OFFLINE utility that quantizes a
model state dict for int8 storage/transport: per-group symmetric scales,
int8 values, and the matching dequantize. Host-side numpy by design (it
runs on checkpoints, not on device); serve by dequantizing at load
(``dequantize_state_dict``) and passing the restored weights to
``init_inference`` — on TPU the bf16/fp32 matmul then runs as usual
(native int8 matmul serving is future work, not claimed here).
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import numpy as np


class WeightQuantization:
    def __init__(self, mlp_extra_grouping: bool = False,
                 quantize_groups: int = 1, num_bits: int = 8):
        self.mlp_extra_grouping = mlp_extra_grouping
        self.quantize_groups = quantize_groups
        self.num_bits = num_bits

    def _groups_for(self, key: str) -> int:
        if self.mlp_extra_grouping and ("mlp" in key or "fc" in key):
            return self.quantize_groups * 2
        return self.quantize_groups

    def quantize_value(self, value: np.ndarray,
                       groups: int) -> Tuple[np.ndarray, np.ndarray]:
        """Returns (int8 values, fp32 per-group scales)."""
        v = np.asarray(value, np.float32)
        flat = v.reshape(groups, -1)
        q_range = 2 ** (self.num_bits - 1) - 1
        scales = np.abs(flat).max(axis=1, keepdims=True) / q_range
        scales = np.where(scales == 0, 1.0, scales)
        q = np.clip(np.round(flat / scales), -q_range - 1,
                    q_range).astype(np.int8)
        return q.reshape(v.shape), scales.astype(np.float32)

    @staticmethod
    def dequantize_value(q: np.ndarray, scales: np.ndarray) -> np.ndarray:
        groups = scales.shape[0]
        flat = q.astype(np.float32).reshape(groups, -1) * scales
        return flat.reshape(q.shape)

    def quantize_state_dict(self, sd: Dict[str, Any]
                            ) -> Tuple[Dict[str, Any], Dict[str, np.ndarray]]:
        """Quantize every matrix-valued entry; returns (quantized sd,
        {key: scales}) — the reference's (sd, all_scales) shape."""
        out: Dict[str, Any] = {}
        all_scales: Dict[str, np.ndarray] = {}
        for key, value in sd.items():
            if np.ndim(value) >= 2 and np.issubdtype(
                    np.asarray(value).dtype, np.floating):
                groups = self._groups_for(key)
                if np.asarray(value).size % groups != 0:
                    out[key] = value
                    continue
                q, scales = self.quantize_value(value, groups)
                out[key] = q
                all_scales[key] = scales
            else:
                out[key] = value
        return out, all_scales

    @staticmethod
    def dequantize_state_dict(sd: Dict[str, Any],
                              all_scales: Dict[str, np.ndarray],
                              dtype=np.float32) -> Dict[str, Any]:
        out = {}
        for key, value in sd.items():
            if key in all_scales:
                out[key] = WeightQuantization.dequantize_value(
                    value, all_scales[key]).astype(dtype)
            else:
                out[key] = value
        return out
