"""Typed-config base machinery.

Capability parity with reference ``deepspeed/runtime/config_utils.py`` —
``DeepSpeedConfigModel`` (:16) with deprecated-field aliasing/migration (:59),
``pp_int`` pretty-printed ints (:120), scientific-notation printing (:139) —
written against pydantic v2 (the reference targets v1).

Deprecated fields are declared with ``Field(json_schema_extra={"deprecated":
True, "new_param": "x.y"})``; on load the old value is migrated onto the new
field and a warning is emitted.
"""

from __future__ import annotations

from functools import reduce
from typing import Any, Dict

from pydantic import BaseModel, ConfigDict, model_validator

from ..utils.logging import logger


class DeepSpeedConfigModel(BaseModel):
    """Base for all config blocks.

    Accepts the string ``"auto"`` for any field (resolved later by the
    autotuner / batch reconciliation), mirroring the reference behavior.
    """

    model_config = ConfigDict(
        validate_default=True,
        validate_assignment=True,
        use_enum_values=True,
        populate_by_name=True,
        extra="allow",
        protected_namespaces=(),
        arbitrary_types_allowed=True,
    )

    def __init__(self, strict: bool = False, **data):
        if not strict:  # This is temporary until we refactor all DS configs
            data = {k: v for k, v in data.items() if (v != "auto" or k == "replace_method")}
        super().__init__(**data)

    @model_validator(mode="before")
    @classmethod
    def _migrate_deprecated(cls, values: Any) -> Any:
        if not isinstance(values, dict):
            return values
        for name, field in cls.model_fields.items():
            extra = field.json_schema_extra or {}
            if not isinstance(extra, dict) or not extra.get("deprecated"):
                continue
            key = field.alias or name
            if key not in values:
                continue
            # assignment re-validation passes current field values back in;
            # only a non-default value signals actual user intent
            if values[key] == field.default:
                continue
            new_param = extra.get("new_param", "")
            logger.warning(f"Config parameter {key} is deprecated" +
                           (f", use {new_param} instead" if new_param else ""))
            if new_param and extra.get("set_new_param", True):
                # dotted path: write the old value into the nested new field
                parts = new_param.split(".")
                tgt = values
                for p in parts[:-1]:
                    tgt = tgt.setdefault(p, {})
                if parts[-1] not in tgt:
                    new_value_fn = extra.get("new_param_fn", lambda x: x)
                    tgt[parts[-1]] = new_value_fn(values[key])
        return values

    def get(self, key, default=None):
        return getattr(self, key, default)

    def __getitem__(self, key):
        return getattr(self, key)


def get_scalar_param(param_dict: Dict, param_name: str, param_default_value):
    return param_dict.get(param_name, param_default_value)


def get_list_param(param_dict: Dict, param_name: str, param_default_value):
    return param_dict.get(param_name, param_default_value)


def get_dict_param(param_dict: Dict, param_name: str, param_default_value):
    return param_dict.get(param_name, param_default_value)


def dict_raise_error_on_duplicate_keys(ordered_pairs):
    """json.load hook rejecting duplicate keys (reference config_utils)."""
    d = dict((k, v) for k, v in ordered_pairs)
    if len(d) != len(ordered_pairs):
        counter = {}
        for k, _ in ordered_pairs:
            counter[k] = counter.get(k, 0) + 1
        keys = [k for k, v in counter.items() if v > 1]
        raise ValueError(f"Duplicate keys in DeepSpeed config: {keys}")
    return d


class ScientificNotationEncoder:
    @staticmethod
    def fmt(x) -> str:
        if isinstance(x, (int, float)) and abs(x) >= 1e4:
            return f"{x:.3e}"
        return str(x)


def pp_int(x: int, comment: str = "") -> str:
    """Pretty-print large ints with thousands separators (reference :120)."""
    return f"{x:,}" + (f" ({comment})" if comment else "")


def get_nested(d: Dict, dotted: str, default=None):
    try:
        return reduce(lambda acc, k: acc[k], dotted.split("."), d)
    except (KeyError, TypeError):
        return default
