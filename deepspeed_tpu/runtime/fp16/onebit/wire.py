"""1-bit Adam with the compressed exchange ON the wire.

The dynamics-only optimizers in this package (``adam.py``/``lamb.py``)
reproduce the reference's error-feedback compression *math* under GSPMD,
where XLA moves dense fp32 gradients. This module is the wire-owning path
(≅ reference ``deepspeed/runtime/fp16/onebit/adam.py:13`` +
``runtime/comm/nccl.py:54 compressed_allreduce``): the engine's train step
runs under ``shard_map`` over the data axis, gradients stay RANK-LOCAL
(no automatic psum), and the cross-device exchange is:

* warmup (``opt_step < freeze_step``): one dense fp32 ``psum`` of the
  gradient — the reference's uncompressed warmup phase;
* compression stage: each rank folds its LOCAL gradient into the momentum
  and the momentum crosses the wire through
  ``runtime/comm/compressed.compressed_allreduce`` — BIT-PACKED uint8
  signs (8 signs/byte, the true 1-bit wire format; ``onebit_packing:
  "int8"`` keeps the one-sign-per-byte fallback) + fp32 per-chunk scales
  via all_to_all + all_gather, with persistent per-rank worker/server
  error feedback. The variance is frozen, exactly as the dynamics-only
  path freezes it.

Per-step logical wire volume (returned in metrics as ``comm_bytes``; the
test suite asserts the drop and that the packed collectives exist in
HLO): dense ring-allreduce moves ~2·4·N·(w-1)/w ≈ 8N bytes/rank; the
packed exchange moves N/8 uint8 (all_to_all) + N/8 uint8 (all_gather) +
scales ≈ N/4 — a ~32x reduction, matching the shape of the reference's
packed compression-phase claim (nccl.py:54-130).

Scope (mirrors the reference's own constraints for 1-bit optimizers):
data parallelism, optionally composed with tensor parallelism (the
reference's OneBitAdam runs under Megatron TP) — the exchange is manual
over the ``data`` mesh axis only (``shard_map(..., axis_names={data})``),
so the ``model`` axis stays a GSPMD *auto* axis: the model's own TP
sharding constraints keep working inside the step, TP-sharded gradients
stay sharded, and the packed collectives over ``data`` run independently
per model rank (each moves its shard of the wire). sp = pp = 1; ZeRO
stage 0 (replicated fp32 master) or stage 1 — stage 1 shards v + the
fp32 master over the data axis as ``onebit["v"]``/``onebit["master_flat"]``
rows and re-gathers bf16 params each step (no replicated master exists);
bf16 compute (no dynamic loss scale), no gradient clipping in the
compression stage.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.flatten_util
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from ....parallel import mesh as mesh_mod
from ...comm.compressed import compressed_allreduce

LANES = 128


def _supports_auto_axes() -> bool:
    """jax >= 0.9 shard_map takes ``axis_names`` (the set of MANUAL axes;
    every other mesh axis stays GSPMD-auto) — what lets the exchange be
    manual over ``data`` while TP sharding constraints keep working."""
    import inspect
    return "axis_names" in inspect.signature(shard_map).parameters


def is_enabled(config, mesh) -> bool:
    """comm_backend_name="compressed" in the optimizer params activates the
    wire path (reference config surface: onebit optimizers take
    comm_backend_name, e.g. "nccl"; here "compressed" = shard_map int8
    collectives, anything else = dynamics-only GSPMD)."""
    opt = config.optimizer
    if opt is None or opt.type is None:
        return False
    if opt.type.lower().replace("_", "") not in (
            "onebitadam", "onebitlamb", "zerooneadam"):
        return False
    return dict(opt.params or {}).get("comm_backend_name") == "compressed"


def check_supported(engine) -> None:
    if mesh_mod.get_sequence_parallel_world_size() > 1:
        raise ValueError("comm_backend_name=compressed does not compose "
                         "with sequence parallelism (sp=1); dp x tp only")
    if engine.mp_world_size != 1 and not _supports_auto_axes():
        raise ValueError("comm_backend_name=compressed with model "
                         "parallelism needs jax.shard_map axis_names "
                         "support (jax >= 0.9); this jax is older — "
                         "run with mp=1")
    if engine.dp_world_size < 2:
        raise ValueError("comm_backend_name=compressed needs dp_world > 1 "
                         "(single rank has no wire to compress)")
    if engine.fp16_enabled:
        raise ValueError("comm_backend_name=compressed requires bf16 "
                         "(dynamic loss scale does not compose with the "
                         "frozen-variance compression stage)")
    if engine.compute_dtype != jnp.bfloat16:
        raise ValueError("comm_backend_name=compressed requires bf16 "
                         "compute (the flat exchange needs the separate "
                         "fp32 master that only non-fp32 compute keeps)")
    if engine.zero_optimization_stage() > 1:
        raise ValueError("comm_backend_name=compressed supports ZeRO stage "
                         "0 or 1 (stage 1 shards v + fp32 master over the "
                         "data axis and re-gathers bf16 params; stage >= 2 "
                         "shards gradients, which the rank-local exchange "
                         "cannot see — the reference's 1-bit optimizers are "
                         "likewise restricted to ZeRO <= 1)")
    opt_params = dict(engine._config.optimizer.params or {})
    if opt_params.get("weight_decay", 0.0) and \
            not opt_params.get("adam_w_mode", True):
        raise ValueError("comm_backend_name=compressed supports AdamW-mode "
                         "weight decay only (classic mode folds decay into "
                         "the gradient, which the compression stage never "
                         "sees after the exchange)")
    if opt_params.get("onebit_packing", "1bit") not in ("1bit", "int8"):
        raise ValueError("onebit_packing must be '1bit' (packed uint8, "
                         "8 signs/byte) or 'int8' (fallback)")


def build_onebit_state(engine, params):
    """Extra engine-state entry: flat fp32 (m, v) + per-rank error buffers.

    Global shapes: m (N,) replicated — the algorithm folds each rank's
    LOCAL gradient into the FULL momentum, so m cannot shard; worker
    error (world, N) and server error (world, N // world) sharded over
    the data axis — each rank persists only its own row.

    ZeRO stage 1 additionally shards what CAN shard: v (frozen in the
    compression stage) and the fp32 master both live as (world, N/world)
    rows; the update runs per shard and bf16 params are re-gathered —
    the reference's "1-bit Adam with ZeRO-1" memory/wire tradeoff.
    """
    world = engine.dp_world_size
    stage1 = engine.zero_optimization_stage() >= 1
    flat, _ = jax.flatten_util.ravel_pytree(
        jax.tree_util.tree_map(lambda p: jnp.zeros(np.shape(p), jnp.float32),
                               params))
    n = flat.shape[0]
    n_pad = -(-n // (world * LANES)) * world * LANES
    mesh = engine.mesh
    rep = NamedSharding(mesh, P())
    ranked = NamedSharding(mesh, P(mesh_mod.DATA_AXIS))
    state = {
        "m": jax.device_put(jnp.zeros((n_pad,), jnp.float32), rep),
        "we": jax.device_put(jnp.zeros((world, n_pad), jnp.float32), ranked),
        "se": jax.device_put(jnp.zeros((world, n_pad // world), jnp.float32),
                             ranked),
    }
    shardings = {"m": rep, "we": ranked, "se": ranked}
    if stage1:
        master_flat = jnp.pad(jax.flatten_util.ravel_pytree(
            jax.tree_util.tree_map(
                lambda p: jnp.asarray(p, jnp.float32), params))[0],
            (0, n_pad - n))
        state["v"] = jax.device_put(
            jnp.zeros((world, n_pad // world), jnp.float32), ranked)
        state["master_flat"] = jax.device_put(
            master_flat.reshape(world, n_pad // world), ranked)
        shardings["v"] = ranked
        shardings["master_flat"] = ranked
    else:
        state["v"] = jax.device_put(jnp.zeros((n_pad,), jnp.float32), rep)
        shardings["v"] = rep
    return state, shardings


def reseed_master_flat(engine, params, onebit):
    """Rebuild the stage-1 sharded fp32 master from externally-loaded
    params. A PARTIAL checkpoint restore (module-only / no optimizer
    states / pre-onebit checkpoint) would otherwise leave the init-time
    ``master_flat`` in place and the next step would regenerate params
    from it — silently discarding the loaded weights (the analog of
    ``OffloadedOptimizer.sync_master_from``). No-op for stage 0 (the
    replicated master pytree is restored through the normal path)."""
    if onebit is None or "master_flat" not in onebit:
        return onebit
    world = engine.dp_world_size
    n_pad = onebit["m"].shape[0]
    flat = jax.flatten_util.ravel_pytree(jax.tree_util.tree_map(
        lambda p: jnp.asarray(p, jnp.float32), params))[0]
    flat = jnp.pad(flat, (0, n_pad - flat.shape[0]))
    ranked = NamedSharding(engine.mesh, P(mesh_mod.DATA_AXIS))
    new = dict(onebit)
    new["master_flat"] = jax.device_put(
        flat.reshape(world, n_pad // world), ranked)
    return new


def build_train_step(engine):
    """Compiled (state, stacked_batch) -> (state, metrics) with the
    shard_map'd compressed exchange. Plugs in as the engine's
    ``_jit_train_batch``."""
    check_supported(engine)
    mesh = engine.mesh
    world = engine.dp_world_size
    axis = mesh_mod.DATA_AXIS
    loss_fn = engine._loss_fn
    lr_fn = engine._lr_fn
    gas = engine.gradient_accumulation_steps()
    clip = engine.gradient_clipping()
    compute_dtype = engine.compute_dtype

    opt_params = dict(engine._config.optimizer.params or {})
    beta1, beta2 = tuple(opt_params.get("betas", (0.9, 0.999)))
    eps = opt_params.get("eps", 1e-8)
    weight_decay = opt_params.get("weight_decay", 0.0)
    freeze_step = opt_params.get("freeze_step", 100000)
    adam_w_mode = opt_params.get("adam_w_mode", True)
    packing = opt_params.get("onebit_packing", "1bit")
    stage1 = engine.zero_optimization_stage() >= 1

    sample = engine.state["master"] if engine.state["master"] is not None \
        else engine.state["params"]
    flat0, unravel = jax.flatten_util.ravel_pytree(sample)
    n = flat0.shape[0]
    n_pad = engine.state["onebit"]["m"].shape[0]

    # logical wire volume per rank per step (bytes) — see module docstring.
    # 1-bit packing ships 8 signs/byte (uint8); int8 fallback 1 sign/byte.
    sign_bytes = n_pad // 8 if packing == "1bit" else n_pad
    # stage 1 re-gathers the updated bf16 params (sharded master)
    param_gather_bytes = 2 * n_pad * (world - 1) // world if stage1 else 0
    dense_bytes = 2 * 4 * n_pad * (world - 1) // world + param_gather_bytes
    comp_bytes = (sign_bytes                 # all_to_all packed signs
                  + 4 * world                # all_to_all scales
                  + sign_bytes               # all_gather packed signs
                  + 4 * world                # all_gather scales
                  + param_gather_bytes)

    def local_step(state, onebit, stacked_batch):
        """Runs per-rank inside shard_map: batch leaves carry the LOCAL
        shard; state replicated; onebit.we/se carry this rank's row."""
        params = state["params"]

        def one_micro(carry, xs):
            mb, micro_index = xs
            loss_acc, grads_acc = carry
            rng = jax.random.fold_in(
                jax.random.fold_in(state["rng"],
                                   state["step"] * 1009 + micro_index),
                jax.lax.axis_index(axis))
            loss, grads = jax.value_and_grad(
                lambda p: loss_fn(p, mb, rng).astype(jnp.float32))(params)
            return (loss_acc + loss,
                    jax.tree_util.tree_map(jnp.add, grads_acc, grads)), None

        zero_grads = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params)
        (loss_sum, grads_sum), _ = jax.lax.scan(
            one_micro, (jnp.zeros((), jnp.float32), zero_grads),
            (stacked_batch, jnp.arange(gas)))
        loss = jax.lax.pmean(loss_sum / gas, axis)

        # local mean gradient, flattened + padded to the exchange layout
        g_local = jax.flatten_util.ravel_pytree(
            jax.tree_util.tree_map(lambda g: g / gas, grads_sum))[0]
        g_local = jnp.pad(g_local, (0, n_pad - n))

        m = onebit["m"]
        v = onebit["v"][0] if stage1 else onebit["v"]  # stage1: my row
        we = onebit["we"][0]          # this rank's rows
        se = onebit["se"][0]
        t = state["opt_step"].astype(jnp.float32) + 1.0
        chunk = n_pad // world
        rank = jax.lax.axis_index(axis)

        def warmup(_):
            g = jax.lax.pmean(g_local, axis)
            if clip > 0:
                norm = jnp.sqrt(jnp.sum(g * g))
                g = g * jnp.minimum(1.0, clip / (norm + 1e-6))
            m_new = beta1 * m + (1.0 - beta1) * g
            if stage1:
                g_sq = jax.lax.dynamic_slice(g, (rank * chunk,), (chunk,))
                v_new = beta2 * v + (1.0 - beta2) * g_sq * g_sq
            else:
                v_new = beta2 * v + (1.0 - beta2) * g * g
            return m_new, v_new, we, se, jnp.asarray(dense_bytes, jnp.float32)

        def compressed(_):
            # fold the LOCAL gradient into the momentum; the exchange
            # averages momenta across ranks (bit-packed uint8 on the wire)
            m_local = beta1 * m + (1.0 - beta1) * g_local
            m_new, we_new, se_new = compressed_allreduce(
                m_local, we, se, axis_name=axis, packing=packing)
            return m_new, v, we_new, se_new, \
                jnp.asarray(comp_bytes, jnp.float32)

        m_new, v_new, we_new, se_new, wire = jax.lax.cond(
            t > freeze_step, compressed, warmup, operand=None)

        bc1 = 1.0 - beta1 ** t
        bc2 = 1.0 - beta2 ** t
        lr = lr_fn(state["step"])
        new_state = dict(state)
        if stage1:
            # sharded update: my (v, master) rows + my chunk of the full
            # momentum; bf16 params re-gathered (ZeRO-1's allgather)
            master_chunk = onebit["master_flat"][0]
            m_chunk = jax.lax.dynamic_slice(m_new, (rank * chunk,), (chunk,))
            denom = jnp.sqrt(v_new / bc2) + eps
            upd = (m_chunk / bc1) / denom
            new_chunk = master_chunk - lr * upd
            if weight_decay != 0.0 and adam_w_mode:
                new_chunk = new_chunk - lr * weight_decay * master_chunk
            gathered = jax.lax.all_gather(
                new_chunk.astype(compute_dtype), axis).reshape(n_pad)
            new_params = unravel(gathered[:n].astype(jnp.float32))
            new_params = jax.tree_util.tree_map(
                lambda np_, p: np_.astype(p.dtype), new_params, params)
            new_master_flat = new_chunk[None]
            new_state["master"] = None
        else:
            # AdamW update on the replicated fp32 master
            master_flat = jnp.pad(
                jax.flatten_util.ravel_pytree(state["master"])[0],
                (0, n_pad - n))
            denom = jnp.sqrt(v_new / bc2) + eps
            update = (m_new / bc1) / denom
            new_flat = master_flat - lr * update
            if weight_decay != 0.0 and adam_w_mode:
                new_flat = new_flat - lr * weight_decay * master_flat
            new_master = unravel(new_flat[:n])
            new_params = jax.tree_util.tree_map(
                lambda mp, p: mp.astype(p.dtype), new_master, params)
            new_state["master"] = new_master
            new_master_flat = None

        new_state["params"] = new_params
        new_state["step"] = state["step"] + 1
        new_state["opt_step"] = state["opt_step"] + 1
        new_onebit = {"m": m_new,
                      "v": v_new[None] if stage1 else v_new,
                      "we": we_new[None], "se": se_new[None]}
        if stage1:
            new_onebit["master_flat"] = new_master_flat
        # RMS proxy for ||mean_r g_r||: exact when ranks hold identical
        # gradients, an upper bound otherwise — forming the true mean
        # would cost the dense allreduce the compression stage exists to
        # avoid
        grad_norm = jnp.sqrt(
            jax.lax.psum(jnp.sum(g_local * g_local), axis) / world)
        metrics = {"loss": loss, "overflow": jnp.asarray(False),
                   "grad_norm": grad_norm, "lr": lr,
                   "loss_scale": jnp.asarray(1.0, jnp.float32),
                   "comm_bytes": wire}
        return new_state, new_onebit, metrics

    rep = P()

    def spec_like(tree, spec):
        return jax.tree_util.tree_map(lambda _: spec, tree)

    # TP composition: pin the updated params (and stage-0 master) back to
    # their engine shardings — the flat unravel would otherwise let GSPMD
    # re-lay them out (e.g. replicate TP shards) on the next step
    param_shardings = jax.tree_util.tree_map(
        lambda a: a.sharding, engine.state["params"])
    master_shardings = None
    if engine.state.get("master") is not None:
        master_shardings = jax.tree_util.tree_map(
            lambda a: a.sharding, engine.state["master"])

    def train_batch(state, stacked_batch):
        state = dict(state)
        onebit = state.pop("onebit")
        state_specs = spec_like(state, rep)
        ranked = P(axis, None)
        onebit_specs = {"m": rep, "v": ranked if stage1 else rep,
                        "we": ranked, "se": ranked}
        if stage1:
            onebit_specs["master_flat"] = ranked
        bspecs = jax.tree_util.tree_map(lambda _: P(None, axis),
                                        stacked_batch)
        metric_specs = spec_like(
            {"loss": 0, "overflow": 0, "grad_norm": 0, "lr": 0,
             "loss_scale": 0, "comm_bytes": 0}, rep)
        # jax >= 0.8 renamed check_rep → check_vma; disable either way (the
        # replicated outputs are made identical by the exchange itself)
        import inspect
        kw = {"check_vma": False} \
            if "check_vma" in inspect.signature(shard_map).parameters \
            else {"check_rep": False}
        if _supports_auto_axes():
            # manual over data only; model (TP) stays a GSPMD auto axis
            kw["axis_names"] = frozenset({axis})
        fn = shard_map(
            local_step, mesh=mesh,
            in_specs=(state_specs, onebit_specs, bspecs),
            out_specs=(state_specs, onebit_specs, metric_specs), **kw)
        new_state, new_onebit, metrics = fn(state, onebit, stacked_batch)
        new_state["onebit"] = new_onebit
        new_state["params"] = jax.lax.with_sharding_constraint(
            new_state["params"], param_shardings)
        if master_shardings is not None and \
                new_state.get("master") is not None:
            new_state["master"] = jax.lax.with_sharding_constraint(
                new_state["master"], master_shardings)
        return new_state, metrics

    return jax.jit(train_batch, donate_argnums=(0,))
