"""1-bit Adam.

Capability parity with reference ``deepspeed/runtime/fp16/onebit/adam.py:13
OnebitAdam`` — Adam with error-compensated 1-bit momentum communication:

* warmup (``step < freeze_step``): plain Adam, both moments update;
* compression stage: the variance is FROZEN, the momentum update is
  compressed to sign·scale with persistent error feedback before it is
  applied (the compression error re-enters next step's momentum).

TPU mapping: under GSPMD the moments are already sharded over the ZeRO
axis, so shard-local sign compression with error feedback reproduces the
reference's per-partition compression exactly; the wire format of the
cross-device exchange is XLA's concern (`runtime/comm/compressed.py` holds
the explicit shard_map collective for schedules that own their comms).
The optimizer *dynamics* — which is what decides convergence — match the
reference stage for stage.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax.numpy as jnp

from ....ops.optimizers import OptimizerDef, _multi_map, _tree_zeros_like


class OnebitAdamState(NamedTuple):
    exp_avg: Any
    exp_avg_sq: Any
    worker_error: Any  # error-feedback residual, aligned with params


def _compress_ef(m: jnp.ndarray, err: jnp.ndarray):
    """Sign-compress with error feedback: returns (compressed m, new err)."""
    c = m + err
    scale = jnp.mean(jnp.abs(c))
    out = jnp.where(c >= 0, scale, -scale)
    return out, c - out


def onebit_adam(betas=(0.9, 0.999), eps: float = 1e-8,
                weight_decay: float = 0.0, freeze_step: int = 100000,
                adam_w_mode: bool = True,
                bias_correction: bool = True) -> OptimizerDef:
    beta1, beta2 = betas

    def init(params):
        return OnebitAdamState(exp_avg=_tree_zeros_like(params),
                               exp_avg_sq=_tree_zeros_like(params),
                               worker_error=_tree_zeros_like(params))

    def update(grads, state: OnebitAdamState, params, lr, step):
        t = step.astype(jnp.float32) + 1.0
        frozen = t > freeze_step
        bc1 = 1.0 - beta1 ** t if bias_correction else 1.0
        bc2 = 1.0 - beta2 ** t if bias_correction else 1.0

        def upd(p, g, m, v, err):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if weight_decay != 0.0 and not adam_w_mode:
                g = g + weight_decay * p32
            m = beta1 * m + (1.0 - beta1) * g
            # variance freezes at the compression stage
            v_new = beta2 * v + (1.0 - beta2) * (g * g)
            v = jnp.where(frozen, v, v_new)
            # compression stage: sign+scale momentum with error feedback;
            # the compressed tensor BECOMES the stored momentum (reference:
            # exp_avg is replaced by the allreduced compressed momentum so
            # all workers stay in sync)
            m_comp, err_new = _compress_ef(m, err)
            m = jnp.where(frozen, m_comp, m)
            err = jnp.where(frozen, err_new, err)
            denom = jnp.sqrt(v / bc2) + eps
            new_p = p32 - lr * (m / bc1) / denom
            if weight_decay != 0.0 and adam_w_mode:
                new_p = new_p - lr * weight_decay * p32
            return new_p.astype(p.dtype), m, v, err

        new_p, new_m, new_v, new_e = _multi_map(
            upd, 4, params, grads, state.exp_avg, state.exp_avg_sq,
            state.worker_error)
        return new_p, OnebitAdamState(exp_avg=new_m, exp_avg_sq=new_v,
                                      worker_error=new_e)

    return OptimizerDef(init=init, update=update, name="OneBitAdam")
