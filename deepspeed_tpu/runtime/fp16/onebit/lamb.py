"""1-bit LAMB.

Capability parity with reference ``deepspeed/runtime/fp16/onebit/lamb.py:14
OnebitLamb`` — LAMB with error-compensated 1-bit momentum communication.
Warmup runs full LAMB and records per-tensor scaling (trust) ratios; in the
compression stage the momentum is sign-compressed with error feedback and
the trust ratio is clipped to the warmup statistics via
``coeff_beta``-smoothed bounds (the reference's frozen lamb coefficients
with ``factor_max_frac`` clamping, simplified to its stable fixed point:
reuse the recorded coefficient).
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from ....ops.optimizers import OptimizerDef, _multi_map, _tree_zeros_like
from .adam import _compress_ef


class OnebitLambState(NamedTuple):
    exp_avg: Any
    exp_avg_sq: Any
    worker_error: Any
    lamb_coeff: Any  # per-tensor frozen trust ratio (scalar leaves)


def onebit_lamb(betas=(0.9, 0.999), eps: float = 1e-8,
                weight_decay: float = 0.0, freeze_step: int = 100000,
                max_coeff: float = 10.0, min_coeff: float = 0.01,
                coeff_beta: float = 0.9,
                bias_correction: bool = True) -> OptimizerDef:
    beta1, beta2 = betas

    def init(params):
        coeff = jax.tree_util.tree_map(
            lambda p: jnp.asarray(1.0, jnp.float32), params)
        return OnebitLambState(exp_avg=_tree_zeros_like(params),
                               exp_avg_sq=_tree_zeros_like(params),
                               worker_error=_tree_zeros_like(params),
                               lamb_coeff=coeff)

    def update(grads, state: OnebitLambState, params, lr, step):
        t = step.astype(jnp.float32) + 1.0
        frozen = t > freeze_step
        bc1 = 1.0 - beta1 ** t if bias_correction else 1.0
        bc2 = 1.0 - beta2 ** t if bias_correction else 1.0

        def upd(p, g, m, v, err, coeff):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            m = beta1 * m + (1.0 - beta1) * g
            v_new = beta2 * v + (1.0 - beta2) * (g * g)
            v = jnp.where(frozen, v, v_new)
            m_comp, err_new = _compress_ef(m, err)
            m = jnp.where(frozen, m_comp, m)
            err = jnp.where(frozen, err_new, err)
            u = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
            if weight_decay != 0.0:
                u = u + weight_decay * p32
            p_norm = jnp.linalg.norm(p32)
            u_norm = jnp.linalg.norm(u)
            fresh = jnp.where((p_norm > 0) & (u_norm > 0),
                              jnp.clip(p_norm / u_norm, min_coeff, max_coeff),
                              1.0)
            # warmup: smooth the coefficient estimate; frozen: reuse it
            coeff = jnp.where(frozen, coeff,
                              coeff_beta * coeff + (1 - coeff_beta) * fresh)
            trust = jnp.where(frozen, coeff, fresh)
            new_p = p32 - lr * trust * u
            return new_p.astype(p.dtype), m, v, err, coeff

        new_p, new_m, new_v, new_e, new_c = _multi_map(
            upd, 5, params, grads, state.exp_avg, state.exp_avg_sq,
            state.worker_error, state.lamb_coeff)
        return new_p, OnebitLambState(exp_avg=new_m, exp_avg_sq=new_v,
                                      worker_error=new_e, lamb_coeff=new_c)

    return OptimizerDef(init=init, update=update, name="OneBitLamb")
