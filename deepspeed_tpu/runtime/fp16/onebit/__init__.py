from .adam import onebit_adam
from .lamb import onebit_lamb
from .zoadam import zero_one_adam

__all__ = ["onebit_adam", "onebit_lamb", "zero_one_adam"]
