"""0/1 Adam.

Capability parity with reference ``deepspeed/runtime/fp16/onebit/zoadam.py:13
ZeroOneAdam`` — the 0/1 Adam algorithm: 1-bit compression with error
feedback from step one, variance updated only at *interval* boundaries
(interval doubling from ``var_update_scaler`` up to
``var_freeze_step``, after which it is frozen), and learning-rate freezing
within local-step windows. The schedule pieces (intervals) are computed from
the step counter so the whole update stays jittable; the learning rate used
by the update is re-latched only at local-step sync boundaries
(``local_step_scaler`` doubling, capped at ``2^local_step_clipper`` spacing)
— the jit-friendly rendering of 0/1 Adam's skipped synchronizations.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax.numpy as jnp

from ....ops.optimizers import OptimizerDef, _multi_map, _tree_zeros_like
from .adam import _compress_ef


class ZeroOneAdamState(NamedTuple):
    exp_avg: Any
    exp_avg_sq: Any
    worker_error: Any
    frozen_lr: Any  # lr latched at the last local-step sync boundary


def zero_one_adam(betas=(0.9, 0.999), eps: float = 1e-8,
                  weight_decay: float = 0.0, var_freeze_step: int = 100000,
                  var_update_scaler: int = 16, local_step_scaler: int = 32678,
                  local_step_clipper: int = 16,
                  bias_correction: bool = True) -> OptimizerDef:
    beta1, beta2 = betas

    def init(params):
        return ZeroOneAdamState(exp_avg=_tree_zeros_like(params),
                                exp_avg_sq=_tree_zeros_like(params),
                                worker_error=_tree_zeros_like(params),
                                frozen_lr=jnp.asarray(-1.0, jnp.float32))

    def _var_update_due(t):
        """Variance updates at exponentially-spaced steps: k·2^i spacing
        grown by var_update_scaler, until var_freeze_step."""
        # update when floor(log2(1 + t/scaler)) changes — a doubling
        # interval schedule that is a pure function of the step
        k = jnp.floor(jnp.log2(1.0 + t / var_update_scaler))
        k_prev = jnp.floor(jnp.log2(1.0 + (t - 1.0) / var_update_scaler))
        boundary = k != k_prev
        early = t <= var_update_scaler  # update every step at the start
        return (early | boundary) & (t <= var_freeze_step)

    def _lr_sync_due(t):
        """Local-step boundaries: doubling spacing from local_step_scaler,
        clipped so windows never exceed 2^local_step_clipper steps."""
        interval_exp = jnp.minimum(
            jnp.floor(jnp.log2(1.0 + t / local_step_scaler)),
            float(local_step_clipper))
        interval = jnp.exp2(interval_exp)
        prev_interval = jnp.exp2(jnp.minimum(
            jnp.floor(jnp.log2(1.0 + (t - 1.0) / local_step_scaler)),
            float(local_step_clipper)))
        return (interval != prev_interval) | (jnp.mod(t, interval) == 0)

    def update(grads, state: ZeroOneAdamState, params, lr, step):
        t = step.astype(jnp.float32) + 1.0
        bc1 = 1.0 - beta1 ** t if bias_correction else 1.0
        bc2 = 1.0 - beta2 ** t if bias_correction else 1.0
        var_due = _var_update_due(t)
        # learning-rate freezing between local-step sync boundaries
        lr = jnp.asarray(lr, jnp.float32)
        sync = _lr_sync_due(t) | (state.frozen_lr < 0)
        effective_lr = jnp.where(sync, lr, state.frozen_lr)
        new_frozen_lr = effective_lr

        def upd(p, g, m, v, err):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            m = beta1 * m + (1.0 - beta1) * g
            # 0/1 Adam compresses from the start, with error feedback
            m_comp, err = _compress_ef(m, err)
            m = m_comp
            v_new = beta2 * v + (1.0 - beta2) * (g * g)
            v = jnp.where(var_due, v_new, v)
            denom = jnp.sqrt(v / bc2) + eps
            new_p = p32 - effective_lr * (m / bc1) / denom
            if weight_decay != 0.0:
                new_p = new_p - effective_lr * weight_decay * p32
            return new_p.astype(p.dtype), m, v, err

        new_p, new_m, new_v, new_e = _multi_map(
            upd, 4, params, grads, state.exp_avg, state.exp_avg_sq,
            state.worker_error)
        return new_p, ZeroOneAdamState(exp_avg=new_m, exp_avg_sq=new_v,
                                       worker_error=new_e,
                                       frozen_lr=new_frozen_lr)

    return OptimizerDef(init=init, update=update, name="ZeroOneAdam")
