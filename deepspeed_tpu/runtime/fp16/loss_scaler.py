"""Static and dynamic loss scaling.

Capability parity with reference ``deepspeed/runtime/fp16/loss_scaler.py``
(``LossScaler`` :67, ``DynamicLossScaler`` :91). Re-architected functionally:
the scaler state is a small pytree living inside the compiled train step, and
overflow-driven skip/adjust happens with ``jnp.where`` — no host round-trip,
so the step stays a single XLA program (the reference pays a device→host sync
per step to branch on overflow).
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

# Config keys (reference runtime/constants / fp16 config)
INITIAL_LOSS_SCALE = "init_scale"
SCALE_WINDOW = "scale_window"
DELAYED_SHIFT = "delayed_shift"
MIN_LOSS_SCALE = "min_scale"


class LossScaleState(NamedTuple):
    """Dynamic loss-scale state carried through the train step."""

    loss_scale: jnp.ndarray  # f32 scalar
    good_steps: jnp.ndarray  # i32 scalar — consecutive overflow-free steps
    hysteresis: jnp.ndarray  # i32 scalar — remaining tolerated overflows


def make_loss_scale_state(init_scale: float = 2.0 ** 16, delayed_shift: int = 1) -> LossScaleState:
    return LossScaleState(
        loss_scale=jnp.asarray(init_scale, dtype=jnp.float32),
        good_steps=jnp.asarray(0, dtype=jnp.int32),
        hysteresis=jnp.asarray(delayed_shift, dtype=jnp.int32),
    )


def has_inf_or_nan(tree: Any) -> jnp.ndarray:
    """Global overflow probe over a pytree of grads (≅ reference
    ``_has_inf_or_nan``, stage3.py:1956 / CheckOverflow runtime/utils.py)."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.asarray(False)
    flags = [~jnp.isfinite(leaf.astype(jnp.float32)).all() for leaf in leaves]
    return jnp.stack(flags).any()


def update_scale(state: LossScaleState, overflow: jnp.ndarray, *, scale_factor: float = 2.0,
                 scale_window: int = 1000, min_scale: float = 1.0,
                 delayed_shift: int = 1, consecutive_hysteresis: bool = False) -> LossScaleState:
    """One dynamic-loss-scale update (≅ DynamicLossScaler.update_scale,
    reference loss_scaler.py:91 semantics incl. hysteresis/delayed_shift)."""
    hysteresis_after_overflow = jnp.maximum(state.hysteresis - 1, 1)
    drop = overflow & (state.hysteresis <= 1)

    new_scale = jnp.where(
        drop, jnp.maximum(state.loss_scale / scale_factor, min_scale), state.loss_scale)
    new_hysteresis = jnp.where(overflow, hysteresis_after_overflow, state.hysteresis)
    if consecutive_hysteresis:
        new_hysteresis = jnp.where(~overflow, jnp.asarray(delayed_shift, jnp.int32),
                                   new_hysteresis)

    good = jnp.where(overflow, 0, state.good_steps + 1)
    grow = (~overflow) & (good >= scale_window)
    new_scale = jnp.where(grow, new_scale * scale_factor, new_scale)
    good = jnp.where(grow, 0, good)
    new_hysteresis = jnp.where(grow & jnp.asarray(not consecutive_hysteresis),
                               jnp.asarray(delayed_shift, jnp.int32), new_hysteresis)
    return LossScaleState(loss_scale=new_scale, good_steps=good, hysteresis=new_hysteresis)


class LossScalerBase:
    """Object-style wrapper with the reference's API (scale_gradient /
    update_scale / backward) for user code written against it."""

    def __init__(self, scale: float = 1.0):
        self.cur_scale = scale

    @property
    def loss_scale(self):
        return self.cur_scale

    def scale_gradient(self, module, grad_in, grad_out):
        return jax.tree_util.tree_map(lambda g: g * self.cur_scale, grad_in)

    def update_scale(self, overflow: bool) -> None:
        pass

    def backward(self, loss, retain_graph: bool = False):
        return loss * self.cur_scale


class LossScaler(LossScalerBase):
    """Static loss scaler (reference :67)."""


class DynamicLossScaler(LossScalerBase):
    """Dynamic loss scaler (reference :91) — host-side mirror of the
    functional ``update_scale`` above for eager callers."""

    def __init__(self, init_scale: float = 2 ** 32, scale_factor: float = 2.0,
                 scale_window: int = 1000, min_scale: float = 1.0, delayed_shift: int = 1,
                 consecutive_hysteresis: bool = False, raise_error_at_min_scale: bool = True,
                 dtype=jnp.float16):
        super().__init__(init_scale)
        self.scale_factor = scale_factor
        self.scale_window = scale_window
        self.min_scale = min_scale
        self.delayed_shift = delayed_shift
        self.cur_hysteresis = delayed_shift
        self.consecutive_hysteresis = consecutive_hysteresis
        self.raise_error_at_min_scale = raise_error_at_min_scale
        self.last_overflow_iter = -1
        self.cur_iter = 0

    def update_scale(self, overflow: bool) -> None:
        if overflow:
            if self.delayed_shift == 1 or self.cur_hysteresis == 1:
                if self.cur_scale == self.min_scale and self.raise_error_at_min_scale:
                    raise Exception(
                        "Current loss scale already at minimum - cannot decrease scale anymore.")
                self.cur_scale = max(self.cur_scale / self.scale_factor, self.min_scale)
            else:
                self.cur_hysteresis -= 1
            self.last_overflow_iter = self.cur_iter
        else:
            if self.consecutive_hysteresis:
                self.cur_hysteresis = self.delayed_shift
            if (self.cur_iter - self.last_overflow_iter) % self.scale_window == 0 and \
                    self.cur_iter > self.last_overflow_iter:
                if not self.consecutive_hysteresis:
                    self.cur_hysteresis = self.delayed_shift
                self.cur_scale *= self.scale_factor
        self.cur_iter += 1


def CreateLossScaler(dtype, static_loss_scale: float, dynamic_scaling: bool,
                     dynamic_loss_args: dict = None):
    """≅ reference CreateLossScaler factory."""
    if dtype == jnp.float16 and dynamic_scaling:
        kwargs = dict(dynamic_loss_args or {})
        return DynamicLossScaler(dtype=dtype, **kwargs)
    scale = static_loss_scale if dtype == jnp.float16 else 1.0
    return LossScaler(scale=scale)
