"""The training engine.

Capability parity with reference ``deepspeed/runtime/engine.py:181
DeepSpeedEngine`` — config plumbing, distributed setup, optimizer wiring,
fp16/bf16/ZeRO, ``forward/backward/step``, checkpoint save/load, monitoring —
re-architected TPU-first:

* The hot loop is ONE compiled XLA program per global step
  (``train_batch``): micro-batch gradient accumulation is a ``lax.scan``,
  the optimizer update (including dynamic-loss-scale overflow skip via
  ``jnp.where``) is fused in, and ZeRO partitioning is expressed as GSPMD
  shardings (see ``zero/policy.py``) — XLA inserts and overlaps the
  reduce-scatters/all-gathers the reference hand-schedules with IPG buckets
  and side streams (stage_1_and_2.py:900, stage3.py:1065).
* The eager ``forward()/backward()/step()`` triple is kept for API parity
  (reference engine.py:1675,1816,2017): forward computes loss+grads in one
  jitted call, backward folds them into a sharded accumulator, step applies
  the update at gradient-accumulation boundaries.
* No parameter broadcast at init (engine.py:997,1030): params are
  deterministic functions of the seed on every process, and GSPMD places
  them — rank-0 broadcast is unnecessary by construction.
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Dict, Iterator, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from .. import comm as dist
from ..monitor.monitor import MonitorMaster
from ..ops.optimizers import OptimizerDef, get_optimizer
from ..parallel import mesh as mesh_mod
from ..utils.logging import log_dist, logger
from ..utils.timer import (
    BACKWARD_GLOBAL_TIMER,
    FORWARD_GLOBAL_TIMER,
    STEP_GLOBAL_TIMER,
    TRAIN_BATCH_TIMER,
    SynchronizedWallClockTimer,
    ThroughputTimer,
)
from .checkpoint_engine.checkpoint_engine import (
    ArrayCheckpointEngine,
    checkpoint_meta_path,
    read_latest,
    write_latest,
)
from .config import DeepSpeedConfig
from .fp16.loss_scaler import (
    LossScaleState,
    has_inf_or_nan,
    make_loss_scale_state,
    update_scale,
)
from .lr_schedules import get_lr_schedule
from .utils import clip_grads_by_global_norm, count_parameters, global_grad_norm
from .zero.policy import ShardingRules, ZeroShardingPolicy

LossFn = Callable[..., jnp.ndarray]  # (params, batch, rng) -> scalar loss


def _replicated(mesh):
    return NamedSharding(mesh, PartitionSpec())


class DeepSpeedEngine:
    """Training engine. Construct via :func:`deepspeed_tpu.initialize`."""

    def __init__(self,
                 model: Any = None,
                 loss_fn: Optional[LossFn] = None,
                 model_parameters: Any = None,
                 config: Union[str, Dict, DeepSpeedConfig, None] = None,
                 sharding_rules: Optional[ShardingRules] = None,
                 training_data=None,
                 lr_scheduler=None,
                 collate_fn=None,
                 mesh=None,
                 dont_change_device: bool = False):
        dist.init_distributed()

        # --- config -------------------------------------------------------
        # world size for batch math = batch replicas (data×expert). The ZeRO
        # shard world is a DIFFERENT number when a seq axis is active (it
        # includes seq; see zero/policy._zero_world) — don't conflate them.
        if mesh is not None:
            mesh_mod.set_mesh(mesh)
        elif not mesh_mod.has_mesh():
            cfg_probe = config if isinstance(config, dict) else {}
            mesh_dims = (cfg_probe.get("mesh", {}) if isinstance(cfg_probe, dict) else {})
            mics = (cfg_probe.get("zero_optimization", {})
                    if isinstance(cfg_probe, dict) else {})
            mesh_mod.initialize_mesh(
                data=mesh_dims.get("data", -1), model=mesh_dims.get("model", 1),
                pipe=mesh_dims.get("pipe", 1), expert=mesh_dims.get("expert", 1),
                seq=mesh_dims.get("seq", 1),
                mics_shard_size=max(int(mics.get("mics_shard_size", -1)), 0))
        self.mesh = mesh_mod.get_mesh()
        self.dp_world_size = mesh_mod.get_data_parallel_world_size()
        self.mp_world_size = mesh_mod.get_model_parallel_world_size()

        # autotuning subprocess mode: the launcher injects the candidate
        # config via env (reference rewrites --deepspeed_config)
        if os.environ.get("DS_AUTOTUNING_CONFIG"):
            config = os.environ["DS_AUTOTUNING_CONFIG"]
        if isinstance(config, DeepSpeedConfig):
            self._config = config
        else:
            self._config = DeepSpeedConfig(config, world_size=self.dp_world_size)

        # --- model --------------------------------------------------------
        self.module = model
        self._user_loss_fn = loss_fn is not None
        self._loss_fn = self._resolve_loss_fn(model, loss_fn)
        self._params_host = model_parameters  # may be None until first batch
        self._rng_seed = self._config.seed

        # --- precision ----------------------------------------------------
        self.compute_dtype = self._config.precision_dtype
        self.fp16_enabled = self._config.fp16.enabled
        self.bf16_enabled = self._config.bf16.enabled
        self._keep_master = self.compute_dtype != jnp.float32

        # --- zero policy --------------------------------------------------
        self.zero_config = self._config.zero_optimization
        self.policy = ZeroShardingPolicy(self.zero_config, self.mesh, sharding_rules)

        # --- optimizer-state offload (ZeRO-Offload / Infinity) ------------
        from .zero.offload_config import OffloadDeviceEnum

        oo = self.zero_config.offload_optimizer
        self._offload_enabled = oo is not None and \
            oo.device != OffloadDeviceEnum.none
        self._offload_cfg = oo
        self._offload_opt = None
        self._jit_offload_grads = None
        self._jit_offload_apply = None
        # parameter offload (ZeRO-Infinity param tier): params live on
        # host/NVMe and STREAM through the chip per layer — the training
        # path is zero/param_offload.py, which subsumes the optimizer
        # offload (host Adam is inherent to it)
        op = self.zero_config.offload_param
        self._param_offload_enabled = op is not None and \
            op.device != OffloadDeviceEnum.none
        self._param_offload = None
        if self._param_offload_enabled:
            from .zero import param_offload as _po

            self._offload_enabled = False  # subsumed by the streaming path
            _po.check_supported(self)
        if self._offload_enabled:
            opt_type = (self._config.optimizer.type
                        if self._config.optimizer else "adam").lower()
            if opt_type not in ("adam", "adamw", "cpuadam"):
                # the reference likewise restricts CPU offload to (CPU)Adam
                raise ValueError(
                    f"offload_optimizer requires Adam/AdamW (got "
                    f"{opt_type!r}); the host step runs DeepSpeedCPUAdam")

        # --- optimizer + schedule ------------------------------------------
        opt_cfg = self._config.optimizer
        self.optimizer_def: OptimizerDef = get_optimizer(
            opt_cfg.type if opt_cfg else "adam", opt_cfg.params if opt_cfg else {})
        # 1-bit compressed exchange (fp16/onebit/wire.py): validated up
        # front so misconfigurations fail at initialize(), not first step
        from .fp16.onebit import wire as onebit_wire
        self._onebit_wire = (not self._offload_enabled
                             and not self._param_offload_enabled
                             and onebit_wire.is_enabled(self._config, self.mesh))
        if self._onebit_wire:
            onebit_wire.check_supported(self)
        self._base_lr = float((opt_cfg.params if opt_cfg else {}).get("lr", 1e-3))
        sched_cfg = self._config.scheduler
        if lr_scheduler is not None:
            self.lr_scheduler = lr_scheduler
        else:
            self.lr_scheduler = get_lr_schedule(
                sched_cfg.type if sched_cfg else None,
                sched_cfg.params if sched_cfg else {})
        # pure lr(step) used inside the compiled step
        if self.lr_scheduler is not None and hasattr(self.lr_scheduler, "lr_at"):
            self._lr_fn = self.lr_scheduler.lr_at
        else:
            self._lr_fn = lambda step: jnp.asarray(self._base_lr, jnp.float32)

        # --- counters / timers / monitor ----------------------------------
        self.global_steps = 0
        self.global_samples = 0
        self.micro_steps = 0
        self.skipped_steps = 0
        self.timers = SynchronizedWallClockTimer()
        self.tput_timer = ThroughputTimer(
            batch_size=self.train_batch_size(), steps_per_output=self.steps_per_print())
        self.monitor = MonitorMaster(self._config.monitor_config)
        # off by default; assign an enabled telemetry.Tracer to record
        # train_batch phase spans (export via engine.tracer.export(path))
        from ..telemetry import Tracer
        self.tracer = Tracer(enabled=False)
        cl = self._config.comms_logger
        dist.configure(enabled=cl.enabled, prof_all=cl.prof_all, prof_ops=cl.prof_ops,
                       verbose=cl.verbose, debug=cl.debug)
        # engine selection ≅ reference _configure_checkpointing: the
        # nebula block picks the async tiered (orbax-backed) engine
        if self._config.nebula.enabled:
            from .checkpoint_engine.nebula_checkpoint_engine import (
                NebulaCheckpointEngine,
            )

            self.checkpoint_engine = NebulaCheckpointEngine()
            # array engine still backs the single-host npz format + the
            # per-process offload files
            self._array_ckpt_engine = ArrayCheckpointEngine()
        else:
            self.checkpoint_engine = ArrayCheckpointEngine()
            self._array_ckpt_engine = self.checkpoint_engine

        # compression training (reference compression/scheduler.py hooks;
        # here the transform runs inside the compiled step)
        self.compression_scheduler = None
        self._compression_transform = None
        self._jit_compression = None
        if self._config.compression_training:
            from ..compression import (
                CompressionScheduler,
                init_compression,
            )

            cc, transform = init_compression(
                self._config.compression_training)
            if cc.enabled:
                self.compression_scheduler = CompressionScheduler(cc)
                self._compression_transform = transform

        # curriculum learning (reference engine.py:1714-1718 seqlen
        # truncation + curriculum_scheduler.py) — bucketed difficulty keeps
        # the set of distinct shapes (and XLA compiles) small
        self.curriculum_scheduler = None
        cl = self._config.curriculum_learning
        if cl.enabled:
            from .data_pipeline.curriculum_scheduler import (
                CurriculumScheduler,
            )

            self.curriculum_scheduler = CurriculumScheduler({
                "min_difficulty": cl.min_difficulty,
                "max_difficulty": cl.max_difficulty,
                "schedule_type": cl.schedule_type,
                "schedule_config": cl.schedule_config,
            })
            self._curriculum_type = cl.curriculum_type

        # activation checkpointing from the JSON block (reference
        # engine._configure_checkpointing → checkpointing.configure,
        # checkpointing.py:789)
        from .activation_checkpointing import checkpointing as _act_ckpt
        from .config import ActivationCheckpointingConfig as _ActCfg

        # Apply this engine's block when it says something non-default;
        # otherwise only fill in defaults if nothing was configured yet
        # (don't clobber an earlier explicit user configure()).
        if (not _act_ckpt.is_configured()
                or self._config.activation_checkpointing != _ActCfg()):
            _act_ckpt.configure(deepspeed_config=self._config)

        # --- compiled-state ----------------------------------------------
        self.state: Optional[Dict[str, Any]] = None
        self._shardings: Optional[Dict[str, Any]] = None
        self._jit_train_batch = None
        self._jit_micro = None
        self._jit_accumulate = None
        self._jit_apply = None
        self._grad_acc = None
        self._loss_acc = 0.0  # eager-path loss accumulator for logging
        self._pending = None  # (loss, grads) stashed by forward()
        self._train_iter = None

        self.training_dataloader = self.deepspeed_io(training_data, collate_fn) \
            if training_data is not None else None

        if model_parameters is not None:
            self._build_state(model_parameters)

        log_dist(
            f"DeepSpeedEngine: zero stage={int(self.zero_config.stage)} "
            f"dtype={self.compute_dtype.__name__ if hasattr(self.compute_dtype, '__name__') else self.compute_dtype} "
            f"dp={self.dp_world_size} mp={self.mp_world_size} "
            f"micro_bs={self.train_micro_batch_size_per_gpu()} gas={self.gradient_accumulation_steps()}",
            ranks=[0])

    # ------------------------------------------------------------------
    # config accessors (reference engine.py:463-835 property style)
    # ------------------------------------------------------------------
    def train_batch_size(self) -> int:
        return self._config.train_batch_size

    def train_micro_batch_size_per_gpu(self) -> int:
        return self._config.train_micro_batch_size_per_gpu

    def gradient_accumulation_steps(self) -> int:
        return self._config.gradient_accumulation_steps

    def steps_per_print(self) -> int:
        return self._config.steps_per_print

    def gradient_clipping(self) -> float:
        return self._config.gradient_clipping

    def zero_optimization_stage(self) -> int:
        return int(self.zero_config.stage)

    def wall_clock_breakdown(self) -> bool:
        return self._config.wall_clock_breakdown

    def get_global_grad_norm(self):
        return self._last_grad_norm

    def get_lr(self):
        return [float(self._lr_fn(jnp.asarray(self.global_steps)))]

    def is_gradient_accumulation_boundary(self) -> bool:
        return (self.micro_steps + 1) % self.gradient_accumulation_steps() == 0

    # ------------------------------------------------------------------
    # model/loss resolution
    # ------------------------------------------------------------------
    def _resolve_loss_fn(self, model, loss_fn) -> LossFn:
        if loss_fn is not None:
            return loss_fn
        if model is None:
            raise ValueError("initialize() needs a model (flax Module) or loss_fn")
        if hasattr(model, "apply"):  # flax.linen.Module convention
            def flax_loss(params, batch, rng):
                rngs = None
                if rng is not None:
                    r1, r2 = jax.random.split(rng)
                    rngs = {"dropout": r1, "gating": r2}
                out = model.apply({"params": params}, batch, rngs=rngs)
                # convention: a tuple return is (loss, aux_loss, *ignored) —
                # ONLY element 1 is folded in (must be scalar, e.g. the MoE
                # load-balancing loss); further elements are metrics and are
                # never differentiated
                if isinstance(out, tuple):
                    loss = out[0]
                    if len(out) > 1 and out[1] is not None:
                        aux = out[1]
                        if jnp.ndim(aux) != 0:
                            raise ValueError(
                                "model returned non-scalar aux loss (tuple "
                                "element 1 must be a scalar added to the loss)")
                        loss = loss + aux
                    return loss
                return out

            return flax_loss
        if callable(model):
            return model
        raise ValueError(f"cannot derive a loss function from model {type(model)}")

    def _init_params_from_batch(self, batch) -> Any:
        if self._params_host is not None:
            return self._params_host
        if not hasattr(self.module, "init"):
            raise ValueError("model has no .init; pass model_parameters to initialize()")
        rng = jax.random.PRNGKey(self._rng_seed)
        # smallest batch-world-divisible slice (shard_map'd models — e.g.
        # sequence-parallel attention — require divisible shapes even at init)
        n = self.dp_world_size

        def host_slice(x):
            if isinstance(x, jax.Array) and not x.is_fully_addressable:
                # multi-process: fetch this process's shard only (init just
                # needs a shape-correct slice, the values are irrelevant)
                x = x.addressable_shards[0].data
            return np.asarray(x[:min(len(x), n)])

        micro = jax.tree_util.tree_map(host_slice, batch)
        variables = self.module.init({"params": rng, "dropout": rng}, micro)
        return variables["params"]

    # ------------------------------------------------------------------
    # state / sharding construction
    # ------------------------------------------------------------------
    def _build_state(self, params_host) -> None:
        mesh = self.mesh
        policy = self.policy

        if self._param_offload_enabled:
            # streamed param-offload path: params never become device
            # state; the runner owns the store + host optimizer
            from .zero.param_offload import ParamOffloadRunner

            self._param_offload = ParamOffloadRunner(self, params_host)
            self._offload_opt = self._param_offload.opt
            self.state = {
                # params stay in the runner's host/NVMe store; checkpoint
                # paths materialize them on demand (full_params_tree)
                "params": None,
                "master": None, "opt_state": None,
                "step": jnp.asarray(0, jnp.int32),
                "opt_step": jnp.asarray(0, jnp.int32),
                "scale": None,
                "rng": jax.random.PRNGKey(self._rng_seed + 1),
            }
            self._shardings = None
            self._num_params = count_parameters(params_host)
            self._last_grad_norm = None
            log_dist(f"engine state built (param offload): "
                     f"{self._num_params / 1e6:.1f}M params streamed",
                     ranks=[0])
            return

        # compute-dtype cast, except for obviously-integer leaves
        def cast(p):
            p = jnp.asarray(p)
            return p.astype(self.compute_dtype) if jnp.issubdtype(p.dtype, jnp.floating) \
                else p

        params = jax.tree_util.tree_map(cast, params_host)
        if self._offload_enabled:
            # fp32 master + moments live on HOST (numpy) inside the offload
            # manager; the device state holds compute params only
            from .zero.offload import OffloadedOptimizer

            opt_cfg = self._config.optimizer
            opt_params = dict(opt_cfg.params if opt_cfg else {})
            opt_params.setdefault("lr", self._base_lr)
            self._offload_opt = OffloadedOptimizer(
                jax.device_get(jax.tree_util.tree_map(
                    lambda p: np.asarray(p), params_host)),
                opt_params, self._offload_cfg,
                aio_config=self._config.aio)
            master = None
            opt_state = None
        else:
            keep_master = self._keep_master
            if self._onebit_wire and int(self.zero_config.stage) >= 1:
                # stage-1 onebit: the fp32 master lives SHARDED as
                # master_flat inside the onebit state (wire.py) — a
                # replicated pytree master would defeat ZeRO-1's memory
                keep_master = False
            master = jax.tree_util.tree_map(
                lambda p: jnp.asarray(p, jnp.float32) if jnp.issubdtype(
                    jnp.asarray(p).dtype, jnp.floating) else jnp.asarray(p),
                params_host) if keep_master else None
            opt_state = None if self._onebit_wire else \
                self.optimizer_def.init(master if master is not None else params)

        param_sh = policy.param_shardings(params)
        master_sh = policy.master_shardings(master) if master is not None else None
        opt_sh = policy.opt_state_shardings(opt_state, master if master is not None
                                            else params) \
            if opt_state is not None else None
        rep = _replicated(mesh)

        scale_state = None
        if self.fp16_enabled:
            fp16_cfg = self._config.fp16
            if fp16_cfg.loss_scale and fp16_cfg.loss_scale > 0:
                init_scale = fp16_cfg.loss_scale
            else:
                init_scale = 2.0 ** fp16_cfg.initial_scale_power
            scale_state = make_loss_scale_state(init_scale, fp16_cfg.hysteresis)

        state = {
            "params": jax.device_put(params, param_sh),
            "master": jax.device_put(master, master_sh) if master is not None else None,
            "opt_state": jax.device_put(opt_state, opt_sh)
            if opt_state is not None else None,
            "step": jnp.asarray(0, jnp.int32),
            "opt_step": jnp.asarray(0, jnp.int32),
            "scale": scale_state,
            "rng": jax.random.PRNGKey(self._rng_seed + 1),
        }
        shardings = {
            "params": param_sh,
            "master": master_sh,
            "opt_state": opt_sh,
            "step": rep,
            "opt_step": rep,
            "scale": jax.tree_util.tree_map(lambda _: rep, scale_state)
            if scale_state is not None else None,
            "rng": rep,
        }
        if self._onebit_wire:
            # 1-bit compressed-exchange path: flat (m, v) + per-rank error
            # buffers replace the OptimizerDef state (fp16/onebit/wire.py)
            from .fp16.onebit import wire as onebit_wire
            ob_state, ob_sh = onebit_wire.build_onebit_state(self, params)
            state["onebit"] = ob_state
            shardings["onebit"] = ob_sh
        self.state = state
        self._shardings = shardings
        self._num_params = count_parameters(params)
        self._last_grad_norm = None
        self._build_jits()
        log_dist(f"engine state built: {self._num_params / 1e6:.1f}M params, "
                 f"{policy.describe()}", ranks=[0])

    # ------------------------------------------------------------------
    # compiled functions
    # ------------------------------------------------------------------
    def _batch_leaf_sharding(self, ndim: int, scan_dim: bool = False):
        """Sharding for one batch leaf: sample dim over the batch axes and —
        when a ``seq`` mesh axis is active — dim 1 (the sequence dim) over it
        (sequence parallelism; ring/Ulysses attention consumes that layout)."""
        entries = [None] if scan_dim else []
        entries.append(tuple(mesh_mod.batch_axes()))
        if mesh_mod.get_sequence_parallel_world_size() > 1 and ndim > len(entries):
            entries.append(mesh_mod.SEQ_AXIS)
        return NamedSharding(self.mesh, PartitionSpec(*entries))

    def _batch_sharding(self, batch):
        return jax.tree_util.tree_map(
            lambda x: self._batch_leaf_sharding(np.ndim(x)), batch)

    def _grad_shardings(self, params_like):
        return self.policy.grad_shardings(params_like)

    def _build_jits(self) -> None:
        policy = self.policy
        loss_fn = self._loss_fn
        opt = self.optimizer_def
        lr_fn = self._lr_fn
        gas = self.gradient_accumulation_steps()
        clip = self.gradient_clipping()
        fp16 = self.fp16_enabled
        fp16_cfg = self._config.fp16
        keep_master = self._keep_master
        compute_dtype = self.compute_dtype
        param_sh = self._shardings["params"]
        prescale = self._config.prescale_gradients
        predivide = self._config.gradient_predivide_factor
        compression_transform = self._compression_transform

        def constrain_grads(grads, ref):
            sh = policy.grad_shardings(ref)
            return jax.tree_util.tree_map(
                lambda g, s: jax.lax.with_sharding_constraint(g, s), grads, sh)

        def scale_value(state):
            if fp16 and state["scale"] is not None:
                return state["scale"].loss_scale
            return jnp.asarray(1.0, jnp.float32)

        def micro_grads(params, batch, rng, scale):
            """loss+grads for one micro batch (grads still loss-scaled)."""

            def scaled_loss(p):
                loss = loss_fn(p, batch, rng)
                return (loss * scale).astype(jnp.float32), loss

            grads, loss = jax.grad(scaled_loss, has_aux=True)(params)
            return loss, grads

        def finalize_grads(state, grads_sum, denom):
            """Unscale, clip, overflow & loss-scale/step bookkeeping — shared
            by the fused device step and the offload path (where the fp32
            grads then travel to host for the CPU-Adam step, ≅
            stage_1_and_2.py:1037's CPU-offload grad copy)."""
            scale = scale_value(state)
            d = scale * denom
            if prescale and predivide != 1.0:
                d = scale * predivide
            grads = jax.tree_util.tree_map(
                lambda g: g.astype(jnp.float32) / d, grads_sum)
            overflow = has_inf_or_nan(grads) if fp16 else jnp.asarray(False)
            norm = global_grad_norm(grads)
            if clip > 0:
                grads, _ = clip_grads_by_global_norm(grads, clip, norm)
            if fp16:
                new_scale = update_scale(
                    state["scale"], overflow,
                    scale_window=fp16_cfg.loss_scale_window,
                    min_scale=fp16_cfg.min_loss_scale,
                    delayed_shift=fp16_cfg.hysteresis)
                if fp16_cfg.loss_scale and fp16_cfg.loss_scale > 0:
                    new_scale = state["scale"]  # static scaling
            else:
                new_scale = state["scale"]
            new_state = dict(state)
            new_state["step"] = state["step"] + 1
            new_state["opt_step"] = state["opt_step"] + \
                jnp.where(overflow, 0, 1).astype(jnp.int32)
            new_state["scale"] = new_scale
            metrics = {"overflow": overflow, "grad_norm": norm,
                       "lr": lr_fn(state["step"]), "loss_scale": scale}
            return new_state, grads, metrics

        def update_from_grads(state, grads_sum, n_micros):
            """finalize + on-device optimizer step + recast — the fused and
            eager (non-offload) paths."""
            new_state, grads, metrics = finalize_grads(state, grads_sum, n_micros)
            overflow = metrics["overflow"]
            master = state["master"] if keep_master else state["params"]
            new_master, new_opt = opt.update(grads, state["opt_state"], master,
                                             metrics["lr"], state["opt_step"])

            def pick(new, old):
                return jax.tree_util.tree_map(
                    lambda n, o: jnp.where(overflow, o, n), new, old)

            if fp16:
                new_master = pick(new_master, master)
                new_opt = pick(new_opt, state["opt_state"])

            if keep_master:
                # recast master → compute dtype; constrain to the param specs
                # (this is the "allgather updated partitions" of
                # stage_1_and_2.py:1642, emitted by XLA)
                new_params = jax.tree_util.tree_map(
                    lambda m, p: m.astype(p.dtype), new_master, state["params"])
                new_params = jax.tree_util.tree_map(
                    jax.lax.with_sharding_constraint, new_params, param_sh)
            else:
                new_params = new_master

            if compression_transform is not None:
                # compression applies to the COMPUTE params only; the fp32
                # master stays exact (reference quantizes the fp16 copy)
                new_params = compression_transform(new_params,
                                                   new_state["step"])

            new_state["params"] = new_params
            new_state["master"] = new_master if keep_master else None
            new_state["opt_state"] = new_opt
            return new_state, metrics

        grads_fn = self._make_grads_fn(micro_grads, constrain_grads, scale_value, gas)

        def offload_train_batch(state, stacked_batch):
            loss, grads_sum, denom = grads_fn(state, stacked_batch)
            new_state, grads, metrics = finalize_grads(state, grads_sum, denom)
            metrics["loss"] = loss
            return new_state, grads, metrics

        def fused_train_batch(state, stacked_batch):
            """One global step: grads over gas micro-batches + update."""
            loss, grads_sum, denom = grads_fn(state, stacked_batch)
            new_state, metrics = update_from_grads(state, grads_sum, denom)
            metrics["loss"] = loss
            return new_state, metrics

        def one_micro(state, batch, micro_index):
            rng = jax.random.fold_in(state["rng"],
                                     state["step"] * 1009 + micro_index)
            loss, grads = micro_grads(state["params"], batch, rng, scale_value(state))
            grads = constrain_grads(grads, state["params"])
            return loss, grads

        state_sh = self._shardings
        self._jit_micro = jax.jit(one_micro)
        self._jit_accumulate = jax.jit(lambda a, g: jax.tree_util.tree_map(
            lambda x, y: x + y, a, g))
        if self._offload_enabled:
            # NOTE: state is NOT donated here — params are replaced from the
            # host after the CPU step, the rest of the state is small
            self._jit_offload_grads = jax.jit(
                offload_train_batch, out_shardings=(state_sh, None, None))
            self._jit_offload_apply = jax.jit(
                lambda state, acc, n: finalize_grads(state, acc, n),
                static_argnums=(2,), out_shardings=(state_sh, None, None))
            return
        if self._onebit_wire:
            from .fp16.onebit import wire as onebit_wire

            self._jit_train_batch = onebit_wire.build_train_step(self)
            self._jit_apply = None  # eager step() does not compose with
            # the shard_map'd exchange; use train_batch()
            return
        # metrics are logically replicated scalars; saying so in
        # out_shardings makes them addressable on EVERY process (a
        # multi-process rank would otherwise fail to fetch the loss)
        metrics_sh = _replicated(self.mesh)
        donate_state = jax.jit(
            fused_train_batch, donate_argnums=(0,),
            out_shardings=(state_sh, metrics_sh))
        self._jit_train_batch = donate_state
        self._jit_apply = jax.jit(
            lambda state, acc, n: update_from_grads(state, acc, n),
            donate_argnums=(0,), static_argnums=(2,),
            out_shardings=(state_sh, metrics_sh))

    def _make_grads_fn(self, micro_grads, constrain_grads, scale_value, gas):
        """Default gradient strategy: lax.scan over the gas micro-batches
        accumulating into a (sharding-constrained) sum. PipelineEngine
        overrides this to feed all micro-batches into the pipelined loss."""

        def grads_fn(state, stacked_batch):
            params = state["params"]
            scale = scale_value(state)
            rng = jax.random.fold_in(state["rng"], state["step"])
            zeros = jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params)

            def body(carry, mb):
                acc, loss_sum, r = carry
                r, sub = jax.random.split(r)
                loss, grads = micro_grads(params, mb, sub, scale)
                acc = jax.tree_util.tree_map(
                    lambda a, g: a + g.astype(jnp.float32), acc, grads)
                acc = constrain_grads(acc, params)
                return (acc, loss_sum + loss, r), None

            (grads_sum, loss_sum, _), _ = jax.lax.scan(
                body, (zeros, jnp.asarray(0.0, jnp.float32), rng), stacked_batch)
            return loss_sum / gas, grads_sum, float(gas)

        return grads_fn

    # ------------------------------------------------------------------
    # data
    # ------------------------------------------------------------------
    def deepspeed_io(self, dataset, collate_fn=None):
        from .dataloader import DeepSpeedDataLoader

        return DeepSpeedDataLoader(
            dataset, batch_size=self.train_micro_batch_size_per_gpu() * self.dp_world_size,
            collate_fn=collate_fn)

    # ------------------------------------------------------------------
    # fused fast path
    # ------------------------------------------------------------------
    def _stack_micro_batches(self, batch_or_iter):
        gas = self.gradient_accumulation_steps()
        if hasattr(batch_or_iter, "__next__"):
            micros = [next(batch_or_iter) for _ in range(gas)]
            stacked = jax.tree_util.tree_map(lambda *xs: np.stack(xs), *micros)
        else:
            def reshape(x):
                x = np.asarray(x)
                global_micro = x.shape[0] // gas
                return x.reshape((gas, global_micro) + x.shape[1:])

            stacked = jax.tree_util.tree_map(reshape, batch_or_iter)
        if self._param_offload_enabled:
            # streamed path slices micro batches host-side; no device put
            return jax.tree_util.tree_map(np.asarray, stacked)
        # micro dim (1) shards over the batch axes; scan dim (0) replicated;
        # sequence dim (2) over `seq` when sequence parallelism is on
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(
                x, self._batch_leaf_sharding(np.ndim(x), scan_dim=True)),
            stacked)

    def train_batch(self, data_iter=None, batch=None):
        """Run one full global step (gas micro-batches) as a single compiled
        program — ≅ PipelineEngine.train_batch semantics for the non-pipeline
        engine, and the recommended TPU hot path."""
        if data_iter is None and batch is None and self.training_dataloader is not None:
            # persistent repeating iterator — successive calls advance through
            # the dataset instead of restarting at batch 0
            if self._train_iter is None:
                from .dataloader import RepeatingLoader

                self._train_iter = iter(RepeatingLoader(self.training_dataloader))
            data_iter = self._train_iter
        assert (data_iter is None) != (batch is None), \
            "pass exactly one of data_iter / batch"
        source = data_iter if data_iter is not None else batch
        stacked = self._stack_micro_batches(source)
        stacked = self._apply_curriculum(stacked)
        if self.state is None:
            first = jax.tree_util.tree_map(lambda x: x[0], stacked)
            self._build_state(self._init_params_from_batch(first))

        if self._config.check_rank_consistency:
            self._check_rank_consistency(stacked)
        self._maybe_profile_flops(stacked)
        self.timers(TRAIN_BATCH_TIMER).start()
        self.tput_timer.start()
        with self.tracer.span("train/step", step=self.global_steps):
            if self._param_offload is not None:
                # streamed path: feed host micro batches (gas-major)
                with self.tracer.span("train/offload_stream"):
                    micros = [jax.tree_util.tree_map(
                        lambda x, i=i: np.asarray(x[i]), stacked)
                        for i in range(self.gradient_accumulation_steps())]
                    metrics = self._param_offload.train_batch(micros)
                self.state["step"] = self.state["step"] + 1
                self.state["opt_step"] = self.state["opt_step"] + 1
            elif self._offload_enabled:
                with self.tracer.span("train/fwd_bwd"):
                    self.state, grads_dev, metrics = self._jit_offload_grads(
                        self.state, stacked)
                with self.tracer.span("train/host_opt_step"):
                    self._host_optimizer_step(grads_dev, metrics)
            else:
                with self.tracer.span("train/fwd_bwd_opt"):
                    self.state, metrics = self._jit_train_batch(
                        self.state, stacked)
            loss = metrics["loss"]
            self.global_steps += 1
            self.global_samples += self.train_batch_size()
            self.micro_steps += self.gradient_accumulation_steps()
            # block on the step's outputs so the recorded wall time is
            # compute, not async dispatch (see utils/timer.py)
            self.tput_timer.stop(global_step=True, block_on=loss)
            self.timers(TRAIN_BATCH_TIMER).stop(block_on=loss)
        self._after_step(metrics)
        return loss

    def _check_rank_consistency(self, stacked) -> None:
        """Debug-mode cross-host assertions (SURVEY §5.2; reference
        stage3.py:1080 assert_ints_same_as_other_ranks analog): in the SPMD
        model the compiled program cannot diverge mid-step, so what CAN
        drift across hosts is its inputs — batch structure, param-tree
        structure, and the step counter. Hash each and compare host-side;
        a mismatch raises on every rank with the per-rank hash table."""
        from ..comm import comm as dist

        dist.assert_same_across_ranks(
            {"step": self.global_steps,
             "gas": self.gradient_accumulation_steps()}, "step/gas counters")
        dist.assert_same_across_ranks(stacked, "batch structure")
        if self.state.get("params") is not None:
            dist.assert_same_across_ranks(
                jax.tree_util.tree_structure(self.state["params"]).__repr__(),
                "param tree structure")

    def _apply_curriculum(self, stacked):
        """Truncate the sequence dim to the current curriculum difficulty
        (seqlen metric) — reference engine.py:1714-1718."""
        if self.curriculum_scheduler is None or \
                self._curriculum_type != "seqlen":
            return stacked
        seqlen = self.curriculum_scheduler.update_difficulty(
            self.global_steps + 1)
        # Anchor the full sequence length to the token-id leaf (dim 2 of the
        # gas-stacked (gas, batch, seq) array, key configurable) rather than
        # guessing by size — a feature axis that coincidentally matches the
        # seqlen must not be truncated. Axes equal to the anchored length are
        # still truncated on every leaf so attention masks (gas, b, seq, seq)
        # stay consistent with input_ids.
        key = self._config.curriculum_learning.seqlen_key
        if isinstance(stacked, dict) and key in stacked \
                and np.ndim(stacked[key]) >= 3:
            full = stacked[key].shape[2]
        else:
            full = max((x.shape[2] for x in jax.tree_util.tree_leaves(stacked)
                        if np.ndim(x) >= 3), default=0)
        if full <= seqlen:
            return stacked

        def truncate(x):
            if np.ndim(x) < 3:
                return x
            idx = tuple(slice(0, seqlen) if i >= 2 and x.shape[i] == full
                        else slice(None) for i in range(np.ndim(x)))
            return x[idx]

        return jax.tree_util.tree_map(truncate, stacked)

    def _maybe_profile_flops(self, stacked_batch) -> None:
        """Engine-integrated flops profiler at ``profile_step`` — reference
        engine.py:1688,1705 flops_profiler hooks."""
        fp = self._config.flops_profiler
        if not fp.enabled or self.global_steps != fp.profile_step \
                or getattr(self, "_flops_profiled", False) \
                or self._param_offload is not None:
            return
        self._flops_profiled = True  # once, even with gas>1 eager forwards
        from ..profiling.flops_profiler import FlopsProfiler

        loss_fn = self._loss_fn
        micro = jax.tree_util.tree_map(lambda x: x[0], stacked_batch)
        rng = jax.random.PRNGKey(0)
        prof = FlopsProfiler(model=self.module, ds_engine=self)
        prof.start_profile()
        prof.profile(lambda p, b: loss_fn(p, b, rng), self.state["params"],
                     micro, run=False)
        prof.print_model_profile(
            profile_step=self.global_steps, module_depth=fp.module_depth,
            top_modules=fp.top_modules, detailed=fp.detailed,
            output_file=fp.output_file)
        prof.end_profile()

    def _host_optimizer_step(self, grads_dev, metrics) -> None:
        """Host half of the offloaded step: fp32 grads → CPU Adam → new
        compute params back to HBM."""
        overflow = self.fp16_enabled and bool(metrics["overflow"])
        if overflow:
            self.skipped_steps += 1
            return
        grads_host = jax.device_get(grads_dev)
        step_num = int(self.state["opt_step"])  # 1-indexed at update time
        new_params = self._offload_opt.step(
            grads_host, float(metrics["lr"]), step_num,
            np.dtype(self.compute_dtype))
        params_dev = jax.device_put(new_params, self._shardings["params"])
        if self._compression_transform is not None:
            # the fused path compresses inside update_from_grads; the
            # offloaded step must apply the same transform on re-upload
            if self._jit_compression is None:
                self._jit_compression = jax.jit(
                    self._compression_transform,
                    out_shardings=self._shardings["params"])
            params_dev = self._jit_compression(params_dev,
                                               self.state["step"])
        self.state["params"] = params_dev

    def _after_step(self, metrics) -> None:
        self._last_grad_norm = metrics.get("grad_norm")
        self._last_metrics = metrics
        if self.compression_scheduler is not None:
            self.compression_scheduler.step()
        at = self._config.autotuning
        if at.enabled and at.metric_path:
            # global_steps here is already incremented (1, 2, ...); treat
            # start_profile_step<=1 as "time from the first completed step"
            start = max(at.start_profile_step, 1)
            if self.global_steps == start or (
                    self.global_steps > start and
                    getattr(self, "_autotuning_t0", None) is None and
                    not getattr(self, "_autotuning_written", False)):
                jax.block_until_ready(metrics["loss"])
                self._autotuning_t0 = time.perf_counter()
                self._autotuning_start_step = self.global_steps
            elif self.global_steps >= at.end_profile_step and \
                    getattr(self, "_autotuning_t0", None) is not None:
                jax.block_until_ready(metrics["loss"])
                elapsed = time.perf_counter() - self._autotuning_t0
                steps = self.global_steps - self._autotuning_start_step
                self._autotuning_written = True
                import json as _json

                with open(at.metric_path, "w") as f:
                    _json.dump({
                        "throughput": steps * self.train_batch_size() /
                        max(elapsed, 1e-9),
                        "latency": elapsed / max(steps, 1),
                        "steps": steps,
                    }, f)
                self._autotuning_t0 = None
                if os.environ.get("DS_AUTOTUNING_EXIT"):
                    # experiment mode: the profile window is the whole job
                    log_dist("autotuning profile window complete; exiting",
                             ranks=[0])
                    raise SystemExit(0)
        if self.monitor.enabled and self.global_steps % self.steps_per_print() == 0:
            events = [
                ("Train/Samples/train_loss", float(metrics["loss"]), self.global_samples),
                ("Train/Samples/lr", float(metrics["lr"]), self.global_samples),
            ]
            if self.fp16_enabled:
                events.append(("Train/Samples/loss_scale",
                               float(metrics["loss_scale"]), self.global_samples))
            self.monitor.write_events(events)
        if self.global_steps % self.steps_per_print() == 0:
            log_dist(
                f"step={self.global_steps} loss={float(metrics['loss']):.4f} "
                f"lr={float(metrics['lr']):.3e} "
                f"grad_norm={float(metrics['grad_norm']):.3f}"
                + (f" scale={float(metrics['loss_scale']):.0f}"
                   if self.fp16_enabled else ""),
                ranks=[0])
        if self.wall_clock_breakdown() and \
                self.global_steps % self.steps_per_print() == 0:
            self.timers.log([TRAIN_BATCH_TIMER, FORWARD_GLOBAL_TIMER,
                             BACKWARD_GLOBAL_TIMER, STEP_GLOBAL_TIMER])

    # ------------------------------------------------------------------
    # eager parity API: forward / backward / step
    # ------------------------------------------------------------------
    def forward(self, batch):
        """Compute loss (grads stashed for backward) — reference
        engine.forward (engine.py:1675)."""
        if self._param_offload_enabled:
            raise RuntimeError(
                "the eager forward()/backward()/step() API does not compose "
                "with offload_param streaming (params are never "
                "device-resident) — drive training with train_batch()")
        if self.state is None:
            self._build_state(self._init_params_from_batch(batch))
        self._maybe_profile_flops(
            jax.tree_util.tree_map(lambda x: np.asarray(x)[None], batch))
        self.timers(FORWARD_GLOBAL_TIMER).start()
        batch = jax.tree_util.tree_map(
            lambda x: jax.device_put(
                np.asarray(x), self._batch_leaf_sharding(np.ndim(x))), batch)
        loss, grads = self._jit_micro(
            self.state, batch,
            jnp.asarray(self.micro_steps % self.gradient_accumulation_steps(),
                        jnp.int32))
        self._pending = (loss, grads)
        self.timers(FORWARD_GLOBAL_TIMER).stop()
        return loss

    __call__ = forward

    def backward(self, loss=None):
        """Fold pending grads into the (sharded) accumulator — reference
        engine.backward (engine.py:1816). The autograd ran inside forward();
        this is the accumulation half of the reference's IPG bucketing."""
        assert self._pending is not None, "backward() before forward()"
        self.timers(BACKWARD_GLOBAL_TIMER).start()
        micro_loss, grads = self._pending
        self._pending = None
        self._loss_acc = self._loss_acc + micro_loss
        if self._grad_acc is None:
            self._grad_acc = grads
        else:
            self._grad_acc = self._jit_accumulate(self._grad_acc, grads)
        self.micro_steps += 1
        self.timers(BACKWARD_GLOBAL_TIMER).stop()
        return loss

    def step(self):
        """Apply the optimizer at a gradient-accumulation boundary —
        reference engine.step (engine.py:2017)."""
        if self._onebit_wire:
            raise RuntimeError(
                "the eager forward()/backward()/step() API does not compose "
                "with comm_backend_name=\"compressed\" (gradients must stay "
                "rank-local inside the shard_map'd exchange) — drive "
                "training with train_batch() instead")
        if (self.micro_steps % self.gradient_accumulation_steps()) != 0:
            return  # mid-accumulation; nothing to do (reference no-ops too)
        assert self._grad_acc is not None, "step() before backward()"
        self.timers(STEP_GLOBAL_TIMER).start()
        n = float(self.gradient_accumulation_steps())
        if self._offload_enabled:
            self.state, grads_dev, metrics = self._jit_offload_apply(
                self.state, self._grad_acc, n)
            self._host_optimizer_step(grads_dev, metrics)
        else:
            self.state, metrics = self._jit_apply(self.state, self._grad_acc, n)
        self._grad_acc = None
        self.global_steps += 1
        self.global_samples += self.train_batch_size()
        # graftlint: allow[hot-loop-host-sync] -- the overflow flag must reach the host once per optimizer step to count skipped steps; a training step is not the serving decode loop
        if not self._offload_enabled and bool(metrics["overflow"]):
            self.skipped_steps += 1  # offload path counts inside _host_optimizer_step
        if self.lr_scheduler is not None and hasattr(self.lr_scheduler, "step"):
            self.lr_scheduler.step()
        metrics["loss"] = self._loss_acc / n
        self._loss_acc = 0.0
        self.timers(STEP_GLOBAL_TIMER).stop()
        self._after_step(metrics)

    # ------------------------------------------------------------------
    # checkpoint (reference engine.py:2553 load / :2858 save)
    # ------------------------------------------------------------------
    def _state_dict(self) -> Dict:
        import flax.serialization as fser

        assert dist.get_world_size() == 1, \
            "_state_dict is the single-host path; multi-host saves go " \
            "through the orbax engine (save_checkpoint dispatches)"
        host = jax.device_get(self.state)
        if self._param_offload is not None:
            # params live in the runner's host/NVMe store
            host["params"] = self._param_offload.full_params_tree()
        sd = {
            "module": fser.to_state_dict(host["params"]),
            "master": fser.to_state_dict(host["master"]) if host["master"] is not None
            else None,
            "optimizer": fser.to_state_dict(host["opt_state"])
            if host["opt_state"] is not None else None,
            "offload_optimizer": self._offload_opt.state_dict()
            if self._offload_opt is not None else None,
            # onebit wire: momentum + error buffers (+ stage-1 sharded
            # master) — without these a resume would re-zero the exchange
            "onebit": fser.to_state_dict(host["onebit"])
            if host.get("onebit") is not None else None,
            "step": int(host["step"]),
            "opt_step": int(host["opt_step"]),
            "scale": fser.to_state_dict(host["scale"]) if host["scale"] is not None
            else None,
            "rng": np.asarray(host["rng"]),
            "global_steps": self.global_steps,
            "global_samples": self.global_samples,
            "micro_steps": self.micro_steps,
            "skipped_steps": self.skipped_steps,
            "dp_world_size": self.dp_world_size,
            "mp_world_size": self.mp_world_size,
            "lr_scheduler": self.lr_scheduler.state_dict()
            if self.lr_scheduler is not None and hasattr(self.lr_scheduler, "state_dict")
            else None,
        }
        return sd

    def _orbax_split_state(self):
        """(sharded array tree, json-able meta) for the orbax engine —
        the multi-host save path (every process writes its addressable
        shards; reference per-zero_pp_rank shard files, engine.py:2485)."""
        import flax.serialization as fser

        # containers flattened to plain dicts: orbax round-trips dicts, not
        # NamedTuples (AdamState / LossScaleState) — leaves stay sharded
        # jax arrays; from_state_dict re-nests on load
        arrays = {
            "params": self.state["params"],
            "master": self.state["master"],
            "opt_state": fser.to_state_dict(self.state["opt_state"])
            if self.state["opt_state"] is not None else None,
            "step": self.state["step"],
            "opt_step": self.state["opt_step"],
            "scale": fser.to_state_dict(self.state["scale"])
            if self.state["scale"] is not None else None,
            "rng": self.state["rng"],
            "onebit": self.state.get("onebit"),
        }
        arrays = {k: v for k, v in arrays.items() if v is not None}
        meta = {
            "global_steps": self.global_steps,
            "global_samples": self.global_samples,
            "micro_steps": self.micro_steps,
            "skipped_steps": self.skipped_steps,
            "dp_world_size": self.dp_world_size,
            "mp_world_size": self.mp_world_size,
            "lr_scheduler": self.lr_scheduler.state_dict()
            if self.lr_scheduler is not None and
            hasattr(self.lr_scheduler, "state_dict") else None,
        }
        return arrays, meta

    def save_checkpoint(self, save_dir: str, tag: Optional[str] = None,
                        client_state: Optional[Dict] = None,
                        save_latest: bool = True) -> None:
        assert self.state is not None, "no state to checkpoint"
        if tag is None:
            tag = f"global_step{self.global_steps}"
        self.checkpoint_engine.create(tag)
        from .checkpoint_engine.orbax_checkpoint_engine import (
            OrbaxCheckpointEngine,
        )

        # param-offload: weights live in the runner's host/NVMe store, not
        # in state["params"] — the orbax array path would silently drop
        # them; the single-host npz path materializes via _state_dict
        # (param offload is single-process, enforced at initialize())
        use_orbax = (dist.get_world_size() > 1 or
                     isinstance(self.checkpoint_engine,
                                OrbaxCheckpointEngine)) and \
            self._param_offload is None
        if use_orbax:
            # orbax writes each process's addressable shards in parallel
            # (multi-host requirement; also the nebula/async engine path)
            if isinstance(self.checkpoint_engine, OrbaxCheckpointEngine):
                engine = self.checkpoint_engine
            else:
                self._orbax_engine = getattr(self, "_orbax_engine", None) or \
                    OrbaxCheckpointEngine()
                engine = self._orbax_engine
            arrays, meta = self._orbax_split_state()
            if client_state:
                meta["client_state"] = client_state
            path = os.path.join(save_dir, str(tag), "orbax_state")
            engine.save({"arrays": arrays, "meta": meta}, path)
            if self._offload_opt is not None:
                # host-resident optimizer state: one file per process
                # (reference per-zero_pp_rank optim files, engine.py:2485)
                self._array_ckpt_engine.save(
                    {"offload_optimizer": self._offload_opt.state_dict()},
                    os.path.join(save_dir, str(tag),
                                 f"offload_pp_rank_{jax.process_index()}"))
            engine.commit(tag)
        else:
            sd = self._state_dict()
            if client_state:
                sd["client_state"] = client_state
            path = checkpoint_meta_path(save_dir, tag, "model",
                                        mp_rank=0, dp_rank=dist.get_rank())
            if dist.get_rank() == 0:
                self.checkpoint_engine.save(sd, path)
            self.checkpoint_engine.commit(tag)
        if save_latest and dist.get_rank() == 0:
            write_latest(save_dir, tag)
        dist.barrier(name="save_checkpoint")
        log_dist(f"saved checkpoint {save_dir}/{tag}", ranks=[0])

    def load_universal_checkpoint(self, load_dir: str,
                                  tag: Optional[str] = None):
        """Load a universal checkpoint at the CURRENT parallelism layout —
        reference engine.py:782 ``load_universal_checkpoint`` +
        checkpoint/universal_checkpoint.py:12. Arrays are whole logical
        tensors; ``device_put`` against this engine's shardings performs the
        re-shard (any dp/tp/pp/sp resize)."""
        import flax.serialization as fser

        from ..checkpoint.universal_checkpoint import (
            load_universal,
            universal_dir,
        )

        if tag is None:
            tag = read_latest(load_dir)
        univ = load_universal(universal_dir(load_dir, tag))
        assert self.state is not None, \
            "engine state not built yet — init params before universal load"
        host = jax.device_get(self.state)
        new_state = dict(self.state)

        fp32 = univ["fp32"]
        if self._param_offload is not None:
            template = self._param_offload.full_params_tree()
            restored = fser.from_state_dict(template, fp32)
            self._param_offload.load_params(jax.tree_util.tree_map(
                lambda m, p: np.asarray(m).astype(np.asarray(p).dtype),
                restored, template))
            self._offload_opt.load_universal(restored, univ["opt"])
            meta = univ["meta"]
            new_state["step"] = jnp.asarray(meta.get("step", 0), jnp.int32)
            new_state["opt_step"] = jnp.asarray(
                meta.get("opt_step", meta.get("step", 0)), jnp.int32)
            self.global_steps = meta.get("global_steps", 0)
            self.global_samples = meta.get("global_samples", 0)
            self.micro_steps = meta.get("micro_steps", 0)
            self.skipped_steps = meta.get("skipped_steps", 0)
            if self.lr_scheduler is not None and meta.get("lr_scheduler") \
                    and hasattr(self.lr_scheduler, "load_state_dict"):
                self.lr_scheduler.load_state_dict(meta["lr_scheduler"])
            self.state = new_state
            log_dist(f"loaded universal checkpoint {load_dir}/{tag} "
                     "(param-offload store)", ranks=[0])
            return load_dir, {}
        if host["master"] is not None:
            restored_master = fser.from_state_dict(host["master"], fp32)
            new_state["master"] = jax.device_put(
                restored_master, self._shardings["master"])
            restored = restored_master
        else:
            restored = fser.from_state_dict(host["params"], fp32)
        # always recast to each param's compute dtype — the universal file
        # is fp32 regardless of how this engine computes
        new_params = jax.tree_util.tree_map(
            lambda m, p: jnp.asarray(m).astype(jnp.asarray(p).dtype),
            restored, host["params"])
        new_state["params"] = jax.device_put(new_params,
                                             self._shardings["params"])

        opt = univ["opt"]
        if self._offload_opt is not None:
            # host-resident master + moments: restore them into the offload
            # manager (fp32 master from the universal file; m/v if present)
            self._offload_opt.load_universal(restored, opt)
        if opt and host["opt_state"] is not None:
            opt_sd = fser.to_state_dict(host["opt_state"])
            merged = dict(opt_sd)
            for name, tree in opt.items():
                if name in merged:
                    merged[name] = tree
            new_state["opt_state"] = jax.device_put(
                fser.from_state_dict(host["opt_state"], merged),
                self._shardings["opt_state"])

        if self.state.get("onebit") is not None:
            # universal files carry the fp32 master — exact reseed of the
            # stage-1 sharded onebit master
            from .fp16.onebit import wire as onebit_wire

            new_state["onebit"] = onebit_wire.reseed_master_flat(
                self, restored, self.state["onebit"])

        meta = univ["meta"]
        new_state["step"] = jnp.asarray(meta.get("step", 0), jnp.int32)
        new_state["opt_step"] = jnp.asarray(
            meta.get("opt_step", meta.get("step", 0)), jnp.int32)
        self.global_steps = meta.get("global_steps", 0)
        self.global_samples = meta.get("global_samples", 0)
        self.micro_steps = meta.get("micro_steps", 0)
        self.skipped_steps = meta.get("skipped_steps", 0)
        if self.lr_scheduler is not None and meta.get("lr_scheduler") and \
                hasattr(self.lr_scheduler, "load_state_dict"):
            self.lr_scheduler.load_state_dict(meta["lr_scheduler"])
        self.state = new_state
        log_dist(f"loaded universal checkpoint {load_dir}/{tag} "
                 f"(saved at dp={meta.get('source_dp_world_size')}, "
                 f"now dp={self.dp_world_size})", ranks=[0])
        return load_dir, {}

    def load_checkpoint(self, load_dir: str, tag: Optional[str] = None,
                        load_module_strict: bool = True,
                        load_optimizer_states: bool = True,
                        load_lr_scheduler_states: bool = True,
                        load_module_only: bool = False):
        import flax.serialization as fser

        if self._config.checkpoint.load_universal:
            return self.load_universal_checkpoint(load_dir, tag)
        if tag is None:
            tag = read_latest(load_dir)
        orbax_path = os.path.join(load_dir, str(tag), "orbax_state")
        if os.path.isdir(orbax_path):
            return self._load_orbax_checkpoint(load_dir, tag,
                                               load_optimizer_states,
                                               load_lr_scheduler_states,
                                               load_module_only)
        path = checkpoint_meta_path(load_dir, tag, "model", mp_rank=0, dp_rank=0)
        sd = self.checkpoint_engine.load(path)
        assert self.state is not None, \
            "engine state not built yet — run or init params before load_checkpoint"

        host = jax.device_get(self.state)
        if self._param_offload is not None:
            host["params"] = self._param_offload.full_params_tree()

        def restore(target, saved):
            return fser.from_state_dict(target, saved)

        new_state = dict(self.state)
        restored_params = restore(host["params"], sd["module"])
        if self._param_offload is not None:
            # install into the streaming store; no device-resident params
            self._param_offload.load_params(restored_params)
            new_state["params"] = None
        else:
            new_state["params"] = jax.device_put(
                restored_params, self._shardings["params"])
        if self._offload_opt is not None and (
                load_module_only or not load_optimizer_states
                or sd.get("offload_optimizer") is None):
            # module-only restore under offload: re-seed the host master so
            # the next step doesn't overwrite the loaded weights
            self._offload_opt.sync_master_from(restored_params)
        if self.state.get("onebit") is not None and (
                load_module_only or not load_optimizer_states
                or sd.get("onebit") is None):
            # same hazard for the stage-1 onebit sharded master
            from .fp16.onebit import wire as onebit_wire

            new_state["onebit"] = onebit_wire.reseed_master_flat(
                self, restored_params, self.state["onebit"])
        if not load_module_only:
            if sd.get("master") is not None and host["master"] is not None:
                new_state["master"] = jax.device_put(
                    restore(host["master"], sd["master"]), self._shardings["master"])
            if load_optimizer_states and sd.get("optimizer") is not None \
                    and host["opt_state"] is not None:
                new_state["opt_state"] = jax.device_put(
                    restore(host["opt_state"], sd["optimizer"]),
                    self._shardings["opt_state"])
            if load_optimizer_states and self._offload_opt is not None \
                    and sd.get("offload_optimizer") is not None:
                self._offload_opt.load_state_dict(sd["offload_optimizer"])
            if load_optimizer_states and sd.get("onebit") is not None \
                    and self.state.get("onebit") is not None:
                new_state["onebit"] = jax.device_put(
                    fser.from_state_dict(host["onebit"], sd["onebit"]),
                    self._shardings["onebit"])
            new_state["step"] = jnp.asarray(sd["step"], jnp.int32)
            new_state["opt_step"] = jnp.asarray(sd.get("opt_step", sd["step"]), jnp.int32)
            if sd.get("scale") is not None and host["scale"] is not None:
                new_state["scale"] = jax.device_put(
                    restore(host["scale"], sd["scale"]), self._shardings["scale"])
            if sd.get("rng") is not None:
                new_state["rng"] = jnp.asarray(sd["rng"], dtype=jnp.uint32)
            self.global_steps = sd.get("global_steps", 0)
            self.global_samples = sd.get("global_samples", 0)
            self.micro_steps = sd.get("micro_steps", 0)
            self.skipped_steps = sd.get("skipped_steps", 0)
            if load_lr_scheduler_states and self.lr_scheduler is not None and \
                    sd.get("lr_scheduler") is not None and \
                    hasattr(self.lr_scheduler, "load_state_dict"):
                self.lr_scheduler.load_state_dict(sd["lr_scheduler"])
        self.state = new_state
        log_dist(f"loaded checkpoint {load_dir}/{tag}", ranks=[0])
        return load_dir, sd.get("client_state", {})

    def _load_orbax_checkpoint(self, load_dir: str, tag: str,
                               load_optimizer_states: bool = True,
                               load_lr_scheduler_states: bool = True,
                               load_module_only: bool = False):
        """Restore an orbax (multi-host/sharded) checkpoint directly into
        the current shardings — each process reads its shards."""
        from .checkpoint_engine.orbax_checkpoint_engine import (
            OrbaxCheckpointEngine,
        )

        path = os.path.join(load_dir, str(tag), "orbax_state")
        assert self.state is not None, \
            "engine state not built yet — init params before load_checkpoint"
        engine = getattr(self, "_orbax_engine", None) or \
            OrbaxCheckpointEngine()
        self._orbax_engine = engine
        arrays, _ = self._orbax_split_state()
        if load_module_only:
            arrays = {"params": arrays["params"]}
        target = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                           sharding=a.sharding), arrays)
        blob = engine.load(path, restore_target=target)
        restored, meta = blob["arrays"], blob["meta"]
        import flax.serialization as fser

        new_state = dict(self.state)
        new_state["params"] = restored["params"]
        if not load_module_only:
            if load_optimizer_states:
                if "master" in restored:
                    new_state["master"] = restored["master"]
                if "opt_state" in restored and \
                        self.state["opt_state"] is not None:
                    new_state["opt_state"] = fser.from_state_dict(
                        self.state["opt_state"], restored["opt_state"])
            for key in ("step", "opt_step", "rng"):
                if key in restored:
                    new_state[key] = restored[key]
            if load_optimizer_states and "onebit" in restored and \
                    self.state.get("onebit") is not None:
                new_state["onebit"] = restored["onebit"]
            if "scale" in restored and self.state["scale"] is not None:
                new_state["scale"] = fser.from_state_dict(
                    self.state["scale"], restored["scale"])
            self.global_steps = meta.get("global_steps", 0)
            self.global_samples = meta.get("global_samples", 0)
            self.micro_steps = meta.get("micro_steps", 0)
            self.skipped_steps = meta.get("skipped_steps", 0)
            if load_lr_scheduler_states and self.lr_scheduler is not None \
                    and meta.get("lr_scheduler") is not None and \
                    hasattr(self.lr_scheduler, "load_state_dict"):
                self.lr_scheduler.load_state_dict(meta["lr_scheduler"])
        if self.state.get("onebit") is not None and (
                "onebit" not in restored or load_module_only
                or not load_optimizer_states):
            from .fp16.onebit import wire as onebit_wire

            new_state["onebit"] = onebit_wire.reseed_master_flat(
                self, jax.device_get(new_state["params"]),
                new_state.get("onebit", self.state["onebit"]))
        self.state = new_state
        if self._offload_opt is not None:
            # restore this process's host optimizer state; without a file,
            # re-seed the master from the loaded params so the next step
            # doesn't clobber them (mirrors the single-host load guard)
            off_path = os.path.join(load_dir, str(tag),
                                    f"offload_pp_rank_{jax.process_index()}")
            loaded_off = False
            if load_optimizer_states and not load_module_only and \
                    os.path.exists(off_path + ".meta"):
                off_sd = self._array_ckpt_engine.load(off_path)
                if off_sd.get("offload_optimizer"):
                    self._offload_opt.load_state_dict(
                        off_sd["offload_optimizer"])
                    loaded_off = True
            if not loaded_off:
                self._offload_opt.sync_master_from(
                    jax.device_get(new_state["params"]))
        log_dist(f"loaded orbax checkpoint {path}", ranks=[0])
        return load_dir, meta.get("client_state", {})

    # ------------------------------------------------------------------
    def eval_batch_fn(self):
        """A jitted loss-only function for evaluation."""
        loss_fn = self._loss_fn

        @jax.jit
        def eval_loss(params, batch):
            return loss_fn(params, batch, None)

        return eval_loss

    @property
    def num_parameters(self) -> int:
        return getattr(self, "_num_params", 0)
