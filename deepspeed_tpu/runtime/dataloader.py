"""Data loading.

Capability parity with reference ``deepspeed/runtime/dataloader.py`` —
``DeepSpeedDataLoader`` (:41) and ``RepeatingLoader`` (:17). TPU-native
differences: batches are numpy pytrees destined for
``jax.device_put``-with-sharding (the engine shards the batch over the data
axes), and in a multi-host setup each process loads only its slice of the
global batch (DistributedSampler semantics via rank/num_shards striding).
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Optional

import numpy as np


class RepeatingLoader:
    """Wraps an iterator to restart on StopIteration (reference :17)."""

    def __init__(self, loader: Iterable):
        self.loader = loader
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            return next(self.data_iter)


def default_collate(samples) -> Any:
    """Stack a list of samples (dicts of arrays / arrays) into one batch."""
    first = samples[0]
    if isinstance(first, dict):
        return {k: np.stack([np.asarray(s[k]) for s in samples]) for k in first}
    if isinstance(first, (tuple, list)):
        return type(first)(np.stack([np.asarray(s[i]) for s in samples])
                           for i in range(len(first)))
    return np.stack([np.asarray(s) for s in samples])


class DeepSpeedDataLoader:
    """Batching loader with distributed-sampler striding (reference :41).

    ``batch_size`` here is the *per-process global micro batch*
    (micro_batch_per_chip × local dp degree); each process strides the dataset
    by (num_processes, rank) like torch's DistributedSampler.
    """

    def __init__(self, dataset, batch_size: int, collate_fn: Optional[Callable] = None,
                 local_rank: int = -1, drop_last: bool = True, shuffle: bool = False,
                 seed: int = 0, num_shards: Optional[int] = None,
                 shard_index: Optional[int] = None):
        self.dataset = dataset
        self.batch_size = batch_size
        self.collate_fn = collate_fn or default_collate
        self.drop_last = drop_last
        self.shuffle = shuffle
        self.seed = seed
        self.epoch = 0
        if num_shards is None:
            try:
                import jax

                num_shards = jax.process_count()
                shard_index = jax.process_index()
            except Exception:
                num_shards, shard_index = 1, 0
        self.num_shards = num_shards
        self.shard_index = shard_index or 0
        self.len = len(dataset) // (batch_size * self.num_shards)

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def __len__(self) -> int:
        return self.len

    def __iter__(self):
        n = len(self.dataset)
        order = np.arange(n)
        if self.shuffle:
            order = np.random.default_rng(self.seed + self.epoch).permutation(n)
        # shard then batch
        order = order[self.shard_index::self.num_shards]
        usable = (len(order) // self.batch_size) * self.batch_size
        for start in range(0, usable, self.batch_size):
            idx = order[start:start + self.batch_size]
            yield self.collate_fn([self.dataset[int(i)] for i in idx])
