from .compressed import compressed_allreduce

__all__ = ["compressed_allreduce"]
