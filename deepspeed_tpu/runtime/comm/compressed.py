"""Error-compensated 1-bit compressed allreduce.

Capability parity with reference ``deepspeed/runtime/comm/nccl.py:54
NcclBackend.compressed_allreduce`` (and the mpi/cupy variant,
``runtime/comm/mpi.py:132``): the two-phase sign-compression collective
behind 1-bit Adam/LAMB —

  1. add the local worker error, split into ``world`` chunks, compress each
     chunk to (int8 signs, fp32 per-chunk scale), remember the new worker
     error;
  2. ``all_to_all`` so rank *i* receives everyone's chunk *i* (the
     reduce-scatter phase; signs travel as int8 = 4x smaller than fp32
     — bit-packing to a true 1-bit/32x wire format is a further packing
     step the XLA collective does not expose);
  3. decompress + average the received chunks, add the server error,
     re-compress, remember the new server error;
  4. ``all_gather`` the compressed server chunks and decompress into the
     full result.

Runs inside ``shard_map`` over a named mesh axis — the int8 tensors are
what crosses ICI/DCN. Single-device (no axis) falls back to local
compression with error feedback, preserving the optimizer dynamics.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def _compress(x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """sign/magnitude compression over the last axis: returns
    (int8 signs, fp32 scale) with scale = mean(|x|)."""
    scale = jnp.mean(jnp.abs(x), axis=-1, keepdims=True)
    signs = jnp.where(x >= 0, 1, -1).astype(jnp.int8)
    return signs, scale


def _decompress(signs: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    return signs.astype(jnp.float32) * scale


def compressed_allreduce(
        x: jnp.ndarray,
        worker_error: jnp.ndarray,
        server_error: jnp.ndarray,
        axis_name: Optional[str] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Returns (averaged_x, new_worker_error, new_server_error).

    ``x``/``worker_error`` are flat fp32 vectors of length ``n`` divisible
    by the axis size; ``server_error`` is this rank's persistent buffer of
    length ``n // world`` (each rank only serves its own chunk — a
    full-length buffer would waste world-fold HBM). Pad ``x`` before
    calling; the optimizer pads its flat buffers.
    """
    if axis_name is None:
        # local fallback: same compression dynamics, no communication
        c = x + worker_error
        signs, scale = _compress(c[None])
        out = _decompress(signs, scale)[0]
        new_worker = c - out
        return out, new_worker, server_error

    world = jax.lax.psum(1, axis_name)
    n = x.shape[0]
    chunk = n // world

    # phase 1: local compression with worker error feedback
    c = x + worker_error
    chunks = c.reshape(world, chunk)
    signs, scales = _compress(chunks)           # (world, chunk) int8, (world, 1)
    new_worker_error = c - _decompress(signs, scales).reshape(n)

    # phase 2: all_to_all — rank i gets every rank's chunk i
    # (split axis 0, concat new leading axis)
    recv_signs = jax.lax.all_to_all(signs[None], axis_name, split_axis=1,
                                    concat_axis=0, tiled=True)
    recv_scales = jax.lax.all_to_all(scales[None], axis_name, split_axis=1,
                                     concat_axis=0, tiled=True)
    # (world, chunk): row j = rank j's version of my chunk
    decompressed = _decompress(recv_signs.reshape(world, chunk),
                               recv_scales.reshape(world, 1))
    server_chunk = jnp.mean(decompressed, axis=0)

    # phase 3: server-side compression with server error feedback
    sc = server_chunk + server_error
    s_signs, s_scale = _compress(sc[None])
    new_server_error = sc - _decompress(s_signs, s_scale)[0]

    # phase 4: all_gather the compressed server chunks
    all_signs = jax.lax.all_gather(s_signs[0], axis_name)   # (world, chunk)
    all_scales = jax.lax.all_gather(s_scale[0], axis_name)  # (world, 1)
    out = _decompress(all_signs, all_scales).reshape(n)
    return out, new_worker_error, new_server_error
