"""Error-compensated 1-bit compressed allreduce.

Capability parity with reference ``deepspeed/runtime/comm/nccl.py:54
NcclBackend.compressed_allreduce`` (and the mpi/cupy variant,
``runtime/comm/mpi.py:132``): the two-phase sign-compression collective
behind 1-bit Adam/LAMB —

  1. add the local worker error, split into ``world`` chunks, compress each
     chunk to (int8 signs, fp32 per-chunk scale), remember the new worker
     error;
  2. ``all_to_all`` so rank *i* receives everyone's chunk *i* (the
     reduce-scatter phase; signs are BIT-PACKED to uint8 — 8 signs/byte,
     the true 1-bit wire format, 32x smaller than fp32 — with
     ``packing="int8"`` as the one-sign-per-byte fallback);
  3. decompress + average the received chunks, add the server error,
     re-compress, remember the new server error;
  4. ``all_gather`` the compressed server chunks and decompress into the
     full result.

Runs inside ``shard_map`` over a named mesh axis — the int8 tensors are
what crosses ICI/DCN. Single-device (no axis) falls back to local
compression with error feedback, preserving the optimizer dynamics.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def _compress(x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """sign/magnitude compression over the last axis: returns
    (int8 signs, fp32 scale) with scale = mean(|x|)."""
    scale = jnp.mean(jnp.abs(x), axis=-1, keepdims=True)
    signs = jnp.where(x >= 0, 1, -1).astype(jnp.int8)
    return signs, scale


def _decompress(signs: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    return signs.astype(jnp.float32) * scale


def _bit_weights():
    # constructed per call ON PURPOSE: caching the array would leak a
    # tracer when first built inside a shard_map trace; XLA constant-folds
    # the literal anyway
    return jnp.asarray([1, 2, 4, 8, 16, 32, 64, 128], jnp.uint8)


def pack_signs(signs: jnp.ndarray) -> jnp.ndarray:
    """int8 ±1 signs (..., k) -> packed uint8 (..., k // 8): the TRUE
    1-bit wire format (8 signs/byte), matching the reference's packed
    compression phase (nccl.py:54-130's 16x claim shape). ``k`` must be
    divisible by 8 — the exchange layout pads to lane multiples anyway."""
    bits = (signs > 0).astype(jnp.uint8).reshape(*signs.shape[:-1], -1, 8)
    return jnp.sum(bits * _bit_weights(), axis=-1, dtype=jnp.uint8)


def unpack_signs(packed: jnp.ndarray) -> jnp.ndarray:
    """packed uint8 (..., k//8) -> int8 ±1 signs (..., k)."""
    bits = (packed[..., None] & _bit_weights()) > 0
    signs = jnp.where(bits, jnp.int8(1), jnp.int8(-1))
    return signs.reshape(*packed.shape[:-1], packed.shape[-1] * 8)


def compressed_allreduce(
        x: jnp.ndarray,
        worker_error: jnp.ndarray,
        server_error: jnp.ndarray,
        axis_name: Optional[str] = None,
        packing: str = "1bit",
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Returns (averaged_x, new_worker_error, new_server_error).

    ``x``/``worker_error`` are flat fp32 vectors of length ``n`` divisible
    by the axis size (and, with the default 1-bit packing, by 8x the axis
    size — the optimizer pads its flat buffers to world x 128 lanes);
    ``server_error`` is this rank's persistent buffer of length
    ``n // world`` (each rank only serves its own chunk — a full-length
    buffer would waste world-fold HBM). Pad ``x`` before calling.

    ``packing``: ``"1bit"`` (default) bit-packs signs to uint8 — 8
    signs/byte on the wire, the reference's packed compression-phase
    format; ``"int8"`` keeps one sign per byte (fallback — same numerics,
    4x more wire volume).
    """
    if packing not in ("1bit", "int8"):
        raise ValueError(f"packing must be '1bit' or 'int8', got {packing!r}")
    pack = pack_signs if packing == "1bit" else (lambda s: s)
    unpack = unpack_signs if packing == "1bit" else (lambda s: s)
    if axis_name is None:
        # local fallback: same compression dynamics, no communication
        c = x + worker_error
        signs, scale = _compress(c[None])
        out = _decompress(signs, scale)[0]
        new_worker = c - out
        return out, new_worker, server_error

    world = jax.lax.psum(1, axis_name)
    n = x.shape[0]
    chunk = n // world
    if packing == "1bit" and chunk % 8 != 0:
        # the PER-RANK chunk is what packs, so the contract is
        # n % (8 * world) == 0, not n % 8
        raise ValueError(
            f"packing='1bit' needs the per-rank chunk divisible by 8 "
            f"(n={n}, world={world} -> chunk={chunk}); pad the buffer to "
            f"a multiple of 8*world or pass packing='int8'")

    # phase 1: local compression with worker error feedback
    c = x + worker_error
    chunks = c.reshape(world, chunk)
    signs, scales = _compress(chunks)           # (world, chunk) int8, (world, 1)
    new_worker_error = c - _decompress(signs, scales).reshape(n)

    # phase 2: all_to_all — rank i gets every rank's chunk i
    # (split axis 0, concat new leading axis). With packing="1bit" the
    # tensor that crosses ICI/DCN is uint8 (world, chunk//8).
    recv_packed = jax.lax.all_to_all(pack(signs)[None], axis_name,
                                     split_axis=1, concat_axis=0, tiled=True)
    recv_scales = jax.lax.all_to_all(scales[None], axis_name, split_axis=1,
                                     concat_axis=0, tiled=True)
    # (world, chunk): row j = rank j's version of my chunk
    decompressed = _decompress(
        unpack(recv_packed.reshape(world, -1)),
        recv_scales.reshape(world, 1))
    server_chunk = jnp.mean(decompressed, axis=0)

    # phase 3: server-side compression with server error feedback
    sc = server_chunk + server_error
    s_signs, s_scale = _compress(sc[None])
    new_server_error = sc - _decompress(s_signs, s_scale)[0]

    # phase 4: all_gather the compressed server chunks
    all_packed = jax.lax.all_gather(pack(s_signs)[0], axis_name)
    all_scales = jax.lax.all_gather(s_scale[0], axis_name)  # (world, 1)
    out = _decompress(unpack(all_packed.reshape(world, -1)),
                      all_scales).reshape(n)
    return out, new_worker_error, new_server_error
