"""MiCS — hierarchical (sub-group) ZeRO sharding.

Capability parity with reference ``deepspeed/runtime/zero/mics.py`` —
``MiCS_Init`` (:54) shards within fixed-size sub-groups instead of the
whole world, ``MiCS_Offload`` (:306) composes with offload, and
``MiCS_Optimizer`` (:350) all-reduces grads across replica groups.

TPU-native: MiCS is a MESH SHAPE, not an optimizer subclass. The data axis
is factored into ``data_outer`` (replica groups) × ``data`` (the shard
group of ``mics_shard_size`` chips): ZeRO state shards over the inner axis
only, so parameter all-gathers stay inside one group's ICI neighborhood —
the reference's hierarchical allgather (:226) — and GSPMD's gradient psum
over both axes reproduces the replica-group all-reduce (:418). Configure
with ``zero_optimization.mics_shard_size`` (the engine builds the factored
mesh automatically) or use :func:`MiCS_Init` to build the mesh explicitly.
"""

from __future__ import annotations

from typing import Optional

from ...parallel import mesh as mesh_mod
from ...utils.logging import log_dist


def MiCS_Init(shard_size: int, data: int = -1, model: int = 1, pipe: int = 1,
              expert: int = 1, seq: int = 1, devices=None):
    """Build and install the MiCS-factored mesh. Returns the mesh.

    ≅ reference ``zero.MiCS_Init(partition_size=...)`` as a context for
    model construction; on TPU construction needs no context manager —
    params are sharded by the engine's policy against this mesh.
    """
    mesh = mesh_mod.initialize_mesh(
        data=data, model=model, pipe=pipe, expert=expert, seq=seq,
        mics_shard_size=shard_size, devices=devices)
    dims = dict(zip(mesh.axis_names, mesh.devices.shape))
    log_dist(
        f"MiCS: shard group={dims.get(mesh_mod.DATA_AXIS, 1)} chips, "
        f"replica groups={dims.get(mesh_mod.DATA_OUTER_AXIS, 1)}",
        ranks=[0])
    return mesh


def mics_enabled() -> bool:
    if not mesh_mod.has_mesh():
        return False
    return mesh_mod.DATA_OUTER_AXIS in mesh_mod.get_mesh().axis_names


def mics_shard_size() -> Optional[int]:
    if not mics_enabled():
        return None
    mesh = mesh_mod.get_mesh()
    return dict(zip(mesh.axis_names,
                    mesh.devices.shape))[mesh_mod.DATA_AXIS]
