"""ZeRO user-facing namespace.

API parity with ``deepspeed.zero`` — ``Init`` (reference
partition_parameters.py:616) and ``GatheredParameters`` (:1545). The
reference needs both because its params are mutable torch objects that get
physically scattered: ``Init`` hijacks module construction to shard at
birth; ``GatheredParameters`` re-materializes shards for user surgery.

Under GSPMD, params are whole *logical* arrays whose placement the engine's
sharding policy owns, so:

* :class:`Init` is a construction context that (a) records the intended
  dtype/device for abstract ("meta") init of models too big to materialize
  unsharded — delegating to ``utils/init_on_device.OnDevice`` — and (b)
  accepts and ignores the reference's process-group/config knobs (sharding
  comes from the engine policy, not construction).
* :class:`GatheredParameters` yields HOST copies of the requested params
  (always "gathered" in the logical sense) and, when ``modifier_rank`` is
  set, writes modifications back into the engine's sharded state on exit.
"""

from __future__ import annotations

from typing import Any, Iterable, Optional

from .config import DeepSpeedZeroConfig, ZeroStageEnum
from .mics import MiCS_Init
from .policy import ShardingRules, ZeroShardingPolicy
from .tiling import TiledLinear


class Init:
    def __init__(self, module=None, data_parallel_group=None,
                 mem_efficient_linear: bool = True, remote_device=None,
                 pin_memory: bool = False, config_dict_or_path=None,
                 config=None, enabled: bool = True, dtype=None, mpu=None):
        self.enabled = enabled
        self.dtype = dtype
        self.remote_device = remote_device
        self._ctx = None

    def __enter__(self):
        if self.enabled:
            from ...utils.init_on_device import OnDevice

            self._ctx = OnDevice(dtype=self.dtype, device="meta")
            self._ctx.__enter__()
        return self

    def __exit__(self, *exc):
        if self._ctx is not None:
            self._ctx.__exit__(*exc)
            self._ctx = None
        return False

    def abstract_init(self, module, *args, **kwargs):
        """Shapes-only init for checkpoint-restore targets (the zero.Init
        use case: construct without materializing)."""
        from ...utils.init_on_device import OnDevice

        ctx = self._ctx or OnDevice(dtype=self.dtype, device="meta")
        return ctx.abstract_init(module, *args, **kwargs)


class GatheredParameters:
    """with zero.GatheredParameters(engine, modifier_rank=0) as params:
        params["block"]["kernel"][:] = ...   # host numpy, mutable

    On exit (when ``modifier_rank`` is not None) the modified tree is
    re-uploaded against the engine's shardings, and the offload master is
    re-synced so the next step keeps the edit.
    """

    def __init__(self, engine_or_params, modifier_rank: Optional[int] = 0,
                 fwd_module=None, enabled: bool = True):
        self.enabled = enabled
        self.modifier_rank = modifier_rank
        self._engine = None
        self._params = None
        if hasattr(engine_or_params, "state"):
            self._engine = engine_or_params
        else:
            self._params = engine_or_params
            if self.modifier_rank is not None and enabled:
                # a raw tree cannot receive write-backs (jax arrays are
                # immutable; the engine holds the authoritative state) —
                # failing loudly beats silently dropping the user's edits
                raise ValueError(
                    "GatheredParameters over a raw params tree is "
                    "read-only: pass modifier_rank=None, or pass the "
                    "engine to persist modifications")

    def __enter__(self):
        import jax

        if not self.enabled:
            return None
        source = self._engine.state["params"] if self._engine is not None \
            else self._params
        self._host = jax.device_get(source)
        return self._host

    def __exit__(self, *exc):
        import jax

        if not self.enabled or self.modifier_rank is None or \
                self._engine is None or exc[0] is not None:
            return False
        self._engine.state["params"] = jax.device_put(
            self._host, self._engine._shardings["params"])
        if self._engine.state.get("master") is not None:
            import jax.numpy as jnp

            master = jax.tree_util.tree_map(
                lambda h, m: jnp.asarray(h, jnp.float32)
                if jnp.issubdtype(jnp.asarray(m).dtype, jnp.floating)
                else jnp.asarray(h),
                self._host, jax.device_get(self._engine.state["master"]))
            self._engine.state["master"] = jax.device_put(
                master, self._engine._shardings["master"])
        if self._engine._offload_opt is not None:
            self._engine._offload_opt.sync_master_from(self._host)
        return False


__all__ = ["Init", "GatheredParameters", "MiCS_Init", "TiledLinear",
           "DeepSpeedZeroConfig", "ZeroStageEnum", "ZeroShardingPolicy",
           "ShardingRules"]
