"""Memory tiling for giant linear layers.

Capability parity with reference ``deepspeed/runtime/zero/tiling.py:32
TiledLinear`` — splits a huge projection into input/output tiles so live
activation + weight memory is bounded (the reference also re-uses ZeRO-3
gather/release per tile). TPU-native: the tiles are a ``lax.scan`` over
kernel slices with ``jax.checkpoint`` on the tile body — XLA materializes
one tile's weights/activations at a time and the scan carries the partial
sum; with ZeRO-3 sharded params, each tile's all-gather is also tile-sized.

The reference's ``contiguous_memory_allocator.py`` (defragmentation for the
eager allocator) has no TPU role: XLA statically plans buffers at compile
time, which is strictly stronger — noted here for the component-inventory
mapping.

``LinearModuleForZeroStage3`` (reference zero/linear.py — an
autograd-friendly linear that avoids saving gathered weights for backward)
maps to the ``remat`` below: recompute instead of save is the same trade,
expressed with ``jax.checkpoint``.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp


class TiledLinear(nn.Module):
    """y = x @ W (+ b), computed in ``in_splits × out_splits`` tiles.

    ``in_splits`` tiles the contraction dim (partial sums accumulated in a
    scan carry), ``out_splits`` tiles the output dim (results concatenated).
    Tile bodies are rematerialized, so backward recomputes per-tile instead
    of keeping every tile's intermediates live.
    """

    features: int
    in_splits: int = 1
    out_splits: int = 1
    use_bias: bool = True
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        in_features = x.shape[-1]
        assert in_features % self.in_splits == 0, \
            f"in_features {in_features} % in_splits {self.in_splits} != 0"
        assert self.features % self.out_splits == 0, \
            f"features {self.features} % out_splits {self.out_splits} != 0"
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (in_features, self.features), self.dtype)
        in_tile = in_features // self.in_splits
        out_tile = self.features // self.out_splits

        # (in_splits, out_splits, in_tile, out_tile) tile grid
        tiles = kernel.reshape(self.in_splits, in_tile,
                               self.out_splits, out_tile)
        tiles = tiles.transpose(0, 2, 1, 3)
        x_tiles = x.reshape(x.shape[:-1] + (self.in_splits, in_tile))
        x_tiles = jnp.moveaxis(x_tiles, -2, 0)  # (in_splits, ..., in_tile)

        @jax.checkpoint
        def tile_matmul(x_t, w_row):
            # x_t: (..., in_tile); w_row: (out_splits, in_tile, out_tile)
            return jnp.einsum("...i,oij->...oj", x_t, w_row)

        def body(acc, inputs):
            x_t, w_row = inputs
            return acc + tile_matmul(x_t, w_row), None

        init = jnp.zeros(x.shape[:-1] + (self.out_splits, out_tile),
                         x.dtype)
        acc, _ = jax.lax.scan(body, init, (x_tiles, tiles))
        y = acc.reshape(x.shape[:-1] + (self.features,))
        if self.use_bias:
            y = y + self.param("bias", nn.initializers.zeros,
                               (self.features,), self.dtype)
        return y
