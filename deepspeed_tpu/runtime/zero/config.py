"""ZeRO configuration (≅ reference ``runtime/zero/config.py:76``).

The knobs keep the reference's JSON names so unmodified user configs parse.
On TPU many of them steer the GSPMD sharding policy / block schedule instead
of eager bucketing:

* ``stage``                       → which state pytrees shard over the data axis
* ``reduce_bucket_size``          → grad reduce-scatter flat-buffer chunking
* ``stage3_prefetch_bucket_size`` / ``stage3_max_live_parameters`` /
  ``stage3_max_reuse_distance``   → static memory budget of the per-block
                                     allgather schedule (reference's trace-based
                                     prefetcher becomes a compile-time schedule)
* ``sub_group_size``              → optimizer-step tiling for offload
"""

from __future__ import annotations

from enum import IntEnum
from typing import Optional

from pydantic import Field

from ..config_utils import DeepSpeedConfigModel, pp_int
from .offload_config import (
    DeepSpeedZeroOffloadOptimizerConfig,
    DeepSpeedZeroOffloadParamConfig,
    OffloadDeviceEnum,
)


class ZeroStageEnum(IntEnum):
    """≅ reference runtime/zero/config.py:67."""

    disabled = 0
    optimizer_states = 1
    gradients = 2
    weights = 3
    max_stage = 3


class DeepSpeedZeroConfig(DeepSpeedConfigModel):
    stage: ZeroStageEnum = ZeroStageEnum.disabled
    contiguous_gradients: bool = True
    reduce_scatter: bool = True
    reduce_bucket_size: int = Field(int(5e8), ge=0)
    allgather_partitions: bool = True
    allgather_bucket_size: int = Field(int(5e8), ge=0)
    overlap_comm: Optional[bool] = None  # default True for stage 3 (set below)
    load_from_fp32_weights: bool = True
    elastic_checkpoint: bool = False

    # offload
    offload_param: Optional[DeepSpeedZeroOffloadParamConfig] = None
    offload_optimizer: Optional[DeepSpeedZeroOffloadOptimizerConfig] = None

    # stage-3 knobs
    sub_group_size: int = Field(int(1e9), ge=0)
    stage3_max_live_parameters: int = Field(int(1e9), ge=0)
    stage3_max_reuse_distance: int = Field(int(1e9), ge=0)
    stage3_prefetch_bucket_size: int = Field(int(5e7), ge=0)
    stage3_param_persistence_threshold: int = Field(int(1e5), ge=0)
    stage3_gather_16bit_weights_on_model_save: bool = False
    stage3_gather_fp16_weights_on_model_save: bool = Field(
        False, json_schema_extra={
            "deprecated": True,
            "new_param": "stage3_gather_16bit_weights_on_model_save"})

    ignore_unused_parameters: bool = True
    legacy_stage1: bool = False
    round_robin_gradients: bool = False
    # MiCS-style hierarchical sharding: shard ZeRO state over a sub-group of
    # the data axis, replicate across the rest (reference runtime/zero/mics.py)
    mics_shard_size: int = Field(-1, alias="mics_shard_size")
    mics_hierarchical_params_gather: bool = False
    memory_efficient_linear: bool = True

    def model_post_init(self, __context) -> None:
        if self.overlap_comm is None:
            self.overlap_comm = self.stage == ZeroStageEnum.weights

    def __repr__(self):
        return (f"DeepSpeedZeroConfig(stage={int(self.stage)}, "
                f"reduce_bucket_size={pp_int(self.reduce_bucket_size)}, "
                f"offload_param={self.offload_param}, "
                f"offload_optimizer={self.offload_optimizer})")
