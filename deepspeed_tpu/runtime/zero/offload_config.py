"""Offload configuration (≅ reference ``runtime/zero/offload_config.py:19,50``).

Device targets on TPU: ``none`` (HBM), ``cpu`` (TPU-VM host DRAM via
jax host memory / pinned_host), ``nvme`` (local SSD via the C++ AIO tier).
"""

from __future__ import annotations

from enum import Enum
from typing import Optional

from pydantic import Field

from ..config_utils import DeepSpeedConfigModel


class OffloadDeviceEnum(str, Enum):
    none = "none"
    cpu = "cpu"
    nvme = "nvme"


class DeepSpeedZeroOffloadParamConfig(DeepSpeedConfigModel):
    """``zero_optimization.offload_param`` block."""

    device: OffloadDeviceEnum = OffloadDeviceEnum.none
    nvme_path: Optional[str] = None
    buffer_count: int = Field(5, ge=0)
    buffer_size: int = Field(int(1e8), ge=0)
    max_in_cpu: int = Field(int(1e9), ge=0)
    pin_memory: bool = False


class DeepSpeedZeroOffloadOptimizerConfig(DeepSpeedConfigModel):
    """``zero_optimization.offload_optimizer`` block."""

    device: OffloadDeviceEnum = OffloadDeviceEnum.none
    nvme_path: Optional[str] = None
    buffer_count: int = Field(4, ge=0)
    pin_memory: bool = False
    pipeline_read: bool = False
    pipeline_write: bool = False
    fast_init: bool = False
    # TPU-repo extension: with device=nvme, keep the fp32 master resident
    # in host DRAM and swap only the Adam moments to NVMe. Halves the
    # per-step NVMe traffic and fits the common budget split (moments are
    # 2/3 of the optimizer bytes) when DRAM can hold params+master but not
    # the full optimizer state.
    swap_master: bool = True

    @property
    def pipeline(self) -> bool:
        return self.pipeline_read or self.pipeline_write
