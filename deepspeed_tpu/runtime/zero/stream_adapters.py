"""Model adapters for the streamed param-offload training path.

The reference's ``remote_device="cpu"|"nvme"`` works for any module built
under ``zero.Init`` (partition_parameters.py:616,288 — per-parameter
hooks). The TPU streaming runner needs slightly more structure — a
scan-stacked block to stream plus a resident embed/head — so model support
is an adapter: anything that can express

* ``split(params) -> (resident, stacked)`` / ``merge`` — which subtree
  streams layer-by-layer,
* ``embed_apply`` / ``head_loss`` — the resident computation around the
  streamed trunk (must match the module's own ``__call__`` numerics
  exactly; trajectory parity with the resident engine is asserted in
  tests),
* ``block_apply(layer_params, x, rng)`` — one streamed layer, with a
  per-layer dropout rng (lifts the round-4 dropout=0 restriction: keys are
  folded from (step, micro, layer), deterministic given the seed — note
  the rng STREAM differs from the resident engine's ``nn.scan`` rng
  split, so dropout>0 trains identically-distributed but not
  bit-identically to the resident path).

Supported families: ``TransformerLM`` (all presets) and
``GPT2LMHeadModel``. ``make_adapter`` is the registry.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp


class StreamedModelAdapter:
    """Protocol; see module docstring."""

    n_layer: int
    dropout: float
    # heterogeneous = True: layers differ structurally (Python-loop blocks
    # with per-layer param subtrees); split/merge deal in LISTS of layer
    # trees and the runner streams via HeteroLayerStore + per-layer-key
    # optimizer updates instead of stacked rows
    heterogeneous: bool = False
    # has_aux = True: block_apply returns (x, aux_loss); the runner
    # accumulates aux across layers and adds aux_weight * total to the
    # loss (the engine's tuple-return convention, engine.py:340)
    has_aux: bool = False
    aux_weight: float = 0.0

    def split(self, params: Dict) -> Tuple[Dict, Any]:
        """Full host param dict -> (resident subtree, stacked block tree
        with leading layer axis)."""
        resident = {k: v for k, v in params.items() if k != "blocks"}
        return resident, params["blocks"]["block"]

    def merge(self, resident: Dict, stacked) -> Dict:
        out = dict(resident)
        out["blocks"] = {"block": stacked}
        return out

    def embed_apply(self, resident, batch):
        raise NotImplementedError

    def block_apply(self, layer_params, x, rng, deterministic=None):
        """One streamed layer. ``deterministic=None`` means train mode
        (dropout active iff the config enables it); True forces eval."""
        raise NotImplementedError

    def head_loss(self, resident, xL, batch):
        raise NotImplementedError


class TransformerLMAdapter(StreamedModelAdapter):
    """``models/transformer_lm.TransformerLM`` — the round-4 behavior,
    plus dropout rng threading."""

    def __init__(self, module, compute_dtype):
        from ...models.transformer_lm import TransformerBlock

        self.cfg = module.config
        self.n_layer = self.cfg.n_layer
        self.dropout = self.cfg.dropout
        self.compute_dtype = compute_dtype
        self._block = TransformerBlock(self.cfg)

    def embed_apply(self, resident, batch):
        from ...models.transformer_lm import _norm

        cfg = self.cfg
        ids = batch["input_ids"]
        B, T = ids.shape
        x = jnp.take(resident["embed_tokens"]["embedding"], ids, axis=0)
        if cfg.pos_emb == "learned":
            pos = jnp.broadcast_to(jnp.arange(T)[None], (B, T))
            x = x + jnp.take(resident["embed_pos"]["embedding"], pos, axis=0)
        if cfg.embed_layernorm:
            x = _norm(cfg, "embed_ln").apply(
                {"params": resident["embed_ln"]}, x)
        return x.astype(self.compute_dtype)

    def block_apply(self, layer_params, x, rng, deterministic=None):
        # train mode = deterministic=False, matching the resident engine's
        # train step (rngs only when dropout actually draws)
        if deterministic is None:
            deterministic = False
        rngs = {"dropout": rng} if (not deterministic and
                                    self.dropout > 0) else None
        # TransformerBlock signature: (x, decode, deterministic, kv_cache)
        # -> (x, new_kv_cache); the training path carries no cache
        return self._block.apply({"params": layer_params}, x, False,
                                 deterministic, rngs=rngs)[0]

    def head_loss(self, resident, xL, batch):
        from ...models.transformer_lm import _norm

        cfg = self.cfg
        # EXACTLY TransformerLM.__call__'s tail (shift + masked xent).
        # Tied head: Embed.attend promotes both operands to cfg.dtype, so
        # the matmul runs in compute dtype — matching it keeps bf16
        # trajectories identical to the resident engine.
        x = _norm(cfg, "ln_f").apply({"params": resident["ln_f"]}, xL)
        if cfg.tie_word_embeddings:
            emb = resident["embed_tokens"]["embedding"]
            logits = x.astype(cfg.dtype) @ emb.T.astype(cfg.dtype)
        else:
            logits = x.astype(jnp.float32) @ \
                resident["lm_head"]["kernel"].astype(jnp.float32)
        return _shifted_xent(logits, batch)


class GPT2Adapter(StreamedModelAdapter):
    """``models/gpt2.GPT2LMHeadModel`` — round-5 generalization target
    (VERDICT r4 next-#3). Resident: wte, wpe, ln_f; streamed: the scanned
    blocks. The embed/head reuse the model's own flax submodules so the
    numerics (including Embed.attend's dtype promotion) match
    ``GPT2LMHeadModel.logits`` exactly."""

    def __init__(self, module, compute_dtype):
        import flax.linen as nn

        from ...models.gpt2 import Block

        self.cfg = module.config
        self.n_layer = self.cfg.n_layer
        self.dropout = self.cfg.dropout
        self.compute_dtype = compute_dtype
        cfg = self.cfg
        self._block = Block(cfg)
        self._wte = nn.Embed(cfg.vocab_size, cfg.n_embd, dtype=cfg.dtype)
        self._wpe = nn.Embed(cfg.n_positions, cfg.n_embd, dtype=cfg.dtype)
        self._ln_f = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon,
                                  dtype=cfg.dtype)

    def embed_apply(self, resident, batch):
        ids = batch["input_ids"]
        B, T = ids.shape
        pos = jnp.arange(T)[None, :]
        x = self._wte.apply({"params": resident["wte"]}, ids) + \
            self._wpe.apply({"params": resident["wpe"]}, pos)
        return x.astype(self.compute_dtype)

    def block_apply(self, layer_params, x, rng, deterministic=None):
        if deterministic is None:
            deterministic = False  # train mode, like the resident engine
        rngs = {"dropout": rng} if (not deterministic and
                                    self.dropout > 0) else None
        return self._block.apply({"params": layer_params}, x, deterministic,
                                 rngs=rngs)

    def head_loss(self, resident, xL, batch):
        x = self._ln_f.apply({"params": resident["ln_f"]}, xL)
        logits = self._wte.apply({"params": resident["wte"]},
                                 x.astype(jnp.float32), method="attend")
        return _shifted_xent(logits, batch)


class GPTMoEAdapter(StreamedModelAdapter):
    """``models/gpt_moe.GPTMoEModel`` — heterogeneous trunk (alternating
    dense / MoE blocks as per-layer param subtrees ``block_i``). Blocks
    return ``(x, aux)``; the runner threads the aux sum into the loss with
    ``cfg.aux_loss_weight`` and the per-layer vjp receives the matching
    aux cotangent, so expert-router gradients flow exactly as in the
    resident engine's compiled step."""

    heterogeneous = True
    has_aux = True

    def __init__(self, module, compute_dtype):
        import flax.linen as nn

        from ...models.gpt_moe import _Block

        self.cfg = module.config
        self.module = module
        self.n_layer = self.cfg.n_layer
        self.dropout = self.cfg.dropout
        self.aux_weight = float(self.cfg.aux_loss_weight)
        self.compute_dtype = compute_dtype
        cfg = self.cfg
        self._blocks = []
        moe_index = 0
        for i in range(cfg.n_layer):
            use_moe = cfg.moe_every > 0 and \
                (i % cfg.moe_every == cfg.moe_every - 1)
            n_exp = module._experts_for_block(moe_index) if use_moe else 0
            if use_moe:
                moe_index += 1
            self._blocks.append(_Block(cfg, use_moe, n_exp))
        self._wte = nn.Embed(cfg.vocab_size, cfg.n_embd, dtype=cfg.dtype)
        self._wpe = nn.Embed(cfg.n_positions, cfg.n_embd, dtype=cfg.dtype)
        self._ln_f = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon,
                                  dtype=cfg.dtype)

    def split(self, params: Dict) -> Tuple[Dict, Any]:
        resident = {k: v for k, v in params.items()
                    if not k.startswith("block_")}
        layers = [params[f"block_{i}"] for i in range(self.n_layer)]
        return resident, layers

    def merge(self, resident: Dict, layers) -> Dict:
        out = dict(resident)
        for i, tree in enumerate(layers):
            out[f"block_{i}"] = tree
        return out

    def layer_key(self, i: int) -> str:
        return f"block_{i}"

    def embed_apply(self, resident, batch):
        ids = batch["input_ids"]
        B, T = ids.shape
        pos = jnp.arange(T)[None, :]
        x = self._wte.apply({"params": resident["wte"]}, ids) + \
            self._wpe.apply({"params": resident["wpe"]}, pos)
        return x.astype(self.compute_dtype)

    def block_apply_layer(self, i, layer_params, x, rng,
                          deterministic=None):
        if deterministic is None:
            deterministic = False  # train mode: MoE capacity/gating differ
        rngs = None
        if not deterministic:
            # gating rng drives RTS / noisy-gate draws (seed-deterministic;
            # the STREAM differs from the resident engine's, so use_rts
            # trains identically-distributed but not bit-identically —
            # parity tests pin use_rts=False)
            rngs = {"gating": jax.random.fold_in(
                jnp.asarray(rng, jnp.uint32), 1)}
            if self.dropout > 0:
                rngs["dropout"] = jnp.asarray(rng, jnp.uint32)
        return self._blocks[i].apply({"params": layer_params}, x,
                                     deterministic, rngs=rngs)

    def head_loss(self, resident, xL, batch):
        # EXACTLY GPTMoEModel.__call__'s tail: ln_f + tied attend +
        # UNMASKED mean shifted NLL (gpt_moe.py:132-143); aux is added by
        # the runner
        x = self._ln_f.apply({"params": resident["ln_f"]}, xL)
        logits = self._wte.apply({"params": resident["wte"]},
                                 x.astype(jnp.float32), method="attend")
        ids = batch["input_ids"]
        labels = batch.get("labels", ids) if hasattr(batch, "get") else ids
        targets = labels[:, 1:]
        logp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
        token_ll = jnp.take_along_axis(logp, targets[..., None],
                                       axis=-1)[..., 0]
        return -jnp.mean(token_ll)


def _shifted_xent(logits, batch):
    """The shared GPT-family tail: causal shift + masked mean xent
    (mirrors GPT2LMHeadModel.__call__ / TransformerLM.__call__)."""
    input_ids = batch["input_ids"]
    labels = batch.get("labels", input_ids) if hasattr(batch, "get") \
        else input_ids
    logits = logits[:, :-1]
    targets = labels[:, 1:]
    mask = (targets >= 0).astype(jnp.float32)
    targets = jnp.maximum(targets, 0)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return (nll * mask).sum() / jnp.maximum(mask.sum(), 1.0)


def make_adapter(module, compute_dtype) -> StreamedModelAdapter:
    """Adapter registry for offload_param streaming; raises with the
    supported-family list for anything else."""
    from ...models.gpt2 import GPT2LMHeadModel
    from ...models.gpt_moe import GPTMoEModel
    from ...models.transformer_lm import TransformerLM

    if isinstance(module, TransformerLM):
        return TransformerLMAdapter(module, compute_dtype)
    if isinstance(module, GPT2LMHeadModel):
        return GPT2Adapter(module, compute_dtype)
    if isinstance(module, GPTMoEModel):
        return GPTMoEAdapter(module, compute_dtype)
    raise ValueError(
        "offload_param streaming supports TransformerLM and "
        f"GPT2LMHeadModel and GPTMoEModel modules (got "
        f"{type(module).__name__}); the module must expose a streamable "
        "per-layer trunk (scan-stacked blocks or per-layer block_i "
        "subtrees) plus a resident embed/head")
