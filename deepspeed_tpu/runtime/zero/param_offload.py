"""ZeRO-Infinity parameter offload for TRAINING — models larger than HBM.

Reference machinery matched: ``zero_optimization.offload_param`` —
``runtime/zero/partition_parameters.py:616`` (``remote_device``
"cpu"|"nvme"), ``swap_tensor/partitioned_param_swapper.py`` (NVMe param
tier), and stage3's prefetch/release discipline
(``runtime/zero/stage3.py:485,1662,1711``) — the capability behind the
reference's "13B trainable on one 32 GB V100" headline
(``docs/_pages/training.md:302``).

TPU-native shape: instead of stage3's per-parameter gather/partition hooks,
the scan-stacked transformer block is streamed through the chip one layer
at a time, twice per step:

* **forward**: layer ``i``'s packed bf16 buffer is uploaded (JAX async
  dispatch double-buffers upload against compute), one jitted block-apply
  reused for every layer produces the boundary activation; only the L+1
  boundary activations stay device-resident (layer-granular activation
  checkpointing by construction).
* **backward**: layers stream in REVERSE; one jitted ``vjp`` per layer
  recomputes the block forward and yields (dx, layer grads). Layer grads
  leave the chip immediately (``copy_to_host_async``) and accumulate into
  host fp32 buffers — the device never holds more than a couple of layers
  of parameters or gradients. Under a data-parallel mesh the grads'
  replicated out-sharding makes XLA insert the cross-replica reduction
  per layer (the reference's reduce-scatter-as-you-go, stage3.py:1065).
* **update**: the host-side :class:`OffloadedOptimizer` (native SIMD Adam,
  optionally NVMe-swapped state) applies the step and the new bf16 params
  replace the host/NVMe store. Device HBM holds O(boundary activations +
  2 layer buffers + resident embeddings/head) — independent of depth.

With ``device: nvme`` the packed per-layer buffers live in files moved by
the async AIO tier (``ops/csrc/aio.cpp``) with read-ahead, so host DRAM
holds O(staging buffers), not O(model). (The post-step rewrite currently
materializes the new param tree transiently in DRAM — device memory is
bounded by streaming; host DRAM must hold one bf16 copy of the model.
The reference's swapper shares this param-sized host staging requirement
via its pinned buffer pools.)

Engine surface: ``zero_optimization.offload_param.device: "cpu"|"nvme"``
turns this on inside :class:`~deepspeed_tpu.runtime.engine.DeepSpeedEngine`
(train via ``train_batch``; the eager triple does not compose with
streaming).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ...parallel import mesh as mesh_mod
from ...utils.logging import log_dist
from ...utils.streaming import LayerWireFormat
from .offload import OffloadedOptimizer, _flatten_with_paths, _unflatten_like
from .offload_config import OffloadDeviceEnum


def _path_str(path) -> str:
    return "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                    for k in path)


def _writable_tree(tree):
    """Leaf-wise: keep writable numpy arrays, copy anything else (numpy
    views of jax arrays are read-only; write_layer mutates rows in place)."""
    return jax.tree_util.tree_map(
        lambda a: a if getattr(a, "flags", None) is not None
        and a.flags.writeable else np.array(a), tree)


def _rank_dir(path: str) -> str:
    """Rank-namespace an NVMe directory under multi-process launch: the
    per-layer param/grad files and optimizer leaf files are rank-agnostic
    names, and two same-host processes sharing one dir would read each
    other's half-written files (no cross-rank barrier inside the
    finalize). Each process keeps its own full replica, same as the cpu
    tier's host arrays."""
    if jax.process_count() > 1:
        import os

        return os.path.join(path, f"rank{jax.process_index()}")
    return path


def _make_aio(aio_config, target_dir):
    """Shared AioHandle construction (LayerParamStore + HeteroLayerStore):
    thread sizing from the aio config, O_DIRECT when the filesystem
    supports it (DS_AIO_NO_ODIRECT=1 forces buffered)."""
    import os

    from ...ops.aio import AioHandle, o_direct_supported

    use_od = os.environ.get("DS_AIO_NO_ODIRECT") != "1" and \
        o_direct_supported(target_dir)
    ac = aio_config
    return AioHandle(
        num_threads=max(1, ac.thread_count if ac else 2),
        block_size=ac.block_size if ac else 1 << 20,
        queue_depth=ac.queue_depth if ac else 0,
        o_direct=use_od,
        single_submit=ac.single_submit if ac else False,
        overlap_events=ac.overlap_events if ac else True)


class _PackedWriteBuffers:
    """Double-buffered pack-and-write pair shared by both layer stores:
    packing layer i+1 overlaps the async write of layer i; the ticket for
    a half is drained only when that half is reused (or at flush)."""

    def __init__(self, aio, nbytes: int):
        from ...ops.aio import aligned_array

        self._aio = aio
        self._bufs = [aligned_array(nbytes) for _ in range(2)]
        self._tickets: List[Optional[int]] = [None, None]
        self._turn = 0

    def write(self, nbytes: int, fill, path: str) -> None:
        turn = self._turn
        if self._tickets[turn] is not None:
            self._aio.wait_ticket(self._tickets[turn])
            self._tickets[turn] = None
        buf = self._bufs[turn][:nbytes]
        fill(buf)
        self._tickets[turn] = self._aio.async_pwrite(buf, path)
        self._turn = 1 - turn

    def flush(self) -> None:
        for t, ticket in enumerate(self._tickets):
            if ticket is not None:
                self._aio.wait_ticket(ticket)
                self._tickets[t] = None


def check_supported(engine) -> None:
    """Fail at initialize() with actionable messages (mirrors the onebit
    wire's up-front validation). Round 5: model support went through the
    adapter registry (stream_adapters.make_adapter — TransformerLM +
    GPT2LMHeadModel) and dropout>0 is allowed (per-layer rng threading)."""
    from .stream_adapters import make_adapter

    make_adapter(engine.module, engine.compute_dtype)  # raises if unsupported
    opt_type = (engine._config.optimizer.type
                if engine._config.optimizer else "adam").lower()
    if opt_type not in ("adam", "adamw", "cpuadam"):
        raise ValueError(f"offload_param requires Adam/AdamW (got "
                         f"{opt_type!r}); the host step runs DeepSpeedCPUAdam")
    if engine.fp16_enabled:
        raise ValueError("offload_param streaming supports bf16/fp32 only "
                         "(no dynamic loss scaling on the host-step path); "
                         "use bf16 like the rest of the TPU stack")
    if engine.mp_world_size != 1 or \
            mesh_mod.get_sequence_parallel_world_size() > 1 or \
            mesh_mod.get_pipe_parallel_world_size() > 1:
        raise ValueError("offload_param streaming composes with data "
                         "parallelism only (mp=sp=pp=1)")
    # multi-process DP is supported (round 5): the per-layer grads carry a
    # replicated out-sharding over the GLOBAL mesh, so XLA's cross-replica
    # (cross-process) reduction runs before the D2H drain — every process
    # accumulates identical reduced grads and the per-process host Adam
    # stays in lockstep (asserted by tests/unit/comm/test_multiprocess.py)
    if engine._config.compression_training:
        raise ValueError("offload_param does not compose with compression "
                         "training (params are not device-resident)")


class LayerParamStore:
    """Host- or NVMe-resident scan-stacked block params served per layer as
    ONE packed byte buffer (the rotating-staging-buffer discipline of
    ``inference/zero_inference.py:_put_layer``, shared rationale documented
    there: pinned-transfer reuse, bounded RSS, no donation on the tunneled
    runtime)."""

    def __init__(self, stacked_host, n_layer: int, compute_dtype,
                 device: OffloadDeviceEnum, nvme_dir: Optional[str] = None,
                 aio_config=None, prefetch: int = 1):
        self.n_layer = n_layer
        self.prefetch = max(0, prefetch)
        self.nvme = device == OffloadDeviceEnum.nvme
        self._dtype = np.dtype(compute_dtype)

        first = jax.tree_util.tree_map(lambda a: np.asarray(a[0]),
                                       stacked_host)
        self.wire = LayerWireFormat(first, compute_dtype)
        self.treedef = self.wire.treedef
        self.leaf_shapes = self.wire.shapes
        self.leaf_wire_dtypes = self.wire.wire_dtypes
        self.leaf_nbytes = self.wire.nbytes
        self.layer_nbytes = self.wire.total_nbytes

        n_slots = self.prefetch + 2
        self._staging: List[np.ndarray] = []
        self._staging_dev: List[Optional[jax.Array]] = [None] * n_slots
        self._aio = None
        if self.nvme:
            import os

            from ...ops.aio import aligned_array

            self.dir = _rank_dir(nvme_dir or "/tmp/ds_tpu_param_nvme")
            os.makedirs(self.dir, exist_ok=True)
            self._aio = _make_aio(aio_config, self.dir)
            # O_DIRECT-compatible staging buffers + the shared
            # double-buffered pack pair
            self._staging = [aligned_array(self.layer_nbytes)
                             for _ in range(n_slots)]
            self._packer = _PackedWriteBuffers(self._aio, self.layer_nbytes)
            self.stacked = None
            self._write_all_layers(stacked_host)
        else:
            self._staging = [np.empty(self.layer_nbytes, np.uint8)
                             for _ in range(n_slots)]
            self.stacked = _writable_tree(stacked_host)
        # streaming bookkeeping (begin_pass/next_layer)
        self._order: List[int] = []
        self._pos = 0
        self._tickets: Dict[int, Any] = {}
        self._slot_of: Dict[int, int] = {}

    # -- packing -------------------------------------------------------
    def _layer_file(self, i: int) -> str:
        import os

        return os.path.join(self.dir, f"layer_{i:05d}.bin")

    def _pack_into(self, layer_tree, buf: np.ndarray) -> None:
        self.wire.pack_into(layer_tree, buf)

    def write_layer(self, i: int, layer_tree) -> None:
        """Install ONE layer's new params (host arrays, wire dtypes).

        cpu tier: in-place row copy into the resident stacked tree (no new
        allocation). nvme tier: pack into the free half of the
        double-buffered pack pair and submit the file write — packing
        layer i+1 overlaps the write of layer i; call ``flush_writes``
        after the last layer."""
        if not self.nvme:
            for dst, src in zip(jax.tree_util.tree_leaves(self.stacked),
                                jax.tree_util.tree_leaves(layer_tree)):
                np.copyto(dst[i], np.asarray(src).astype(dst.dtype,
                                                         copy=False))
            return
        self._packer.write(self.layer_nbytes,
                           lambda buf: self._pack_into(layer_tree, buf),
                           self._layer_file(i))

    def flush_writes(self) -> None:
        if self.nvme:
            self._packer.flush()

    def _write_all_layers(self, stacked) -> None:
        """(Re)write every per-layer NVMe file from a stacked host tree
        (init / checkpoint-restore path; the training step streams
        per-layer via ``write_layer`` instead)."""
        for i in range(self.n_layer):
            layer = jax.tree_util.tree_map(lambda a: np.asarray(a[i]),
                                           stacked)
            self.write_layer(i, layer)
        self.flush_writes()

    def unpack(self, flat):
        """Traced: packed buffer -> layer param tree. Training wires are
        dtype-uniform, so the buffer ships TYPED and unpacks by
        slice+reshape (see LayerWireFormat.uniform_dtype for why the byte
        path is a real-TPU hazard)."""
        if self.wire.uniform_dtype is not None:
            return self.wire.unpack_typed(flat)
        return self.wire.unpack(flat)

    # -- streaming -----------------------------------------------------
    def begin_pass(self, order: List[int]) -> None:
        """Declare the exact layer visit order for the next pass (ascending
        for forward, descending for backward); read-ahead follows it."""
        assert not self._tickets, "previous pass not drained"
        self._order = list(order)
        self._pos = 0
        self._slot_of = {}
        if self.nvme:
            for j in range(min(self.prefetch + 1, len(self._order))):
                self._submit_read(j)

    def _submit_read(self, pos: int) -> None:
        i = self._order[pos]
        slot = pos % len(self._staging)
        prev = self._staging_dev[slot]
        if prev is not None:
            prev.block_until_ready()  # host buffer still feeding a transfer
            self._staging_dev[slot] = None
        self._slot_of[i] = slot
        self._tickets[i] = self._aio.async_pread(self._staging[slot],
                                                 self._layer_file(i))

    def next_layer(self):
        """(layer_index, packed device buffer) following the declared
        order; submits the next read-ahead (nvme) before returning."""
        pos = self._pos
        i = self._order[pos]
        self._pos += 1
        if self.nvme:
            slot = self._slot_of.pop(i)
            self._aio.wait_ticket(self._tickets.pop(i))
            nxt = pos + self.prefetch + 1
            if nxt < len(self._order):
                self._submit_read(nxt)
        else:
            slot = pos % len(self._staging)
            prev = self._staging_dev[slot]
            if prev is not None:
                prev.block_until_ready()
                self._staging_dev[slot] = None
            layer = jax.tree_util.tree_map(lambda a: np.asarray(a[i]),
                                           self.stacked)
            self._pack_into(layer, self._staging[slot])
        # release guard refs for landed transfers (device footprint stays
        # O(prefetch+1 layers)); runtimes without is_ready keep the refs
        for s, dev in enumerate(self._staging_dev):
            if dev is not None and s != slot:
                try:
                    if dev.is_ready():
                        self._staging_dev[s] = None
                except AttributeError:
                    break
        buf = self._staging[slot]
        uni = self.wire.uniform_dtype
        if uni is not None:
            buf = buf.view(uni)  # zero-copy typed view of the staging bytes
        payload = buf.copy() if jax.default_backend() == "cpu" else buf
        dev = jax.device_put(payload)
        self._staging_dev[slot] = dev
        return i, dev

    def update_from_stacked(self, new_stacked) -> None:
        """Install a full stacked host tree (checkpoint-restore path; the
        training step streams per-layer via ``write_layer`` instead)."""
        if self.nvme:
            self._write_all_layers(new_stacked)
        else:
            self.stacked = _writable_tree(new_stacked)

    def materialize_stacked(self):
        """Full stacked host tree (reads every NVMe layer file) — the
        checkpoint path."""
        if not self.nvme:
            return self.stacked
        from ...ops.aio import aligned_array

        out_leaves = [np.empty((self.n_layer,) + s, d) for s, d in
                      zip(self.leaf_shapes, self.leaf_wire_dtypes)]
        buf = aligned_array(self.layer_nbytes)
        for i in range(self.n_layer):
            self._aio.async_pread(buf, self._layer_file(i))
            self._aio.wait()
            layer = self.wire.unpack_host(buf)
            for leaf, lv in zip(out_leaves,
                                jax.tree_util.tree_leaves(layer)):
                leaf[i] = lv
        return jax.tree_util.tree_unflatten(self.treedef, out_leaves)


class HeteroLayerStore:
    """Per-layer param store for models whose layers DIFFER in structure
    (gpt_moe: alternating dense / MoE blocks — a Python loop, not
    ``nn.scan``). Same streaming discipline as :class:`LayerParamStore`
    (rotating staging slots, NVMe read-ahead, double-buffered writeback)
    with one :class:`LayerWireFormat` per layer KIND; ``next_layer``
    additionally yields the kind so the runner picks the matching jitted
    block function."""

    def __init__(self, layers_host: List, compute_dtype,
                 device: OffloadDeviceEnum, nvme_dir: Optional[str] = None,
                 aio_config=None, prefetch: int = 1):
        self.n_layer = len(layers_host)
        self.prefetch = max(0, prefetch)
        self.nvme = device == OffloadDeviceEnum.nvme

        # group layers by structural signature -> kinds
        self.kind_of: List[int] = []
        self.wires: List[LayerWireFormat] = []
        sig_to_kind: Dict[Any, int] = {}
        for tree in layers_host:
            leaves_wp, treedef = jax.tree_util.tree_flatten(tree)
            sig = (treedef, tuple((np.shape(a), str(np.asarray(a).dtype))
                                  for a in leaves_wp))
            if sig not in sig_to_kind:
                sig_to_kind[sig] = len(self.wires)
                self.wires.append(LayerWireFormat(tree, compute_dtype))
            self.kind_of.append(sig_to_kind[sig])
        self.max_nbytes = max(w.total_nbytes for w in self.wires)

        n_slots = self.prefetch + 2
        self._staging_dev: List[Optional[jax.Array]] = [None] * n_slots
        self._aio = None
        if self.nvme:
            import os

            from ...ops.aio import aligned_array

            self.dir = _rank_dir(nvme_dir or "/tmp/ds_tpu_param_nvme")
            os.makedirs(self.dir, exist_ok=True)
            self._aio = _make_aio(aio_config, self.dir)
            self._staging = [aligned_array(self.max_nbytes)
                             for _ in range(n_slots)]
            self._packer = _PackedWriteBuffers(self._aio, self.max_nbytes)
            self.layers = None
            for i, tree in enumerate(layers_host):
                self.write_layer(i, tree)
            self.flush_writes()
        else:
            self._staging = [np.empty(self.max_nbytes, np.uint8)
                             for _ in range(n_slots)]
            self.layers = [_writable_tree(t) for t in layers_host]
        self._order: List[int] = []
        self._pos = 0
        self._tickets: Dict[int, Any] = {}
        self._slot_of: Dict[int, int] = {}

    def _layer_file(self, i: int) -> str:
        import os

        return os.path.join(self.dir, f"layer_{i:05d}.bin")

    def unpack(self, kind: int, flat):
        w = self.wires[kind]
        if w.uniform_dtype is not None:
            return w.unpack_typed(flat)
        return w.unpack(flat)

    def begin_pass(self, order: List[int]) -> None:
        assert not self._tickets, "previous pass not drained"
        self._order = list(order)
        self._pos = 0
        self._slot_of = {}
        if self.nvme:
            for j in range(min(self.prefetch + 1, len(self._order))):
                self._submit_read(j)

    def _submit_read(self, pos: int) -> None:
        i = self._order[pos]
        slot = pos % len(self._staging)
        prev = self._staging_dev[slot]
        if prev is not None:
            prev.block_until_ready()
            self._staging_dev[slot] = None
        self._slot_of[i] = slot
        nbytes = self.wires[self.kind_of[i]].total_nbytes
        self._tickets[i] = self._aio.async_pread(
            self._staging[slot][:nbytes], self._layer_file(i))

    def next_layer(self):
        """(layer_index, kind, packed device buffer) in declared order."""
        pos = self._pos
        i = self._order[pos]
        kind = self.kind_of[i]
        w = self.wires[kind]
        self._pos += 1
        if self.nvme:
            slot = self._slot_of.pop(i)
            self._aio.wait_ticket(self._tickets.pop(i))
            nxt = pos + self.prefetch + 1
            if nxt < len(self._order):
                self._submit_read(nxt)
        else:
            slot = pos % len(self._staging)
            prev = self._staging_dev[slot]
            if prev is not None:
                prev.block_until_ready()
                self._staging_dev[slot] = None
            w.pack_into(self.layers[i], self._staging[slot][:w.total_nbytes])
        for s, dev in enumerate(self._staging_dev):
            if dev is not None and s != slot:
                try:
                    if dev.is_ready():
                        self._staging_dev[s] = None
                except AttributeError:
                    break
        buf = self._staging[slot][:w.total_nbytes]
        if w.uniform_dtype is not None:
            buf = buf.view(w.uniform_dtype)
        payload = buf.copy() if jax.default_backend() == "cpu" else buf
        dev = jax.device_put(payload)
        self._staging_dev[slot] = dev
        return i, kind, dev

    def write_layer(self, i: int, layer_tree) -> None:
        if not self.nvme:
            for dst, src in zip(jax.tree_util.tree_leaves(self.layers[i]),
                                jax.tree_util.tree_leaves(layer_tree)):
                np.copyto(dst, np.asarray(src).astype(dst.dtype, copy=False))
            return
        w = self.wires[self.kind_of[i]]
        self._packer.write(w.total_nbytes,
                           lambda buf: w.pack_into(layer_tree, buf),
                           self._layer_file(i))

    def flush_writes(self) -> None:
        if self.nvme:
            self._packer.flush()

    def materialize_layers(self) -> List:
        """All layers as host trees (checkpoint surface)."""
        if not self.nvme:
            return list(self.layers)
        from ...ops.aio import aligned_array

        out = []
        buf = aligned_array(self.max_nbytes)
        for i in range(self.n_layer):
            w = self.wires[self.kind_of[i]]
            t = self._aio.async_pread(buf[:w.total_nbytes],
                                      self._layer_file(i))
            self._aio.wait_ticket(t)
            out.append(w.unpack_host(buf[:w.total_nbytes]))
        return out


class GradRowStore:
    """Per-layer gradient accumulation for the streamed backward.

    dram mode: fp32 row arrays per (leaf, layer), freed per layer by the
    finalize. nvme mode (the full ZeRO-Infinity grad tier,
    ``swap_tensor``'s gradient swap analog): each layer's packed fp32 grad
    rows live in ONE file; accumulation is read-modify-write per micro
    batch and the per-layer sum-of-squares is captured on the LAST micro,
    so the global-norm clip never needs the whole grad tree in DRAM —
    host memory stays O(layer) for the entire step."""

    def __init__(self, n_layer: int, leaf_shapes, nvme_dir: Optional[str],
                 aio=None, per_layer_shapes=None):
        """``leaf_shapes``: shared per-layer leaf shapes (scan-stacked
        models); ``per_layer_shapes`` overrides with one shape list PER
        layer (heterogeneous models, e.g. alternating dense/MoE blocks)."""
        self.n_layer = n_layer
        if per_layer_shapes is None:
            per_layer_shapes = [list(leaf_shapes)] * n_layer
        self._layer_shapes = [list(s) for s in per_layer_shapes]
        self._layer_sizes = [[int(np.prod(s)) if s else 1 for s in shapes]
                             for shapes in self._layer_shapes]
        self._layer_offsets = [np.cumsum([0] + sizes)
                               for sizes in self._layer_sizes]
        self._layer_total = [int(off[-1]) for off in self._layer_offsets]
        self.nvme = nvme_dir is not None
        self.sq: Dict[int, float] = {}
        if self.nvme:
            import os

            from ...ops.aio import aligned_array

            self.dir = os.path.join(nvme_dir, "grads")
            os.makedirs(self.dir, exist_ok=True)
            self._aio = aio
            self._buf = aligned_array(
                max(self._layer_total) * 4).view(np.float32)
            self._have: set = set()
        else:
            self.rows: Dict[int, Optional[np.ndarray]] = {}

    def _file(self, li: int) -> str:
        import os

        return os.path.join(self.dir, f"grad_{li:05d}.bin")

    def _pack(self, li: int, leaves, out: np.ndarray) -> None:
        for off, size, leaf in zip(self._layer_offsets[li],
                                   self._layer_sizes[li], leaves):
            out[off:off + size] = np.asarray(leaf, np.float32).ravel()

    def accumulate(self, li: int, leaves, is_last: bool) -> None:
        """Add one micro batch's fp32 grad rows for layer ``li``; on the
        last micro also record the layer's sum of squares."""
        total = self._layer_total[li]
        if not self.nvme:
            flat = self.rows.get(li)
            if flat is None:
                flat = np.empty(total, np.float32)
                self._pack(li, leaves, flat)
                self.rows[li] = flat
            else:
                for off, size, leaf in zip(self._layer_offsets[li],
                                           self._layer_sizes[li], leaves):
                    flat[off:off + size] += np.asarray(
                        leaf, np.float32).ravel()
            if is_last:
                self.sq[li] = float(np.dot(flat, flat))
            return
        # per-ticket waits only: the AioHandle is SHARED with
        # LayerParamStore — a handle-global wait() here would drain the
        # store's in-flight layer prefetches / pack writes and serialize
        # the streaming pipeline
        buf = self._buf[:total]
        if li in self._have:
            t = self._aio.async_pread(buf, self._file(li))
            self._aio.wait_ticket(t)
            for off, size, leaf in zip(self._layer_offsets[li],
                                       self._layer_sizes[li], leaves):
                buf[off:off + size] += np.asarray(
                    leaf, np.float32).ravel()
        else:
            self._pack(li, leaves, buf)
            self._have.add(li)
        if is_last:
            self.sq[li] = float(np.dot(buf, buf))
        t = self._aio.async_pwrite(buf, self._file(li))
        self._aio.wait_ticket(t)

    def total_sq(self) -> float:
        return float(sum(self.sq.values()))

    def read_rows(self, li: int):
        """The layer's accumulated fp32 rows (leaf-shaped views)."""
        if not self.nvme:
            flat = self.rows[li]
        else:
            flat = self._buf[:self._layer_total[li]]
            t = self._aio.async_pread(flat, self._file(li))
            self._aio.wait_ticket(t)  # shared handle: no global wait
        return [flat[off:off + size].reshape(shape)
                for off, size, shape in zip(self._layer_offsets[li],
                                            self._layer_sizes[li],
                                            self._layer_shapes[li])]

    def free(self, li: int) -> None:
        if not self.nvme:
            self.rows[li] = None
        # nvme: the file is simply overwritten next step

    def reset(self) -> None:
        self.sq = {}
        if self.nvme:
            self._have = set()
        else:
            self.rows = {}


class ParamOffloadRunner:
    """The engine's ``offload_param`` training path: streamed forward /
    backward over :class:`LayerParamStore` + host :class:`OffloadedOptimizer`
    step. Driven by ``DeepSpeedEngine.train_batch``."""

    RESIDENT_KEYS = ("embed_tokens", "embed_pos", "embed_ln", "ln_f",
                     "lm_head")

    def __init__(self, engine, params_host):
        from .stream_adapters import make_adapter

        check_supported(engine)
        self.engine = engine
        cfg = engine.module.config
        self.cfg = cfg
        self.mesh = engine.mesh
        self.compute_dtype = engine.compute_dtype
        self.adapter = make_adapter(engine.module, engine.compute_dtype)
        self.clip = engine.gradient_clipping()
        self.gas = engine.gradient_accumulation_steps()
        self.op_cfg = engine.zero_config.offload_param
        self._base_rng = jax.random.PRNGKey(
            getattr(engine._config, "seed", 1234) or 1234)

        params_host = jax.tree_util.tree_map(lambda a: np.asarray(a),
                                             params_host)
        self._treedef = jax.tree_util.tree_structure(params_host)
        # canonical flat paths (must match OffloadedOptimizer's keys)
        self._all_keys = list(_flatten_with_paths(params_host).keys())

        # host optimizer over the FULL tree (resident + stacked) — master
        # placement per offload_optimizer config (default: host DRAM)
        oo = engine.zero_config.offload_optimizer
        if oo is None or oo.device == OffloadDeviceEnum.none:
            from .offload_config import DeepSpeedZeroOffloadOptimizerConfig

            oo = DeepSpeedZeroOffloadOptimizerConfig(device="cpu")
        opt_cfg = engine._config.optimizer
        opt_params = dict(opt_cfg.params if opt_cfg else {})
        opt_params.setdefault("lr", engine._base_lr)
        self.opt = OffloadedOptimizer(params_host, opt_params, oo,
                                      aio_config=engine._config.aio)

        # split the tree: resident (device) vs streamed (store)
        self.hetero = getattr(self.adapter, "heterogeneous", False)
        self.has_aux = getattr(self.adapter, "has_aux", False)
        self._resident_host, streamed = self.adapter.split(params_host)
        if self.hetero:
            self.store = HeteroLayerStore(
                streamed, self.compute_dtype, self.op_cfg.device,
                nvme_dir=self.op_cfg.nvme_path,
                aio_config=engine._config.aio,
                prefetch=max(1, min(self.op_cfg.buffer_count - 1, 4)))
        else:
            self.store = LayerParamStore(
                streamed, cfg.n_layer, self.compute_dtype,
                self.op_cfg.device, nvme_dir=self.op_cfg.nvme_path,
                aio_config=engine._config.aio,
                prefetch=max(1, min(self.op_cfg.buffer_count - 1, 4)))

        rep = NamedSharding(self.mesh, PartitionSpec())
        self._rep = rep
        batch_axes = tuple(mesh_mod.batch_axes())
        self._data_sh = NamedSharding(self.mesh, PartitionSpec(batch_axes))

        def to_dev(tree):
            def put(a):
                a = np.asarray(a)
                if jnp.issubdtype(a.dtype, jnp.floating):
                    a = a.astype(self.compute_dtype)
                return jax.device_put(a, rep)

            return jax.tree_util.tree_map(put, tree)

        self.resident = to_dev(self._resident_host)

        adapter = self.adapter

        # ---- jitted pieces (each reused for every layer/micro) --------
        if self.hetero:
            self._build_hetero_block_fns(rep)
        else:
            unpack = self.store.unpack

            def block_fwd(packed, x, rng):
                return adapter.block_apply(unpack(packed), x, rng)

            self._jit_block_fwd = jax.jit(
                block_fwd, out_shardings=self._data_sh)

            def block_fwd_eval(packed, x, rng):
                return adapter.block_apply(unpack(packed), x, rng,
                                           deterministic=True)

            self._jit_block_fwd_eval = jax.jit(
                block_fwd_eval, out_shardings=self._data_sh)

            def block_bwd(packed, x, dy, rng):
                layer = unpack(packed)

                def f(lp, xi):
                    return adapter.block_apply(lp, xi, rng)

                _, vjp = jax.vjp(f, layer, x)
                dlayer, dx = vjp(dy)
                return dx, dlayer

            grad_rep = jax.tree_util.tree_map(
                lambda _: rep,
                jax.tree_util.tree_unflatten(
                    self.store.treedef,
                    [0] * len(self.store.leaf_shapes)))
            self._jit_block_bwd = jax.jit(
                block_bwd, out_shardings=(self._data_sh, grad_rep))

        def embed_fwd(resident, batch):
            return adapter.embed_apply(resident, batch)

        self._jit_embed = jax.jit(embed_fwd, out_shardings=self._data_sh)

        head_loss = adapter.head_loss

        def head_bwd(resident, xL, batch):
            (loss, (dres, dx)) = jax.value_and_grad(
                head_loss, argnums=(0, 1))(resident, xL, batch)
            return loss, dres, dx

        res_rep = jax.tree_util.tree_map(lambda _: rep, self.resident)
        self._jit_head_bwd = jax.jit(
            head_bwd, out_shardings=(rep, res_rep, self._data_sh))
        # loss-only head for evaluation: no value_and_grad over the
        # resident tree (ADVICE r4: eval_loss must not pay the head
        # backward + gradient buffers)
        self._jit_head_loss = jax.jit(head_loss, out_shardings=rep)

        def embed_bwd(resident, batch, dx0, dres_head):
            _, vjp = jax.vjp(lambda r: embed_fwd(r, batch), resident)
            (dres,) = vjp(dx0.astype(self.compute_dtype))
            return jax.tree_util.tree_map(
                lambda a, b: a.astype(jnp.float32) + b.astype(jnp.float32),
                dres, dres_head)

        res_rep32 = jax.tree_util.tree_map(lambda _: rep, self.resident)
        self._jit_embed_bwd = jax.jit(embed_bwd, out_shardings=res_rep32)

        self._acc_add = jax.jit(lambda a, b: jax.tree_util.tree_map(
            lambda x, y: x + y, a, b))

        # per-layer grad accumulation: DRAM rows (cpu tier) or per-layer
        # NVMe files (nvme tier — the ZeRO-Infinity gradient-swap analog,
        # O(layer) host DRAM for the whole step)
        if self.hetero:
            self.grads = GradRowStore(
                self.store.n_layer, None,
                self.store.dir if self.store.nvme else None,
                aio=self.store._aio,
                per_layer_shapes=[
                    self.store.wires[k].shapes for k in self.store.kind_of])
        else:
            self.grads = GradRowStore(
                self.store.n_layer, self.store.leaf_shapes,
                self.store.dir if self.store.nvme else None,
                aio=self.store._aio)
        self.last_timings: Dict[str, float] = {}
        nbytes = self.store.max_nbytes if self.hetero \
            else self.store.layer_nbytes
        log_dist(
            f"ZeRO param offload: device={self.op_cfg.device} "
            f"{cfg.n_layer} layers x {nbytes / 1e6:.1f} MB streamed, "
            f"optimizer={'nvme' if self.opt.nvme else 'cpu'}"
            + ("" if self.opt.swap_master or not self.opt.nvme
               else " (moments-only swap)"), ranks=[0])

    # -- helpers -------------------------------------------------------
    def _build_hetero_block_fns(self, rep_sharding):
        """One jitted fwd/bwd/eval per structural KIND (dense vs each MoE
        shape) — layers of the same kind share the compiled program. Block
        outputs are ``(x, aux)``; the bwd vjp receives ``aux_weight`` as
        the aux cotangent so router grads match the resident engine."""
        adapter = self.adapter
        store = self.store
        aux_ct = jnp.asarray(getattr(adapter, "aux_weight", 0.0),
                             jnp.float32)
        rep_layer = {}
        for i, k in enumerate(store.kind_of):
            rep_layer.setdefault(k, i)
        self._jit_block_fwd_k = {}
        self._jit_block_fwd_eval_k = {}
        self._jit_block_bwd_k = {}
        for k, ri in rep_layer.items():
            def fwd(packed, x, rng, _k=k, _ri=ri):
                return adapter.block_apply_layer(
                    _ri, store.unpack(_k, packed), x, rng)

            def fwd_eval(packed, x, rng, _k=k, _ri=ri):
                return adapter.block_apply_layer(
                    _ri, store.unpack(_k, packed), x, rng,
                    deterministic=True)

            def bwd(packed, x, dy, rng, _k=k, _ri=ri):
                layer = store.unpack(_k, packed)

                def f(lp, xi):
                    return adapter.block_apply_layer(_ri, lp, xi, rng)

                _, vjp = jax.vjp(f, layer, x)
                dlayer, dx = vjp((dy, aux_ct))
                return dx, dlayer

            grad_rep = jax.tree_util.tree_map(
                lambda _: rep_sharding,
                jax.tree_util.tree_unflatten(
                    store.wires[k].treedef,
                    [0] * len(store.wires[k].shapes)))
            self._jit_block_fwd_k[k] = jax.jit(
                fwd, out_shardings=(self._data_sh, rep_sharding))
            self._jit_block_fwd_eval_k[k] = jax.jit(
                fwd_eval, out_shardings=(self._data_sh, rep_sharding))
            self._jit_block_bwd_k[k] = jax.jit(
                bwd, out_shardings=(self._data_sh, grad_rep))

    def _layer_paths(self, i: int):
        """Canonical flat param paths of heterogeneous layer ``i``."""
        kind = self.store.kind_of[i]
        w = self.store.wires[kind]
        leaves_wp, _ = jax.tree_util.tree_flatten_with_path(
            jax.tree_util.tree_unflatten(w.treedef,
                                         list(range(len(w.shapes)))))
        prefix = self.adapter.layer_key(i) + "/"
        return [prefix + _path_str(p) for p, _ in leaves_wp]

    def _stacked_paths(self):
        """Canonical flat path prefix for stacked leaves."""
        leaves_wp, _ = jax.tree_util.tree_flatten_with_path(
            jax.tree_util.tree_unflatten(
                self.store.treedef, list(range(len(self.store.leaf_shapes)))))
        return ["blocks/block/" + _path_str(p) for p, _ in leaves_wp]


    # -- the step ------------------------------------------------------
    def train_batch(self, micro_batches) -> Dict[str, Any]:
        """One global step over ``gas`` micro batches (host numpy trees).
        Returns the engine-shaped metrics dict."""
        t0 = time.perf_counter()
        self.grads.reset()
        L = self.store.n_layer
        stacked_paths = None if self.hetero else self._stacked_paths()
        aux_sum = 0.0
        res_grad_acc = None
        loss_sum = 0.0
        t_fwd = t_bwd = 0.0
        eng = self.engine
        # per-(micro, layer) dropout keys, one device op per step; numpy
        # rows feed the jitted block fns (same key for fwd and bwd vjp so
        # the recompute sees identical masks)
        step_rng = jax.random.fold_in(self._base_rng, eng.global_steps)
        np_keys = np.asarray(jax.random.split(
            step_rng, max(1, len(micro_batches)) * L)).reshape(
                max(1, len(micro_batches)), L, -1)

        for mi, mb in enumerate(micro_batches):
            mb = jax.tree_util.tree_map(
                lambda a: jax.device_put(np.asarray(a), self._data_sh), mb)
            tf0 = time.perf_counter()
            x = self._jit_embed(self.resident, mb)
            acts = [x]
            micro_aux = []  # device scalars; fetched with the loss below
            self.store.begin_pass(list(range(L)))
            for li in range(L):
                if self.hetero:
                    _, kind, packed = self.store.next_layer()
                    x, aux = self._jit_block_fwd_k[kind](
                        packed, x, np_keys[mi, li])
                    if self.has_aux:
                        micro_aux.append(aux)
                else:
                    _, packed = self.store.next_layer()
                    x = self._jit_block_fwd(packed, x, np_keys[mi, li])
                acts.append(x)
            loss, dres_head, dy = self._jit_head_bwd(
                self.resident, acts[-1], mb)
            t_fwd += time.perf_counter() - tf0

            tb0 = time.perf_counter()
            is_last = mi == len(micro_batches) - 1
            pending = deque()  # (layer, dlayer) with D2H in flight
            self.store.begin_pass(list(range(L - 1, -1, -1)))
            for li in range(L - 1, -1, -1):
                if self.hetero:
                    _, kind, packed = self.store.next_layer()
                    dy, dlayer = self._jit_block_bwd_k[kind](
                        packed, acts[li], dy, np_keys[mi, li])
                else:
                    _, packed = self.store.next_layer()
                    dy, dlayer = self._jit_block_bwd(packed, acts[li], dy,
                                                     np_keys[mi, li])
                acts[li + 1] = None  # free the boundary activation
                for g in jax.tree_util.tree_leaves(dlayer):
                    g.copy_to_host_async()
                pending.append((li, dlayer))
                if len(pending) > 1:
                    self._drain_grad(pending.popleft(), is_last)
            while pending:
                self._drain_grad(pending.popleft(), is_last)
            dres = self._jit_embed_bwd(
                self.resident, mb, dy, dres_head)
            res_grad_acc = dres if res_grad_acc is None else \
                self._acc_add(res_grad_acc, dres)
            loss_sum += float(loss)
            if self.has_aux and micro_aux:
                # engine tuple-return convention: metric = loss + w * aux;
                # sum on device (scalar adds), ONE host fetch per micro
                aux_dev = micro_aux[0]
                for a in micro_aux[1:]:
                    aux_dev = self._acc_add(aux_dev, a)
                aux_sum += self.adapter.aux_weight * float(aux_dev)
            acts = None
            t_bwd += time.perf_counter() - tb0

        # ---- finalize: norm, clip, host Adam, store update ------------
        # Layer-streamed (round 5, VERDICT r4 next-#4): resident leaves go
        # through the pipelined whole-leaf step; the stacked trunk updates
        # one LAYER at a time (per-row Adam via step_rows, write_layer
        # writeback, grad rows freed as they land) — the full new param
        # tree never materializes in host DRAM.
        t2 = time.perf_counter()
        res_host = jax.device_get(res_grad_acc)
        res_flat = {k: np.asarray(v, np.float32) for k, v in
                    _flatten_with_paths(res_host).items()}
        inv_gas = 1.0 / float(self.gas)
        sq = self.grads.total_sq()
        for a in res_flat.values():
            flat = a.reshape(-1)
            sq += float(np.dot(flat, flat))
        grad_norm = float(np.sqrt(sq)) * inv_gas
        scale = inv_gas
        if self.clip > 0 and grad_norm > self.clip:
            scale *= self.clip / (grad_norm + 1e-6)

        lr = float(eng._lr_fn(jnp.asarray(eng.global_steps)))
        step_num = eng.global_steps + 1
        new_res_flat = self.opt.step(
            res_flat, lr, step_num, np.dtype(self.compute_dtype),
            grad_scale=scale, release_grads=True,
            keys=set(res_flat.keys()))
        self._resident_host = _unflatten_like(
            self._resident_host, new_res_flat)
        self.resident = jax.tree_util.tree_map(
            lambda a: jax.device_put(np.asarray(a), self._rep),
            self._resident_host)
        t3 = time.perf_counter()

        for li in range(L):
            rows = self.grads.read_rows(li)
            if self.hetero:
                # per-layer param subtrees: whole-leaf pipelined step over
                # just this layer's keys (same O(layer) discipline)
                paths = self._layer_paths(li)
                new_flat = self.opt.step(
                    dict(zip(paths, rows)), lr, step_num,
                    np.dtype(self.compute_dtype), grad_scale=scale,
                    release_grads=True, keys=set(paths))
                self.grads.free(li)
                kind = self.store.kind_of[li]
                self.store.write_layer(li, jax.tree_util.tree_unflatten(
                    self.store.wires[kind].treedef,
                    [new_flat[p] for p in paths]))
                continue
            new_rows = [
                self.opt.step_rows(path, li, row, lr, step_num,
                                   np.dtype(self.compute_dtype),
                                   grad_scale=scale)
                for path, row in zip(stacked_paths, rows)]
            self.opt.drain_row_writes()  # one drain per layer, not per row
            self.grads.free(li)
            self.store.write_layer(li, jax.tree_util.tree_unflatten(
                self.store.treedef, new_rows))
        self.store.flush_writes()
        self.opt.drain_row_writes()
        t4 = time.perf_counter()

        self.last_timings = {
            "forward_stream_s": t_fwd, "backward_stream_s": t_bwd,
            "grad_finalize_s": t2 - t0 - t_fwd - t_bwd,
            "host_adam_s": t3 - t2,  # resident leaves (pipelined step)
            # stacked trunk: per-layer Adam + writeback, streamed
            "param_writeback_s": t4 - t3,
            **{f"adam_{k}": v for k, v in
               getattr(self.opt, "last_timings", {}).items()},
        }
        return {
            "loss": (loss_sum + aux_sum) * inv_gas,
            "grad_norm": grad_norm,
            "lr": lr,
            "overflow": False,
            "loss_scale": 1.0,
        }

    def _drain_grad(self, item, is_last: bool) -> None:
        li, dlayer = item
        self.grads.accumulate(
            li, jax.tree_util.tree_leaves(dlayer), is_last)

    # -- eval / checkpoint surface -------------------------------------
    def eval_loss(self, batch) -> float:
        """Streamed forward + loss (no grads) — evaluation under offload.
        Uses the loss-only head jit (no resident backward / grad buffers)
        and deterministic blocks (eval keys are unused when dropout=0 and
        fixed when dropout>0 — evaluation never drops, matching the
        resident engine's eval_batch)."""
        mb = jax.tree_util.tree_map(
            lambda a: jax.device_put(np.asarray(a), self._data_sh), batch)
        x = self._jit_embed(self.resident, mb)
        L = self.store.n_layer
        zero_key = np.zeros_like(
            np.asarray(jax.random.PRNGKey(0)))
        aux_dev = None
        self.store.begin_pass(list(range(L)))
        for _ in range(L):
            if self.hetero:
                _, kind, packed = self.store.next_layer()
                x, aux = self._jit_block_fwd_eval_k[kind](packed, x,
                                                          zero_key)
                if self.has_aux:
                    aux_dev = aux if aux_dev is None else \
                        self._acc_add(aux_dev, aux)
            else:
                _, packed = self.store.next_layer()
                x = self._jit_block_fwd_eval(packed, x, zero_key)
        loss = float(self._jit_head_loss(self.resident, x, mb))
        if aux_dev is not None:
            loss += self.adapter.aux_weight * float(aux_dev)
        return loss

    def full_params_tree(self):
        """The complete param pytree as host arrays (checkpoint surface;
        materializes the NVMe store)."""
        if self.hetero:
            tree = self.adapter.merge(self._resident_host,
                                      self.store.materialize_layers())
        else:
            tree = self.adapter.merge(self._resident_host,
                                      self.store.materialize_stacked())
        # restore original key order via the saved treedef
        flat = _flatten_with_paths(tree)
        return jax.tree_util.tree_unflatten(
            self._treedef, [flat[k] for k in self._all_keys])

    def load_params(self, params_host) -> None:
        """Install externally-loaded params (checkpoint restore); the
        caller is responsible for optimizer state (engine handles it via
        sync_master_from / load_state_dict, same as the resident path)."""
        params_host = jax.tree_util.tree_map(lambda a: np.asarray(a),
                                             params_host)
        self._resident_host, streamed = self.adapter.split(params_host)
        self.resident = jax.tree_util.tree_map(
            lambda a: jax.device_put(
                a.astype(self.compute_dtype) if jnp.issubdtype(
                    a.dtype, jnp.floating) else a, self._rep),
            self._resident_host)
        if self.hetero:
            for i, tree in enumerate(streamed):
                self.store.write_layer(i, tree)
            self.store.flush_writes()
        else:
            self.store.update_from_stacked(streamed)
