"""ZeRO-Offload / ZeRO-Infinity optimizer-state offload tiers.

Reference machinery being matched: stage-1/2 CPU-offload grad path
(``stage_1_and_2.py:1037``) + ``DeepSpeedCPUAdam`` host step, and the
ZeRO-Infinity optimizer-state NVMe swappers (``runtime/zero/stage3.py:485``,
``swap_tensor/partitioned_optim_swapper.py``).

TPU-native shape: the compiled device step produces (loss, clipped fp32
grads); grads come to host DRAM once per global step, the native SIMD Adam
(``ops/csrc/cpu_adam.cpp``) updates fp32 master + moments in place, and the
new compute-dtype params are device_put back — the host↔HBM transfer pair is
the analog of the reference's PCIe pinned-buffer shuttle. With
``device: nvme``, moments and master live in files under ``nvme_path``
between steps, moved with the async AIO library (``ops/csrc/aio.cpp``):
reads are submitted for all leaves up front and overlap; writes drain after
the step (≅ PipelinedOptimizerSwapper's overlap, phase-granular).
"""

from __future__ import annotations

import os
import re
from typing import Any, Dict, Optional

import numpy as np

from ...ops.adam.cpu_adam import DeepSpeedCPUAdam
from ...utils.logging import log_dist, logger
from .offload_config import DeepSpeedZeroOffloadOptimizerConfig, OffloadDeviceEnum


def _flatten_with_paths(tree):
    import jax

    leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    out = {}
    for path, leaf in leaves:
        p = "/".join(str(getattr(k, "key", getattr(k, "idx", k))) for k in path)
        out[p] = leaf
    return out


def _unflatten_like(tree, flat: Dict[str, Any]):
    import jax

    def pick(path, leaf):
        p = "/".join(str(getattr(k, "key", getattr(k, "idx", k))) for k in path)
        return flat[p]

    return jax.tree_util.tree_map_with_path(pick, tree)


class OffloadedOptimizer:
    """Host-resident Adam over fp32 master params + moments, optionally
    swapped to NVMe between steps."""

    def __init__(self, params_host, opt_params: Dict,
                 config: DeepSpeedZeroOffloadOptimizerConfig,
                 compute_dtype=None, aio_config=None):
        self.config = config
        self._aio_config = aio_config
        self.nvme = config.device == OffloadDeviceEnum.nvme
        betas = tuple(opt_params.get("betas", (0.9, 0.999)))
        self.opt = DeepSpeedCPUAdam(
            lr=opt_params.get("lr", 1e-3), betas=betas,
            eps=opt_params.get("eps", 1e-8),
            weight_decay=opt_params.get("weight_decay", 0.0),
            adamw_mode=opt_params.get("adam_w_mode", True),
            bias_correction=opt_params.get("bias_correction", True))
        log_dist(f"ZeRO-Offload optimizer: device={config.device} "
                 f"native_adam={self.opt.native}", ranks=[0])

        # nvme tier: optionally keep the fp32 master DRAM-resident and swap
        # only the moments (offload_config.swap_master=False) — moments are
        # 2/3 of the optimizer bytes and the master is what every other
        # subsystem (checkpoint, debug APIs) touches most
        self.swap_master = bool(getattr(config, "swap_master", True))
        self._aio = None
        if self.nvme:
            from ...ops.aio import AioHandle

            self.nvme_dir = config.nvme_path or "/tmp/ds_tpu_nvme"
            import jax as _jax

            if _jax.process_count() > 1:
                # rank-namespace: leaf files are rank-agnostic names and
                # same-host processes must not clobber each other's state
                self.nvme_dir = os.path.join(
                    self.nvme_dir, f"rank{_jax.process_index()}")
            os.makedirs(self.nvme_dir, exist_ok=True)
            ac = self._aio_config
            # aio.thread_count only overrides the historical buffer_count
            # sizing when the user actually set it (the config model always
            # materializes with defaults)
            ac_set = set()
            if ac is not None:
                ac_set = getattr(ac, "model_fields_set",
                                 getattr(ac, "__fields_set__", set()))
            threads = ac.thread_count if "thread_count" in ac_set \
                else max(1, config.buffer_count)
            # NVMe-tier semantics: bypass the page cache when the target
            # filesystem allows it (the reference's aio kernels are
            # O_DIRECT-always) — that is also what makes swap-out writes
            # block in the DEVICE, freeing the core for the overlapped Adam
            # compute. DS_AIO_NO_ODIRECT=1 forces the buffered path.
            from ...ops.aio import o_direct_supported

            use_od = os.environ.get("DS_AIO_NO_ODIRECT") != "1" and \
                o_direct_supported(self.nvme_dir)
            self._aio = AioHandle(
                num_threads=max(1, threads),
                block_size=ac.block_size if ac else 1 << 20,
                queue_depth=ac.queue_depth if ac else 0,
                o_direct=use_od,
                single_submit=ac.single_submit if ac else False,
                overlap_events=ac.overlap_events if ac else True)

        flat = _flatten_with_paths(params_host)
        self._template = params_host
        self.master: Dict[str, Optional[np.ndarray]] = {}
        self.m: Dict[str, Optional[np.ndarray]] = {}
        self.v: Dict[str, Optional[np.ndarray]] = {}
        self._shapes: Dict[str, tuple] = {}
        self._float: Dict[str, bool] = {}
        for p, leaf in flat.items():
            a = np.asarray(leaf)
            self._shapes[p] = a.shape
            self._float[p] = np.issubdtype(a.dtype, np.floating) or \
                str(a.dtype) == "bfloat16"
            if not self._float[p]:
                self.master[p] = np.asarray(a)  # integer leaf: passthrough
                continue
            self.master[p] = np.ascontiguousarray(a, np.float32)
            self.m[p] = np.zeros(a.size, np.float32)
            self.v[p] = np.zeros(a.size, np.float32)
            if self.nvme:
                # swap THIS leaf out before touching the next one: peak
                # transient host RAM stays O(largest leaf), not O(model)
                # (zero-moment init of a 10B-class model would otherwise
                # commit the full fp32 m+v before the first write)
                self._submit_leaf_swap_out(p)
                self._aio.wait()
                self.m[p] = self.v[p] = None
                if self.swap_master:
                    self.master[p] = None

    # --- nvme swap ------------------------------------------------------
    def _leaf_file(self, p: str, kind: str) -> str:
        safe = re.sub(r"[^A-Za-z0-9_.-]", "_", p)
        return os.path.join(self.nvme_dir, f"{safe}.{kind}.bin")

    def _submit_leaf_swap_out(self, p: str) -> None:
        """Queue one leaf's m/v (and, when ``swap_master``, master) writes
        (layout: moments raveled 1-D, master raveled from its shape).
        Caller drains with _aio.wait()."""
        self._aio.async_pwrite(self.m[p], self._leaf_file(p, "m"))
        self._aio.async_pwrite(self.v[p], self._leaf_file(p, "v"))
        if self.swap_master:
            self._aio.async_pwrite(self.master[p].ravel(),
                                   self._leaf_file(p, "master"))

    def _drop_stores(self) -> None:
        for p in self.m:
            if self._float[p]:
                self.m[p] = self.v[p] = None
                if self.swap_master:
                    self.master[p] = None

    def _swap_out_all(self) -> None:
        for p in list(self.m):
            if not self._float[p] or self.m[p] is None:
                continue
            self._submit_leaf_swap_out(p)
        self._aio.wait()
        self._drop_stores()

    @staticmethod
    def _alloc(n: int) -> np.ndarray:
        """4096-aligned fp32 buffer — unaligned pointers silently fall back
        to the buffered fd in the AIO chunk router, which would defeat the
        O_DIRECT device path the NVMe tier relies on."""
        from ...ops.aio import aligned_array

        return aligned_array(n * 4).view(np.float32)

    def _submit_swap_in_all(self, keys=None) -> Dict[str, list]:
        """Allocate every swapped-out leaf's buffers and SUBMIT their reads
        without draining. Returns {leaf: [tickets]} for per-leaf
        ``wait_ticket`` — the pipelined step overlaps leaf i's Adam compute
        with leaves i+1..'s reads. ``keys`` restricts to a subset (the
        param-offload finalize swaps resident leaves only; stacked leaves
        go through ``step_rows``)."""
        tickets: Dict[str, list] = {}
        for p, shape in self._shapes.items():
            if not self._float[p] or (keys is not None and p not in keys):
                continue
            if self.m[p] is not None:
                continue  # in-memory copy live (see _swap_in_all)
            n = int(np.prod(shape)) if shape else 1
            self.m[p] = self._alloc(n)
            self.v[p] = self._alloc(n)
            tickets[p] = [
                self._aio.async_pread(self.m[p], self._leaf_file(p, "m")),
                self._aio.async_pread(self.v[p], self._leaf_file(p, "v")),
            ]
            if self.swap_master:
                self.master[p] = self._alloc(n).reshape(shape)
                tickets[p].append(self._aio.async_pread(
                    self.master[p].reshape(-1) if shape else
                    self.master[p].ravel(), self._leaf_file(p, "master")))
        return tickets

    def _swap_in_all(self) -> None:
        for p, shape in self._shapes.items():
            if not self._float[p]:
                continue
            if self.m[p] is not None:
                # in-memory copy still live (e.g. a prior swap-out drain
                # failed and the files may be partial) — it is authoritative;
                # reading the file would clobber good state with garbage
                continue
            n = int(np.prod(shape)) if shape else 1
            self.m[p] = self._alloc(n)
            self.v[p] = self._alloc(n)
            self._aio.async_pread(self.m[p], self._leaf_file(p, "m"))
            self._aio.async_pread(self.v[p], self._leaf_file(p, "v"))
            if self.swap_master:
                self.master[p] = self._alloc(n).reshape(shape)
                self._aio.async_pread(self.master[p].reshape(-1) if shape
                                      else self.master[p].ravel(),
                                      self._leaf_file(p, "master"))
        self._aio.wait()

    def read_leaf(self, kind: str, key: str) -> Optional[np.ndarray]:
        """Fetch ONE leaf (kind: master|m|v) regardless of swap state —
        O(leaf) NVMe I/O, not a whole-model swap (used by the
        safe_get_full_* debug APIs)."""
        store = {"master": self.master, "m": self.m, "v": self.v}[kind]
        if key not in store:
            return None
        if store[key] is not None:
            arr = np.asarray(store[key], np.float32)
        else:
            shape = self._shapes[key]
            n = int(np.prod(shape)) if shape else 1
            arr = np.empty(n, np.float32)
            self._aio.async_pread(arr, self._leaf_file(key, kind))
            self._aio.wait()
        return arr.reshape(self._shapes[key]).copy()

    def write_leaf(self, kind: str, key: str, value: np.ndarray) -> bool:
        """Overwrite ONE leaf, persisting to the NVMe tier when swapped."""
        store = {"master": self.master, "m": self.m, "v": self.v}[kind]
        if key not in store:
            return False
        flat = np.ascontiguousarray(np.asarray(value, np.float32))
        if store[key] is not None:
            # in-memory layout: master keeps the param shape, moments are
            # raveled 1-D buffers (see __init__)
            store[key] = flat.reshape(self._shapes[key]) \
                if kind == "master" else flat.ravel()
        else:
            self._aio.async_pwrite(flat.ravel(), self._leaf_file(key, kind))
            self._aio.wait()
        return True

    # --- per-row (layer-streamed) step ----------------------------------
    _row_pending: list = None

    def drain_row_writes(self) -> None:
        """Wait all deferred step_rows writes (per-ticket; the handle may
        be shared). The streamed finalize calls this once per LAYER."""
        pending, self._row_pending = self._row_pending or [], []
        for tickets, _bufs in pending:
            for t in tickets:
                self._aio.wait_ticket(t)

    def step_rows(self, key: str, row: int, grad_row: np.ndarray, lr: float,
                  step_num: int, compute_dtype, grad_scale: float = 1.0
                  ) -> np.ndarray:
        """Adam-update ONE leading-axis row of a stacked leaf and return
        the new compute-dtype row (param_offload's layer-streamed finalize:
        host DRAM never holds a full new param tree — O(row) transient).

        In the NVMe tier the row's master/moment slices move with
        byte-offset I/O against the whole-leaf files (layout: moments
        raveled 1-D, master raveled row-major, so row ``i`` of an
        ``(L, *s)`` leaf is the contiguous span ``[i*n, (i+1)*n)``)."""
        import ml_dtypes

        shape = self._shapes[key]
        assert shape and self._float[key], key
        n = int(np.prod(shape[1:])) if len(shape) > 1 else 1
        off = row * n * 4
        g = np.ascontiguousarray(np.asarray(grad_row, np.float32)).ravel()
        if grad_scale != 1.0:
            g = g * np.float32(grad_scale)
        if self._row_pending is None:
            self._row_pending = []
        swapped = self.nvme and self.m[key] is None
        if swapped:
            m = self._alloc(n)
            v = self._alloc(n)
            t = [self._aio.async_pread(m, self._leaf_file(key, "m"), off),
                 self._aio.async_pread(v, self._leaf_file(key, "v"), off)]
            if self.swap_master:
                master = self._alloc(n)
                t.append(self._aio.async_pread(
                    master, self._leaf_file(key, "master"), off))
            else:
                master = self.master[key].reshape(-1)[row * n:(row + 1) * n]
            for ticket in t:
                self._aio.wait_ticket(ticket)
        else:
            m = self.m[key][row * n:(row + 1) * n]
            v = self.v[key][row * n:(row + 1) * n]
            master = self.master[key].reshape(-1)[row * n:(row + 1) * n]
        self.opt.step(master, g, m, v, step_num, lr=lr)
        if swapped:
            # submit writes and DEFER the drain: buffers stay referenced in
            # _row_pending until drain_row_writes() (called once per layer
            # by the streamed finalize), so row i's writes overlap row
            # i+1's reads/Adam instead of serializing per row
            tickets = [
                self._aio.async_pwrite(m, self._leaf_file(key, "m"), off),
                self._aio.async_pwrite(v, self._leaf_file(key, "v"), off)]
            bufs = [m, v]
            if self.swap_master:
                tickets.append(self._aio.async_pwrite(
                    master, self._leaf_file(key, "master"), off))
                bufs.append(master)
            self._row_pending.append((tickets, bufs))
        if compute_dtype is not None and \
                np.dtype(compute_dtype) == np.dtype(ml_dtypes.bfloat16):
            new_row = self.opt.to_bf16(master)
        elif compute_dtype is None:
            new_row = master.copy()
        else:
            new_row = master.astype(compute_dtype)
        return new_row.reshape(shape[1:])

    # --- step -----------------------------------------------------------
    def step(self, grads_host, lr: float, step_num: int, compute_dtype,
             grad_scale: float = 1.0, release_grads: bool = False,
             keys=None):
        """Apply one host Adam step. ``grads_host``: pytree of fp32 numpy
        (already unscaled/clipped, or scaled here via ``grad_scale`` —
        applied in the per-leaf contiguous copy, so deferred clip/averaging
        costs no extra pass). ``release_grads`` drops each leaf's grad
        reference the moment its update finishes — with the caller's own
        references dropped, peak host RAM falls as the step progresses
        (the streamed param-offload path hands over ~param-sized fp32
        buffers). NOTE the in-place contract: with ``release_grads=True``
        and a dict ``grads_host``, this method SETS the caller's dict
        values to None as updates complete — pass an owned dict, not one
        reused after step(). Returns the new compute-dtype param pytree
        (host arrays, ready for device_put); with ``keys`` set, only that
        subset is updated and a flat ``{path: new_leaf}`` dict is returned
        instead. ``step_num`` 1-indexed.

        NVMe tier pipelining (≅ PipelinedOptimizerSwapper): ALL leaves'
        swap-in reads are submitted up front and the compute loop waits
        per-leaf (``wait_ticket``) — leaf i's Adam update runs while leaves
        i+1.. are still streaming in; each leaf's swap-OUT writes are then
        submitted the moment its update finishes, so writes overlap the
        remaining compute, with one drain at the end. ``last_timings``
        records {swap_in_s (first leaf's read wait), compute_s (incl.
        overlapped read waits), drain_s}."""
        import time

        import ml_dtypes

        t0 = time.perf_counter()
        tickets: Dict[str, list] = {}
        if self.nvme:
            tickets = self._submit_swap_in_all(keys=keys)
        t_in = time.perf_counter()
        grads = _flatten_with_paths(grads_host)
        out: Dict[str, np.ndarray] = {}
        to_bf16 = compute_dtype is not None and \
            np.dtype(compute_dtype) == np.dtype(ml_dtypes.bfloat16)
        try:
            for p, master in self.master.items():
                if keys is not None and p not in keys:
                    continue
                if not self._float[p]:
                    out[p] = master
                    continue
                if p in tickets:
                    # wait for THIS leaf's reads only; later leaves keep
                    # streaming while this one computes (popped only after
                    # ALL its reads land — a failed wait leaves it in
                    # `tickets` so the unwind drops its garbage buffers)
                    for t in tickets[p]:
                        self._aio.wait_ticket(t)
                    del tickets[p]
                    master = self.master[p]
                g = np.asarray(grads[p], np.float32)
                if grad_scale != 1.0:
                    g = g * np.float32(grad_scale)
                g = np.ascontiguousarray(g).ravel()
                if release_grads:
                    # progressive release needs the CALLER's container to
                    # drop its ref too — effective when grads_host is the
                    # flat {path: array} dict the streaming path hands over
                    grads[p] = None
                    if isinstance(grads_host, dict) and p in grads_host:
                        grads_host[p] = None
                self.opt.step(
                    master.reshape(-1) if master.shape else master.ravel(),
                    g, self.m[p], self.v[p], step_num, lr=lr)
                if compute_dtype is None or \
                        master.dtype == np.dtype(compute_dtype):
                    out[p] = master.copy()
                elif to_bf16:
                    out[p] = self.opt.to_bf16(master.reshape(-1)).reshape(
                        self._shapes[p])
                else:
                    out[p] = master.astype(compute_dtype)
                if self.nvme:
                    # submit this leaf's swap-out NOW — the write overlaps
                    # the next leaves' Adam compute (the handle keeps the
                    # buffers alive until the drain)
                    self._submit_leaf_swap_out(p)
            t_compute = time.perf_counter()
        except BaseException:
            # an exception mid-loop must still drain in-flight writes, or a
            # later _swap_in_all could read partially-written files. Drain
            # non-raising here: an IOError raised inside cleanup would
            # REPLACE the original in-flight exception (the root cause).
            if self.nvme:
                # leaves whose reads never completed hold UNINITIALIZED
                # buffers — drop them so retry re-reads from disk instead
                # of treating garbage as authoritative in-memory state
                for p in tickets:
                    self.m[p] = self.v[p] = None
                    if self.swap_master:
                        self.master[p] = None
                try:
                    self._aio.wait()
                except IOError as io_err:
                    # a failed drain means the on-disk leaf files may be
                    # partially written — keep the completed leaves'
                    # in-memory copies (no _drop_stores) so they stay
                    # authoritative for retry
                    logger.warning("swap-out drain failed during exception "
                                   "unwind: %s — keeping in-memory optimizer "
                                   "state authoritative", io_err)
                else:
                    self._drop_stores()
            raise
        else:
            if self.nvme:
                self._aio.wait()  # raises on any failed chunk
                self._drop_stores()
        t_drain = time.perf_counter()
        self.last_timings = {"swap_in_s": t_in - t0,
                             "compute_s": t_compute - t_in,
                             "drain_s": t_drain - t_compute}
        if keys is not None:
            return out
        return _unflatten_like(self._template, out)

    def sync_master_from(self, params_host) -> None:
        """Re-seed the fp32 master from externally-loaded params (used when
        a checkpoint restores module weights without offloaded optimizer
        state — otherwise the next step would clobber them with params
        recomputed from the stale master)."""
        flat = _flatten_with_paths(params_host)
        if self.nvme:
            self._swap_in_all()
        for p, leaf in flat.items():
            if self._float[p]:
                self.master[p] = np.ascontiguousarray(
                    np.asarray(leaf, np.float32))
            else:
                self.master[p] = np.asarray(leaf)
        if self.nvme:
            self._swap_out_all()

    # --- checkpoint surface --------------------------------------------
    def state_dict(self) -> Dict:
        if self.nvme:
            self._swap_in_all()
        sd = {"master": {p: (a.copy() if a is not None else None)
                         for p, a in self.master.items()},
              "m": {p: (a.copy() if a is not None else None)
                    for p, a in self.m.items()},
              "v": {p: (a.copy() if a is not None else None)
                    for p, a in self.v.items()}}
        if self.nvme:
            self._swap_out_all()
        return sd

    def load_universal(self, master_tree, opt_trees: Dict) -> None:
        """Restore from a universal checkpoint: fp32 master from the nested
        param tree, Adam moments from ``opt_trees['exp_avg'/'exp_avg_sq']``
        (nested, param-shaped) when present — keeps momentum across elastic
        resumes instead of silently re-zeroing it."""
        self.sync_master_from(master_tree)
        name_to_attr = {"exp_avg": self.m, "exp_avg_sq": self.v}
        if self.nvme:
            self._swap_in_all()
        for name, store in name_to_attr.items():
            tree = opt_trees.get(name)
            if tree is None:
                continue
            flat = _flatten_with_paths(tree)
            for p, leaf in flat.items():
                if p in store and self._float.get(p):
                    store[p] = np.ascontiguousarray(
                        np.asarray(leaf, np.float32)).ravel()
        if self.nvme:
            self._swap_out_all()

    def load_state_dict(self, sd: Dict) -> None:
        if self.nvme:
            self._swap_in_all()
        for p in self.master:
            if sd["master"].get(p) is not None:
                self.master[p] = np.ascontiguousarray(sd["master"][p], np.float32) \
                    if self._float[p] else np.asarray(sd["master"][p])
            if self._float[p]:
                if sd["m"].get(p) is not None:
                    self.m[p] = np.ascontiguousarray(sd["m"][p], np.float32).ravel()
                if sd["v"].get(p) is not None:
                    self.v[p] = np.ascontiguousarray(sd["v"][p], np.float32).ravel()
        if self.nvme:
            self._swap_out_all()
