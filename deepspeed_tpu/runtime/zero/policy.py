"""ZeRO stages as GSPMD sharding policies.

This replaces the reference's imperative ZeRO machinery — stage-1/2 flat
partitions + IPG bucketing (``runtime/zero/stage_1_and_2.py:90,799,900``) and
stage-3 param sharding with hook-driven gather/release
(``runtime/zero/stage3.py:65``, ``partition_parameters.py:616``,
``partitioned_param_coordinator.py:55``) — with *declarative* sharding specs
consumed by ``jax.jit``:

* **stage 1** (optimizer-state partitioning): fp32 master params + moments are
  sharded over the ZeRO axes; XLA emits one reduce-scatter of the grads into
  the shard, a local update, and an all-gather of updated compute params —
  exactly the reference's ``step()``-then-allgather (stage_1_and_2.py:1642)
  but compiler-scheduled and fused into the step.
* **stage 2** (+gradient partitioning): grads get an explicit sharding
  constraint so accumulated grads live reduce-scattered (the analog of IPG
  bucketing + ``average_tensor`` rank-sliced reduction, stage_1_and_2.py:900).
  Inside a single fused step this only changes peak memory under gradient
  accumulation — which is precisely its role in the reference.
* **stage 3** (+parameter partitioning): compute params are *persistently*
  sharded over the ZeRO axes; XLA all-gathers each param at its use site and
  frees it after (the gather/release hook pair, parameter_offload.py:370/374),
  with prefetch overlap handled by XLA's scheduler rather than a recorded
  trace. Small params stay replicated below
  ``stage3_param_persistence_threshold`` (stage3 persistent-param logic,
  parameter_offload.py:339).

Tensor-parallel (model-axis) specs compose: the ZeRO axes shard a dimension
not already taken by TP.
"""

from __future__ import annotations

import math
import re
from typing import Any, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ...parallel.mesh import ZERO_AXES
from .config import DeepSpeedZeroConfig, ZeroStageEnum


def _zero_world(mesh) -> int:
    dims = dict(zip(mesh.axis_names, mesh.devices.shape))
    return int(np.prod([dims.get(a, 1) for a in ZERO_AXES]))


def _used_axes(spec: Optional[PartitionSpec]) -> set:
    used = set()
    if spec is None:
        return used
    for entry in spec:
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            used.update(entry)
        else:
            used.add(entry)
    return used


def _spec_dim(spec: Optional[PartitionSpec], ndim: int, i: int):
    if spec is None or i >= len(spec):
        return None
    return spec[i]


def zero_shard_spec(shape: Sequence[int],
                    mesh,
                    stage_applies: bool,
                    tp_spec: Optional[PartitionSpec] = None,
                    persistence_threshold: int = 0) -> PartitionSpec:
    """Compose a ZeRO-sharding PartitionSpec for one tensor.

    Picks the largest dimension divisible by the ZeRO world size that TP has
    not claimed and shards it over ``("data", "expert", "seq")``. Tensors at
    or below ``persistence_threshold`` elements (or with no divisible dim)
    stay at their TP spec — the analog of ZeRO-3 persistent small params.
    """
    ndim = len(shape)
    base = list(tp_spec) if tp_spec is not None else []
    base += [None] * (ndim - len(base))

    if not stage_applies:
        return PartitionSpec(*base)

    size = math.prod(shape) if shape else 1
    if persistence_threshold and size <= persistence_threshold:
        return PartitionSpec(*base)

    zero_world = _zero_world(mesh)
    if zero_world == 1:
        return PartitionSpec(*base)

    taken = _used_axes(tp_spec)
    zero_axes = tuple(a for a in ZERO_AXES if a not in taken)
    if not zero_axes:
        return PartitionSpec(*base)
    dims = dict(zip(mesh.axis_names, mesh.devices.shape))
    shard_world = int(np.prod([dims.get(a, 1) for a in zero_axes]))
    if shard_world == 1:
        return PartitionSpec(*base)

    # largest free dim divisible by the shard world
    candidates = [i for i in range(ndim) if base[i] is None and shape[i] % shard_world == 0
                  and shape[i] > 0]
    if not candidates:
        return PartitionSpec(*base)
    best = max(candidates, key=lambda i: shape[i])
    base[best] = zero_axes if len(zero_axes) > 1 else zero_axes[0]
    return PartitionSpec(*base)


class ShardingRules:
    """Regex path → PartitionSpec rules for tensor-parallel params.

    The TPU-native analog of AutoTP's layer classification
    (``module_inject/auto_tp.py:13``): instead of swapping nn.Linear for
    LinearLayer/LinearAllreduce modules, a rule maps a parameter path to the
    mesh axes each dimension shards over.
    """

    def __init__(self, rules: Optional[Sequence[Tuple[str, Sequence]]] = None):
        self.raw_rules = list(rules or [])
        self.rules = [(re.compile(pat), PartitionSpec(*spec)) for pat, spec in self.raw_rules]

    def spec_for(self, path: str) -> Optional[PartitionSpec]:
        for pat, spec in self.rules:
            if pat.search(path):
                return spec
        return None


def _path_str(path) -> str:
    return "/".join(str(getattr(k, "key", getattr(k, "idx", k))) for k in path)


class ZeroShardingPolicy:
    """Maps every parameter / optimizer-state leaf to a NamedSharding.

    stage 0: params+state replicated (grads all-reduced by GSPMD)
    stage 1: master params + optimizer moments sharded
    stage 2: + gradient accumulator sharded
    stage 3: + compute params sharded
    """

    def __init__(self, zero_config: DeepSpeedZeroConfig, mesh,
                 sharding_rules: Optional[ShardingRules] = None):
        self.config = zero_config
        self.mesh = mesh
        self.rules = sharding_rules or ShardingRules()
        self.stage = int(zero_config.stage)

    # --- per-leaf specs ---------------------------------------------------
    def tp_spec(self, path: str) -> Optional[PartitionSpec]:
        return self.rules.spec_for(path)

    def param_spec(self, path: str, shape) -> PartitionSpec:
        return zero_shard_spec(
            shape, self.mesh,
            stage_applies=self.stage >= ZeroStageEnum.weights,
            tp_spec=self.tp_spec(path),
            persistence_threshold=self.config.stage3_param_persistence_threshold,
        )

    def master_spec(self, path: str, shape) -> PartitionSpec:
        return zero_shard_spec(
            shape, self.mesh,
            stage_applies=self.stage >= ZeroStageEnum.optimizer_states,
            tp_spec=self.tp_spec(path),
            # master shards regardless of size when stage>=1 (flat-partition
            # analog); persistence threshold only applies to compute params
            persistence_threshold=0,
        )

    def grad_spec(self, path: str, shape) -> PartitionSpec:
        if self.stage >= ZeroStageEnum.gradients:
            return self.master_spec(path, shape)
        return zero_shard_spec(shape, self.mesh, stage_applies=False,
                               tp_spec=self.tp_spec(path))

    # --- pytree-level shardings ------------------------------------------
    def _tree_shardings(self, tree, spec_fn):
        def leaf_sharding(path, leaf):
            spec = spec_fn(_path_str(path), np.shape(leaf))
            return NamedSharding(self.mesh, spec)

        return jax.tree_util.tree_map_with_path(leaf_sharding, tree)

    def param_shardings(self, params):
        return self._tree_shardings(params, self.param_spec)

    def master_shardings(self, params):
        return self._tree_shardings(params, self.master_spec)

    def grad_shardings(self, params):
        return self._tree_shardings(params, self.grad_spec)

    def opt_state_shardings(self, opt_state, params):
        """Optimizer moments follow the master-param sharding. ``opt_state``
        is any pytree whose array leaves are shaped like some param; leaves
        are matched to params by shape equality within the aligned subtree."""
        param_shardings = self.master_shardings(params)

        def match(path, leaf):
            # opt_state trees from OptimizerDef.init are built by tree_map
            # over params, so each state field subtree is congruent to params.
            return NamedSharding(self.mesh,
                                 self.master_spec(_path_str(path), np.shape(leaf)))

        del param_shardings
        return jax.tree_util.tree_map_with_path(match, opt_state)

    def describe(self) -> str:
        return (f"ZeroShardingPolicy(stage={self.stage}, "
                f"zero_world={_zero_world(self.mesh)})")
