from . import checkpointing  # noqa: F401
from .checkpointing import (  # noqa: F401
    checkpoint,
    checkpoint_name,
    checkpoint_sequential,
    checkpoint_wrapper,
    configure,
    fold_in_model_parallel_rank,
    get_rng_tracker,
    is_configured,
    model_parallel_manual_seed,
    partition,
)
