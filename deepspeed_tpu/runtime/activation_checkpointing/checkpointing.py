"""Activation checkpointing — TPU-native rematerialisation.

Capability parity with the reference's Megatron-derived
``deepspeed/runtime/activation_checkpointing/checkpointing.py`` —
``checkpoint()`` (:708), ``configure()`` (:789), activation *partitioning*
across model-parallel ranks (:366, re-gathered in backward :255), CPU
checkpointing (:461), ``num_checkpoints`` segmenting, and the model-parallel
RNG tracker for dropout determinism (:121,198) — re-architected for XLA:

* ``checkpoint(fn, *args)`` is ``jax.checkpoint`` with a policy derived from
  the configured JSON block. The reference's custom autograd Function saving
  / restoring tensors by hand is replaced by remat: XLA recomputes the body
  in backward, and residual choice is a *policy*, not imperative code.
* ``partition_activations`` becomes a GSPMD sharding constraint on the saved
  layer inputs over the ``model`` mesh axis: each model-parallel shard holds
  ``1/mp`` of every checkpointed activation and XLA inserts the all-gather in
  backward — the same memory/communication trade the reference hand-codes
  with narrow()/all_gather.
* ``cpu_checkpointing`` offloads named activations to host memory via the
  ``save_and_offload_only_these_names`` policy (pinned-host memory space)
  instead of ``.cpu()`` copies on side streams.
* The CUDA RNG-state tracker is unnecessary under JAX's explicit keys; the
  parity surface (``get_rng_tracker``, ``model_parallel_manual_seed``) is
  kept, and in-jit per-model-rank dropout determinism is one
  ``fold_in_model_parallel_rank``.
"""

from __future__ import annotations

import contextlib
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name  # noqa: F401  (re-export)
from jax.sharding import NamedSharding, PartitionSpec

from ...parallel import mesh as mesh_mod
from ...utils.logging import log_dist

# Name used to tag activations eligible for host offload under
# ``cpu_checkpointing`` (tag values inside your layer with
# ``checkpoint_name(x, OFFLOAD_NAME)``).
OFFLOAD_NAME = "ds_activation"

MODEL_PARALLEL_AXIS = "model"


class _CheckpointConfig:
    partition_activations: bool = False
    contiguous_memory_optimization: bool = False
    cpu_checkpointing: bool = False
    num_checkpoints: Optional[int] = None
    synchronize: bool = False
    profile: bool = False
    configured: bool = False


_CONFIG = _CheckpointConfig()


def configure(mpu_=None,
              deepspeed_config=None,
              partition_activations: Optional[bool] = None,
              contiguous_checkpointing: Optional[bool] = None,
              num_checkpoints: Optional[int] = None,
              checkpoint_in_cpu: Optional[bool] = None,
              synchronize: Optional[bool] = None,
              profile: Optional[bool] = None) -> None:
    """Configure from a DeepSpeed JSON/``DeepSpeedConfig`` and/or overrides
    (≅ reference checkpointing.py:789). ``mpu_`` is accepted for API parity;
    the model axis comes from the global mesh."""
    acc = None
    if deepspeed_config is not None:
        from ..config import DeepSpeedConfig

        if isinstance(deepspeed_config, (str, dict)):
            deepspeed_config = DeepSpeedConfig(deepspeed_config, world_size=1)
        acc = deepspeed_config.activation_checkpointing

    def pick(override, from_cfg, default):
        if override is not None:
            return override
        if acc is not None:
            return from_cfg
        return default

    _CONFIG.partition_activations = pick(
        partition_activations, acc.partition_activations if acc else None, False)
    _CONFIG.contiguous_memory_optimization = pick(
        contiguous_checkpointing,
        acc.contiguous_memory_optimization if acc else None, False)
    _CONFIG.cpu_checkpointing = pick(
        checkpoint_in_cpu, acc.cpu_checkpointing if acc else None, False)
    _CONFIG.num_checkpoints = pick(
        num_checkpoints, acc.number_checkpoints if acc else None, None)
    _CONFIG.synchronize = pick(
        synchronize, acc.synchronize_checkpoint_boundary if acc else None, False)
    _CONFIG.profile = pick(profile, acc.profile if acc else None, False)
    _CONFIG.configured = True
    log_dist(
        f"Activation checkpointing configured: "
        f"partition_activations={_CONFIG.partition_activations} "
        f"cpu_checkpointing={_CONFIG.cpu_checkpointing} "
        f"num_checkpoints={_CONFIG.num_checkpoints}", ranks=[0])


def is_configured() -> bool:
    return _CONFIG.configured


def reset() -> None:
    _CONFIG.__dict__.clear()


# ---------------------------------------------------------------------------
# Policies
# ---------------------------------------------------------------------------


def _policy():
    """Residual policy for the configured mode."""
    if _CONFIG.cpu_checkpointing:
        return jax.checkpoint_policies.save_and_offload_only_these_names(
            names_which_can_be_saved=[],
            names_which_can_be_offloaded=[OFFLOAD_NAME],
            offload_src="device",
            offload_dst="pinned_host")
    # Full remat: recompute everything from the (possibly partitioned) inputs.
    return jax.checkpoint_policies.nothing_saveable


def partition(x: jnp.ndarray) -> jnp.ndarray:
    """Shard a saved activation over the model-parallel mesh axis
    (≅ reference ``partition_activations`` narrow()+slice at
    checkpointing.py:366; the backward all-gather :255 is inserted by GSPMD).

    No-op when there is no mesh / no model axis / non-divisible leading dim.
    """
    if not mesh_mod.has_mesh():
        return x
    mesh = mesh_mod.get_mesh()
    if MODEL_PARALLEL_AXIS not in mesh.axis_names:
        return x
    mp = mesh.shape[MODEL_PARALLEL_AXIS]
    if mp <= 1 or x.ndim == 0 or x.shape[0] % mp != 0:
        return x
    spec = PartitionSpec(MODEL_PARALLEL_AXIS, *([None] * (x.ndim - 1)))
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def _maybe_partition_args(args):
    if not _CONFIG.partition_activations:
        return args
    return jax.tree_util.tree_map(
        lambda a: partition(a) if isinstance(a, jnp.ndarray)
        and jnp.issubdtype(a.dtype, jnp.floating) else a, args)


# ---------------------------------------------------------------------------
# Public checkpoint API
# ---------------------------------------------------------------------------


def checkpoint(function: Callable, *args) -> Any:
    """Checkpoint (remat) ``function(*args)`` (≅ reference
    checkpointing.py:708). Saved residuals are the function inputs —
    partitioned over the model axis when configured — and the body is
    recomputed in backward."""
    args = _maybe_partition_args(args)
    fn = jax.checkpoint(function, policy=_policy(), prevent_cse=False)
    return fn(*args)


def checkpoint_wrapper(function: Callable) -> Callable:
    """Decorator form: returns a remat'd callable with the configured policy."""

    def wrapped(*args):
        return checkpoint(function, *args)

    return wrapped


def checkpoint_sequential(layers: Sequence[Callable],
                          x: Any,
                          num_checkpoints: Optional[int] = None) -> Any:
    """Run ``layers`` sequentially, checkpointing in ``num_checkpoints``
    contiguous segments (≅ reference ``num_checkpoints``/
    ``contiguous_memory_optimization``: only segment boundaries are live).

    With the default (None), every layer is its own checkpoint segment.
    """
    if not layers:
        return x
    n = len(layers)
    k = num_checkpoints if num_checkpoints is not None else _CONFIG.num_checkpoints
    if not k or k <= 0 or k > n:
        k = n
    # split into k contiguous segments, sizes as equal as possible
    base, rem = divmod(n, k)
    out = x
    idx = 0
    for seg in range(k):
        size = base + (1 if seg < rem else 0)
        seg_layers = layers[idx:idx + size]
        idx += size

        def run_segment(h, _layers=tuple(seg_layers)):
            for layer in _layers:
                h = layer(h)
            return h

        out = checkpoint(run_segment, out)
    return out


# ---------------------------------------------------------------------------
# RNG tracker (parity surface for Megatron-style dropout determinism,
# reference checkpointing.py:121 CudaRNGStatesTracker / :198 tracker fns)
# ---------------------------------------------------------------------------

_MODEL_PARALLEL_RNG = "model-parallel-rng"


def fold_in_model_parallel_rank(key: jax.Array,
                                axis_name: str = MODEL_PARALLEL_AXIS) -> jax.Array:
    """In-jit: derive a per-model-parallel-rank dropout key. Use inside
    ``shard_map`` bodies; outside a mapped context returns the key unchanged."""
    try:
        idx = jax.lax.axis_index(axis_name)
    except NameError:
        return key
    return jax.random.fold_in(key, idx)


class RNGStatesTracker:
    """Host-level named PRNG-key store (≅ CudaRNGStatesTracker,
    checkpointing.py:121). JAX keys are values, not device state, so
    ``fork()`` simply yields the named key; callers split it functionally."""

    def __init__(self):
        self.states_ = {}

    def reset(self):
        self.states_.clear()

    def get_states(self):
        return dict(self.states_)

    def add(self, name: str, seed: int):
        if name in self.states_:
            raise Exception(f"RNG state {name} already exists")
        self.states_[name] = jax.random.PRNGKey(seed)

    @contextlib.contextmanager
    def fork(self, name: str = _MODEL_PARALLEL_RNG):
        if name not in self.states_:
            raise Exception(f"RNG state {name} is not added")
        key, self.states_[name] = tuple(jax.random.split(self.states_[name]))
        yield key


_RNG_TRACKER = RNGStatesTracker()


def get_rng_tracker() -> RNGStatesTracker:
    return _RNG_TRACKER


# Reference-name alias (get_cuda_rng_tracker); device-agnostic here.
get_cuda_rng_tracker = get_rng_tracker


def model_parallel_manual_seed(seed: int, mp_rank: int = 0) -> None:
    """Seed data-parallel + model-parallel RNG streams (≅
    model_parallel_cuda_manual_seed, checkpointing.py:198): the model-parallel
    stream is offset per rank so TP shards draw different dropout."""
    _RNG_TRACKER.reset()
    _RNG_TRACKER.add(_MODEL_PARALLEL_RNG, seed + 2718 + mp_rank)


model_parallel_cuda_manual_seed = model_parallel_manual_seed
