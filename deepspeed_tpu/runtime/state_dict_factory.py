"""Inference-time MP resharding of Megatron-style state dicts.

Capability parity with reference ``deepspeed/runtime/state_dict_factory.py``
(:21 ``SDLoaderFactory``, :190 ``MegatronSDLoader``) — load a checkpoint
saved at one model-parallel degree and serve a shard for a DIFFERENT target
degree: merge ckpt shards when target < saved, split when target > saved,
with the qkv / row / column classification by layer-name heuristics.

Used by the inference engine when a reference checkpoint's TP degree does
not match the serving mesh. Tensors are numpy; torch ``.pt`` inputs load
via the CPU torch wheel.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

import numpy as np

from ..checkpoint.deepspeed_checkpoint import get_layer_cat_dim
from ..utils.logging import logger


class SDLoaderFactory:
    @staticmethod
    def get_sd_loader_json(json_file, checkpoint_engine=None):
        if isinstance(json_file, str):
            with open(json_file) as f:
                data = json.load(f)
        else:
            data = json_file
        sd_type = data.get("type", "Megatron")
        ckpt_list = data.get("checkpoints", [])
        version = data.get("version", 0.0)
        return SDLoaderFactory.get_sd_loader(ckpt_list, sd_type, version)

    @staticmethod
    def get_sd_loader(ckpt_list, sd_type: str = "Megatron",
                      version=0.0):
        if sd_type.lower() == "megatron":
            return MegatronSDLoader(ckpt_list, version)
        raise ValueError(f"unknown sd_type {sd_type}")


def _load_file(path: str) -> Dict[str, Any]:
    if path.endswith(".npz"):
        data = np.load(path, allow_pickle=False)
        return {k: data[k] for k in data.files}
    import torch

    sd = torch.load(path, map_location="cpu", weights_only=False)
    sd = sd.get("module", sd) if isinstance(sd, dict) else sd
    out = {}
    for k, v in sd.items():
        if hasattr(v, "detach"):
            t = v.detach().cpu()
            if "bfloat16" in str(t.dtype):
                t = t.float()
            out[k] = t.numpy()
        else:
            out[k] = v
    return out


class MegatronSDLoader:
    def __init__(self, ckpt_list: List[str], version=0.0):
        self.ckpt_list = list(ckpt_list)
        self.version = version

    @property
    def ckpt_mp_size(self) -> int:
        return len(self.ckpt_list)

    def load(self, mp_world_size: int, mp_rank: int,
             quantize: bool = False) -> Dict[str, Any]:
        """Return the state dict for ``mp_rank`` of ``mp_world_size``."""
        n = self.ckpt_mp_size
        if mp_world_size == n:
            return _load_file(self.ckpt_list[mp_rank])
        if mp_world_size < n:
            assert n % mp_world_size == 0, \
                f"cannot merge {n} shards into {mp_world_size}"
            per = n // mp_world_size
            shards = [_load_file(p) for p in
                      self.ckpt_list[mp_rank * per:(mp_rank + 1) * per]]
            return self.merge_state_dicts(shards)
        assert mp_world_size % n == 0, \
            f"cannot split {n} shards into {mp_world_size}"
        per = mp_world_size // n
        src = _load_file(self.ckpt_list[mp_rank // per])
        return self.split_state_dict(src, per, mp_rank % per)

    # -- merge / split ----------------------------------------------------
    def merge_state_dicts(self, shards: List[Dict[str, Any]]
                          ) -> Dict[str, Any]:
        merged: Dict[str, Any] = {}
        for key in shards[0]:
            values = [s[key] for s in shards]
            dim = get_layer_cat_dim(key)
            if dim is None or np.ndim(values[0]) == 0:
                merged[key] = values[0]
            elif self._is_qkv(key):
                merged[key] = self.merge_query_key_value(values, dim)
            else:
                merged[key] = np.concatenate(values, axis=dim)
        return merged

    def split_state_dict(self, sd: Dict[str, Any], num_splits: int,
                         split_idx: int) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for key, value in sd.items():
            dim = get_layer_cat_dim(key)
            if dim is None or np.ndim(value) == 0:
                out[key] = value
            elif self._is_qkv(key):
                out[key] = self.split_query_key_value(value, num_splits,
                                                      split_idx, dim)
            else:
                assert value.shape[dim] % num_splits == 0, \
                    f"{key}: dim {dim} size {value.shape[dim]} not " \
                    f"divisible by {num_splits}"
                out[key] = np.split(value, num_splits, axis=dim)[split_idx]
        return out

    # -- qkv handling (reference :190 merge/split by ckpt version) --------
    @staticmethod
    def _is_qkv(key: str) -> bool:
        return "query_key_value" in key or "qkv" in key

    def merge_query_key_value(self, values: List[np.ndarray],
                              dim: int = 0) -> np.ndarray:
        """Megatron qkv layouts by checkpoint version (reference
        state_dict_factory.py:220): version 0 stores [Q_shard; K_shard;
        V_shard] fused per shard → merging must split each shard into
        thirds and regroup so the result is [Q_all; K_all; V_all];
        versions 1.0/2.0 store per-head-grouped layouts where a plain
        concat over shards is already correct."""
        if float(self.version) >= 1.0:
            return np.concatenate(values, axis=dim)
        qs, ks, vs = [], [], []
        for v in values:
            q, k, u = np.split(v, 3, axis=dim)
            qs.append(q)
            ks.append(k)
            vs.append(u)
        return np.concatenate(
            [np.concatenate(qs, axis=dim), np.concatenate(ks, axis=dim),
             np.concatenate(vs, axis=dim)], axis=dim)

    def split_query_key_value(self, value: np.ndarray, num_splits: int,
                              split_idx: int, dim: int = 0) -> np.ndarray:
        if float(self.version) >= 1.0:
            return np.split(value, num_splits, axis=dim)[split_idx]
        q, k, v = np.split(value, 3, axis=dim)
        return np.concatenate(
            [np.split(q, num_splits, axis=dim)[split_idx],
             np.split(k, num_splits, axis=dim)[split_idx],
             np.split(v, num_splits, axis=dim)[split_idx]], axis=dim)
