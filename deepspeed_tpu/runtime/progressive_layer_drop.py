"""Progressive Layer Dropping (PLD).

Capability parity with reference ``deepspeed/runtime/progressive_layer_drop.py``
— the keep-probability schedule θ(t) = (1-θ̄)·exp(-γt) + θ̄ from the PLD
paper, fed to the model each step (reference engine.py:1553,1709). The flax
side consumes ``pld_theta`` as a per-layer keep probability: layer i of L
keeps with probability 1 - (i/L)·(1-θ); :class:`LayerDrop` implements that
stochastic skip with the residual as identity.
"""

from __future__ import annotations

import numpy as np

from ..utils.logging import log_dist


class ProgressiveLayerDrop:
    def __init__(self, theta: float = 0.5, gamma: float = 0.001):
        self.theta = theta
        self.gamma = gamma
        self.current_theta = 1.0
        log_dist(f"Enabled progressive layer dropping (theta = {self.theta})",
                 ranks=[0])

    def get_state(self) -> dict:
        return {"progressive_layer_drop": True, "pld_theta": self.get_theta()}

    def get_theta(self) -> float:
        return self.current_theta

    def update_state(self, global_step: int) -> None:
        self.current_theta = \
            (1.0 - self.theta) * float(np.exp(-self.gamma * global_step)) + \
            self.theta


class LayerDrop:
    """Functional helper: stochastically skip ``layer_fn`` with the PLD
    per-depth keep probability. Use inside a flax module:

        keep_p = pld_keep_prob(theta, layer_id, num_layers)
        x = maybe_drop_layer(rng, keep_p, x, lambda h: block(h), deterministic)
    """


def pld_keep_prob(theta: float, layer_id: int, num_layers: int) -> float:
    """Deeper layers drop more often (PLD paper eq. 5)."""
    return 1.0 - (float(layer_id + 1) / max(num_layers, 1)) * (1.0 - theta)


def maybe_drop_layer(rng, keep_prob, x, layer_fn, deterministic: bool = False):
    """Bernoulli layer skip with identity residual; at eval, always run and
    scale is unnecessary because PLD trains with unscaled residuals."""
    import jax
    import jax.numpy as jnp

    if deterministic or keep_prob >= 1.0:
        return layer_fn(x)
    keep = jax.random.bernoulli(rng, keep_prob)
    return jax.lax.cond(keep, layer_fn, lambda h: h, x)
