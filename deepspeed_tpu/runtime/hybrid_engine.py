"""Hybrid engine — RLHF train/generate mode switching.

Capability parity with reference ``deepspeed/runtime/hybrid_engine.py:32
DeepSpeedHybridEngine`` — one engine that trains (ZeRO sharded) and serves
``generate()`` with inference-optimized execution for generate-heavy RLHF
loops. The reference swaps module containers and gathers ZeRO-3 params
(:84,:178,:367); on TPU the training params ARE whole logical arrays under
GSPMD, so mode switching reduces to: reuse the current training params in
the inference engine's compiled prefill/decode path (KV cache, greedy or
sampled), invalidating that cache whenever a training step advances the
params. LoRA fuse/unfuse (:130,:143) folds ``lora_a``/``lora_b`` adapter
pairs into their base kernels before generation and keeps training params
untouched.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Union

import jax
import jax.numpy as jnp

from ..inference.config import DeepSpeedInferenceConfig
from ..utils.logging import log_dist
from .engine import DeepSpeedEngine


class DeepSpeedHybridEngine(DeepSpeedEngine):
    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._inference_engine = None
        self._inference_param_version = -1
        self._param_version = 0
        he = getattr(self._config, "hybrid_engine", None)
        self._lora_scaling = float(getattr(he, "lora_scaling", 1.0)) \
            if he is not None else 1.0
        self._in_eval = False
        log_dist("DeepSpeedHybridEngine: train/generate mode switching on",
                 ranks=[0])

    # -- mode flips (reference eval()/train() container swaps) ----------
    def eval(self) -> None:
        self._in_eval = True

    def train(self, mode: bool = True) -> None:
        self._in_eval = not mode

    # -- param versioning ------------------------------------------------
    def train_batch(self, *args, **kwargs):
        out = super().train_batch(*args, **kwargs)
        self._param_version += 1
        return out

    def step(self):
        before = self.global_steps
        out = super().step()
        if self.global_steps > before:  # mid-accumulation step() is a no-op
            self._param_version += 1
        return out

    # -- LoRA fuse/unfuse (reference :130,:143) -------------------------
    @staticmethod
    def _find_lora_pairs(tree: Dict, prefix=()) -> Dict:
        pairs = {}
        if not isinstance(tree, dict):
            return pairs
        if "lora_a" in tree and "lora_b" in tree and "kernel" in tree:
            pairs[prefix] = tree
        for k, v in tree.items():
            pairs.update(DeepSpeedHybridEngine._find_lora_pairs(
                v, prefix + (k,)))
        return pairs

    def fuse_lora_weight(self, params: Dict) -> Dict:
        """kernel_eff = kernel + scaling · (lora_a @ lora_b); returns a new
        tree, training params untouched. ``lora_a`` is zeroed in the fused
        tree — the module's forward still applies its LoRA branch, which now
        contributes nothing instead of double-counting the adapter."""
        pairs = self._find_lora_pairs(params)
        if not pairs:
            return params

        def visit(node, prefix=()):
            if not isinstance(node, dict):
                return node
            out = {k: visit(v, prefix + (k,)) for k, v in node.items()}
            if prefix in pairs:
                fused = out["kernel"] + self._lora_scaling * \
                    (out["lora_a"] @ out["lora_b"]).astype(out["kernel"].dtype)
                out = dict(out)
                out["kernel"] = fused
                out["lora_a"] = jnp.zeros_like(out["lora_a"])
            return out

        return visit(params)

    def unfuse_lora_weight(self, params: Dict) -> Dict:
        """Subtract the adapter product back out of the kernel. Applies to
        trees whose ``lora_a/lora_b`` are intact (e.g. fused in place by a
        caller) — NOT to the output of :meth:`fuse_lora_weight`, which
        zeroes ``lora_a`` and is already functional (training tree is never
        mutated, so nothing needs unfusing on the engine's own flow)."""
        pairs = self._find_lora_pairs(params)
        if not pairs:
            return params

        def visit(node, prefix=()):
            if not isinstance(node, dict):
                return node
            out = {k: visit(v, prefix + (k,)) for k, v in node.items()}
            if prefix in pairs:
                out = dict(out)
                out["kernel"] = out["kernel"] - self._lora_scaling * \
                    (out["lora_a"] @ out["lora_b"]).astype(
                        out["kernel"].dtype)
            return out

        return visit(params)

    # -- generate --------------------------------------------------------
    def _refresh_inference_engine(self) -> None:
        from ..inference.engine import InferenceEngine

        if self._inference_engine is not None and \
                self._inference_param_version == self._param_version:
            return
        assert self.state is not None, \
            "run a forward/train_batch first so params exist"
        params = self.state["params"]
        params = self.fuse_lora_weight(params)
        if self._inference_engine is None:
            inf_cfg = DeepSpeedInferenceConfig(
                dtype=("bfloat16" if self.bf16_enabled else
                       ("float16" if self.fp16_enabled else "float32")))
            self._inference_engine = InferenceEngine(
                model=self.module, config=inf_cfg,
                model_parameters=jax.device_get(params), mesh=self.mesh)
        else:
            # swap the params in place; compiled prefill/decode stay valid
            # (same shapes/dtypes — only values changed)
            self._inference_engine.params = jax.device_put(
                params, self._inference_engine._param_shardings) \
                if self._inference_engine._param_shardings is not None \
                else params
            if self._inference_engine.params is None or \
                    self._inference_engine._jit_decode is None:
                self._inference_engine._params_host = jax.device_get(params)
                self._inference_engine.params = None
        self._inference_param_version = self._param_version

    def generate(self, input_ids, **kwargs):
        """Inference-optimized generation on the CURRENT training params —
        reference hybrid_engine.generate (:178)."""
        self._refresh_inference_engine()
        return self._inference_engine.generate(input_ids, **kwargs)
