"""Hessian max-eigenvalue estimation by power iteration.

Capability parity with reference ``deepspeed/runtime/eigenvalue.py:12
Eigenvalue`` — per-block power iteration on the loss Hessian, used by MoQ
to schedule quantization aggressiveness (engine.py:1540,2041). The torch
version needs autograd.grad(create_graph=True) gymnastics; in JAX a
Hessian-vector product is one ``jvp``-of-``grad`` composition, jittable
end-to-end.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.logging import log_dist


def _tree_dot(a, b) -> jnp.ndarray:
    parts = jax.tree_util.tree_map(lambda x, y: jnp.sum(x * y), a, b)
    return jax.tree_util.tree_reduce(lambda s, x: s + x, parts, 0.0)


def _tree_norm(a) -> jnp.ndarray:
    return jnp.sqrt(_tree_dot(a, a))


class Eigenvalue:
    def __init__(self, verbose: bool = False, max_iter: int = 100,
                 tol: float = 1e-2, stability: float = 1e-6,
                 gas_boundary_resolution: int = 1, layer_name: str = "",
                 layer_num: int = 0):
        self.verbose = verbose
        self.max_iter = max_iter
        self.tol = tol
        self.stability = stability
        self.gas_boundary_resolution = gas_boundary_resolution
        self.layer_name = layer_name
        self.layer_num = layer_num
        log_dist(
            f"enabled eigenvalue: max_iter={max_iter}, tol={tol}, "
            f"stability={stability}, layer_name={layer_name!r}, "
            f"layer_num={layer_num}", ranks=[0])

    def select_block(self, params: Dict, block_index: int) -> Optional[Dict]:
        """Navigate ``layer_name`` (dot path) then index ``block_index`` —
        reference get_layers()."""
        node: Any = params
        if self.layer_name:
            for scope in self.layer_name.split("."):
                if not isinstance(node, dict) or scope not in node:
                    return None
                node = node[scope]
        key = str(block_index)
        for candidate in (key, f"layers_{block_index}", f"h_{block_index}",
                          f"blocks_{block_index}"):
            if isinstance(node, dict) and candidate in node:
                return node[candidate]
        return None

    def compute_eigenvalue(self, loss_fn: Callable[[Dict], jnp.ndarray],
                           params: Dict, rng: Optional[jax.Array] = None,
                           scale: float = 1.0) -> List[Tuple[float, float]]:
        """Power-iterate the Hessian of ``loss_fn`` w.r.t. each selected
        block; returns [(eigenvalue, layer_id)] like the reference (padded
        with the max over blocks when a block is missing). ``scale`` divides
        the loss (loss-scale compensation, reference compute_eigenvalue
        scale arg)."""
        if rng is None:
            rng = jax.random.PRNGKey(0)

        def scaled_loss(p):
            return loss_fn(p) / scale

        grad_fn = jax.grad(scaled_loss)

        def hvp(p, v):
            return jax.jvp(grad_fn, (p,), (v,))[1]

        results: List[Optional[float]] = []
        for block in range(max(self.layer_num, 1)):
            sub = self.select_block(params, block)
            if sub is None and self.layer_name:
                results.append(None)
                continue

            # power iteration restricted to this block: v has the full
            # param structure but is zero outside the block
            rng, sub_rng = jax.random.split(rng)
            leaves, treedef = jax.tree_util.tree_flatten(params)
            keys = jax.random.split(sub_rng, len(leaves))
            v_full = jax.tree_util.tree_unflatten(treedef, [
                jax.random.normal(k, jnp.shape(l), jnp.float32)
                for k, l in zip(keys, leaves)])
            if self.layer_name:
                # projector onto the selected block: applied to the initial
                # vector AND to every Hv (power iteration on P·H·P — the
                # block-diagonal restriction; without re-projection every
                # block would converge to the global eigenvalue)
                prefix = tuple(self.layer_name.split("."))
                block_names = (str(block), f"layers_{block}", f"h_{block}",
                               f"blocks_{block}")

                def in_block(path) -> bool:
                    names = tuple(str(getattr(k, "key", getattr(k, "idx", k)))
                                  for k in path)
                    if names[:len(prefix)] != prefix:
                        return False
                    rest = names[len(prefix):]
                    return bool(rest) and rest[0] in block_names

                def project(tree):
                    return jax.tree_util.tree_map_with_path(
                        lambda path, leaf: leaf if in_block(path)
                        else jnp.zeros_like(leaf), tree)

                v_full = project(v_full)
            else:
                def project(tree):
                    return tree

            eigenvalue = None
            v = v_full
            norm = _tree_norm(v) + self.stability
            v = jax.tree_util.tree_map(lambda x: x / norm, v)
            for i in range(self.max_iter):
                Hv = project(hvp(params, v))
                Hv = jax.tree_util.tree_map(jnp.nan_to_num, Hv)
                next_eig = float(_tree_dot(v, Hv))
                norm = _tree_norm(Hv) + self.stability
                v = jax.tree_util.tree_map(lambda x: x / norm, Hv)
                if eigenvalue is not None and abs(next_eig) > 0 and \
                        abs((next_eig - eigenvalue) / next_eig) < self.tol:
                    eigenvalue = next_eig
                    break
                eigenvalue = next_eig
            results.append(abs(eigenvalue) if eigenvalue is not None else None)
            if self.verbose:
                log_dist(f"block {block} eigenvalue {results[-1]}", ranks=[0])

        # post-process: replace missing entries with the max (reference
        # behavior — "it makes no sense to estimate with 0")
        known = [r for r in results if r is not None]
        fill = max(known) if known else 1.0
        return [(r if r is not None else fill, i)
                for i, r in enumerate(results)]
