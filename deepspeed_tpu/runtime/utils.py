"""Runtime math helpers.

Capability parity with the relevant parts of reference
``deepspeed/runtime/utils.py`` (975 LoC): ``clip_grad_norm_`` /
``get_global_norm``, ``CheckOverflow``, ``see_memory_usage`` — functional,
jit-compatible versions.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from ..utils.logging import log_dist


def global_grad_norm(grads: Any, ord: int = 2) -> jnp.ndarray:
    """L2 norm over an entire pytree of grads. Under GSPMD, sharded leaves
    contribute their global (not per-shard) norm — XLA inserts the psum —
    matching the model-parallel allreduce in the reference's
    ``get_grad_norm`` (runtime/utils.py / stage_1_and_2.py:1466)."""
    leaves = [g for g in jax.tree_util.tree_leaves(grads) if g is not None]
    if not leaves:
        return jnp.asarray(0.0, jnp.float32)
    if ord == 2:
        sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves)
        return jnp.sqrt(sq)
    stacked = jnp.stack([jnp.max(jnp.abs(g.astype(jnp.float32))) for g in leaves])
    return jnp.max(stacked)


def clip_grads_by_global_norm(grads: Any, max_norm: float,
                              norm: Optional[jnp.ndarray] = None):
    """≅ reference ``clip_grad_norm_``: scale grads so the global norm is at
    most ``max_norm``. Returns (clipped_grads, pre_clip_norm)."""
    if norm is None:
        norm = global_grad_norm(grads)
    # guard non-finite norms: factor 1.0 (the step will be skipped anyway)
    safe_norm = jnp.where(jnp.isfinite(norm), norm, jnp.asarray(0.0, jnp.float32))
    factor = jnp.minimum(1.0, max_norm / (safe_norm + 1e-6))
    clipped = jax.tree_util.tree_map(lambda g: (g * factor).astype(g.dtype), grads)
    return clipped, norm


def see_memory_usage(message: str, force: bool = False) -> None:
    """≅ reference ``see_memory_usage`` — device HBM stats via the
    accelerator seam."""
    if not force:
        return
    from ..accelerator import get_accelerator

    acc = get_accelerator()
    ga = acc.memory_allocated() / (1024 ** 3)
    peak = acc.max_memory_allocated() / (1024 ** 3)
    total = acc.total_memory() / (1024 ** 3)
    log_dist(f"{message} | allocated: {ga:.2f}GB | peak: {peak:.2f}GB | "
             f"limit: {total:.2f}GB", ranks=[0])


def count_parameters(params: Any) -> int:
    return sum(int(x.size) for x in jax.tree_util.tree_leaves(params))
