"""Curriculum-learning difficulty scheduler.

Capability parity with reference
``deepspeed/runtime/data_pipeline/curriculum_scheduler.py:11
CurriculumScheduler`` — maps global step → difficulty (e.g. sequence
length) under ``fixed_linear`` / ``fixed_root`` / ``fixed_discrete`` /
``custom`` schedules. Pure arithmetic; on TPU the consumer additionally
**buckets** the difficulty (see ``difficulty_step``) so the set of distinct
sequence lengths — and hence XLA recompiles — stays small.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, Optional

from ...utils.logging import logger

FIXED_LINEAR = "fixed_linear"
FIXED_ROOT = "fixed_root"
FIXED_DISCRETE = "fixed_discrete"
CUSTOM = "custom"


class CurriculumScheduler:
    def __init__(self, config: Dict[str, Any]):
        for key in ("min_difficulty", "max_difficulty", "schedule_type"):
            assert key in config, \
                f"Curriculum learning requires the config '{key}'"
        self.state: Dict[str, Any] = {
            "min_difficulty": config["min_difficulty"],
            "max_difficulty": config["max_difficulty"],
            "current_difficulty": config["min_difficulty"],
            "schedule_type": config["schedule_type"],
        }
        self.first_step = True
        self.custom_get_difficulty: Optional[Callable[[int], int]] = None
        schedule_type = config["schedule_type"]
        schedule_config = config.get("schedule_config", {})

        if schedule_type == FIXED_DISCRETE:
            # difficulty: [d0, d1, ...], max_step: [s0, s1, ...] with one
            # fewer steps than difficulties (last difficulty holds forever)
            assert "difficulty" in schedule_config
            assert "max_step" in schedule_config
            assert len(schedule_config["difficulty"]) == \
                len(schedule_config["max_step"]) + 1
        elif schedule_type in (FIXED_LINEAR, FIXED_ROOT):
            assert "total_curriculum_step" in schedule_config, \
                f"'{schedule_type}' schedule requires total_curriculum_step"
            assert "difficulty_step" in schedule_config, \
                f"'{schedule_type}' schedule requires difficulty_step"
            if schedule_type == FIXED_ROOT:
                assert "root_degree" in schedule_config, \
                    "'fixed_root' schedule requires root_degree"
            if schedule_config["difficulty_step"] % 8 != 0:
                logger.warning(
                    "difficulty_step should be a multiple of 8 so seqlen "
                    "buckets stay MXU-tile friendly (and recompiles stay "
                    "few) — disregard if the metric is not seqlen")
        elif schedule_type == CUSTOM:
            pass  # set_custom_get_difficulty must be called
        else:
            raise RuntimeError(f"Unsupported schedule type {schedule_type}")
        self.state["schedule_config"] = schedule_config

    # -- reference API ----------------------------------------------------
    def get_current_difficulty(self) -> int:
        return self.state["current_difficulty"]

    def set_current_difficulty(self, difficulty: int) -> None:
        self.state["current_difficulty"] = difficulty

    def set_custom_get_difficulty(self, fn: Callable[[int], int]) -> None:
        self.custom_get_difficulty = fn

    def get_state(self) -> Dict[str, Any]:
        return self.state

    def set_state(self, state: Dict[str, Any]) -> None:
        self.state = state

    # -- schedule math ----------------------------------------------------
    def __fixed_discrete_get_difficulty(self, global_steps: int) -> int:
        sc = self.state["schedule_config"]
        for i, max_step in enumerate(sc["max_step"]):
            if global_steps <= max_step:
                return sc["difficulty"][i]
        return sc["difficulty"][-1]

    def __fixed_root_get_difficulty(self, global_steps: int,
                                    root_degree: Optional[int] = None) -> int:
        sc = self.state["schedule_config"]
        if root_degree is None:
            root_degree = sc["root_degree"]
        next_difficulty = (float(global_steps) /
                           sc["total_curriculum_step"]) ** (1.0 / root_degree)
        next_difficulty = math.floor(
            next_difficulty *
            (self.state["max_difficulty"] - self.state["min_difficulty"]) +
            self.state["min_difficulty"])
        # bucket to a multiple of difficulty_step (bounds recompiles on TPU)
        next_difficulty -= next_difficulty % sc["difficulty_step"]
        return min(next_difficulty, self.state["max_difficulty"])

    def get_difficulty(self, global_steps: int) -> int:
        stype = self.state["schedule_type"]
        if stype == FIXED_DISCRETE:
            return self.__fixed_discrete_get_difficulty(global_steps)
        if stype == FIXED_LINEAR:
            return self.__fixed_root_get_difficulty(global_steps, 1)
        if stype == FIXED_ROOT:
            return self.__fixed_root_get_difficulty(global_steps)
        if stype == CUSTOM:
            assert self.custom_get_difficulty is not None, \
                "custom schedule requires set_custom_get_difficulty()"
            return self.custom_get_difficulty(global_steps)
        raise RuntimeError(f"Unsupported schedule type {stype}")

    def update_difficulty(self, global_steps: int) -> int:
        if self.state["current_difficulty"] < self.state["max_difficulty"]:
            self.state["current_difficulty"] = max(
                self.get_difficulty(global_steps),
                self.state["min_difficulty"])
        return self.state["current_difficulty"]

    # -- checkpoint -------------------------------------------------------
    def state_dict(self) -> Dict[str, Any]:
        return dict(self.state)

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        self.state = dict(sd)
