"""Memory-mapped indexed dataset.

Capability parity with reference
``deepspeed/runtime/data_pipeline/data_sampling/indexed_dataset.py`` (617
LoC, the Megatron-LM mmap format) — a binary token file (``.bin``) plus an
index (``.idx``) of per-document offsets/lengths, read zero-copy via numpy
memmap. Used by the data analyzer / curriculum sampler to address samples
by difficulty without loading the corpus.

Format (own layout, same capability): ``.idx`` holds a header
(magic, version, dtype code, count) followed by int64 offsets and int32
lengths; ``.bin`` is the raw concatenated sample arrays.
"""

from __future__ import annotations

import os
import struct
from typing import List, Sequence

import numpy as np

_MAGIC = b"DSTPUIDX"
_VERSION = 1

_DTYPES = {
    1: np.uint8, 2: np.int8, 3: np.int16, 4: np.int32,
    5: np.int64, 6: np.float32, 7: np.float64, 8: np.uint16,
}
_DTYPE_CODES = {np.dtype(v): k for k, v in _DTYPES.items()}


def data_file_path(prefix: str) -> str:
    return prefix + ".bin"


def index_file_path(prefix: str) -> str:
    return prefix + ".idx"


class MMapIndexedDatasetBuilder:
    def __init__(self, out_file_prefix: str, dtype=np.int32):
        self._prefix = out_file_prefix
        self._dtype = np.dtype(dtype)
        self._data_file = open(data_file_path(out_file_prefix), "wb")
        self._lengths: List[int] = []

    def add_item(self, array: Sequence) -> None:
        arr = np.asarray(array, dtype=self._dtype)
        self._data_file.write(arr.tobytes(order="C"))
        self._lengths.append(arr.size)

    def merge_file_(self, another_prefix: str) -> None:
        other = MMapIndexedDataset(another_prefix)
        for i in range(len(other)):
            self.add_item(other[i])

    def finalize(self) -> None:
        self._data_file.close()
        lengths = np.asarray(self._lengths, dtype=np.int32)
        offsets = np.zeros(len(lengths), dtype=np.int64)
        if len(lengths) > 1:
            np.cumsum(lengths[:-1] * self._dtype.itemsize, out=offsets[1:])
        with open(index_file_path(self._prefix), "wb") as f:
            f.write(_MAGIC)
            f.write(struct.pack("<HHq", _VERSION,
                                _DTYPE_CODES[self._dtype], len(lengths)))
            f.write(offsets.tobytes(order="C"))
            f.write(lengths.tobytes(order="C"))


class MMapIndexedDataset:
    def __init__(self, prefix: str, skip_warmup: bool = True):
        self._prefix = prefix
        with open(index_file_path(prefix), "rb") as f:
            magic = f.read(len(_MAGIC))
            assert magic == _MAGIC, f"bad index file magic in {prefix}.idx"
            version, dtype_code, count = struct.unpack("<HHq", f.read(12))
            assert version == _VERSION
            self._dtype = np.dtype(_DTYPES[dtype_code])
            self._count = count
            header = f.tell()
        self._offsets = np.memmap(index_file_path(prefix), dtype=np.int64,
                                  mode="r", offset=header, shape=(count,))
        self._lengths = np.memmap(index_file_path(prefix), dtype=np.int32,
                                  mode="r", offset=header + 8 * count,
                                  shape=(count,))
        self._data = np.memmap(data_file_path(prefix), dtype=self._dtype,
                               mode="r")

    def __len__(self) -> int:
        return self._count

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return [self[i] for i in range(*idx.indices(len(self)))]
        offset = int(self._offsets[idx]) // self._dtype.itemsize
        length = int(self._lengths[idx])
        return np.asarray(self._data[offset:offset + length])

    def get(self, idx: int, offset: int = 0, length: int = None):
        base = int(self._offsets[idx]) // self._dtype.itemsize + offset
        if length is None:
            length = int(self._lengths[idx]) - offset
        return np.asarray(self._data[base:base + length])

    @property
    def sizes(self) -> np.ndarray:
        return np.asarray(self._lengths)

    @property
    def dtype(self):
        return self._dtype

    @staticmethod
    def exists(prefix: str) -> bool:
        return os.path.exists(index_file_path(prefix)) and \
            os.path.exists(data_file_path(prefix))
