"""Memory-mapped indexed dataset.

Capability parity with reference
``deepspeed/runtime/data_pipeline/data_sampling/indexed_dataset.py`` (617
LoC, the Megatron-LM mmap format) — a binary token file (``.bin``) plus an
index (``.idx``) of per-document offsets/lengths, read zero-copy via numpy
memmap. Used by the data analyzer / curriculum sampler to address samples
by difficulty without loading the corpus.

Format (own layout, same capability): ``.idx`` holds a header
(magic, version, dtype code, count) followed by int64 offsets and int32
lengths; ``.bin`` is the raw concatenated sample arrays.
"""

from __future__ import annotations

import os
import struct
from typing import List, Sequence

import numpy as np

_MAGIC = b"DSTPUIDX"
_VERSION = 1

_DTYPES = {
    1: np.uint8, 2: np.int8, 3: np.int16, 4: np.int32,
    5: np.int64, 6: np.float32, 7: np.float64, 8: np.uint16,
}
_DTYPE_CODES = {np.dtype(v): k for k, v in _DTYPES.items()}


def data_file_path(prefix: str) -> str:
    return prefix + ".bin"


def index_file_path(prefix: str) -> str:
    return prefix + ".idx"


class MMapIndexedDatasetBuilder:
    def __init__(self, out_file_prefix: str, dtype=np.int32):
        self._prefix = out_file_prefix
        self._dtype = np.dtype(dtype)
        self._data_file = open(data_file_path(out_file_prefix), "wb")
        self._lengths: List[int] = []

    def add_item(self, array: Sequence) -> None:
        arr = np.asarray(array, dtype=self._dtype)
        self._data_file.write(arr.tobytes(order="C"))
        self._lengths.append(arr.size)

    def merge_file_(self, another_prefix: str) -> None:
        other = MMapIndexedDataset(another_prefix)
        for i in range(len(other)):
            self.add_item(other[i])

    def finalize(self) -> None:
        self._data_file.close()
        lengths = np.asarray(self._lengths, dtype=np.int32)
        offsets = np.zeros(len(lengths), dtype=np.int64)
        if len(lengths) > 1:
            np.cumsum(lengths[:-1] * self._dtype.itemsize, out=offsets[1:])
        with open(index_file_path(self._prefix), "wb") as f:
            f.write(_MAGIC)
            f.write(struct.pack("<HHq", _VERSION,
                                _DTYPE_CODES[self._dtype], len(lengths)))
            f.write(offsets.tobytes(order="C"))
            f.write(lengths.tobytes(order="C"))


class MMapIndexedDataset:
    def __init__(self, prefix: str, skip_warmup: bool = True):
        self._prefix = prefix
        with open(index_file_path(prefix), "rb") as f:
            magic = f.read(len(_MAGIC))
            assert magic == _MAGIC, f"bad index file magic in {prefix}.idx"
            version, dtype_code, count = struct.unpack("<HHq", f.read(12))
            assert version == _VERSION
            self._dtype = np.dtype(_DTYPES[dtype_code])
            self._count = count
            header = f.tell()
        self._offsets = np.memmap(index_file_path(prefix), dtype=np.int64,
                                  mode="r", offset=header, shape=(count,))
        self._lengths = np.memmap(index_file_path(prefix), dtype=np.int32,
                                  mode="r", offset=header + 8 * count,
                                  shape=(count,))
        self._data = np.memmap(data_file_path(prefix), dtype=self._dtype,
                               mode="r")

    def __len__(self) -> int:
        return self._count

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return [self[i] for i in range(*idx.indices(len(self)))]
        offset = int(self._offsets[idx]) // self._dtype.itemsize
        length = int(self._lengths[idx])
        return np.asarray(self._data[offset:offset + length])

    def get(self, idx: int, offset: int = 0, length: int = None):
        base = int(self._offsets[idx]) // self._dtype.itemsize + offset
        if length is None:
            length = int(self._lengths[idx]) - offset
        return np.asarray(self._data[base:base + length])

    @property
    def sizes(self) -> np.ndarray:
        return np.asarray(self._lengths)

    @property
    def dtype(self):
        return self._dtype

    @staticmethod
    def exists(prefix: str) -> bool:
        return os.path.exists(index_file_path(prefix)) and \
            os.path.exists(data_file_path(prefix))


# ---------------------------------------------------------------------------
# Megatron-LM mmap format interop
# ---------------------------------------------------------------------------
# Byte-compatible reader/writer for the layout the reference ships
# (``deepspeed/runtime/data_pipeline/data_sampling/indexed_dataset.py``,
# MMapIndexedDataset.Index): existing Megatron-preprocessed corpora are
# consumed directly — curriculum/analyzer tooling does not require a
# re-encode. Layout: ``.idx`` = magic 'MMIDIDX\x00\x00' + u64 version(=1)
# + u8 dtype code + u64 n_seqs + u64 n_docs + i32 sizes[n] + i64
# pointers[n] (byte offsets) + i64 doc_idx[n_docs]; ``.bin`` = the raw
# concatenated token arrays.

MEGATRON_MAGIC = b"MMIDIDX\x00\x00"

_MEGATRON_DTYPES = {
    1: np.uint8, 2: np.int8, 3: np.int16, 4: np.int32, 5: np.int64,
    6: np.float64, 7: np.double, 8: np.uint16, 9: np.uint32, 10: np.uint64,
}
# Newer readers accept codes 9/10, but Megatron-LM's and the reference's
# own tables stop at 8 — the WRITER emits only codes both sides read, or
# the 'readable by the reference' claim breaks with a remote KeyError.
_MEGATRON_WRITABLE_CODES = {np.dtype(v): k
                            for k, v in _MEGATRON_DTYPES.items() if k <= 8}


class MegatronMMapIndexedDataset:
    """Zero-copy reader for the Megatron-LM / reference mmap layout.

    Same access surface as :class:`MMapIndexedDataset` (``__getitem__``,
    ``get``, ``sizes``, ``dtype``) plus ``doc_idx`` (document boundaries,
    which the native layout does not track).
    """

    def __init__(self, prefix: str, skip_warmup: bool = True):
        self._prefix = prefix
        path = index_file_path(prefix)
        with open(path, "rb") as f:
            magic = f.read(9)
            assert magic == MEGATRON_MAGIC, \
                f"{path} is not a Megatron-format index"
            (version,) = struct.unpack("<Q", f.read(8))
            assert version == 1, f"unsupported Megatron index v{version}"
            (code,) = struct.unpack("<B", f.read(1))
            self._dtype = np.dtype(_MEGATRON_DTYPES[code])
            (self._count,) = struct.unpack("<Q", f.read(8))
            (self._doc_count,) = struct.unpack("<Q", f.read(8))
            header = f.tell()
        n = self._count
        self._sizes = np.memmap(path, dtype=np.int32, mode="r",
                                offset=header, shape=(n,))
        self._pointers = np.memmap(path, dtype=np.int64, mode="r",
                                   offset=header + 4 * n, shape=(n,))
        self._doc_idx = np.memmap(path, dtype=np.int64, mode="r",
                                  offset=header + 4 * n + 8 * n,
                                  shape=(self._doc_count,))
        self._data = np.memmap(data_file_path(prefix), dtype=self._dtype,
                               mode="r")

    def __len__(self) -> int:
        return self._count

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return [self[i] for i in range(*idx.indices(len(self)))]
        offset = int(self._pointers[idx]) // self._dtype.itemsize
        length = int(self._sizes[idx])
        return np.asarray(self._data[offset:offset + length])

    def get(self, idx: int, offset: int = 0, length: int = None):
        base = int(self._pointers[idx]) // self._dtype.itemsize + offset
        if length is None:
            length = int(self._sizes[idx]) - offset
        return np.asarray(self._data[base:base + length])

    @property
    def sizes(self) -> np.ndarray:
        return np.asarray(self._sizes)

    @property
    def doc_idx(self) -> np.ndarray:
        return np.asarray(self._doc_idx)

    @property
    def dtype(self):
        return self._dtype

    @staticmethod
    def exists(prefix: str) -> bool:
        path = index_file_path(prefix)
        if not (os.path.exists(path) and
                os.path.exists(data_file_path(prefix))):
            return False
        with open(path, "rb") as f:
            return f.read(9) == MEGATRON_MAGIC


class MegatronMMapIndexedDatasetBuilder:
    """Writer emitting the reference's byte layout (corpus export /
    fixtures readable by Megatron-LM and the reference itself)."""

    def __init__(self, out_file_prefix: str, dtype=np.int32):
        self._prefix = out_file_prefix
        self._dtype = np.dtype(dtype)
        if self._dtype not in _MEGATRON_WRITABLE_CODES:
            raise ValueError(
                f"dtype {self._dtype} has no Megatron-LM dtype code "
                "(reference readers know codes 1-8: u8/i8/i16/i32/i64/"
                "f64/double/u16) — use the native MMapIndexedDatasetBuilder")
        self._data_file = open(data_file_path(out_file_prefix), "wb")
        self._sizes: List[int] = []
        self._doc_idx: List[int] = [0]

    def add_item(self, array: Sequence) -> None:
        arr = np.asarray(array, dtype=self._dtype)
        self._data_file.write(arr.tobytes(order="C"))
        self._sizes.append(arr.size)

    def end_document(self) -> None:
        self._doc_idx.append(len(self._sizes))

    def finalize(self) -> None:
        self._data_file.close()
        sizes = np.asarray(self._sizes, dtype=np.int32)
        pointers = np.zeros(len(sizes), dtype=np.int64)
        if len(sizes) > 1:
            np.cumsum(sizes[:-1].astype(np.int64) * self._dtype.itemsize,
                      out=pointers[1:])
        if self._doc_idx[-1] != len(self._sizes):
            self.end_document()
        doc_idx = np.asarray(self._doc_idx, dtype=np.int64)
        with open(index_file_path(self._prefix), "wb") as f:
            f.write(MEGATRON_MAGIC)
            f.write(struct.pack("<Q", 1))
            f.write(struct.pack("<B", _MEGATRON_WRITABLE_CODES[self._dtype]))
            f.write(struct.pack("<Q", len(sizes)))
            f.write(struct.pack("<Q", len(doc_idx)))
            f.write(sizes.tobytes(order="C"))
            f.write(pointers.tobytes(order="C"))
            f.write(doc_idx.tobytes(order="C"))


def load_indexed_dataset(prefix: str, skip_warmup: bool = True):
    """Open ``prefix``.bin/.idx in WHICHEVER layout it carries — native
    (DSTPUIDX) or Megatron (MMIDIDX) — by sniffing the index magic, the
    reference's ``infer_dataset_impl`` behavior."""
    with open(index_file_path(prefix), "rb") as f:
        magic = f.read(9)
    if magic == MEGATRON_MAGIC:
        return MegatronMMapIndexedDataset(prefix, skip_warmup=skip_warmup)
    if magic[:len(_MAGIC)] == _MAGIC:
        return MMapIndexedDataset(prefix, skip_warmup=skip_warmup)
    raise ValueError(f"{prefix}.idx: unrecognized index magic {magic!r}")
