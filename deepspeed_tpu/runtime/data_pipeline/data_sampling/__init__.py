from .data_sampler import DeepSpeedDataSampler
from .indexed_dataset import MMapIndexedDataset, MMapIndexedDatasetBuilder

__all__ = ["DeepSpeedDataSampler", "MMapIndexedDataset",
           "MMapIndexedDatasetBuilder"]
