from .data_analyzer import DataAnalyzer
from .data_sampler import DeepSpeedDataSampler
from .indexed_dataset import MMapIndexedDataset, MMapIndexedDatasetBuilder

__all__ = ["DataAnalyzer", "DeepSpeedDataSampler", "MMapIndexedDataset",
           "MMapIndexedDatasetBuilder"]
