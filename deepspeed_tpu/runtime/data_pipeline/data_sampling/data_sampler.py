"""Curriculum-learning data sampler.

Capability parity with reference
``deepspeed/runtime/data_pipeline/data_sampling/data_sampler.py:36
DeepSpeedDataSampler`` — samples global batches restricted to the current
curriculum difficulty, using per-sample metric values (e.g. seqlen,
vocab rarity) indexed offline by the data analyzer. Samples are grouped
into difficulty *clusters*; each batch draws from the union of unlocked
clusters, and previously-seen clusters are reshuffled when exhausted.

Metric modes (reference constants):
  * ``value`` — difficulty thresholds compare raw metric values
  * ``percentile`` — thresholds are percentiles of the metric distribution
"""

from __future__ import annotations

import os
from typing import Any, Dict, Iterator, List, Optional, Sequence

import numpy as np

from ....utils.logging import logger
from ..curriculum_scheduler import CurriculumScheduler


class DeepSpeedDataSampler:
    def __init__(self, data_efficiency_config: Dict[str, Any],
                 one_epoch_total_samples: int,
                 micro_batch_size: int,
                 data_parallel_rank: int,
                 data_parallel_size: int,
                 gradient_accumulation_steps: int = 1,
                 global_rank: int = 0,
                 drop_last: bool = True,
                 metric_values: Optional[Sequence[float]] = None,
                 seed: int = 1234):
        self.config = data_efficiency_config
        self.total_samples = one_epoch_total_samples
        self.micro_batch_size = micro_batch_size
        self.dp_rank = data_parallel_rank
        self.dp_size = data_parallel_size
        self.gas = gradient_accumulation_steps
        self.global_batch_size = micro_batch_size * data_parallel_size * \
            gradient_accumulation_steps
        self.drop_last = drop_last
        self.rng = np.random.default_rng(seed)
        self.consumed_samples = 0

        cl_cfg = data_efficiency_config.get("curriculum_learning", {})
        self.curriculum_enabled = bool(cl_cfg.get("enabled", False))
        self.curriculum_schedulers: Dict[str, CurriculumScheduler] = {}
        self.difficulty_type: Dict[str, str] = {}
        self.metric_values: Dict[str, np.ndarray] = {}
        self.current_difficulties: Dict[str, int] = {}
        if self.curriculum_enabled:
            metrics = cl_cfg.get("curriculum_metrics", {})
            for name, mcfg in metrics.items():
                self.curriculum_schedulers[name] = CurriculumScheduler(mcfg)
                self.difficulty_type[name] = mcfg.get("difficulty_type",
                                                      "value")
                if metric_values is not None and not isinstance(
                        metric_values, dict):
                    self.metric_values[name] = np.asarray(metric_values)
            if isinstance(metric_values, dict):
                for name, vals in metric_values.items():
                    self.metric_values[name] = np.asarray(vals)
            for name, mcfg in metrics.items():
                if name in self.metric_values:
                    continue
                # load the offline data analyzer's index when configured
                # (reference: sample_to_metric index files)
                path = mcfg.get("sample_to_metric_path")
                if path:
                    from .data_analyzer import DataAnalyzer

                    if os.path.isdir(path):
                        self.metric_values[name] = \
                            DataAnalyzer.load_metric_values(path, name)
                    else:
                        self.metric_values[name] = np.load(path)
            for name in self.curriculum_schedulers:
                assert name in self.metric_values, \
                    f"metric values for '{name}' are required — run the " \
                    f"offline DataAnalyzer and pass metric_values or set " \
                    f"sample_to_metric_path"
        self.np_rng = self.rng

    def __len__(self) -> int:
        return self.total_samples

    def set_custom_curriculum_learning_schedule(self, schedule_func_dict):
        for name, fn in schedule_func_dict.items():
            if name in self.curriculum_schedulers:
                self.curriculum_schedulers[name].set_custom_get_difficulty(fn)

    # -- difficulty-constrained index pool --------------------------------
    def _eligible_indices(self) -> np.ndarray:
        if not self.curriculum_enabled:
            return np.arange(self.total_samples)
        mask = np.ones(self.total_samples, dtype=bool)
        for name, sched in self.curriculum_schedulers.items():
            difficulty = self.current_difficulties[name]
            values = self.metric_values[name][:self.total_samples]
            if self.difficulty_type[name] == "percentile":
                threshold = np.percentile(values, difficulty)
            else:
                threshold = difficulty
            mask &= values <= threshold
        idx = np.nonzero(mask)[0]
        if idx.size == 0:
            # never return an empty pool: fall back to the easiest samples
            # by the FIRST configured metric (schedulers dict preserves the
            # config's metric order), restricted to this dataset's samples
            first = next(iter(self.curriculum_schedulers))
            values = self.metric_values[first][:self.total_samples]
            idx = np.argsort(values)[:self.global_batch_size]
        return idx

    def get_next_global_batch(self) -> np.ndarray:
        step = self.consumed_samples // self.global_batch_size
        if self.curriculum_enabled:
            for name, sched in self.curriculum_schedulers.items():
                self.current_difficulties[name] = sched.update_difficulty(step)
        pool = self._eligible_indices()
        batch = self.np_rng.choice(pool, size=self.global_batch_size,
                                   replace=pool.size < self.global_batch_size)
        self.consumed_samples += self.global_batch_size
        return batch

    def __iter__(self) -> Iterator[List[int]]:
        """One epoch of batches (standard batch-sampler contract):
        ``drop_last=True`` floors to whole global batches; ``False`` adds a
        final wrapped batch covering the remainder. Restart iteration for
        the next epoch — curriculum difficulty carries across epochs via
        ``consumed_samples``."""
        full_batches = self.total_samples // self.global_batch_size
        remainder = self.total_samples % self.global_batch_size
        n_batches = full_batches + (1 if remainder and not self.drop_last
                                    else 0)
        for _ in range(n_batches):
            batch = self.get_next_global_batch()
            # this dp rank's contiguous slice (reference get_start_end_idx)
            start = self.dp_rank * self.micro_batch_size * self.gas
            end = start + self.micro_batch_size * self.gas
            yield batch[start:end].tolist()

    def state_dict(self) -> Dict[str, Any]:
        return {
            "consumed_samples": self.consumed_samples,
            "curriculum_states": {
                name: sched.state_dict()
                for name, sched in self.curriculum_schedulers.items()
            },
            "rng": self.np_rng.bit_generator.state,
        }

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        self.consumed_samples = sd["consumed_samples"]
        for name, state in sd.get("curriculum_states", {}).items():
            if name in self.curriculum_schedulers:
                self.curriculum_schedulers[name].load_state_dict(state)
        if "rng" in sd:
            self.np_rng.bit_generator.state = sd["rng"]
