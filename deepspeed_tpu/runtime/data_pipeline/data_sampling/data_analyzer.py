"""Offline data analyzer — difficulty indexing for curriculum learning.

Capability parity with reference ``deepspeed/runtime/data_pipeline/
data_sampling/data_analyzer.py`` (``DataAnalyzer.run_map`` :180 /
``run_reduce`` :411): computes user metric functions over every sample of
a dataset ahead of training and writes the index files the curriculum
sampler consumes. The map phase shards the dataset over (num_workers ×
num_threads) and writes one partial result per shard; the reduce phase
merges shards into:

* ``{metric}_sample_to_metric.npy`` — per-sample metric value, aligned to
  dataset order (what ``DeepSpeedDataSampler`` needs),
* ``{metric}_metric_to_sample.json`` — metric value → sample ids (the
  reference's metric_to_sample index used for value-bucketed sampling),
* ``{metric}_meta.json`` — min/max/count.

The reference stores these as mmap indexed datasets + CSVs; npy/json hold
the same information at the scales the sampler reads once per run.
"""

from __future__ import annotations

import json
import os
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from ....utils.logging import log_dist


class DataAnalyzer:
    """Map/reduce difficulty indexing (reference data_analyzer.py:20).

    Args:
      dataset: indexable dataset (``__len__`` + ``__getitem__``).
      metric_functions: {metric name: fn(sample) -> scalar} — e.g. sequence
        length or vocabulary rarity (reference passes a list; a dict names
        the output files).
      save_path: output directory for the index files.
      num_workers/worker_id: shard the map phase across processes or hosts;
        each worker covers samples [worker_id::num_workers].
      num_threads: intra-worker parallelism of the map phase.
    """

    def __init__(self, dataset, metric_functions: Dict[str, Callable[[Any], float]],
                 save_path: str, num_workers: int = 1, worker_id: int = 0,
                 num_threads: int = 1, batch_size: int = 1024):
        assert metric_functions, "need at least one metric function"
        self.dataset = dataset
        self.metric_functions = dict(metric_functions)
        self.save_path = save_path
        self.num_workers = num_workers
        self.worker_id = worker_id
        self.num_threads = max(1, num_threads)
        self.batch_size = batch_size
        os.makedirs(save_path, exist_ok=True)

    # -- map phase --------------------------------------------------------
    def _worker_indices(self) -> np.ndarray:
        return np.arange(self.worker_id, len(self.dataset), self.num_workers)

    def _shard_file(self, metric: str, worker_id: int) -> str:
        return os.path.join(self.save_path,
                            f"{metric}_worker{worker_id}_map.npz")

    def run_map(self) -> None:
        """Compute every metric over this worker's shard and persist the
        partial (sample_id, value) arrays."""
        indices = self._worker_indices()

        def eval_chunk(chunk: np.ndarray) -> Dict[str, List[float]]:
            out: Dict[str, List[float]] = {m: [] for m in self.metric_functions}
            for i in chunk:
                sample = self.dataset[int(i)]
                for m, fn in self.metric_functions.items():
                    out[m].append(float(fn(sample)))
            return out

        chunks = [indices[i:i + self.batch_size]
                  for i in range(0, len(indices), self.batch_size)]
        results: Dict[str, List[float]] = {m: [] for m in self.metric_functions}
        if self.num_threads > 1 and len(chunks) > 1:
            with ThreadPoolExecutor(max_workers=self.num_threads) as pool:
                for part in pool.map(eval_chunk, chunks):
                    for m, vals in part.items():
                        results[m].extend(vals)
        else:
            for chunk in chunks:
                for m, vals in eval_chunk(chunk).items():
                    results[m].extend(vals)

        for metric, vals in results.items():
            np.savez(self._shard_file(metric, self.worker_id),
                     sample_ids=indices, values=np.asarray(vals, np.float64))
        log_dist(f"data analyzer map: worker {self.worker_id}/"
                 f"{self.num_workers} indexed {len(indices)} samples "
                 f"({list(self.metric_functions)})", ranks=[0])

    # -- reduce phase -----------------------------------------------------
    def run_reduce(self) -> Dict[str, np.ndarray]:
        """Merge all workers' partial results into the final index files.
        Returns {metric: per-sample values} for in-process use."""
        merged: Dict[str, np.ndarray] = {}
        n = len(self.dataset)
        for metric in self.metric_functions:
            values = np.zeros(n, np.float64)
            seen = np.zeros(n, bool)  # explicit mask: NaN is a legal value
            for w in range(self.num_workers):
                path = self._shard_file(metric, w)
                if not os.path.exists(path):
                    raise FileNotFoundError(
                        f"missing map shard {path} — run_map every worker "
                        f"before run_reduce")
                part = np.load(path)
                values[part["sample_ids"]] = part["values"]
                seen[part["sample_ids"]] = True
            if not seen.all():
                missing = np.nonzero(~seen)[0]
                raise RuntimeError(
                    f"metric {metric}: {missing.size} samples were never "
                    f"indexed (first missing ids {missing[:5].tolist()}) — "
                    f"did every worker run_map with the same num_workers?")
            np.save(self._sample_to_metric_path(self.save_path, metric),
                    values)
            # metric value -> sample ids (reference metric_to_sample index);
            # keys are plain repr(float) so numpy 1.x/2.x hosts agree and
            # consumers can float() them back
            m2s: Dict[str, List[int]] = {}
            for idx, v in enumerate(values):
                m2s.setdefault(repr(float(v)), []).append(idx)
            with open(os.path.join(self.save_path,
                                   f"{metric}_metric_to_sample.json"),
                      "w") as f:
                json.dump(m2s, f)
            with open(os.path.join(self.save_path, f"{metric}_meta.json"),
                      "w") as f:
                json.dump({"min": float(values.min()),
                           "max": float(values.max()),
                           "count": int(n)}, f)
            merged[metric] = values
        log_dist(f"data analyzer reduce: wrote indexes for "
                 f"{list(self.metric_functions)} to {self.save_path}",
                 ranks=[0])
        return merged

    def run_map_reduce(self) -> Dict[str, np.ndarray]:
        """Single-process convenience: map this worker (must be the only
        one) then reduce."""
        assert self.num_workers == 1, \
            "run_map_reduce is single-worker; run run_map per worker then " \
            "run_reduce once"
        self.run_map()
        return self.run_reduce()

    # -- consumption ------------------------------------------------------
    @staticmethod
    def _sample_to_metric_path(save_path: str, metric: str) -> str:
        return os.path.join(save_path, f"{metric}_sample_to_metric.npy")

    @staticmethod
    def load_metric_values(save_path: str, metric: str) -> np.ndarray:
        """Read a metric's per-sample values (what DeepSpeedDataSampler
        takes as ``metric_values[name]``)."""
        return np.load(DataAnalyzer._sample_to_metric_path(save_path, metric))
