"""Random-LTD schedule.

Capability parity with reference
``deepspeed/runtime/data_pipeline/data_routing/scheduler.py`` — ramps the
number of *kept* tokens from ``min_value`` to ``max_value`` over
``total_layer_token_budget`` steps. Values are bucketed to
``value_step_size`` so the set of distinct reserved lengths (and hence XLA
compiles) stays bounded.
"""

from __future__ import annotations

from typing import Any, Dict


class RandomLTDScheduler:
    def __init__(self, config: Dict[str, Any]):
        sched = config.get("random_ltd_schedule", config)
        self.min_value = int(sched.get("min_value", 128))
        self.max_value = int(sched.get("max_value", 1024))
        self.schedule_type = sched.get("schedule_type", "fixed_linear")
        sc = sched.get("schedule_config", {})
        self.total_steps = int(sc.get("require_steps",
                                      sc.get("total_curriculum_step", 10000)))
        self.step_size = int(sc.get("seq_per_step", 8))
        self.current_value = self.min_value
        self.global_steps = 0

    def get_current_seq(self) -> int:
        return self.current_value

    def update_seq(self, global_steps: int) -> int:
        self.global_steps = global_steps
        if self.schedule_type == "fixed_linear":
            value = self.min_value + \
                (self.max_value - self.min_value) * \
                min(1.0, global_steps / max(self.total_steps, 1))
        else:
            raise RuntimeError(
                f"Unsupported random-ltd schedule {self.schedule_type}")
        value = int(value) - int(value) % self.step_size
        self.current_value = max(self.min_value,
                                 min(value, self.max_value))
        return self.current_value

    def state_dict(self) -> Dict[str, Any]:
        return {"current_value": self.current_value,
                "global_steps": self.global_steps}

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        self.current_value = sd["current_value"]
        self.global_steps = sd["global_steps"]
