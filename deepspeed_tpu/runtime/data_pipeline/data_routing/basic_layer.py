"""Random layerwise token dropping (random-LTD).

Capability parity with reference
``deepspeed/runtime/data_pipeline/data_routing/basic_layer.py:14
RandomLayerTokenDrop`` — wraps a transformer layer so that during training
only a random subset of tokens flows through it; the rest bypass via the
residual. The reference mutates the wrapped torch module; the flax version
is a combinator module, and the reserved length arrives as a *static*
argument (bucketed by :class:`RandomLTDScheduler`) so each bucket compiles
once.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ....ops.random_ltd import gather_tokens, sample_tokens, scatter_tokens


class RandomLayerTokenDrop(nn.Module):
    """Wraps ``layer`` (a flax Module taking (hidden, *args, **kwargs) and
    returning hidden of the same shape) with token dropping."""

    layer: nn.Module
    rng_collection: str = "random_ltd"

    @nn.compact
    def __call__(self, hidden_states: jnp.ndarray, *args,
                 reserved_length: Optional[int] = None,
                 attention_mask: Optional[jnp.ndarray] = None,
                 deterministic: bool = False, **kwargs):
        seq_length = hidden_states.shape[1]
        if deterministic or reserved_length is None or \
                reserved_length >= seq_length:
            if attention_mask is not None:
                kwargs["attention_mask"] = attention_mask
            return self.layer(hidden_states, *args, **kwargs)

        rng = self.make_rng(self.rng_collection)
        idx = sample_tokens(rng, hidden_states.shape[0], seq_length,
                            reserved_length)
        part = gather_tokens(hidden_states, idx)
        if attention_mask is not None:
            # slice the mask to the selected tokens (reference
            # bert/gpt_sample_tokens return the partitioned mask alongside):
            # (b, s) keys → gather dim 1; (b, s, s) / (b, h, s, s) pairwise
            # masks → gather the last two dims
            if attention_mask.ndim == 2:
                kwargs["attention_mask"] = jnp.take_along_axis(
                    attention_mask, idx, axis=1)
            else:
                b, r = idx.shape
                mid = (1,) * (attention_mask.ndim - 3)
                rows = idx.reshape(b, *mid, r, 1)
                cols = idx.reshape(b, *mid, 1, r)
                m = jnp.take_along_axis(attention_mask, rows, axis=-2)
                kwargs["attention_mask"] = jnp.take_along_axis(m, cols,
                                                               axis=-1)
        out = self.layer(part, *args, **kwargs)
        if isinstance(out, tuple):
            out, *rest = out
            return (scatter_tokens(hidden_states, out, idx), *rest)
        return scatter_tokens(hidden_states, out, idx)
