from .basic_layer import RandomLayerTokenDrop
from .scheduler import RandomLTDScheduler

__all__ = ["RandomLayerTokenDrop", "RandomLTDScheduler"]
