"""Sparse tensor for embedding-gradient exchange.

Capability parity with reference ``deepspeed/runtime/sparse_tensor.py:13
SparseTensor`` — a (indices, values) COO view of a row-sparse tensor (the
shape embedding gradients take), with dense round-trip via ``to_dense``.
On TPU the engine's grads stay dense under GSPMD (row-sparse collectives
don't beat the ICI all-reduce for typical vocab sizes), so this type serves
the API surface: user code and tests that construct/inspect sparse grads
keep working and convert at the boundary.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np


class SparseTensor:
    def __init__(self, dense_tensor: Optional[jnp.ndarray] = None):
        self.orig_dense_tensor = dense_tensor
        if dense_tensor is not None:
            self.dims = tuple(dense_tensor.shape)
            row_mask = jnp.any(dense_tensor != 0, axis=tuple(
                range(1, dense_tensor.ndim))) if dense_tensor.ndim > 1 \
                else dense_tensor != 0
            self.indices = jnp.nonzero(row_mask)[0].astype(jnp.int32)
            self.values = dense_tensor[self.indices]
            self.dense_size = int(np.prod(self.dims))
        else:
            self.dims = ()
            self.indices = None
            self.values = None
            self.dense_size = 0

    @staticmethod
    def type() -> str:
        return "deepspeed.SparseTensor"

    def to_dense(self) -> jnp.ndarray:
        # .add, not .set: after add() the index list may contain duplicates
        # whose contributions must sum (COO semantics)
        dense = jnp.zeros(self.dims, dtype=self.values.dtype)
        return dense.at[self.indices].add(self.values)

    def sparse_size(self) -> Tuple[int, int]:
        return int(self.indices.size + self.values.size), self.dense_size

    def add(self, b: "SparseTensor") -> "SparseTensor":
        assert self.dims == b.dims, "unmatched shapes"
        out = SparseTensor()
        out.dims = self.dims
        out.dense_size = self.dense_size
        out.indices = jnp.concatenate([self.indices, b.indices])
        out.values = jnp.concatenate([self.values, b.values])
        return out

    def __str__(self) -> str:
        return (f"SparseTensor(dims={self.dims}, "
                f"nnz_rows={0 if self.indices is None else self.indices.size})")

    def __repr__(self) -> str:
        return self.__str__()
