"""Learning-rate schedules.

Capability parity with reference ``deepspeed/runtime/lr_schedules.py``:
``LRRangeTest`` (:258), ``OneCycle`` (:361), ``WarmupLR`` (:626),
``WarmupDecayLR`` (:715). Each schedule is a *pure function of the step*
(jit-friendly — usable inside the compiled train step) wrapped in a class with
the reference's ``step()/get_lr()/state_dict()`` surface.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional

LR_RANGE_TEST = "LRRangeTest"
ONE_CYCLE = "OneCycle"
WARMUP_LR = "WarmupLR"
WARMUP_DECAY_LR = "WarmupDecayLR"
WARMUP_COSINE_LR = "WarmupCosineLR"

VALID_LR_SCHEDULES = [LR_RANGE_TEST, ONE_CYCLE, WARMUP_LR, WARMUP_DECAY_LR, WARMUP_COSINE_LR]


class _Schedule:
    """Base: tracks step count, exposes pure ``lr_at(step)``."""

    def __init__(self, optimizer=None, last_batch_iteration: int = -1):
        self.optimizer = optimizer
        self.last_batch_iteration = last_batch_iteration

    def lr_at(self, step) -> Any:
        raise NotImplementedError

    def get_lr(self) -> List[float]:
        return [float(self.lr_at(max(self.last_batch_iteration, 0)))]

    def get_last_lr(self) -> List[float]:
        return self.get_lr()

    def step(self, last_batch_iteration: Optional[int] = None) -> None:
        if last_batch_iteration is None:
            last_batch_iteration = self.last_batch_iteration + 1
        self.last_batch_iteration = last_batch_iteration
        if self.optimizer is not None and hasattr(self.optimizer, "set_lr"):
            self.optimizer.set_lr(self.get_lr()[0])

    def state_dict(self) -> Dict:
        return {"last_batch_iteration": self.last_batch_iteration}

    def load_state_dict(self, sd: Dict) -> None:
        self.last_batch_iteration = sd["last_batch_iteration"]


class WarmupLR(_Schedule):
    """Linear warmup then constant (reference :626).

    warmup_type 'log' matches the reference default: lr rises on a log curve.
    """

    def __init__(self, optimizer=None, warmup_min_lr: float = 0.0,
                 warmup_max_lr: float = 0.001, warmup_num_steps: int = 1000,
                 warmup_type: str = "log", last_batch_iteration: int = -1):
        super().__init__(optimizer, last_batch_iteration)
        self.warmup_min_lr = warmup_min_lr
        self.warmup_max_lr = warmup_max_lr
        self.warmup_num_steps = max(2, warmup_num_steps)
        self.warmup_type = warmup_type
        self.inverse_log_warm_up = 1.0 / math.log(self.warmup_num_steps)

    def _warmup_frac(self, step):
        import jax.numpy as jnp

        s = jnp.asarray(step, dtype=jnp.float32)
        if self.warmup_type == "log":
            frac = self.inverse_log_warm_up * jnp.log(jnp.maximum(s, 1.0))
        else:
            frac = s / self.warmup_num_steps
        return jnp.clip(frac, 0.0, 1.0)

    def lr_at(self, step):
        frac = self._warmup_frac(step)
        return self.warmup_min_lr + (self.warmup_max_lr - self.warmup_min_lr) * frac


class WarmupDecayLR(WarmupLR):
    """Warmup then linear decay to 0 over total_num_steps (reference :715)."""

    def __init__(self, optimizer=None, total_num_steps: int = 10000, warmup_min_lr: float = 0.0,
                 warmup_max_lr: float = 0.001, warmup_num_steps: int = 1000,
                 warmup_type: str = "log", last_batch_iteration: int = -1):
        super().__init__(optimizer, warmup_min_lr, warmup_max_lr, warmup_num_steps,
                         warmup_type, last_batch_iteration)
        self.total_num_steps = total_num_steps

    def lr_at(self, step):
        import jax.numpy as jnp

        warm = super().lr_at(step)
        s = jnp.asarray(step, dtype=jnp.float32)
        decay = jnp.clip(
            (self.total_num_steps - s) /
            jnp.maximum(float(self.total_num_steps - self.warmup_num_steps), 1.0),
            0.0, 1.0)
        return jnp.where(s < self.warmup_num_steps, warm, self.warmup_max_lr * decay)


class WarmupCosineLR(WarmupLR):
    """Warmup then cosine decay — beyond-parity convenience (the reference
    gained this later; standard for TPU LLM training)."""

    def __init__(self, optimizer=None, total_num_steps: int = 10000, warmup_min_lr: float = 0.0,
                 warmup_max_lr: float = 0.001, warmup_num_steps: int = 1000,
                 cos_min_ratio: float = 0.0001, warmup_type: str = "linear",
                 last_batch_iteration: int = -1):
        super().__init__(optimizer, warmup_min_lr, warmup_max_lr, warmup_num_steps,
                         warmup_type, last_batch_iteration)
        self.total_num_steps = total_num_steps
        self.cos_min_ratio = cos_min_ratio

    def lr_at(self, step):
        import jax.numpy as jnp

        warm = super().lr_at(step)
        s = jnp.asarray(step, dtype=jnp.float32)
        progress = jnp.clip((s - self.warmup_num_steps) /
                            max(self.total_num_steps - self.warmup_num_steps, 1), 0.0, 1.0)
        cosine = self.cos_min_ratio + (1 - self.cos_min_ratio) * 0.5 * (
            1 + jnp.cos(jnp.pi * progress))
        return jnp.where(s < self.warmup_num_steps, warm, self.warmup_max_lr * cosine)


class OneCycle(_Schedule):
    """1-cycle policy (reference :361): cycle lr up then down, then decay."""

    def __init__(self, optimizer=None, cycle_min_lr: float = 0.0001, cycle_max_lr: float = 0.001,
                 decay_lr_rate: float = 0.0, cycle_first_step_size: int = 2000,
                 cycle_second_step_size: Optional[int] = None,
                 cycle_first_stair_count: int = 0, cycle_second_stair_count: Optional[int] = None,
                 decay_step_size: int = 0, last_batch_iteration: int = -1, **_momentum_kwargs):
        super().__init__(optimizer, last_batch_iteration)
        self.cycle_min_lr = cycle_min_lr
        self.cycle_max_lr = cycle_max_lr
        self.decay_lr_rate = decay_lr_rate
        self.first = cycle_first_step_size
        self.second = cycle_second_step_size if cycle_second_step_size is not None else self.first
        self.decay_step_size = decay_step_size

    def lr_at(self, step):
        import jax.numpy as jnp

        s = jnp.asarray(step, dtype=jnp.float32)
        total = self.first + self.second
        up = jnp.clip(s / self.first, 0.0, 1.0)
        down = jnp.clip((s - self.first) / self.second, 0.0, 1.0)
        in_cycle = self.cycle_min_lr + (self.cycle_max_lr - self.cycle_min_lr) * jnp.where(
            s < self.first, up, 1.0 - down)
        if self.decay_step_size > 0:
            decay_steps = jnp.maximum(s - total, 0.0) / self.decay_step_size
            post = self.cycle_min_lr / (1.0 + decay_steps * self.decay_lr_rate)
        else:
            post = jnp.asarray(self.cycle_min_lr, dtype=jnp.float32)
        return jnp.where(s < total, in_cycle, post)


class LRRangeTest(_Schedule):
    """LR range-test sweep (reference :258)."""

    def __init__(self, optimizer=None, lr_range_test_min_lr: float = 1e-3,
                 lr_range_test_step_size: int = 2000, lr_range_test_step_rate: float = 1.0,
                 lr_range_test_staircase: bool = False, last_batch_iteration: int = -1):
        super().__init__(optimizer, last_batch_iteration)
        self.min_lr = lr_range_test_min_lr
        self.step_size = lr_range_test_step_size
        self.step_rate = lr_range_test_step_rate
        self.staircase = lr_range_test_staircase

    def lr_at(self, step):
        import jax.numpy as jnp

        s = jnp.asarray(step, dtype=jnp.float32)
        interval = jnp.floor(s / self.step_size) if self.staircase else s / self.step_size
        return self.min_lr * (1.0 + interval * self.step_rate)


SCHEDULE_REGISTRY = {
    LR_RANGE_TEST: LRRangeTest,
    ONE_CYCLE: OneCycle,
    WARMUP_LR: WarmupLR,
    WARMUP_DECAY_LR: WarmupDecayLR,
    WARMUP_COSINE_LR: WarmupCosineLR,
}


def get_lr_schedule(name: Optional[str], params: Dict, optimizer=None):
    if name is None:
        return None
    if name not in SCHEDULE_REGISTRY:
        raise ValueError(f"unknown lr schedule {name}; valid: {VALID_LR_SCHEDULES}")
    return SCHEDULE_REGISTRY[name](optimizer=optimizer, **params)
