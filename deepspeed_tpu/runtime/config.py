"""Master JSON config (≅ reference ``deepspeed/runtime/config.py``).

Parses the DeepSpeed JSON surface — unmodified user configs must parse — into
a typed tree, enforcing the central batch invariant
``train_batch_size = micro_batch_per_gpu × gradient_accumulation_steps × dp_world_size``
(reference runtime/config.py batch reconciliation), plus TPU-specific
extensions under the ``"mesh"`` key (tp/pp/ep/sp degrees).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Literal, Optional, Union

from pydantic import Field

from ..utils.logging import logger
from .config_utils import DeepSpeedConfigModel, dict_raise_error_on_duplicate_keys
from .zero.config import DeepSpeedZeroConfig

# ---------------------------------------------------------------------------
# Sub-configs
# ---------------------------------------------------------------------------


class FP16Config(DeepSpeedConfigModel):
    """``fp16`` block (reference runtime/fp16 + constants.py)."""

    enabled: bool = False
    auto_cast: bool = False
    loss_scale: float = 0.0  # 0 = dynamic
    initial_scale_power: int = 16
    loss_scale_window: int = 1000
    hysteresis: int = 2
    min_loss_scale: float = 1.0
    fp16_master_weights_and_grads: bool = False


class BF16Config(DeepSpeedConfigModel):
    enabled: bool = False
    # TPU-native: keep fp32 master weights in optimizer state (ZeRO-1 style)
    bf16_master_weights: bool = True


class OptimizerConfig(DeepSpeedConfigModel):
    type: str = "Adam"
    params: Dict[str, Any] = Field(default_factory=dict)
    legacy_fusion: bool = False


class SchedulerConfig(DeepSpeedConfigModel):
    type: Optional[str] = None
    params: Dict[str, Any] = Field(default_factory=dict)


class ActivationCheckpointingConfig(DeepSpeedConfigModel):
    """``activation_checkpointing`` block (reference checkpointing.py:789)."""

    partition_activations: bool = False
    contiguous_memory_optimization: bool = False
    cpu_checkpointing: bool = False
    number_checkpoints: Optional[int] = None
    synchronize_checkpoint_boundary: bool = False
    profile: bool = False


class TensorBoardConfig(DeepSpeedConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class WandbConfig(DeepSpeedConfigModel):
    enabled: bool = False
    group: Optional[str] = None
    team: Optional[str] = None
    project: str = "deepspeed"


class CSVConfig(DeepSpeedConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class JSONLConfig(DeepSpeedConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class MonitorConfig(DeepSpeedConfigModel):
    tensorboard: TensorBoardConfig = Field(default_factory=TensorBoardConfig)
    wandb: WandbConfig = Field(default_factory=WandbConfig)
    csv_monitor: CSVConfig = Field(default_factory=CSVConfig)
    jsonl: JSONLConfig = Field(default_factory=JSONLConfig)

    @property
    def enabled(self) -> bool:
        return (self.tensorboard.enabled or self.wandb.enabled
                or self.csv_monitor.enabled or self.jsonl.enabled)


class CommsLoggerConfig(DeepSpeedConfigModel):
    enabled: bool = False
    verbose: bool = False
    prof_all: bool = True
    debug: bool = False
    prof_ops: List[str] = Field(default_factory=list)


class FlopsProfilerConfig(DeepSpeedConfigModel):
    enabled: bool = False
    recompute_fwd_factor: float = 0.0
    profile_step: int = 1
    module_depth: int = -1
    top_modules: int = 1
    detailed: bool = True
    output_file: Optional[str] = None


class PipelineConfig(DeepSpeedConfigModel):
    stages: Union[int, str] = "auto"
    partition: str = "best"
    seed_layers: bool = False
    activation_checkpoint_interval: int = 0
    pipe_partitioned: bool = True
    grad_partitioned: bool = True
    # executed schedule: "1f1b" = TrainSchedule-interleaved executor with the
    # constant-in-M activation ring (reference schedule.py:189); "gpipe" =
    # forward roll + autodiff transpose (activations linear in micro count)
    schedule: Literal["1f1b", "gpipe"] = "1f1b"


class AutotuningBlock(DeepSpeedConfigModel):
    """``autotuning`` block (reference autotuning/config.py) — engine-side
    fields; the full search config lives in autotuning.AutotuningConfig."""

    enabled: bool = False
    metric: str = "throughput"
    metric_path: Optional[str] = None
    start_profile_step: int = 3
    end_profile_step: int = 5
    model_info: Dict[str, Any] = Field(default_factory=dict)


class HybridEngineConfig(DeepSpeedConfigModel):
    """``hybrid_engine`` block (reference DeepSpeedHybridEngineConfig)."""

    enabled: bool = False
    max_out_tokens: int = 512
    inference_tp_size: int = 1
    release_inference_cache: bool = False
    pin_parameters: bool = True
    tp_gather_partition_size: int = 8
    lora_scaling: float = 1.0  # TPU extension: LoRA fuse scale


class MeshDims(DeepSpeedConfigModel):
    """TPU extension: degrees of parallelism for the global device mesh."""

    data: int = -1  # -1 = fill remaining devices
    model: int = 1
    pipe: int = 1
    expert: int = 1
    seq: int = 1


class NebulaConfig(DeepSpeedConfigModel):
    """``nebula`` block (reference deepspeed/nebula/config.py) — selects
    the async tiered checkpoint engine."""

    enabled: bool = False
    persistent_storage_path: Optional[str] = None
    persistent_time_interval: int = 100
    num_of_version_in_retention: int = 2
    enable_nebula_load: bool = True


class CheckpointConfig(DeepSpeedConfigModel):
    tag_validation: str = "Warn"  # Ignore | Warn | Fail
    load_universal: bool = False
    use_node_local_storage: bool = False
    parallel_write: Dict[str, Any] = Field(default_factory=dict)


class DataTypesConfig(DeepSpeedConfigModel):
    grad_accum_dtype: Optional[str] = None


class AioConfig(DeepSpeedConfigModel):
    """``aio`` block (reference csrc/aio + op_builder/async_io.py)."""

    block_size: int = 1048576
    queue_depth: int = 8
    thread_count: int = 1
    single_submit: bool = False
    overlap_events: bool = True


class CurriculumParams(DeepSpeedConfigModel):
    enabled: bool = False
    curriculum_type: str = "seqlen"
    min_difficulty: int = 8
    max_difficulty: int = 1024
    schedule_type: str = "fixed_linear"
    schedule_config: Dict[str, Any] = Field(default_factory=dict)
    # batch key whose dim 2 (after gas-stacking) is the sequence axis; used to
    # anchor seqlen truncation instead of guessing by size
    seqlen_key: str = "input_ids"


class EigenvalueConfig(DeepSpeedConfigModel):
    enabled: bool = False
    verbose: bool = False
    max_iter: int = 100
    tol: float = 1e-2
    stability: float = 1e-6
    gas_boundary_resolution: int = 1
    layer_name: str = "bert.encoder.layer"
    layer_num: int = 0


# ---------------------------------------------------------------------------
# Master config
# ---------------------------------------------------------------------------


class DeepSpeedConfig(DeepSpeedConfigModel):
    train_batch_size: Optional[int] = None
    train_micro_batch_size_per_gpu: Optional[int] = None
    gradient_accumulation_steps: Optional[int] = None

    steps_per_print: int = 10
    wall_clock_breakdown: bool = False
    memory_breakdown: bool = False
    dump_state: bool = False
    # SURVEY §5.2 analog of ZeRO-3 safe-mode cross-rank assertions
    # (stage3.py:1080): hash config/param-structure/batch-structure and
    # compare across hosts at step boundaries
    check_rank_consistency: bool = False

    prescale_gradients: bool = False
    gradient_predivide_factor: float = 1.0
    gradient_clipping: float = 0.0
    sparse_gradients: bool = False

    zero_optimization: DeepSpeedZeroConfig = Field(default_factory=DeepSpeedZeroConfig)
    fp16: FP16Config = Field(default_factory=FP16Config)
    bf16: BF16Config = Field(default_factory=BF16Config)
    optimizer: Optional[OptimizerConfig] = None
    scheduler: Optional[SchedulerConfig] = None

    activation_checkpointing: ActivationCheckpointingConfig = Field(
        default_factory=ActivationCheckpointingConfig)

    tensorboard: Optional[TensorBoardConfig] = None  # legacy top-level (deprecated)
    monitor_config: MonitorConfig = Field(default_factory=MonitorConfig)
    comms_logger: CommsLoggerConfig = Field(default_factory=CommsLoggerConfig)
    flops_profiler: FlopsProfilerConfig = Field(default_factory=FlopsProfilerConfig)
    pipeline: PipelineConfig = Field(default_factory=PipelineConfig)
    hybrid_engine: HybridEngineConfig = Field(default_factory=HybridEngineConfig)
    mesh: MeshDims = Field(default_factory=MeshDims)
    checkpoint: CheckpointConfig = Field(default_factory=CheckpointConfig)
    nebula: NebulaConfig = Field(default_factory=NebulaConfig)
    data_types: DataTypesConfig = Field(default_factory=DataTypesConfig)
    aio: AioConfig = Field(default_factory=AioConfig)
    curriculum_learning: CurriculumParams = Field(default_factory=CurriculumParams)
    eigenvalue: EigenvalueConfig = Field(default_factory=EigenvalueConfig)
    # compression_training keeps the reference's free-form schema (parsed by
    # compression.CompressionConfig, not pydantic)
    compression_training: Optional[Dict[str, Any]] = None
    autotuning: AutotuningBlock = Field(default_factory=AutotuningBlock)

    zero_allow_untested_optimizer: bool = False
    zero_force_ds_cpu_optimizer: bool = True
    communication_data_type: Optional[str] = None
    seed: int = 1234
    disable_allgather: bool = False

    # populated by reconciliation
    _world_size: int = 1

    def __init__(self, config: Union[str, Dict, None] = None, mpu=None, world_size: int = 1,
                 **kwargs):
        if config is None:
            data = dict(kwargs)
        elif isinstance(config, str):
            with open(config, "r") as fh:
                data = json.load(fh, object_pairs_hook=dict_raise_error_on_duplicate_keys)
        elif isinstance(config, dict):
            data = dict(config)
        else:
            raise ValueError(f"Expected a path or dict config, got {type(config)}")

        # legacy top-level monitor keys fold into monitor_config
        monitor = data.setdefault("monitor_config", {})
        for legacy in ("tensorboard", "wandb", "csv_monitor", "jsonl"):
            if legacy in data and legacy not in monitor:
                monitor[legacy] = data[legacy]

        super().__init__(**data)
        object.__setattr__(self, "_world_size", world_size)
        self._do_batch_reconciliation(world_size)
        self._do_sanity_check()

    # --- batch invariant -------------------------------------------------
    def _do_batch_reconciliation(self, world_size: int) -> None:
        """train_batch = micro_batch × gas × dp_world (reference semantics:
        any two determine the third; one alone fills defaults)."""
        train = self.train_batch_size
        micro = self.train_micro_batch_size_per_gpu
        gas = self.gradient_accumulation_steps

        if train is not None and micro is not None and gas is not None:
            pass
        elif train is not None and micro is not None:
            gas = train // (micro * world_size)
        elif train is not None and gas is not None:
            micro = train // (gas * world_size)
        elif micro is not None and gas is not None:
            train = micro * gas * world_size
        elif train is not None:
            gas = 1
            micro = train // world_size
        elif micro is not None:
            gas = 1
            train = micro * world_size
        else:
            raise ValueError(
                "Either train_batch_size or train_micro_batch_size_per_gpu needs "
                "to be provided")

        self.train_batch_size = train
        self.train_micro_batch_size_per_gpu = micro
        self.gradient_accumulation_steps = gas

        if train != micro * gas * world_size:
            raise ValueError(
                f"Check batch related parameters. train_batch_size is not equal to "
                f"micro_batch_per_gpu * gradient_acc_step * world_size: "
                f"{train} != {micro} * {gas} * {world_size}")

    def _do_sanity_check(self) -> None:
        if self.fp16.enabled and self.bf16.enabled:
            raise ValueError("fp16 and bf16 modes cannot be enabled simultaneously")
        if self.zero_optimization.stage > 0 and not (self.fp16.enabled or self.bf16.enabled):
            logger.warning("ZeRO enabled with full fp32 precision — consider bf16 on TPU")

    # --- convenience accessors (mirror engine property style) -----------
    @property
    def zero_enabled(self) -> bool:
        return self.zero_optimization.stage > 0

    @property
    def precision_dtype(self):
        import jax.numpy as jnp

        if self.bf16.enabled:
            return jnp.bfloat16
        if self.fp16.enabled:
            return jnp.float16
        return jnp.float32

    def print_config(self, name: str = "DeepSpeedConfig") -> None:
        logger.info(f"{name}:\n{json.dumps(self.model_dump(mode='json'), indent=2, default=str)}")
