"""Nebula-style async tiered checkpoint engine.

The reference's ``NebulaCheckpointEngine``
(runtime/checkpoint_engine/nebula_checkpoint_engine.py:20) provides async,
tiered persistence via Azure Nebula. The TPU-native engine with those
properties is the orbax engine (async background write, per-process
sharded tiers, commit barrier) — exported here under the reference's name
and selected by the ``nebula.enabled`` config block (the reference's
selection path, engine._configure_checkpointing).
"""

from .orbax_checkpoint_engine import OrbaxCheckpointEngine


class NebulaCheckpointEngine(OrbaxCheckpointEngine):
    def __init__(self, config_params=None):
        super().__init__(config_params, use_async=True)
