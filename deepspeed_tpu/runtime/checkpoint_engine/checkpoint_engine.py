"""Checkpoint-engine abstraction.

Capability parity with reference
``runtime/checkpoint_engine/checkpoint_engine.py:9`` (``CheckpointEngine``
ABC: create/save/load/commit) and ``torch_checkpoint_engine.py:12``. The
default implementation serializes JAX pytrees (state dicts of numpy arrays)
with an ``.npz`` + tree-structure JSON format; an async engine (Nebula-style
tiering, nebula_checkpoint_engine.py:20) can subclass the same surface.
"""

from __future__ import annotations

import json
import os
import pickle
from typing import Any

import numpy as np

from ...utils.logging import log_dist, logger


class CheckpointEngine:
    def __init__(self, config_params=None):
        pass

    def create(self, tag: str) -> None:
        """Hook for per-tag setup (log/start async session)."""
        log_dist(f"[DSTPU] Saving checkpoint tag {tag}", ranks=[0])

    def makedirs(self, path: str, exist_ok: bool = False) -> None:
        os.makedirs(path, exist_ok=exist_ok)

    def save(self, state_dict: Any, path: str) -> None:
        raise NotImplementedError

    def load(self, path: str, map_location=None) -> Any:
        raise NotImplementedError

    def commit(self, tag: str) -> bool:
        """Mark all saves for ``tag`` durable (the ``latest`` protocol relies
        on this ordering)."""
        return True


def _flatten_state_dict(sd: Any, prefix: str = "") -> dict:
    flat = {}
    if isinstance(sd, dict):
        for k, v in sd.items():
            flat.update(_flatten_state_dict(v, f"{prefix}{k}/"))
    elif isinstance(sd, (list, tuple)):
        for i, v in enumerate(sd):
            flat.update(_flatten_state_dict(v, f"{prefix}{i}/"))
    else:
        flat[prefix[:-1]] = sd
    return flat


class ArrayCheckpointEngine(CheckpointEngine):
    """Default synchronous engine: one ``.npz`` of arrays + a pickle for
    non-array leaves (the torch.save analog, torch_checkpoint_engine.py:12)."""

    # ml_dtypes (bfloat16, fp8) are not numpy-native; persist them as raw
    # integer views and record the true dtype in the sidecar metadata
    _VIEW_DTYPES = {1: np.uint8, 2: np.uint16, 4: np.uint32}

    def save(self, state_dict: Any, path: str) -> None:
        flat = _flatten_state_dict(state_dict)
        arrays = {}
        dtypes = {}
        others = {}
        for k, v in flat.items():
            if v is None:
                others[k] = None
                continue
            try:
                arr = np.asarray(v)
                if arr.dtype == object:
                    raise ValueError
                if arr.dtype.name not in ("float64", "float32", "float16", "int64",
                                          "int32", "int16", "int8", "uint8", "uint16",
                                          "uint32", "uint64", "bool"):
                    dtypes[k] = arr.dtype.name  # e.g. bfloat16, float8_e4m3fn
                    arr = arr.view(self._VIEW_DTYPES[arr.dtype.itemsize])
                arrays[k] = arr
            except (ValueError, TypeError):
                others[k] = v
        os.makedirs(os.path.dirname(path), exist_ok=True)
        # npz keys can't contain some chars on all systems; index them
        index = {f"a{i}": k for i, k in enumerate(sorted(arrays))}
        np.savez(path + ".npz", **{f"a{i}": arrays[k]
                                   for i, k in enumerate(sorted(arrays))})
        with open(path + ".meta", "wb") as fh:
            pickle.dump({"index": index, "others": others, "dtypes": dtypes}, fh)
        logger.debug(f"saved checkpoint shard {path}")

    def load(self, path: str, map_location=None) -> dict:
        import ml_dtypes

        with open(path + ".meta", "rb") as fh:
            meta = pickle.load(fh)
        data = np.load(path + ".npz", allow_pickle=False)
        flat = {}
        for ak, key in meta["index"].items():
            arr = data[ak]
            if key in meta.get("dtypes", {}):
                arr = arr.view(getattr(ml_dtypes, meta["dtypes"][key]))
            flat[key] = arr
        flat.update(meta["others"])
        # unflatten into nested dicts
        nested: dict = {}
        for key, value in flat.items():
            parts = key.split("/")
            d = nested
            for p in parts[:-1]:
                d = d.setdefault(p, {})
            d[parts[-1]] = value
        return nested


def write_latest(save_dir: str, tag: str) -> None:
    """``latest`` tag file protocol (reference engine.py:3045)."""
    with open(os.path.join(save_dir, "latest"), "w") as fh:
        fh.write(tag)


def read_latest(load_dir: str) -> str:
    latest_path = os.path.join(load_dir, "latest")
    with open(latest_path, "r") as fh:
        return fh.read().strip()


def checkpoint_meta_path(save_dir: str, tag: str, kind: str, mp_rank: int = 0,
                         dp_rank: int = 0) -> str:
    """Reference checkpoint naming (engine.py:2485-2503):
    ``mp_rank_XX_model_states`` / ``zero_pp_rank_X_mp_rank_XX_optim_states``."""
    if kind == "model":
        name = f"mp_rank_{mp_rank:02d}_model_states"
    elif kind == "optim":
        name = f"zero_pp_rank_{dp_rank}_mp_rank_{mp_rank:02d}_optim_states"
    else:
        raise ValueError(kind)
    return os.path.join(save_dir, str(tag), name)
